from paddle_tpu.distributed import collective
from paddle_tpu.distributed.collective import (
    ReduceOp,
    all_gather,
    all_reduce,
    all_to_all,
    barrier,
    broadcast,
    permute,
    reduce_scatter,
    shift,
)
from paddle_tpu.distributed.mesh import (
    AXES,
    HybridMesh,
    current_mesh,
    make_mesh,
    single_device_mesh,
)
from paddle_tpu.distributed.sharded import (
    maybe_shard,
    opt_state_specs,
    partition_specs,
    shard_module,
    with_sharding_constraint,
)
from paddle_tpu.distributed.ring_attention import (
    make_ring_attention, make_zigzag_ring_attention, ring_attention,
    zigzag_inverse_permutation, zigzag_permutation, zigzag_ring_attention)
from paddle_tpu.distributed.ulysses import make_ulysses_attention, ulysses_attention
from paddle_tpu.distributed.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    parallel_cross_entropy,
)


def init_parallel_env():
    """Ref: paddle.distributed.init_parallel_env — multi-host bring-up.
    Single-process is a no-op; multi-host uses jax.distributed."""
    import jax
    if jax.process_count() > 1:
        return  # already initialised by launcher
    return


def get_world_size():
    import jax
    return jax.device_count()


def get_rank():
    import jax
    return jax.process_index()


def recompute(fn, *args, **kwargs):
    """Ref: paddle.distributed.fleet.utils.recompute — rematerialise
    ``fn``'s activations in backward. Direct mapping onto jax.checkpoint."""
    import jax
    preserve = kwargs.pop("preserve_rng_state", None)  # reference kwarg; rng
    # is explicit in this framework so nothing to preserve
    return jax.checkpoint(lambda *a: fn(*a, **kwargs))(*args)


# -- reference communication-API parity (ref python/paddle/distributed/) -----

from paddle_tpu.distributed import fleet, launch  # noqa: E402
from paddle_tpu.distributed.collective import (  # noqa: E402
    all_gather_object,
    gather,
    recv,
    reduce,
    scatter,
    send,
)

# reference spells all_to_all "alltoall"
alltoall = all_to_all


def alltoall_single(x, *, axis_name: str):
    """Ref alltoall_single: equal splits of the leading dim exchanged over
    the group (split axis == concat axis == 0)."""
    return all_to_all(x, axis_name=axis_name, split_axis=0, concat_axis=0)


# isend/irecv: XLA collectives are compiler-scheduled; there is no async
# handle to wait on — the names map to the same static-edge ppermute.
isend = send
irecv = recv


def wait(tensor, group=None, use_calc_stream=True):
    """Ref communication/wait: stream sync. XLA orders collectives in the
    compiled program; host-side sync is block_until_ready."""
    try:
        tensor.block_until_ready()
    except AttributeError:
        pass
    return tensor


class Group:
    """Process-group handle (ref collective.Group). On TPU a group IS a
    mesh axis: ``axis_name`` binds the collectives that take this group."""

    def __init__(self, ranks, axis_name=None, id=0):
        self.ranks = list(ranks)
        self.axis_name = axis_name
        self.id = id

    @property
    def nranks(self):
        return len(self.ranks)

    def __repr__(self):
        return f"Group(ranks={self.ranks}, axis_name={self.axis_name!r})"


_groups: dict = {}
_next_group_id = [0]


def new_group(ranks=None, backend=None, axis_name=None):
    """Ref new_group. GSPMD note: collectives are compiled against mesh
    axes, so a 'group' here names an axis of the active HybridMesh (default:
    the data-parallel axis) rather than wiring a communicator."""
    import jax
    if ranks is None:
        ranks = list(range(jax.device_count()))
    g = Group(ranks, axis_name=axis_name or "dp", id=_next_group_id[0])
    _next_group_id[0] += 1
    _groups[g.id] = g
    return g


def get_group(gid=0):
    return _groups.get(gid)


def is_initialized():
    return True


def get_backend(group=None):
    return "xla"


def destroy_process_group(group=None):
    if group is None:
        _groups.clear()
    else:
        _groups.pop(getattr(group, "id", group), None)


class ParallelEnv:
    """Ref parallel.ParallelEnv — rank/world-size view of the runtime."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        # consistent with module-level get_world_size (device count under
        # SPMD — one program per chip, unlike the reference's per-process
        # trainers)
        return get_world_size()

    @property
    def device_id(self):
        import jax
        return jax.devices()[0].id

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return self.rank


class DataParallel:
    """Ref paddle.DataParallel wrapper. Under GSPMD data parallelism is a
    sharding property, not a wrapper: batch inputs sharded over the ``dp``
    axis replicate params and all-reduce grads inside the compiled step.
    This class keeps the reference entry point — it forwards to the module
    and exposes the same attrs; pair it with HybridMesh(dp=N)."""

    def __init__(self, layers, **kwargs):
        self._layers = layers

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        if name == "_layers":  # not yet set (unpickling/copy) — no recursion
            raise AttributeError(name)
        return getattr(self._layers, name)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)


_split_layers: dict = {}


def split(x, size, operation="linear", axis=0, gather_out=True, weight_attr=None,
          bias_attr=None, name=None):
    """Ref paddle.distributed.split — build a tensor-parallel linear/
    embedding and apply it. Like the reference, every unnamed call creates
    FRESH parameters; pass ``name=`` to retain the layer across calls and
    fetch it with ``get_split_layer`` for training/state_dict. Prefer
    constructing ColumnParallelLinear / RowParallelLinear /
    VocabParallelEmbedding directly in new code."""
    layer = _split_layers.get(name) if name is not None else None
    if layer is None:
        if operation == "linear":
            cls = ColumnParallelLinear if axis == 1 else RowParallelLinear
            layer = cls(size[0], size[1])
        elif operation == "embedding":
            layer = VocabParallelEmbedding(size[0], size[1])
        else:
            raise ValueError(f"unsupported split operation {operation!r}")
        if name is not None:  # unnamed calls get fresh params (reference)
            _split_layers[name] = layer
    return layer(x)


def get_split_layer(name_or_key):
    """Layer created by ``split`` (see its docstring)."""
    return _split_layers.get(name_or_key)


def spawn(func, args=(), nprocs=1, **kwargs):
    """Ref paddle.distributed.spawn. On TPU pods process bring-up is done by
    the launcher (paddle_tpu.distributed.launch / jax.distributed); spawn
    runs ``func`` once per local process via multiprocessing for CPU tests."""
    if nprocs == 1:
        return func(*args)
    import multiprocessing as mp
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=func, args=args) for _ in range(nprocs)]
    for p in procs:
        p.start()
    for p in procs:
        p.join()
        if p.exitcode != 0:
            raise RuntimeError(f"spawned process failed with {p.exitcode}")
