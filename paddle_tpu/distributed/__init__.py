from paddle_tpu.distributed import collective
from paddle_tpu.distributed.collective import (
    ReduceOp,
    all_gather,
    all_reduce,
    all_to_all,
    barrier,
    broadcast,
    permute,
    reduce_scatter,
    shift,
)
from paddle_tpu.distributed.mesh import (
    AXES,
    HybridMesh,
    current_mesh,
    make_mesh,
    single_device_mesh,
)
from paddle_tpu.distributed.sharded import (
    maybe_shard,
    opt_state_specs,
    partition_specs,
    shard_module,
    with_sharding_constraint,
)
from paddle_tpu.distributed.ring_attention import (
    make_ring_attention, make_zigzag_ring_attention, ring_attention,
    zigzag_inverse_permutation, zigzag_permutation, zigzag_ring_attention)
from paddle_tpu.distributed.ulysses import make_ulysses_attention, ulysses_attention
from paddle_tpu.distributed.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    parallel_cross_entropy,
)


def init_parallel_env():
    """Ref: paddle.distributed.init_parallel_env — multi-host bring-up.
    Single-process is a no-op; multi-host uses jax.distributed."""
    import jax
    if jax.process_count() > 1:
        return  # already initialised by launcher
    return


def get_world_size():
    import jax
    return jax.device_count()


def get_rank():
    import jax
    return jax.process_index()


def recompute(fn, *args, **kwargs):
    """Ref: paddle.distributed.fleet.utils.recompute — rematerialise
    ``fn``'s activations in backward. Direct mapping onto jax.checkpoint."""
    import jax
    preserve = kwargs.pop("preserve_rng_state", None)  # reference kwarg; rng
    # is explicit in this framework so nothing to preserve
    return jax.checkpoint(lambda *a: fn(*a, **kwargs))(*args)
