"""``paddle.incubate`` namespace parity (ref: ``python/paddle/incubate/``).

Everything here is implemented elsewhere in the package under its TPU-native
home; this module re-exports with the reference's incubate paths so ported
code finds it: ``incubate.nn.functional.fused_*``, ``incubate.LookAhead``,
``incubate.distributed.models.moe``…
"""
from paddle_tpu.incubate import nn, optimizer, distributed
from paddle_tpu.optimizer import ExponentialMovingAverage, LookAhead, Lion

__all__ = ["nn", "optimizer", "distributed", "LookAhead",
           "ExponentialMovingAverage", "Lion", "softmax_mask_fuse"]


def softmax_mask_fuse(x, mask):
    """ref incubate.softmax_mask_fuse — XLA fuses this chain natively."""
    import jax
    return jax.nn.softmax(x + mask.astype(x.dtype), axis=-1)
