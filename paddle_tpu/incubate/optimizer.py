"""``paddle.incubate.optimizer`` re-exports."""
from paddle_tpu.optimizer import (ExponentialMovingAverage, LookAhead, Lion,
                                  Adafactor)
