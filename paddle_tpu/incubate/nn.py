"""``paddle.incubate.nn`` re-exports (FusedTransformer-family capability
lives in paddle_tpu.nn.transformer + models/decoding)."""
from types import SimpleNamespace

from paddle_tpu.nn.transformer import (MultiHeadAttention as FusedMultiHeadAttention,
                                       TransformerEncoderLayer as FusedTransformerEncoderLayer)
from paddle_tpu.ops import (fused_dropout_add, fused_layer_norm, fused_linear,
                            fused_linear_activation, fused_rms_norm)
from paddle_tpu.ops.attention import (flash_attention,
                                      fused_rotary_position_embedding)

def masked_multihead_attention(x, cache_k, cache_v, pos, num_heads,
                               window=None):
    """Single-step decode attention with an in-place-style KV cache update
    (ref incubate.nn.functional.masked_multihead_attention — the fused
    decode kernel behind fused_multi_transformer).

    TPU shape convention: ``x`` is the fused qkv for ONE step,
    [B, (3*H)*D]; ``cache_k/v`` are [B, max_len, H, D]; ``pos`` is the
    write position (traced int). Returns (out [B, H*D], new_k, new_v).
    The causal mask over the cache is implicit (keys <= pos)."""
    import jax.numpy as jnp

    from paddle_tpu.models.decoding import _attend_with_cache

    b = x.shape[0]
    h = num_heads
    d = cache_k.shape[-1]
    q, k, v = jnp.split(x.reshape(b, 3 * h, d), 3, axis=1)
    out, new_k, new_v = _attend_with_cache(
        q[:, None, :, :].reshape(b, 1, h, d), cache_k, cache_v,
        k.reshape(b, 1, h, d), v.reshape(b, 1, h, d), pos, window=window)
    return out.reshape(b, h * d), new_k, new_v


functional = SimpleNamespace(
    masked_multihead_attention=masked_multihead_attention,
    fused_rms_norm=fused_rms_norm,
    fused_layer_norm=fused_layer_norm,
    fused_linear=fused_linear,
    fused_linear_activation=fused_linear_activation,
    fused_dropout_add=fused_dropout_add,
    fused_rotary_position_embedding=fused_rotary_position_embedding,
    flash_attention=flash_attention,
)
