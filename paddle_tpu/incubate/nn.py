"""``paddle.incubate.nn`` re-exports (FusedTransformer-family capability
lives in paddle_tpu.nn.transformer + models/decoding)."""
from types import SimpleNamespace

from paddle_tpu.nn.transformer import (MultiHeadAttention as FusedMultiHeadAttention,
                                       TransformerEncoderLayer as FusedTransformerEncoderLayer)
from paddle_tpu.ops import (fused_dropout_add, fused_layer_norm, fused_linear,
                            fused_linear_activation, fused_rms_norm)
from paddle_tpu.ops.attention import (flash_attention,
                                      fused_rotary_position_embedding)

functional = SimpleNamespace(
    fused_rms_norm=fused_rms_norm,
    fused_layer_norm=fused_layer_norm,
    fused_linear=fused_linear,
    fused_linear_activation=fused_linear_activation,
    fused_dropout_add=fused_dropout_add,
    fused_rotary_position_embedding=fused_rotary_position_embedding,
    flash_attention=flash_attention,
)
