"""``paddle.incubate.nn`` re-exports (FusedTransformer-family capability
lives in paddle_tpu.nn.transformer + models/decoding)."""
from types import SimpleNamespace

from paddle_tpu.nn.transformer import (MultiHeadAttention as FusedMultiHeadAttention,
                                       TransformerEncoderLayer as FusedTransformerEncoderLayer)
from paddle_tpu.ops import (fused_dropout_add, fused_layer_norm, fused_linear,
                            fused_linear_activation, fused_rms_norm)
from paddle_tpu.ops.attention import (flash_attention,
                                      fused_bias_dropout_residual_layer_norm,
                                      fused_rotary_position_embedding)
from paddle_tpu.nn.functional import swiglu


def fused_multi_head_attention(x, qkv_weight, qkv_bias, out_weight, out_bias,
                               num_heads, attn_mask=None, causal=False,
                               dropout_p=0.0, training=True,
                               pre_layer_norm=False, ln_scale=None,
                               ln_bias=None, add_residual=True,
                               epsilon=1e-5, rng=None):
    """Ref incubate.nn.functional.fused_multi_head_attention: the full
    fused block — (pre-)LN, fused qkv projection, SDPA (flash on TPU),
    out projection, residual add, (post-)LN. qkv_weight: [h, 3*h]."""
    import jax.numpy as jnp

    from paddle_tpu.nn import functional as _F
    from paddle_tpu.ops.attention import scaled_dot_product_attention
    b, s, h = x.shape
    d = h // num_heads
    residual = x
    if pre_layer_norm:  # layer_norm defaults affine to ones/zeros when None
        x = _F.layer_norm(x, h, ln_scale, ln_bias, epsilon)
    qkv = x @ qkv_weight
    if qkv_bias is not None:
        qkv = qkv + qkv_bias
    q, k, v = jnp.split(qkv, 3, axis=-1)
    out = scaled_dot_product_attention(
        q.reshape(b, s, num_heads, d), k.reshape(b, s, num_heads, d),
        v.reshape(b, s, num_heads, d), attn_mask=attn_mask, is_causal=causal,
        dropout_p=dropout_p, training=training, rng=rng)
    out = out.reshape(b, s, h) @ out_weight
    if out_bias is not None:
        out = out + out_bias
    if add_residual:
        out = out + residual
    if not pre_layer_norm:
        out = _F.layer_norm(out, h, ln_scale, ln_bias, epsilon)
    return out


def fused_feedforward(x, w1, b1, w2, b2, activation="gelu", dropout_p=0.0,
                      training=True, rng=None, pre_layer_norm=False,
                      ln_scale=None, ln_bias=None, add_residual=True,
                      epsilon=1e-5):
    """Ref incubate.nn.functional.fused_feedforward: the full fused block —
    residual + dropout(linear2(dropout(act(linear1((pre-)LN(x)))))), with
    post-LN when pre_layer_norm=False. XLA fuses the chain (the reference
    fuses it by hand in CUDA)."""
    from paddle_tpu.nn import functional as _F
    residual = x
    if pre_layer_norm:
        x = _F.layer_norm(x, x.shape[-1], ln_scale, ln_bias, epsilon)
    act = {"gelu": _F.gelu, "relu": _F.relu, "silu": _F.silu}[activation]
    # two INDEPENDENT dropout masks (ref uses two distinct dropout ops)
    rng1 = rng2 = rng
    if dropout_p and rng is not None:
        import jax
        rng1, rng2 = jax.random.split(rng)
    h = act(x @ w1 + (b1 if b1 is not None else 0))
    h = _F.dropout(h, dropout_p, training, rng=rng1) if dropout_p else h
    h = h @ w2 + (b2 if b2 is not None else 0)
    h = _F.dropout(h, dropout_p, training, rng=rng2) if dropout_p else h
    if add_residual:
        h = h + residual
    if not pre_layer_norm:
        h = _F.layer_norm(h, h.shape[-1], ln_scale, ln_bias, epsilon)
    return h

def masked_multihead_attention(x, cache_k, cache_v, pos, num_heads,
                               window=None):
    """Single-step decode attention with an in-place-style KV cache update
    (ref incubate.nn.functional.masked_multihead_attention — the fused
    decode kernel behind fused_multi_transformer).

    TPU shape convention: ``x`` is the fused qkv for ONE step,
    [B, (3*H)*D]; ``cache_k/v`` are [B, max_len, H, D]; ``pos`` is the
    write position (traced int). Returns (out [B, H*D], new_k, new_v).
    The causal mask over the cache is implicit (keys <= pos)."""
    import jax.numpy as jnp

    from paddle_tpu.models.decoding import _attend_with_cache

    b = x.shape[0]
    h = num_heads
    d = cache_k.shape[-1]
    q, k, v = jnp.split(x.reshape(b, 3 * h, d), 3, axis=1)
    out, new_k, new_v = _attend_with_cache(
        q[:, None, :, :].reshape(b, 1, h, d), cache_k, cache_v,
        k.reshape(b, 1, h, d), v.reshape(b, 1, h, d), pos, window=window)
    return out.reshape(b, h * d), new_k, new_v


functional = SimpleNamespace(
    masked_multihead_attention=masked_multihead_attention,
    swiglu=swiglu,
    fused_bias_dropout_residual_layer_norm=fused_bias_dropout_residual_layer_norm,
    fused_multi_head_attention=fused_multi_head_attention,
    fused_feedforward=fused_feedforward,
    fused_rms_norm=fused_rms_norm,
    fused_layer_norm=fused_layer_norm,
    fused_linear=fused_linear,
    fused_linear_activation=fused_linear_activation,
    fused_dropout_add=fused_dropout_add,
    fused_rotary_position_embedding=fused_rotary_position_embedding,
    flash_attention=flash_attention,
)
