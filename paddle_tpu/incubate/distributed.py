"""``paddle.incubate.distributed`` re-exports (MoE expert parallel)."""
from types import SimpleNamespace

from paddle_tpu.distributed import moe as _moe

models = SimpleNamespace(moe=_moe)
