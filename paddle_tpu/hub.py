"""``paddle.hub`` (ref: ``python/paddle/hapi/hub.py``) — local-only.

The environment has zero egress, so github/gitee sources are unsupported;
local hubconf directories (the reference's ``source='local'``) work fully.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.pop(0)
    return mod


def _check_source(source):
    if source != "local":
        raise NotImplementedError(
            "paddle_tpu.hub supports source='local' only (no network egress); "
            "clone the repo first and point hub at the directory")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    _check_source(source)
    return getattr(_load_hubconf(repo_dir), model).__doc__


def load(repo_dir, model, *args, source="local", force_reload=False, **kwargs):
    _check_source(source)
    return getattr(_load_hubconf(repo_dir), model)(*args, **kwargs)
