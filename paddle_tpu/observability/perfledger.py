"""Bench-history perf ledger (ISSUE 12): the tool that READS the bench
artifacts the repo has been accumulating.

Every bench round leaves a ``BENCH_rNN.json`` artifact ({"n", "cmd",
"rc", "tail", "parsed"}) and ``bench.py`` appends the result line of
each run it performs to ``BENCH_HISTORY.jsonl`` next to itself. Until
now nothing read them back — five artifact files and no trajectory.
This module parses the history into per-leg series (the headline
tokens/sec, MFU, the per-config values under ``extra.configs``, and
every ``metrics.*`` sub-object's speedup), computes the newest round's
deltas against the previous parseable round, and renders a
markdown/JSON verdict with a configurable regression threshold.

Comparability: a degraded round (CPU smoke during a tunnel outage) is
never compared against an on-chip round — such a pair yields
``incomparable`` verdicts and cannot fail the gate. All GATED legs are
greater-is-better (throughputs, MFU, speedups). Memory legs
(``kv_bytes_per_token`` and the per-state ``kv_peak_*`` occupancy from
the KV memory ledger, ISSUE 13) are TRACKED as trajectories but never
gated: lower bytes-per-token is better and peak occupancy is
workload-shaped, so the greater-is-better regression rule does not
apply — they get a ``tracked`` verdict instead.

Deliberately **pure stdlib, zero imports from this package**: bench.py's
orchestrator loads this file via ``importlib.util.spec_from_file_location``
for its ``--ledger-check`` mode, and the orchestrator must never import
jax or the ``paddle_tpu`` root (same constraint as ``flops.py``).

CLI::

    python -m paddle_tpu.observability.perfledger            # markdown
    python -m paddle_tpu.observability.perfledger --json
    python -m paddle_tpu.observability.perfledger --check    # rc 1 on
                                                             # regression
    python bench.py --ledger-check                           # same gate
"""
from __future__ import annotations

import argparse
import glob
import json
import os
from typing import Optional

__all__ = ["DEFAULT_THRESHOLD", "HISTORY_BASENAME", "append_history",
           "flatten_legs", "load_rounds", "build_report",
           "render_markdown", "main"]

DEFAULT_THRESHOLD = 0.05          # a leg must drop >5% to count as regressed
HISTORY_BASENAME = "BENCH_HISTORY.jsonl"

# leg-name markers for memory-ledger trajectories: tracked, never gated
# (not greater-is-better, so the regression rule would misfire)
_TRACKED_MARKERS = (":kv_bytes_per_token", ":kv_peak_")

_NUM = (int, float)


def _gated(leg: str) -> bool:
    """Whether a leg participates in the regression gate."""
    return not any(m in leg for m in _TRACKED_MARKERS)


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, _NUM) and not isinstance(v, bool) \
        else None


def flatten_legs(parsed) -> dict:
    """One bench result line → flat {leg name: value}. Legs: the
    headline ``value``, ``extra.mfu`` (when measured, i.e. > 0), every
    ``extra.configs.<name>.value``, and every ``metrics.<name>``
    sub-object's first of speedup/tokens_per_sec/value."""
    legs: dict = {}
    if not isinstance(parsed, dict):
        return legs
    v = _num(parsed.get("value"))
    if v is not None:
        legs["headline"] = v
    extra = parsed.get("extra")
    if isinstance(extra, dict):
        m = _num(extra.get("mfu"))
        if m is not None and m > 0.0:
            legs["mfu"] = m
        cfgs = extra.get("configs")
        if isinstance(cfgs, dict):
            for name in sorted(cfgs):
                if isinstance(cfgs[name], dict):
                    cv = _num(cfgs[name].get("value"))
                    if cv is not None:
                        legs[f"config:{name}"] = cv
    mets = parsed.get("metrics")
    if isinstance(mets, dict):
        for name in sorted(mets):
            sub = mets[name]
            if not isinstance(sub, dict) or "error" in sub:
                continue
            for key in ("speedup", "tokens_per_sec", "value"):
                sv = _num(sub.get(key))
                if sv is not None:
                    legs[f"metrics:{name}"] = sv
                    break
            # memory-ledger trajectories (ISSUE 13): per-leg HBM bytes
            # per resident token and peak occupancy by state — tracked
            # (never gated; see _TRACKED_MARKERS)
            bt = _num(sub.get("kv_bytes_per_token"))
            if bt is not None and bt > 0.0:
                legs[f"metrics:{name}:kv_bytes_per_token"] = bt
            pk = sub.get("kv_peak_blocks")
            if isinstance(pk, dict):
                for state in sorted(pk):
                    pv = _num(pk[state])
                    if pv is not None:
                        legs[f"metrics:{name}:kv_peak_{state}"] = pv
    return legs


def _round_entry(label: str, doc: dict) -> dict:
    parsed = doc.get("parsed") if isinstance(doc, dict) else None
    ok = isinstance(parsed, dict)
    return {"label": label,
            "rc": doc.get("rc") if isinstance(doc, dict) else None,
            "parsed_ok": ok,
            "degraded": bool(parsed.get("degraded")) if ok else None,
            "legs": flatten_legs(parsed)}


def load_rounds(root: str) -> list:
    """Chronological round entries: every ``BENCH_r*.json`` under
    ``root`` (sorted by filename — the round number is zero-padded),
    then the ``BENCH_HISTORY.jsonl`` lines bench.py appended itself.
    History lines whose parsed result exactly duplicates a file round
    are dropped (the driver snapshots the same run into the next
    ``BENCH_rNN.json``)."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        label = os.path.splitext(os.path.basename(path))[0]
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            rounds.append({"label": label, "rc": None, "parsed_ok": False,
                           "degraded": None, "legs": {},
                           "error": f"{type(e).__name__}: {e}"})
            continue
        rounds.append(_round_entry(label, doc))
    seen = [r["legs"] for r in rounds if r["parsed_ok"]]
    hist = os.path.join(root, HISTORY_BASENAME)
    if os.path.exists(hist):
        try:
            with open(hist) as f:
                lines = f.read().splitlines()
        except OSError:
            lines = []
        n = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            n += 1
            entry = _round_entry(f"run{n:02d}", {"rc": 0, "parsed": doc})
            if entry["parsed_ok"] and entry["legs"] in seen:
                continue
            rounds.append(entry)
    return rounds


def append_history(result: dict, root: str) -> bool:
    """Append one bench result line to the ledger (bench.py calls this
    at the end of every orchestrated run). Never raises — a read-only
    checkout must not break the bench itself."""
    try:
        with open(os.path.join(root, HISTORY_BASENAME), "a") as f:
            f.write(json.dumps(result, sort_keys=True,
                               separators=(",", ":")) + "\n")
        return True
    except OSError:
        return False


def build_report(rounds: list, threshold: float = DEFAULT_THRESHOLD) -> dict:
    """Trajectory + newest-vs-previous deltas + per-leg verdicts.
    Verdicts: ``regressed``/``ok``/``improved`` (beyond ±threshold) when
    the newest two parseable rounds are comparable (same degraded flag),
    ``incomparable`` otherwise, ``new``/``missing`` when only one side
    has the leg, ``tracked`` for memory-ledger legs (trajectory only —
    never gated). ``status`` is ``fail`` iff something regressed."""
    leg_names: list = []
    for r in rounds:
        for leg in r["legs"]:
            if leg not in leg_names:
                leg_names.append(leg)
    trajectory = {leg: [(r["label"], r["legs"].get(leg)) for r in rounds]
                  for leg in leg_names}
    parseable = [r for r in rounds if r["parsed_ok"]]
    newest = parseable[-1] if parseable else None
    prev = parseable[-2] if len(parseable) >= 2 else None
    comparable = (newest is not None and prev is not None
                  and newest["degraded"] == prev["degraded"])
    legs: dict = {}
    if newest is not None:
        union = list(newest["legs"])
        if prev is not None:
            union += [leg for leg in prev["legs"] if leg not in union]
        for leg in union:
            new = newest["legs"].get(leg)
            old = prev["legs"].get(leg) if prev is not None else None
            if new is None:
                verdict, pct = "missing", None
            elif old is None:
                verdict, pct = "new", None
            elif not comparable:
                verdict, pct = "incomparable", None
            else:
                pct = (new - old) / old if old else 0.0
                if not _gated(leg):
                    verdict = "tracked"     # memory leg: trajectory only
                else:
                    verdict = ("regressed" if pct < -threshold else
                               "improved" if pct > threshold else "ok")
            legs[leg] = {"new": new, "old": old, "delta_pct": pct,
                         "verdict": verdict}
    regressed = sorted(k for k, v in legs.items()
                       if v["verdict"] == "regressed")
    return {"rounds": [{k: r.get(k) for k in
                        ("label", "rc", "parsed_ok", "degraded")}
                       for r in rounds],
            "trajectory": trajectory,
            "newest": newest["label"] if newest else None,
            "previous": prev["label"] if prev else None,
            "comparable": comparable,
            "threshold": threshold,
            "legs": legs,
            "regressed": regressed,
            "status": "fail" if regressed else "ok"}


def _fmt(v) -> str:
    if v is None:
        return "—"
    return f"{v:g}"


def render_markdown(report: dict) -> str:
    """The human verdict: a trajectory table (legs × rounds) and the
    newest-vs-previous delta table."""
    labels = [r["label"] for r in report["rounds"]]
    flags = ["✗" if not r["parsed_ok"] else
             "degraded" if r["degraded"] else "on-chip"
             for r in report["rounds"]]
    lines = ["# bench trajectory", "",
             "| leg | " + " | ".join(labels) + " |",
             "|-----|" + "|".join("---" for _ in labels) + "|",
             "| *(round)* | " + " | ".join(flags) + " |"]
    for leg, series in report["trajectory"].items():
        lines.append("| " + leg + " | "
                     + " | ".join(_fmt(v) for _, v in series) + " |")
    lines += ["",
              f"## {report['newest'] or '—'} vs {report['previous'] or '—'}"
              f" (threshold ±{report['threshold']:.0%})", ""]
    if not report["legs"]:
        lines.append("no parseable rounds to compare.")
    else:
        if not report["comparable"]:
            lines.append("rounds are not comparable (degraded vs on-chip) "
                         "— deltas withheld.")
            lines.append("")
        lines += ["| leg | old | new | delta | verdict |",
                  "|-----|-----|-----|-------|---------|"]
        for leg, d in report["legs"].items():
            pct = ("—" if d["delta_pct"] is None
                   else f"{d['delta_pct']:+.1%}")
            lines.append(f"| {leg} | {_fmt(d['old'])} | {_fmt(d['new'])} "
                         f"| {pct} | {d['verdict']} |")
    lines += ["", f"**status: {report['status']}**"
              + (f" — regressed: {', '.join(report['regressed'])}"
                 if report["regressed"] else "")]
    return "\n".join(lines) + "\n"


def _default_root() -> str:
    """The repo root (two package levels up from this file) — where the
    driver's BENCH_r*.json artifacts live."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="perfledger",
        description="parse BENCH_r*.json history into a per-leg "
                    "trajectory and a regression verdict")
    ap.add_argument("--dir", default=_default_root(),
                    help="directory holding BENCH_r*.json "
                         "(default: the repo root)")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="relative drop that counts as a regression "
                         "(default 0.05 = 5%%)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full JSON report instead of markdown")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when the newest round regresses a leg "
                         "past the threshold")
    args = ap.parse_args(argv)
    rounds = load_rounds(args.dir)
    if not rounds:
        print(f"perfledger: no BENCH_r*.json under {args.dir}")
        return 2 if args.check else 0
    report = build_report(rounds, threshold=args.threshold)
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(render_markdown(report), end="")
    if args.check and report["status"] == "fail":
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
