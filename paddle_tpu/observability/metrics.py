"""Process-global metrics registry (ref: ``paddle.profiler`` statistics +
the Prometheus exposition conventions production serving stacks expect).

Three instrument kinds over one registry:

  * :class:`Counter`    — monotonically increasing (``inc``)
  * :class:`Gauge`      — settable point-in-time value (``set``/``inc``/``dec``)
  * :class:`Histogram`  — fixed bucket boundaries, cumulative counts +
                          sum/count (Prometheus semantics)

Labels are declared at creation (``labelnames=("site",)``) and bound per
observation either inline (``c.inc(site="x")``) or pre-bound for hot
paths (``child = c.labels(site="x"); child.inc()``).

Design constraints (ISSUE 2):
  * process-global singleton (:data:`METRICS`) — instruments are created
    at module import by the subsystems that emit them; creation is
    idempotent (same name → same instrument; a conflicting re-register
    raises).
  * ZERO overhead when disabled — every mutating call is gated on one
    ``bool`` attribute read; ``METRICS.disable()`` turns the whole layer
    into no-ops (export still works, frozen at the last enabled state).
  * host-side only — nothing here ever traces into a jitted program.
  * two export formats: one-line JSON (:meth:`MetricsRegistry.to_json`)
    and Prometheus text exposition 0.0.4
    (:meth:`MetricsRegistry.to_prometheus`).
"""
from __future__ import annotations

import json
import threading
from bisect import bisect_left
from typing import Optional, Sequence

__all__ = ["METRICS", "MetricsRegistry", "Counter", "Gauge", "Histogram",
           "DEFAULT_BUCKETS"]

# Prometheus client default buckets — latency-shaped (seconds).
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0)


def _fmt_value(v: float) -> str:
    """Prometheus number formatting: integers without a trailing .0."""
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _label_str(labelnames: tuple, labelvalues: tuple) -> str:
    if not labelnames:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in zip(labelnames, labelvalues))
    return "{" + inner + "}"


class _Instrument:
    """Shared base: name/help/labelnames + the per-labelset series dict."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self._reg = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._series: dict[tuple, object] = {}
        self._lock = threading.Lock()

    def _key(self, labels: dict) -> tuple:
        if tuple(sorted(labels)) != tuple(sorted(self.labelnames)):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} do not match declared "
                f"labelnames {sorted(self.labelnames)}")
        return tuple(str(labels[k]) for k in self.labelnames)

    def labels(self, **labels) -> "_Bound":
        """Pre-bind a label set (hot-path form: no per-call dict)."""
        return _Bound(self, self._key(labels))

    # ---- overridden per kind -------------------------------------------
    def _zero(self):
        raise NotImplementedError

    def _get(self, key: tuple):
        with self._lock:
            if key not in self._series:
                self._series[key] = self._zero()
            return self._series[key]


class _Bound:
    """An instrument bound to one label-value tuple."""

    def __init__(self, inst: _Instrument, key: tuple):
        self._inst = inst
        self._key = key

    def inc(self, n: float = 1.0):
        self._inst._inc_key(self._key, n)

    def dec(self, n: float = 1.0):
        self._inst._inc_key(self._key, -n)

    def set(self, v: float):
        self._inst._set_key(self._key, v)

    def observe(self, v: float):
        self._inst._observe_key(self._key, v)

    def value(self):
        return self._inst._value_key(self._key)


class Counter(_Instrument):
    kind = "counter"

    def _zero(self):
        return [0.0]

    def inc(self, n: float = 1.0, **labels):
        if not self._reg._enabled:
            return
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (n={n})")
        self._inc_key(self._key(labels), n)

    def _inc_key(self, key: tuple, n: float):
        if not self._reg._enabled:
            return
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (n={n})")
        cell = self._get(key)
        with self._lock:
            cell[0] += n

    def value(self, **labels) -> float:
        return self._value_key(self._key(labels))

    def _value_key(self, key: tuple) -> float:
        return self._series.get(key, [0.0])[0]


class Gauge(_Instrument):
    kind = "gauge"

    def _zero(self):
        return [0.0]

    def set(self, v: float, **labels):
        if not self._reg._enabled:
            return
        self._set_key(self._key(labels), v)

    def inc(self, n: float = 1.0, **labels):
        if not self._reg._enabled:
            return
        self._inc_key(self._key(labels), n)

    def dec(self, n: float = 1.0, **labels):
        self.inc(-n, **labels)

    def _set_key(self, key: tuple, v: float):
        if not self._reg._enabled:
            return
        cell = self._get(key)
        with self._lock:
            cell[0] = float(v)

    def _inc_key(self, key: tuple, n: float):
        if not self._reg._enabled:
            return
        cell = self._get(key)
        with self._lock:
            cell[0] += n

    def value(self, **labels) -> float:
        return self._value_key(self._key(labels))

    def _value_key(self, key: tuple) -> float:
        return self._series.get(key, [0.0])[0]


class _HistSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)   # +1 = the +Inf bucket
        self.sum = 0.0
        self.count = 0


class Histogram(_Instrument):
    """Fixed-boundary histogram. ``buckets`` are UPPER bounds (le),
    strictly increasing; an implicit +Inf bucket is appended. Exported
    counts are cumulative, matching Prometheus exposition."""

    kind = "histogram"

    def __init__(self, registry, name, help="", labelnames=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(registry, name, help, labelnames)
        b = tuple(float(x) for x in buckets)
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError(f"{name}: buckets must be non-empty and "
                             f"strictly increasing, got {b}")
        self.buckets = b

    def _zero(self):
        return _HistSeries(len(self.buckets))

    def observe(self, v: float, **labels):
        if not self._reg._enabled:
            return
        self._observe_key(self._key(labels), v)

    def _observe_key(self, key: tuple, v: float):
        if not self._reg._enabled:
            return
        s = self._get(key)
        v = float(v)
        with self._lock:
            s.counts[bisect_left(self.buckets, v)] += 1
            s.sum += v
            s.count += 1

    def value(self, **labels) -> dict:
        """{"buckets": {le: cumulative}, "sum", "count"} for one series."""
        return self._value_key(self._key(labels))

    def quantile(self, q: float, **labels) -> float:
        """Estimated q-quantile (Prometheus ``histogram_quantile``
        semantics): linear interpolation inside the bucket containing
        rank ``q*count``, assuming observations spread uniformly within
        it. Rank landing in the +Inf bucket returns the highest finite
        bound; an empty (or never-observed) series returns NaN."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"{self.name}: quantile q must be in [0, 1], "
                             f"got {q}")
        s = self._series.get(self._key(labels))
        if s is None:
            return float("nan")
        with self._lock:
            counts, count = list(s.counts), s.count
        if count == 0:
            return float("nan")
        rank = q * count
        cum = 0.0
        for i, bound in enumerate(self.buckets):
            prev_cum = cum
            cum += counts[i]
            if cum >= rank and counts[i] > 0:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                return lo + (bound - lo) * ((rank - prev_cum) / counts[i])
        return self.buckets[-1]

    def _value_key(self, key: tuple):
        return self._snapshot_series(self._series.get(
            key, _HistSeries(len(self.buckets))))

    def _snapshot_series(self, s: _HistSeries) -> dict:
        cum, out = 0, {}
        for bound, c in zip(self.buckets, s.counts):
            cum += c
            out[_fmt_value(bound)] = cum
        out["+Inf"] = cum + s.counts[-1]
        return {"buckets": out, "sum": s.sum, "count": s.count}


class MetricsRegistry:
    """Name → instrument table. ``counter``/``gauge``/``histogram`` are
    get-or-create: the same name always returns the same instrument, and
    a re-register with a different kind/labelnames/buckets raises (two
    subsystems silently sharing one series would corrupt both)."""

    def __init__(self):
        self._instruments: dict[str, _Instrument] = {}
        self._enabled = True
        self._lock = threading.Lock()

    # ------------------------------------------------------------ admin
    def enable(self):
        self._enabled = True

    def disable(self):
        """Turn every instrument into a no-op (one bool read per call)."""
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    def reset(self):
        """Zero every series (instruments survive — module-level handles
        stay valid). Test hygiene, not a production operation."""
        with self._lock:
            for inst in self._instruments.values():
                inst._series.clear()

    def get(self, name: str) -> Optional[_Instrument]:
        return self._instruments.get(name)

    # --------------------------------------------------------- creation
    def _make(self, cls, name, help, labelnames, **kw):
        with self._lock:
            have = self._instruments.get(name)
            if have is not None:
                same = (type(have) is cls
                        and have.labelnames == tuple(labelnames)
                        and kw.get("buckets") in (
                            None, getattr(have, "buckets", None)))
                if not same:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{have.kind}{have.labelnames} — conflicting "
                        f"re-registration")
                return have
            inst = cls(self, name, help, labelnames, **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._make(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._make(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._make(Histogram, name, help, labelnames, buckets=buckets)

    # ----------------------------------------------------------- export
    def snapshot(self) -> dict:
        """{"counters": {series: value}, "gauges": {...},
        "histograms": {series: {"buckets": {le: cum}, "sum", "count"}}}.
        Series keys carry their labels Prometheus-style:
        ``name{site="serving.alloc"}``."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name in sorted(self._instruments):
            inst = self._instruments[name]
            dst = out[inst.kind + "s"]
            for key in sorted(inst._series):
                series = name + _label_str(inst.labelnames, key)
                dst[series] = inst._value_key(key)
        return out

    def to_json(self) -> str:
        """The whole registry as ONE line of JSON (log-shipping-friendly:
        one snapshot per scrape per line)."""
        return json.dumps(self.snapshot(), sort_keys=True,
                          separators=(",", ":"))

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines = []
        for name in sorted(self._instruments):
            inst = self._instruments[name]
            if not inst._series:
                continue
            if inst.help:
                lines.append(f"# HELP {name} {inst.help}")
            lines.append(f"# TYPE {name} {inst.kind}")
            for key in sorted(inst._series):
                if isinstance(inst, Histogram):
                    snap = inst._value_key(key)
                    for le, cum in snap["buckets"].items():
                        ls = _label_str(inst.labelnames + ("le",),
                                        key + (le,))
                        lines.append(f"{name}_bucket{ls} {cum}")
                    ls = _label_str(inst.labelnames, key)
                    lines.append(f"{name}_sum{ls} {_fmt_value(snap['sum'])}")
                    lines.append(f"{name}_count{ls} {snap['count']}")
                else:
                    ls = _label_str(inst.labelnames, key)
                    lines.append(
                        f"{name}{ls} {_fmt_value(inst._value_key(key))}")
        return "\n".join(lines) + ("\n" if lines else "")


METRICS = MetricsRegistry()
