"""Generated metrics reference: ``python -m paddle_tpu.observability``
prints every registered instrument (name, kind, labels, help) as a
markdown table (ISSUE 9 doc satellite).

Instruments register at module import, so the reference is built by
importing every instrument-bearing module and then walking the global
registry — the listing can never drift from the code the way a
hand-maintained table would. Importing the training stack pulls in jax;
that is fine here (an offline doc command), and any module that fails
to import is reported rather than silently skipped.
"""
from __future__ import annotations

import importlib

from paddle_tpu.observability.metrics import METRICS

# every module that registers instruments at import time (a test_lint
# rule asserts every METRICS.counter/gauge/histogram caller is listed)
_INSTRUMENT_MODULES = (
    "paddle_tpu.observability.flops",
    "paddle_tpu.observability.roofline",
    "paddle_tpu.observability.compile",
    "paddle_tpu.observability.goodput",
    "paddle_tpu.observability.memledger",
    "paddle_tpu.observability.slo",
    "paddle_tpu.serving.telemetry",
    "paddle_tpu.serving.quant",
    "paddle_tpu.serving.cp",
    "paddle_tpu.ops.pallas.paged_attention",
    "paddle_tpu.train.trainer",
    "paddle_tpu.train.checkpoint",
    "paddle_tpu.train.elastic",
    "paddle_tpu.distributed.collective",
    "paddle_tpu.io.prefetch",
    "paddle_tpu.utils.faults",
    "paddle_tpu.utils.profiler",
)


def metrics_reference() -> str:
    """Import all instrument-bearing modules, then render the registry
    as a markdown table sorted by instrument name."""
    failures = []
    for mod in _INSTRUMENT_MODULES:
        try:
            importlib.import_module(mod)
        except Exception as e:
            failures.append(f"{mod}: {type(e).__name__}: {e}")
    rows = []
    for name in sorted(METRICS._instruments):
        inst = METRICS._instruments[name]
        labels = ", ".join(inst.labelnames) if inst.labelnames else "—"
        rows.append(f"| `{name}` | {inst.kind} | {labels} | {inst.help} |")
    lines = ["# paddle_tpu metrics reference", "",
             f"{len(rows)} instruments registered by "
             f"{len(_INSTRUMENT_MODULES)} modules.", "",
             "| name | kind | labels | help |",
             "|------|------|--------|------|", *rows]
    if failures:
        lines += ["", "## import failures", ""]
        lines += [f"- {f}" for f in failures]
    return "\n".join(lines) + "\n"


if __name__ == "__main__":
    print(metrics_reference(), end="")
