"""Compile introspection (ISSUE 4): make every XLA compile visible.

``jax.jit`` hides its trace/lower/compile pipeline behind the first
call; a production stack needs to see a compile happen — they cost
seconds to minutes on real models, and an unexpected RE-compile (a
shape bucket miss, a donation change) silently halves throughput.

:class:`InstrumentedJit` wraps one function with an EXPLICIT AOT cache
keyed on the abstract signature (pytree structure + shape/dtype of
every array leaf, value of every static leaf). A miss runs the
``trace → lower → compile`` pipeline under trace spans (``jit.trace``,
``jit.lower``, ``jit.compile``), lands the wall time in the
``compile_seconds`` histogram, counts a ``compile_cache_misses_total``,
pulls the XLA ``cost_analysis`` FLOPs estimate into the
``compile_flops_estimate`` gauge (the Trainer feeds it into
``flops.record_throughput`` when no analytic FLOPs model was given),
and drops a ``compile`` event on the flight recorder. A hit is one
dict lookup and a ``compile_cache_hits_total`` increment — no new
compile span.

Robustness: jax's own dispatch cache stays the backstop. If the AOT
path fails for a function (an exotic backend, a remote-compile quirk),
the wrapper permanently falls back to the plain jitted callable — same
program, same numerics, just without the introspection.

``PT_COMPILE_INTROSPECTION=0`` turns the whole layer off at creation
time (:func:`instrumented_jit` then returns a bare ``jax.jit``).
"""
from __future__ import annotations

import functools
import os
import time
from typing import Callable, Optional

from paddle_tpu.observability.flight import FLIGHT
from paddle_tpu.observability.metrics import METRICS
from paddle_tpu.observability.tracing import span as _span

__all__ = ["InstrumentedJit", "instrumented_jit", "introspection_enabled",
           "cost_analysis_flops"]

# compiles are seconds-to-minutes shaped, not request-latency shaped
_COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0)

_HITS = METRICS.counter(
    "compile_cache_hits_total",
    "jitted calls served from an already-compiled executable",
    labelnames=("fn",))
_MISSES = METRICS.counter(
    "compile_cache_misses_total",
    "jitted calls that had to trace/lower/compile first",
    labelnames=("fn",))
_COMPILE_S = METRICS.histogram(
    "compile_seconds", "wall time of one trace+lower+compile",
    labelnames=("fn",), buckets=_COMPILE_BUCKETS)
_COMPILE_FLOPS = METRICS.gauge(
    "compile_flops_estimate",
    "XLA cost_analysis FLOPs per call of the newest compiled program",
    labelnames=("fn",))


def introspection_enabled() -> bool:
    return os.environ.get("PT_COMPILE_INTROSPECTION", "1").lower() \
        not in ("0", "false", "off")


def cost_analysis_flops(compiled) -> float:
    """FLOPs-per-call estimate from an AOT-compiled executable; 0.0 when
    the backend does not report one. Normalises the jax version drift
    (list-of-dicts vs one dict)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return 0.0
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    try:
        return float(ca.get("flops", 0.0) or 0.0)
    except Exception:
        return 0.0


def _leaf_sig(leaf):
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    try:
        hash(leaf)
        return ("py", leaf)
    except TypeError:
        return ("py", repr(leaf))


class InstrumentedJit:
    """One jitted function + an explicit signature→executable cache."""

    def __init__(self, fn: Callable, name: Optional[str] = None, **jit_kwargs):
        import jax
        self._jax = jax
        self._jit = jax.jit(fn, **jit_kwargs)
        self.name = name or getattr(fn, "__name__", None) or "jit"
        self._compiled: dict = {}
        self._broken = False      # AOT path failed once → plain jit forever
        self.flops_per_call: float = 0.0   # newest compile's estimate
        self._hits = _HITS.labels(fn=self.name)
        self._misses = _MISSES.labels(fn=self.name)
        functools.update_wrapper(self, fn)

    # ------------------------------------------------------------- introspection
    @property
    def cache_size(self) -> int:
        return len(self._compiled)

    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    # ------------------------------------------------------------------ call
    def _sig(self, args, kwargs):
        leaves, treedef = self._jax.tree_util.tree_flatten((args, kwargs))
        return (treedef, tuple(_leaf_sig(l) for l in leaves))

    def _compile(self, args, kwargs):
        t0 = time.monotonic()
        if hasattr(self._jit, "trace"):      # jax >= 0.4.3x: 3-stage AOT
            with _span("jit.trace", fn=self.name):
                traced = self._jit.trace(*args, **kwargs)
            with _span("jit.lower", fn=self.name):
                lowered = traced.lower()
        else:
            with _span("jit.lower", fn=self.name):
                lowered = self._jit.lower(*args, **kwargs)
        with _span("jit.compile", fn=self.name):
            compiled = lowered.compile()
        dt = time.monotonic() - t0
        _COMPILE_S.observe(dt, fn=self.name)
        flops = cost_analysis_flops(compiled)
        if flops:
            self.flops_per_call = flops
            _COMPILE_FLOPS.set(flops, fn=self.name)
        FLIGHT.record("compile", fn=self.name, seconds=round(dt, 6),
                      flops=flops, cached=len(self._compiled) + 1)
        return compiled

    def __call__(self, *args, **kwargs):
        if self._broken:
            return self._jit(*args, **kwargs)
        try:
            key = self._sig(args, kwargs)
        except Exception:
            self._broken = True
            return self._jit(*args, **kwargs)
        entry = self._compiled.get(key)
        if entry is not None:
            self._hits.inc()
            try:
                return entry(*args, **kwargs)
            except (TypeError, ValueError):
                # aval/sharding drift the shape/dtype signature could not
                # see — jax validates inputs BEFORE execution, so nothing
                # ran; let jax's own cache handle this call
                return self._jit(*args, **kwargs)
        self._misses.inc()
        try:
            compiled = self._compile(args, kwargs)
        except Exception:
            self._broken = True
            return self._jit(*args, **kwargs)
        self._compiled[key] = compiled
        return compiled(*args, **kwargs)


def instrumented_jit(fn: Callable = None, *, name: Optional[str] = None,
                     **jit_kwargs):
    """``jax.jit`` with compile introspection. Usable as a decorator
    (with or without arguments) or a direct call; honours the
    ``PT_COMPILE_INTROSPECTION`` kill switch."""
    if fn is None:
        return functools.partial(instrumented_jit, name=name, **jit_kwargs)
    if not introspection_enabled():
        import jax
        return jax.jit(fn, **jit_kwargs)
    return InstrumentedJit(fn, name=name, **jit_kwargs)
