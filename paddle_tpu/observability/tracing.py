"""Trace spans → Chrome-trace/Perfetto timeline (ref: ``paddle.profiler``
RecordEvent + chrome-trace export; host-side complement to XLA's own
``jax.profiler`` device timeline).

:func:`span` is a context manager AND a decorator::

    with span("engine.step", tick=3):
        ...

    @span("ckpt.save")
    def save(...): ...

Spans clock with ``time.monotonic_ns`` (never wall clock — a stepped
NTP correction inside a span would report negative durations), record
their thread id, and nest naturally: Chrome's "X" (complete) events
reconstruct the hierarchy from ts/dur containment per thread.

The global :data:`TRACER` starts DISABLED. Enablement is checked when a
span is ENTERED (not when it is created), so a ``@span(...)`` decorator
applied at import time starts tracing the moment the tracer is turned
on; a span entered while tracing is off is one bool read and no buffer
write. :func:`instant` emits zero-duration "i" events — fault
injections use it so a chaos run's timeline shows exactly where each
fault landed.

Cross-thread/cross-replica stitching (ISSUE 9): :meth:`Tracer.flow`
emits Chrome-trace flow events — ``ph`` "s" (start) / "t" (step) /
"f" (end) sharing an ``id`` draw as one connected arrow across
threads, which is how one request's hops over prefill and decode
replicas become a single timeline in Perfetto. :meth:`Tracer.track_tid`
assigns a stable synthetic tid to a named logical track (e.g. a
replica name) and labels it with a "M" ``thread_name`` metadata event
prepended at export, so events can be pinned to a lane that is not a
real OS thread.
"""
from __future__ import annotations

import functools
import json
import os
import threading
import time

__all__ = ["TRACER", "Tracer", "span", "instant", "export_chrome_trace"]


class _Span:
    """One span site. Create fresh per use (``with span(...):``); the
    decorator form re-opens a fresh span per call, so one decoration is
    safe under recursion and concurrent threads."""

    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._t0 = None

    def __enter__(self):
        self._t0 = time.monotonic_ns() if self._tracer._enabled else None
        return self

    def __exit__(self, *exc):
        if self._t0 is None:             # tracing was off at entry
            return False
        t1 = time.monotonic_ns()
        self._tracer._emit({
            "name": self.name, "ph": "X", "cat": "host",
            "ts": self._t0 / 1e3, "dur": (t1 - self._t0) / 1e3,
            "pid": self._tracer._pid, "tid": threading.get_ident(),
            **({"args": self.args} if self.args else {}),
        })
        return False

    def __call__(self, fn):
        tracer, name, args = self._tracer, self.name, self.args

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with _Span(tracer, name, args):
                return fn(*a, **kw)
        return wrapped


class Tracer:
    """Event buffer + export. ``max_events`` bounds memory: the buffer
    drops NEW events past the cap (and counts the drops) instead of
    growing without bound during a long traced run."""

    _TRACK_TID_BASE = 1 << 22       # clear of real OS thread ids' low range

    def __init__(self, max_events: int = 200_000):
        self.max_events = max_events
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._enabled = False
        self._pid = os.getpid()
        self._tracks: dict = {}      # label -> synthetic tid (survives clear)
        self.dropped = 0

    # ------------------------------------------------------------ admin
    def enable(self):
        self._enabled = True

    def disable(self):
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    def clear(self):
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def __enter__(self):                 # `with TRACER:` traces a block
        self.enable()
        return self

    def __exit__(self, *exc):
        self.disable()
        return False

    # ----------------------------------------------------------- record
    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args)

    def instant(self, name: str, **args):
        """Zero-duration marker ("i" event) — fault injections, restarts."""
        if not self._enabled:
            return
        self._emit({
            "name": name, "ph": "i", "cat": "host", "s": "t",
            "ts": time.monotonic_ns() / 1e3,
            "pid": self._pid, "tid": threading.get_ident(),
            **({"args": args} if args else {}),
        })

    def counter(self, name: str, **values):
        """Chrome-trace counter event ("C"): Perfetto renders the
        series in ``values`` as one stacked counter track, so e.g. KV
        pool occupancy-by-state draws as an area chart over time next
        to the span timeline."""
        if not self._enabled:
            return
        self._emit({
            "name": name, "ph": "C", "cat": "host",
            "ts": time.monotonic_ns() / 1e3,
            "pid": self._pid, "tid": threading.get_ident(),
            "args": {k: float(v) for k, v in values.items()},
        })

    def track_tid(self, label: str) -> int:
        """Stable synthetic tid for a named logical track. Registration
        survives :meth:`clear` — the label registry is metadata, not
        events — and export prepends a ``thread_name`` "M" event per
        track so Perfetto shows the label instead of a bare number."""
        with self._lock:
            tid = self._tracks.get(label)
            if tid is None:
                tid = self._TRACK_TID_BASE + len(self._tracks)
                self._tracks[label] = tid
            return tid

    def flow(self, name: str, flow_id: int, phase: str,
             track: str = None, **args):
        """One flow event. ``phase`` is "s" (start), "t" (step) or "f"
        (end); events sharing ``flow_id`` stitch into one arrow across
        threads. ``track`` pins the event onto a named synthetic track
        (see :meth:`track_tid`) instead of the calling thread's lane."""
        if not self._enabled:
            return
        if phase not in ("s", "t", "f"):
            raise ValueError(f"flow phase must be s/t/f, got {phase!r}")
        tid = self.track_tid(track) if track else threading.get_ident()
        ev = {
            "name": name, "ph": phase, "cat": "flow", "id": int(flow_id),
            "ts": time.monotonic_ns() / 1e3, "pid": self._pid, "tid": tid,
            **({"args": args} if args else {}),
        }
        if phase == "f":
            ev["bp"] = "e"           # bind to enclosing slice
        self._emit(ev)

    def _emit(self, ev: dict):
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    # ----------------------------------------------------------- export
    def export(self) -> dict:
        """Chrome-trace JSON object (load at chrome://tracing or
        ui.perfetto.dev)."""
        with self._lock:
            meta = [{"name": "thread_name", "ph": "M", "pid": self._pid,
                     "tid": tid, "args": {"name": label}}
                    for label, tid in self._tracks.items()]
            events = meta + list(self._events)
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"producer": "paddle_tpu.observability",
                              "dropped_events": self.dropped}}

    def export_chrome_trace(self, path: str = None) -> str:
        """Serialise the timeline; write to ``path`` when given. Returns
        the JSON string either way."""
        s = json.dumps(self.export(), separators=(",", ":"))
        if path is not None:
            with open(path, "w") as f:
                f.write(s)
        return s


TRACER = Tracer()


def span(name: str, **args) -> _Span:
    """Module-level sugar over the global tracer."""
    return TRACER.span(name, **args)


def instant(name: str, **args):
    return TRACER.instant(name, **args)


def export_chrome_trace(path: str = None) -> str:
    return TRACER.export_chrome_trace(path)
