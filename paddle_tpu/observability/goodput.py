"""Goodput ledger (ISSUE 9): attribute every device token to useful vs
wasted work, MegaScale-style.

``serving_tokens_total`` counts what came out; it says nothing about
what the device burned to get there. The ledger splits device token
work into

  * **goodput** — sampled/committed tokens the caller keeps; the
    ``serving_goodput_tokens_total`` counter increments at exactly the
    same sites as ``serving_tokens_total``, so the two reconcile
    tick-for-tick by construction, and
  * **waste** — ``serving_waste_total{why}`` token-positions computed
    and thrown away:

      ``spec_rejected``     draft tokens the target model refused
      ``replay_prefill``    re-prefilled positions after a preemption
                            replay (minus prefix-cache hits)
      ``pad_rows``          whole padding rows in chunked-prefill and
                            spec-verify batches (row slots launched
                            with no live sequence)
      ``moe_capacity_drop`` MoE routing assignments dropped at expert
                            capacity
      ``chaos_abort``       drafted-but-never-verified tokens when a
                            fault aborts a spec tick
      ``async_overrun``     async-pipeline ticks that ran on device for
                            a slot the host had already torn down by
                            the time the window drained

A third, token-level column closes the books: **saved** —
``serving_goodput_saved_tokens_total`` — prefill token-positions the
device never had to compute because admission adopted them from the
prefix cache (full-block shares plus the radix trie's partial
copy-on-write hits). Saved tokens are neither good nor waste: they are
work that did not happen, the direct counterpart of the
``replay_prefill`` waste column.

The lifetime ratio good/(good+waste) is exported as the
``serving_goodput_ratio`` gauge (refreshed by the engine's gauge sweep
and on demand via :meth:`GoodputLedger.refresh_gauge`), and a stock
low-goodput health rule in :mod:`paddle_tpu.observability.health`
flags a fleet whose waste fraction says the devices mostly heat air.

All state lives in the metrics registry — the ledger owns no counters
of its own, so the conftest registry reset is the only hygiene needed.

Usage metering (ISSUE 19): the ledger is also the tenant-attribution
choke point. An attached sink (the SLO tracker's cost ledger) receives
every ``good``/``waste``/``saved`` charge together with the ``tenant=``
the call site knows (``None`` for batch-level overheads like padding
rows) — because attribution happens INSIDE the same call that moves the
counters, per-tenant sums reconcile with the untenanted totals exactly,
by construction, not by auditing call sites.
"""
from __future__ import annotations

from paddle_tpu.observability.metrics import METRICS

__all__ = ["GOODPUT", "GoodputLedger", "WASTE_WHYS"]

WASTE_WHYS = ("spec_rejected", "replay_prefill", "pad_rows",
              "moe_capacity_drop", "chaos_abort", "async_overrun")

_GOOD = METRICS.counter(
    "serving_goodput_tokens_total",
    "device tokens that produced output the caller keeps (same increment "
    "sites as serving_tokens_total, so the two reconcile)")
_WASTE = METRICS.counter(
    "serving_waste_total",
    "device token-positions computed then thrown away, by cause "
    "(spec_rejected, replay_prefill, pad_rows, moe_capacity_drop, "
    "chaos_abort, async_overrun)",
    labelnames=("why",))
_RATIO = METRICS.gauge(
    "serving_goodput_ratio",
    "lifetime goodput/(goodput+waste) token ratio")
_SAVED = METRICS.counter(
    "serving_goodput_saved_tokens_total",
    "prefill token-positions skipped outright at admission — adopted "
    "from the prefix cache instead of recomputed")


def _series_total(inst) -> float:
    return float(sum(cell[0] for cell in inst._series.values()))


class GoodputLedger:
    """Thin façade over the three instruments. Methods never allocate
    beyond the counter increment; ``waste(n<=0)`` is a no-op so call
    sites can pass raw deltas without guarding. ``tenant=`` is optional
    attribution metadata forwarded to the attached metering sink (if
    any) — it never affects the untenanted counters."""

    def __init__(self):
        self._sink = None

    def attach_sink(self, sink):
        """Install (or clear, with ``None``) the tenant-attribution
        sink — an object with ``good(tenant, n)`` / ``waste(tenant,
        why, n)`` / ``saved(tenant, n)``. One sink per process; the SLO
        tracker's cost ledger attaches itself at construction."""
        self._sink = sink

    def good(self, n: int = 1, tenant=None):
        _GOOD.inc(n)
        if self._sink is not None:
            self._sink.good(tenant, n)

    def waste(self, why: str, n: int, tenant=None):
        if n > 0:
            _WASTE.inc(n, why=why)
            if self._sink is not None:
                self._sink.waste(tenant, why, n)

    def saved(self, n: int, tenant=None):
        """Token-positions admission adopted from the prefix cache —
        device work avoided entirely (no-op for n <= 0)."""
        if n > 0:
            _SAVED.inc(n)
            if self._sink is not None:
                self._sink.saved(tenant, n)

    def saved_total(self) -> float:
        return _series_total(_SAVED)

    def good_total(self) -> float:
        return _series_total(_GOOD)

    def waste_total(self) -> float:
        return _series_total(_WASTE)

    def waste_by_why(self) -> dict:
        return {key[0] if key else "": float(cell[0])
                for key, cell in _WASTE._series.items()}

    def ratio(self) -> float:
        """good/(good+waste); NaN while no tokens have been accounted
        (no traffic is not 0% goodput)."""
        g, w = self.good_total(), self.waste_total()
        return g / (g + w) if (g + w) else float("nan")

    def refresh_gauge(self):
        """Push the current ratio into ``serving_goodput_ratio`` (skipped
        while there is no data, so the gauge stays absent not zero)."""
        g, w = self.good_total(), self.waste_total()
        if g + w:
            _RATIO.set(g / (g + w))


GOODPUT = GoodputLedger()
