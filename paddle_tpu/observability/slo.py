"""Per-tenant SLO tracking + usage-metering cost ledger (ISSUE 19).

The observability stack up to here answers "is the fleet healthy now";
this module answers the two production questions it couldn't: **are we
meeting our objectives per tenant** (and how fast is the error budget
burning), and **which tenant consumed the chip** (a ledger a billing or
capacity-planning system can read).

:class:`SLOTracker` holds declarative objectives per tenant — the
fleet-wide key ``"*"`` is both the fleet's own scorecard (computed from
the untenanted instruments) and the default objective set applied to
every tenant without an explicit entry:

    ``ttft_p95``        p95 submission → first token  ≤ target seconds
    ``queue_wait_p95``  p95 submission → admission    ≤ target seconds
    ``inter_token_p95`` p95 inter-token gap           ≤ target seconds
    ``availability``    1 − (timeouts + rejections + replica deaths)
                        / finished  ≥ target fraction

Latency objectives read histogram bucket-count deltas (per tenant from
``serving_tenant_*_seconds``, fleet-wide from the untenanted
histograms): an observation landing in a bucket whose bound exceeds the
target counts against the budget — exact when the target sits on a
bucket bound, conservatively early otherwise. Availability reads
``serving[_tenant]_finished_total{reason}`` + rejections.

Alerting is SRE-style **multi-window, multi-burn-rate**: the error rate
over the window, divided by the objective's budget, is the burn rate
(burn 1.0 = spending the budget exactly at the sustainable pace). A
breach requires BOTH gates — fast burn (default 14.4×) over the short
window AND slow burn (default 6×) over the long/compliance window — so
one bad poll can't page and a slow leak can't hide. Breaches increment
``serving_slo_breaches_total``, drop a ``serving.slo_breach`` flight
event naming tenant + objective, and the per-poll gauges
``serving_slo_burn_rate{tenant,objective}`` (short-window burn) /
``serving_slo_budget_remaining`` feed the stock health rules and the
optional degradation-ladder signal.

The **cost ledger** attributes device resources to tenants *by
construction*, not by auditing call sites:

  * device-seconds — the engine's ``step()`` charges each tick's
    ``serving_tick_seconds`` observation to the tenants holding device
    state that tick (active slots, chunked prefills, beam groups), one
    equal row share each, remainder-balanced so the shares sum to the
    tick total exactly; an idle tick bills ``__idle__``.
  * block-seconds — each tick integrates every request's live KV block
    count (the MemLedger's per-request live table) × tick seconds.
  * goodput/waste/saved tokens — the :data:`GOODPUT` ledger forwards
    every charge to the tracker's sink together with the tenant the
    call site knows, so per-tenant sums reconcile with the untenanted
    goodput counters exactly, tick-for-tick. Batch-level overheads
    (padding rows, chaos aborts, MoE drops) bill ``__system__``.

``PT_SLO=0`` is the kill switch, read per call: polling, tick charges,
and the goodput sink all become a few dict reads, and a disabled run is
bit-identical to a build without the tracker. The tracker is polled
from the engine/Router gauge sweep with the same owner-claim protocol
as the degradation ladder (a Router claims it so N replicas don't
multiply the poll cadence). ``GET /slo`` and ``GET /tenants`` on the
metrics HTTP server serve :func:`slo_doc` / :func:`tenants_doc`.
"""
from __future__ import annotations

import itertools
import os
import time
import weakref
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from paddle_tpu.observability.flight import FLIGHT
from paddle_tpu.observability.goodput import GOODPUT
from paddle_tpu.observability.metrics import METRICS
from paddle_tpu.observability.windows import WindowedReads

__all__ = ["SLOTracker", "Objective", "CostLedger", "default_objectives",
           "slo_doc", "tenants_doc", "SYSTEM_TENANT", "IDLE_TENANT"]

# reserved ledger rows: batch-level work no tenant owns, and ticks with
# no resident work at all — real tenants' rows still sum with these to
# the untenanted totals, so reconciliation never needs special cases
SYSTEM_TENANT = "__system__"
IDLE_TENANT = "__idle__"

# finish reasons that count against availability (rejections are
# tracked by their own counters; cancellations are caller-initiated)
_BAD_FINISH_REASONS = ("timeout", "replica_death")

# objective name -> (fleet-wide instrument, per-tenant instrument)
_LATENCY_SOURCES = {
    "ttft_p95": ("serving_ttft_seconds",
                 "serving_tenant_ttft_seconds"),
    "queue_wait_p95": ("serving_queue_wait_seconds",
                       "serving_tenant_queue_wait_seconds"),
    "inter_token_p95": ("serving_token_latency_seconds",
                        "serving_tenant_token_latency_seconds"),
}

_BURN = METRICS.gauge(
    "serving_slo_burn_rate",
    "short-window error-budget burn rate per tenant and objective "
    "(1.0 = spending the budget exactly at the sustainable pace; the "
    "breach gate also requires the slow burn over the long window)",
    labelnames=("tenant", "objective"))
_BUDGET_LEFT = METRICS.gauge(
    "serving_slo_budget_remaining",
    "fraction of the error budget left over the compliance window, per "
    "tenant and objective (1.0 = untouched, 0.0 = exhausted)",
    labelnames=("tenant", "objective"))
_BREACHES = METRICS.counter(
    "serving_slo_breaches_total",
    "multi-window burn-rate alerts fired (fast AND slow gates both "
    "over threshold), by tenant and objective",
    labelnames=("tenant", "objective"))
_DEV_SECONDS = METRICS.counter(
    "serving_tenant_device_seconds_total",
    "engine tick wall-seconds attributed to each tenant (equal row "
    "share of every tick the tenant held device state; __idle__ for "
    "empty ticks) — sums over tenants to serving_tick_seconds' total",
    labelnames=("tenant",))
_BLOCK_SECONDS = METRICS.counter(
    "serving_tenant_kv_block_seconds_total",
    "KV-pool occupancy integrated over time per tenant (live blocks x "
    "tick seconds, from the memory ledger's per-request live counts)",
    labelnames=("tenant",))

_TRACKERS: "weakref.WeakSet" = weakref.WeakSet()
_SEQ = itertools.count()


def slo_enabled() -> bool:
    """``PT_SLO=0`` kill switch, read per call so a mid-flight flip
    stops all tracking on the very next poll/charge."""
    return os.environ.get("PT_SLO", "1") != "0"


def _guard(tenant) -> str:
    """Map a raw tenant id onto its (cardinality-guarded) ledger row."""
    if tenant is None:
        return SYSTEM_TENANT
    from paddle_tpu.serving.telemetry import tenant_label
    return tenant_label(tenant)


# ------------------------------------------------------------- objectives
@dataclass
class Objective:
    """One declarative objective. ``target`` is a latency threshold in
    seconds for the p95 objectives, or the availability fraction for
    ``availability``. ``budget`` is the allowed bad fraction of events
    — default 0.05 for the p95 objectives (5% of observations may
    exceed the threshold) and ``1 - target`` for availability. The
    long/compliance window is ``window_s``; the fast gate reads
    ``short_s``."""
    name: str
    target: float
    window_s: float = 3600.0
    short_s: float = 300.0
    budget: Optional[float] = None
    fast_burn: float = 14.4
    slow_burn: float = 6.0

    def __post_init__(self):
        if self.name not in _LATENCY_SOURCES and self.name != "availability":
            raise ValueError(
                f"unknown objective {self.name!r} — expected one of "
                f"{sorted(_LATENCY_SOURCES)} or 'availability'")
        if self.name == "availability" and not 0.0 < self.target < 1.0:
            raise ValueError("availability target must be in (0, 1), "
                             f"got {self.target}")
        if self.name != "availability" and self.target <= 0:
            raise ValueError(f"latency target must be > 0, got {self.target}")
        if not 0 < self.short_s <= self.window_s:
            raise ValueError("need 0 < short_s <= window_s, got "
                             f"{self.short_s} / {self.window_s}")
        if self.budget is None:
            self.budget = ((1.0 - self.target)
                           if self.name == "availability" else 0.05)
        if not 0.0 < self.budget <= 1.0:
            raise ValueError(f"budget must be in (0, 1], got {self.budget}")

    def describe(self) -> dict:
        return {"name": self.name, "target": self.target,
                "window_s": self.window_s, "short_s": self.short_s,
                "budget": self.budget, "fast_burn": self.fast_burn,
                "slow_burn": self.slow_burn}


def default_objectives() -> List[Objective]:
    """The stock objective set — lab-scale latency targets and
    three-nines availability over a one-hour compliance window."""
    return [Objective("ttft_p95", target=1.0),
            Objective("queue_wait_p95", target=1.0),
            Objective("inter_token_p95", target=0.25),
            Objective("availability", target=0.999)]


# ------------------------------------------------------------ cost ledger
class CostLedger:
    """Host-side usage-metering dicts, keyed by (cardinality-guarded)
    tenant. The token columns are fed by the GOODPUT sink; the
    time-integral columns by :meth:`SLOTracker.charge_tick`. All
    methods are a few dict ops; with ``PT_SLO=0`` they return after one
    env read."""

    def __init__(self):
        self.device_seconds: Dict[str, float] = {}
        self.block_seconds: Dict[str, float] = {}
        self.good_tokens: Dict[str, int] = {}
        self.waste_tokens: Dict[str, Dict[str, int]] = {}
        self.saved_tokens: Dict[str, int] = {}
        # untenanted mirrors, accumulated term-by-term alongside the
        # per-tenant cells so the reconciliation invariant (sum of rows
        # == total) is arithmetic, not bookkeeping
        self.device_seconds_total = 0.0
        self.block_seconds_total = 0.0
        self.ticks = 0

    # ------------------------------------------------ GOODPUT sink API
    def good(self, tenant, n):
        if not slo_enabled():
            return
        k = _guard(tenant)
        self.good_tokens[k] = self.good_tokens.get(k, 0) + int(n)

    def waste(self, tenant, why, n):
        if not slo_enabled():
            return
        k = _guard(tenant)
        by = self.waste_tokens.setdefault(k, {})
        by[why] = by.get(why, 0) + int(n)

    def saved(self, tenant, n):
        if not slo_enabled():
            return
        k = _guard(tenant)
        self.saved_tokens[k] = self.saved_tokens.get(k, 0) + int(n)

    # -------------------------------------------------------- reports
    def good_total(self) -> int:
        return sum(self.good_tokens.values())

    def waste_total(self) -> int:
        return sum(n for by in self.waste_tokens.values()
                   for n in by.values())

    def saved_total(self) -> int:
        return sum(self.saved_tokens.values())

    def tenants(self) -> List[str]:
        keys = set(self.device_seconds) | set(self.block_seconds) \
            | set(self.good_tokens) | set(self.waste_tokens) \
            | set(self.saved_tokens)
        return sorted(keys)

    def snapshot(self) -> dict:
        rows = {}
        for t in self.tenants():
            rows[t] = {
                "device_seconds": self.device_seconds.get(t, 0.0),
                "block_seconds": self.block_seconds.get(t, 0.0),
                "good_tokens": self.good_tokens.get(t, 0),
                "waste_tokens": dict(self.waste_tokens.get(t, {})),
                "saved_tokens": self.saved_tokens.get(t, 0),
            }
        return {"ticks": self.ticks,
                "device_seconds_total": self.device_seconds_total,
                "block_seconds_total": self.block_seconds_total,
                "good_tokens_total": self.good_total(),
                "waste_tokens_total": self.waste_total(),
                "saved_tokens_total": self.saved_total(),
                "tenants": rows}


# --------------------------------------------------------------- tracker
class SLOTracker:
    """Construct one and hand it to a standalone engine
    (``LLMEngine(..., slo=tracker)`` — polled from its gauge sweep,
    charged from its tick) or to the Router (``Router(..., slo=
    tracker)`` — shared by every replica, polled once per router step).
    Constructing a tracker also attaches its cost ledger as the
    process-wide GOODPUT attribution sink."""

    def __init__(self, objectives=None, *, registry=None,
                 clock: Callable[[], float] = None):
        if objectives is None:
            objectives = {"*": default_objectives()}
        if isinstance(objectives, (list, tuple)):
            objectives = {"*": list(objectives)}
        self.objectives: Dict[str, List[Objective]] = {}
        for tenant, objs in objectives.items():
            objs = list(objs)
            names = [o.name for o in objs]
            if len(set(names)) != len(names):
                raise ValueError(f"duplicate objective for tenant "
                                 f"{tenant!r}: {names}")
            self.objectives[str(tenant)] = objs
        self.windows = WindowedReads(registry)
        self.registry = self.windows.registry
        self.ledger = CostLedger()
        self.clock = clock or time.monotonic
        # who polls: None = the owning engine's gauge sweep; a Router
        # claims the tracker so N replicas sharing it don't advance the
        # burn-rate windows N times per step (same protocol as the
        # degradation ladder)
        self.owner: object = None
        self.seq = next(_SEQ)
        self.polls = 0
        self.state: Dict[Tuple[str, str], dict] = {}
        self.breaches: List[dict] = []        # host-side audit trail
        self._hist: Dict[Tuple[str, str], deque] = {}
        self._alerting: set = set()
        GOODPUT.attach_sink(self.ledger)
        _TRACKERS.add(self)

    enabled = staticmethod(slo_enabled)

    # ----------------------------------------------------- tick charge
    def charge_tick(self, engine, seconds: float):
        """Called from the engine's ``step()`` finally block with the
        tick's ``serving_tick_seconds`` observation. Splits the tick
        over the tenants holding device state (equal row shares,
        remainder-balanced so the shares sum to ``seconds`` exactly)
        and integrates each request's live KV blocks over the tick."""
        if not slo_enabled():
            return
        led = self.ledger
        led.ticks += 1
        led.device_seconds_total += seconds
        rids = {int(r) for r in engine.slot_req[engine.active]}
        rids.update(int(r) for r in engine.prefilling)
        rids.update(int(r) for r in engine.groups)
        rids.discard(-1)
        keys = []
        for rid in sorted(rids):
            req = engine.requests.get(rid)
            keys.append(_guard(getattr(req, "tenant_id", None)))
        if not keys:
            keys = [IDLE_TENANT]
        share, acc = seconds / len(keys), 0.0
        for k in keys[:-1]:
            led.device_seconds[k] = led.device_seconds.get(k, 0.0) + share
            _DEV_SECONDS.inc(share, tenant=k)
            acc += share
        rem = seconds - acc       # the last share absorbs the rounding
        last = keys[-1]
        led.device_seconds[last] = led.device_seconds.get(last, 0.0) + rem
        _DEV_SECONDS.inc(rem, tenant=last)
        mem = engine.kv.ledger
        if mem.enabled:
            for sid, nblocks in mem._req_live.items():
                if not nblocks:
                    continue
                rid = sid[0] if isinstance(sid, tuple) else sid
                req = engine.requests.get(rid)
                k = _guard(getattr(req, "tenant_id", None))
                c = nblocks * seconds
                led.block_seconds[k] = led.block_seconds.get(k, 0.0) + c
                led.block_seconds_total += c
                _BLOCK_SECONDS.inc(c, tenant=k)

    # ---------------------------------------------------------- polling
    def poll(self):
        """One burn-rate sweep: windowed deltas per (tenant, objective),
        burn rates over the fast and slow windows, gauges, and the
        AND-gated breach edge. Called from the gauge sweep."""
        if not slo_enabled():
            return
        self.polls += 1
        now = self.clock()
        w = self.windows
        hist = {name: w.window_histogram_series(name)
                for pair in _LATENCY_SOURCES.values() for name in pair}
        fin = w.window_counter_series("serving_finished_total")
        rej = w.window_counter_series("serving_rejections_total")
        tfin = w.window_counter_series("serving_tenant_finished_total")
        trej = w.window_counter_series("serving_tenant_rejections_total")
        tenants = {"*"} | set(self.objectives)
        tenants.update(k[0] for k in tfin)
        tenants.update(k[0] for k in trej)
        for _, tname in _LATENCY_SOURCES.values():
            tenants.update(k[0] for k in hist[tname])
        for tenant in sorted(tenants):
            objs = self.objectives.get(tenant,
                                       self.objectives.get("*", ()))
            for obj in objs:
                bad, total = self._window_delta(
                    obj, tenant, hist, fin, rej, tfin, trej)
                self._update(obj, tenant, now, bad, total)

    def _window_delta(self, obj, tenant, hist, fin, rej, tfin, trej):
        """(bad, total) event deltas for one (objective, tenant) since
        the previous poll."""
        if obj.name == "availability":
            if tenant == "*":
                total = sum(fin.values())
                bad = sum(fin.get((r,), 0.0) for r in _BAD_FINISH_REASONS)
                bad += sum(rej.values())
            else:
                total = sum(v for k, v in tfin.items() if k[0] == tenant)
                bad = sum(tfin.get((tenant, r), 0.0)
                          for r in _BAD_FINISH_REASONS)
                bad += trej.get((tenant,), 0.0)
            # a pure-reject window has bad > finished: clamp the
            # denominator up so the error rate saturates at 1
            return bad, max(total, bad)
        fleet_name, tenant_name = _LATENCY_SOURCES[obj.name]
        if tenant == "*":
            series = hist[fleet_name]
            inst = self.registry.get(fleet_name)
            deltas = None
            for d in series.values():
                deltas = d if deltas is None else \
                    [a + b for a, b in zip(deltas, d)]
        else:
            inst = self.registry.get(tenant_name)
            deltas = hist[tenant_name].get((tenant,))
        if inst is None or deltas is None:
            return 0.0, 0.0
        total = sum(deltas)
        # an observation lands in the first bucket whose bound >= value,
        # so buckets with bound <= target are within the objective; the
        # bucket straddling a mid-bucket target counts as bad
        # (conservative — alarms early, never late)
        good = sum(d for b, d in zip(inst.buckets, deltas)
                   if b <= obj.target)
        return float(total - good), float(total)

    def _update(self, obj, tenant, now, bad, total):
        key = (tenant, obj.name)
        dq = self._hist.setdefault(key, deque())
        dq.append((now, bad, total))
        horizon = now - obj.window_s
        while dq and dq[0][0] < horizon:
            dq.popleft()

        def window(win_s):
            lo = now - win_s
            b = sum(x[1] for x in dq if x[0] >= lo)
            t = sum(x[2] for x in dq if x[0] >= lo)
            return b, t

        bad_s, tot_s = window(obj.short_s)
        bad_l, tot_l = window(obj.window_s)
        rate_s = bad_s / tot_s if tot_s > 0 else 0.0
        rate_l = bad_l / tot_l if tot_l > 0 else 0.0
        burn_s = rate_s / obj.budget
        burn_l = rate_l / obj.budget
        allowed = obj.budget * tot_l
        remaining = (1.0 if allowed == 0 else
                     min(1.0, max(0.0, 1.0 - bad_l / allowed)))
        _BURN.set(burn_s, tenant=tenant, objective=obj.name)
        _BUDGET_LEFT.set(remaining, tenant=tenant, objective=obj.name)
        breaching = burn_s >= obj.fast_burn and burn_l >= obj.slow_burn
        if breaching and key not in self._alerting:
            self._alerting.add(key)
            _BREACHES.inc(tenant=tenant, objective=obj.name)
            event = {"tenant": tenant, "objective": obj.name,
                     "burn_short": round(burn_s, 3),
                     "burn_long": round(burn_l, 3),
                     "budget_remaining": round(remaining, 4),
                     "target": obj.target, "t": now}
            FLIGHT.record("serving.slo_breach", **event)
            self.breaches.append(event)
        elif not breaching:
            self._alerting.discard(key)
        self.state[key] = {
            "tenant": tenant, "objective": obj.name,
            "burn_short": burn_s, "burn_long": burn_l,
            "budget_remaining": remaining,
            "compliance": 1.0 - rate_l,
            "window_bad": bad_l, "window_total": tot_l,
            "breaching": breaching,
        }

    # --------------------------------------------------------- reports
    def snapshot(self) -> dict:
        """The ``GET /slo`` document: configured objectives plus the
        last poll's compliance/burn/budget per (tenant, objective)."""
        return {
            "tracker": self.seq,
            "enabled": slo_enabled(),
            "polls": self.polls,
            "objectives": {t: [o.describe() for o in objs]
                           for t, objs in sorted(self.objectives.items())},
            "status": [self.state[k] for k in sorted(self.state)],
            "breaches": list(self.breaches),
        }

    def tenants_snapshot(self) -> dict:
        """The ``GET /tenants`` document: the cost-ledger rows."""
        doc = self.ledger.snapshot()
        doc["tracker"] = self.seq
        doc["enabled"] = slo_enabled()
        return doc


def slo_doc() -> dict:
    """Every live tracker's SLO scorecard (the /slo endpoint)."""
    trackers = sorted(_TRACKERS, key=lambda t: t.seq)
    return {"enabled": slo_enabled(),
            "trackers": [t.snapshot() for t in trackers]}


def tenants_doc() -> dict:
    """Every live tracker's cost ledger (the /tenants endpoint)."""
    trackers = sorted(_TRACKERS, key=lambda t: t.seq)
    return {"enabled": slo_enabled(),
            "trackers": [t.tenants_snapshot() for t in trackers]}
