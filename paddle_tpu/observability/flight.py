"""Flight recorder — a process-global, thread-safe bounded ring of
structured runtime events (ISSUE 4 tentpole).

The metrics registry answers "how many" while the process is alive and
someone is polling ``/metrics``; the flight recorder answers "what were
the last N things that happened" AFTER the process is dead. Every
instrumented subsystem drops cheap structured events into the ring
(:meth:`FlightRecorder.record` is a lock + dict append):

    train.step          one per drained optimizer step (step, loss)
    train.nan_skip      a non-finite loss skipped the update
    train.nan_backoff   a backoff sleep was taken during a NaN streak
    train.giveup        the NaN streak hit max_bad_steps
    train.crash         fit() is about to re-raise — last event of a run
    fault               a chaos rule fired (site, hit)
    watchdog.trip       the stall watchdog gave up waiting for a poke
    elastic.restart / elastic.giveup
    serving.preempt / serving.timeout / serving.cancel
    ckpt.save           a checkpoint became durable (step)
    compile             a jitted function compiled (fn, seconds, flops)

On crash, NaN give-up, or watchdog trip the instrumented sites call
:meth:`FlightRecorder.dump`, which atomically writes
``flight_<step>.json`` (same tmp + ``os.replace`` durability idiom as
the checkpoints) so a dead run always leaves its last N events behind.

Dumping is gated on a destination directory: set :attr:`FlightRecorder.dir`
(or the ``PT_FLIGHT_DIR`` environment variable) to enable it. Recording
is always on — the ring costs a few hundred dicts of memory — and an
unconfigured recorder simply never touches the filesystem.

Import-light on purpose: stdlib only, so the faults/watchdog layers can
feed it without any import cycle.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

__all__ = ["FLIGHT", "FlightRecorder"]

_DEFAULT_CAPACITY = 512


class FlightRecorder:
    """Bounded ring of event dicts. ``record`` never raises and never
    blocks beyond the ring lock; ``dump`` writes the whole ring as one
    JSON document via tmp + ``os.replace`` (atomic on POSIX)."""

    def __init__(self, capacity: int = None, directory: Optional[str] = None):
        if capacity is None:
            capacity = int(os.environ.get("PT_FLIGHT_CAPACITY",
                                          _DEFAULT_CAPACITY))
        if capacity < 1:
            raise ValueError(f"flight capacity must be >= 1, got {capacity}")
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.last_step = 0          # newest step seen in any event
        self.dumps = 0              # dump() calls that produced a file
        # dump destination; None/"" = recording only, never write a file
        self.dir: Optional[str] = (directory if directory is not None
                                   else os.environ.get("PT_FLIGHT_DIR") or None)

    # ---------------------------------------------------------- recording
    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def set_capacity(self, capacity: int):
        """Resize the ring, keeping the newest events."""
        if capacity < 1:
            raise ValueError(f"flight capacity must be >= 1, got {capacity}")
        with self._lock:
            self._ring = deque(self._ring, maxlen=capacity)

    def record(self, kind: str, **fields):
        """Append one structured event. ``step=`` (when present and an
        int) also advances :attr:`last_step`, which names the dump file."""
        step = fields.get("step")
        with self._lock:
            self._seq += 1
            if isinstance(step, int) and step > self.last_step:
                self.last_step = step
            self._ring.append({"seq": self._seq, "t_mono": time.monotonic(),
                               "kind": kind, **fields})

    @property
    def total_recorded(self) -> int:
        """Events ever recorded (>= len(events()) once the ring wraps)."""
        return self._seq

    def events(self) -> list:
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self.last_step = 0

    # ------------------------------------------------------------ dumping
    def dump(self, reason: str = "", directory: Optional[str] = None,
             path: Optional[str] = None) -> Optional[str]:
        """Atomically write ``flight_<step>.json`` and return its path.

        ``directory`` overrides :attr:`dir` for this call; ``path`` pins
        the exact file. With no destination configured anywhere, returns
        None without touching the filesystem — crash paths call this
        unconditionally, so "not configured" must be a cheap no-op."""
        if path is None:
            d = directory or self.dir
            if not d:
                return None
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"flight_{self.last_step:08d}.json")
        with self._lock:
            events = list(self._ring)
            total = self._seq
        doc = {
            "reason": reason,
            "t_wall": time.time(),      # humans correlate dumps by wall clock
            "last_step": self.last_step,
            "capacity": self._ring.maxlen,
            "total_recorded": total,
            "dropped": max(0, total - len(events)),
            "events": events,
        }
        # slowest/failed request timelines (ISSUE 9) — lazy import keeps
        # this module stdlib-only for everyone who never enables tracking
        try:
            from paddle_tpu.observability.requests import REQUESTS
            if len(REQUESTS):
                doc["requests"] = REQUESTS.flight_excerpt()
        except Exception:
            pass                        # dump paths must never raise
        # KV memory-ledger snapshots (ISSUE 13): where every pool block
        # was when the dump fired — the OOM-forensics payload
        try:
            from paddle_tpu.observability.memledger import flight_excerpt
            mem = flight_excerpt()
            if mem:
                doc["memory"] = mem
        except Exception:
            pass                        # dump paths must never raise
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self.dumps += 1
        return path


FLIGHT = FlightRecorder()
