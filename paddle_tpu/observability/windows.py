"""Windowed metric reads (ISSUE 19): delta reads of counters and
histogram bucket counts against a per-reader snapshot.

Extracted from the degradation ladder (``serving/degrade.py``) so the
same machinery drives both the ladder's pressure signals and the SLO
tracker's burn-rate windows. Each :class:`WindowedReads` instance owns
its own snapshot dict, so two consumers polling at different cadences
never steal each other's deltas.

Semantics (unchanged from the ladder):

  * the FIRST read of a name baselines at the current total, so
    pre-existing counts never register as a window delta;
  * counter deltas clamp at zero (a registry reset between polls reads
    as an empty window, not a negative one);
  * an empty histogram window quantile is NaN — no traffic is healthy,
    not zero-latency.

The per-series variants (:meth:`window_counter_series`,
:meth:`window_histogram_series`) snapshot EVERY series of an instrument
in one call and return per-label-tuple deltas; call them once per poll
and fan the result out, rather than once per label (each call advances
the window).

This module registers no instruments — it only reads them.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from paddle_tpu.observability.metrics import METRICS, Histogram

__all__ = ["WindowedReads"]


def _nan() -> float:
    return float("nan")


class WindowedReads:
    """Snapshot-diff reads over a metrics registry. Host-side dicts
    only; safe to call from any gauge sweep."""

    def __init__(self, registry=None):
        self.registry = registry if registry is not None else METRICS
        self._snap: dict = {}

    # ------------------------------------------------------- aggregate
    def window_counter(self, name: str) -> float:
        """Counter delta (summed over label series) since the previous
        poll. The first read of a name baselines it at the current
        total, so pre-existing counts never trigger the consumer."""
        inst = self.registry.get(name)
        total = 0.0 if inst is None else \
            float(sum(cell[0] for cell in inst._series.values()))
        key = ("c", name)
        prev = self._snap.get(key, total)
        self._snap[key] = total
        return max(0.0, total - prev)

    def gauge(self, name: str) -> float:
        """Instantaneous gauge read (summed over label series)."""
        inst = self.registry.get(name)
        if inst is None:
            return 0.0
        return float(sum(cell[0] for cell in inst._series.values()))

    def window_goodput(self) -> Tuple[float, float]:
        """(goodput ratio, token volume) over the window — NaN ratio on
        an empty window, so no-traffic polls read as healthy."""
        good = self.window_counter("serving_goodput_tokens_total")
        waste = self.window_counter("serving_waste_total")
        volume = good + waste
        return (good / volume if volume > 0 else _nan()), volume

    def window_quantile(self, name: str, q: float) -> float:
        """Histogram quantile over THIS window's observations: per-
        bucket count deltas vs the previous poll, interpolated exactly
        like ``Histogram.quantile``. NaN when the window saw nothing."""
        inst = self.registry.get(name)
        if not isinstance(inst, Histogram):
            return _nan()
        n = len(inst.buckets) + 1
        agg = [0] * n
        for s in inst._series.values():
            for i, c in enumerate(s.counts):
                agg[i] += c
        key = ("h", name)
        prev = self._snap.get(key, agg)
        self._snap[key] = agg
        delta = [max(0, a - p) for a, p in zip(agg, prev)]
        return quantile_from_deltas(inst.buckets, delta, q)

    # ------------------------------------------------------ per-series
    def window_counter_series(self, name: str) -> Dict[tuple, float]:
        """Per-label-series counter deltas since the previous poll, as
        ``{label_values_tuple: delta}``. The first poll of an instrument
        baselines every existing series at its current total (all-zero
        deltas, matching :meth:`window_counter`); a series appearing on
        a LATER poll reports its full count — a brand-new series'
        increments all happened inside this window."""
        inst = self.registry.get(name)
        key = ("cs", name)
        prev = self._snap.get(key)
        if inst is None:
            self._snap[key] = {}
            return {}
        cur = {k: float(cell[0]) for k, cell in inst._series.items()}
        self._snap[key] = cur
        if prev is None:                       # first poll: baseline
            return {k: 0.0 for k in cur}
        return {k: max(0.0, v - prev.get(k, 0.0)) for k, v in cur.items()}

    def window_histogram_series(self, name: str) \
            -> Dict[tuple, List[int]]:
        """Per-label-series histogram bucket-count deltas since the
        previous poll, as ``{label_values_tuple: [delta per bucket]}``
        (last entry is the +Inf overflow bucket). First-poll baselining
        and late-series semantics match :meth:`window_counter_series`."""
        inst = self.registry.get(name)
        key = ("hs", name)
        prev = self._snap.get(key)
        if not isinstance(inst, Histogram):
            self._snap[key] = {}
            return {}
        cur = {k: list(s.counts) for k, s in inst._series.items()}
        self._snap[key] = cur
        if prev is None:                       # first poll: baseline
            return {k: [0] * len(c) for k, c in cur.items()}
        out = {}
        for k, counts in cur.items():
            p = prev.get(k, [0] * len(counts))
            out[k] = [max(0, a - b) for a, b in zip(counts, p)]
        return out


def quantile_from_deltas(buckets, delta, q: float) -> float:
    """Interpolated quantile over one window's bucket-count deltas —
    the same linear interpolation ``Histogram.quantile`` applies to
    lifetime counts. NaN on an empty window; an overflow-only window
    reads as the highest finite bound."""
    count = sum(delta)
    if count == 0:
        return _nan()
    rank, cum = q * count, 0.0
    for i, bound in enumerate(buckets):
        prev_cum = cum
        cum += delta[i]
        if cum >= rank and delta[i] > 0:
            lo = buckets[i - 1] if i > 0 else 0.0
            return lo + (bound - lo) * ((rank - prev_cum) / delta[i])
    return buckets[-1]
