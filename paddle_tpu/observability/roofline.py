"""Serving roofline ledger (ISSUE 12): per-phase FLOPs *and* bytes.

``flops.py`` answers "how close to peak compute" — the right question
for training, where every matmul is large. Serving is different: decode
at continuous-batching sizes streams the whole weight set plus every
cached KV position per emitted token, so it pins HBM long before the
MXU, and MFU alone cannot say whether the decode tick is at hardware
speed (Williams et al., "Roofline: An Insightful Visual Performance
Model", CACM 2009). This module pairs the peak-FLOPs table with a peak
HBM-bandwidth table and carries analytic per-phase FLOPs and bytes
models, so every serving phase gets THREE numbers:

  * ``serving_mfu{phase}``              — FLOPs/s vs the chip's bf16 peak
  * ``serving_mbu{phase}``              — bytes/s vs the chip's HBM peak
  * ``serving_arith_intensity{phase}``  — FLOPs/byte, placing the phase
    left (bandwidth-bound) or right (compute-bound) of the machine
    balance point

Phases are the engine tick's anatomy: ``prefill`` (admission + chunked
prefill forwards), ``decode`` (the fused one-token tick), ``spec_draft``
(draft-model feeds), ``spec_verify`` (the batched (slots, k+1) target
chunk). The engine accumulates per-phase seconds / tokens / weight
passes / KV-read positions and folds them through
:func:`record_serving_throughput` — the single choke point, mirroring
``flops.record_throughput`` — at every gauge sweep.

Conventions shared with ``flops.py``: import-light (nothing here may
import jax or the ``paddle_tpu`` root — bench.py's orchestrator and the
perfledger must be able to reason about rooflines off-device), and an
unknown chip yields peak 0.0 → every utilisation gauge reads 0.0 =
"undefined", never a fabricated number. ``PT_ROOFLINE_KIND`` overrides
the detected device kind (e.g. ``PT_ROOFLINE_KIND="TPU v5e"``) for
what-if analysis and for testing the TPU arithmetic on CPU.

Bytes model scope: weights (every resident weight streamed once per
jitted forward — all experts for MoE, the batch routes across them),
KV reads (2 × kv_heads × head_dim per layer per attended position —
GQA grouping shrinks this by heads/kv_heads; the engine counts decode
positions block-rounded because the paged kernel reads whole blocks),
KV writes (one position per token), and f32 logits. Activations are
deliberately excluded — they are layer-local and VMEM-resident at
serving batch sizes.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, asdict

from paddle_tpu.observability.metrics import METRICS
from paddle_tpu.observability.flops import PEAK_BF16, chip_peak_flops

__all__ = ["PEAK_HBM_BPS", "chip_peak_hbm_bw", "resolve_serving_peaks",
           "ModelGeometry", "weight_bytes", "kv_bytes_per_position",
           "phase_flops", "phase_bytes", "arith_intensity",
           "roofline_verdict", "record_serving_throughput",
           "serving_roofline_report", "reset_serving_roofline"]

# Peak HBM bandwidth per chip, bytes/sec — the denominator of MBU, keyed
# exactly like PEAK_BF16 so the two tables can never disagree about what
# a "chip" is. (v5e 819 GB/s, v5p 2765 GB/s, v4 1228 GB/s, v6e 1640 GB/s.)
PEAK_HBM_BPS = {
    "TPU v5 lite": 819e9,    # v5e
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v4": 1228e9,
    "TPU v6": 1640e9,
}

assert set(PEAK_HBM_BPS) == set(PEAK_BF16), \
    "PEAK_HBM_BPS and PEAK_BF16 must cover the same chips"


def chip_peak_hbm_bw(dev=None, kind: str = None) -> float:
    """Peak HBM bytes/sec for a jax device (or an explicit
    ``device_kind`` string). Same convention as ``chip_peak_flops``:
    unknown TPU kinds assume v5e-class, anything that is not known to be
    a TPU returns 0.0 — callers treat 0 peak as "MBU undefined"."""
    platform = None
    if kind is None:
        kind = getattr(dev, "device_kind", "") or ""
        platform = getattr(dev, "platform", "") or ""
        if platform and platform != "tpu":
            return 0.0
    for k, v in PEAK_HBM_BPS.items():
        if kind.startswith(k) or k in kind:
            return v
    if "TPU" in kind.upper():
        return PEAK_HBM_BPS["TPU v5e"]
    if kind == "" and platform == "tpu":
        return PEAK_HBM_BPS["TPU v5e"]
    return 0.0


def resolve_serving_peaks(dev=None) -> tuple:
    """(peak_flops, peak_hbm_bps) for the serving roofline.
    ``PT_ROOFLINE_KIND`` (a device-kind string, e.g. ``TPU v5e``)
    overrides the detected device — what-if analysis, and the only way
    to exercise the TPU arithmetic in a CPU test without fabricating
    utilisation by default."""
    kind = os.environ.get("PT_ROOFLINE_KIND")
    if kind:
        return chip_peak_flops(kind=kind), chip_peak_hbm_bw(kind=kind)
    return chip_peak_flops(dev), chip_peak_hbm_bw(dev)


@dataclass(frozen=True)
class ModelGeometry:
    """The shape facts the FLOPs/bytes models need — duck-typed off any
    of the repo's LLM configs via :meth:`from_config`, never a live
    model (so the roofline stays importable without jax)."""
    num_layers: int
    hidden: int
    intermediate: int
    vocab: int
    heads: int
    kv_heads: int
    head_dim: int
    dtype_bytes: int = 2          # bf16 weights and KV
    num_experts: int = 0          # routed experts (0 = dense MLP)
    experts_per_tok: int = 0
    # quantized serving (ISSUE 17) — actual storage dtypes, so an int8
    # pool or weight-only model is not billed at bf16 (which would
    # double its bytes and overstate MBU). 0 = inherit dtype_bytes.
    kv_dtype_bytes: int = 0       # bytes per cached KV element
    kv_scale_bytes: int = 0       # extra bytes per (position, kv-head)
    weight_dtype_bytes: float = 0.0   # 1.0 int8, 0.5 packed int4
    # context-parallel serving (ISSUE 18) — cp>1 means every decode
    # token pays a cross-shard partial merge (psum of the online-softmax
    # (o, m, l) triple per layer); billed as extra bytes so
    # serving_mbu{decode} stays honest about the per-step gather cost.
    cp: int = 1

    @classmethod
    def from_config(cls, cfg, dtype_bytes: int = 2) -> "ModelGeometry":
        h = int(cfg.hidden_size)
        nh = int(cfg.num_attention_heads)
        experts = int(getattr(cfg, "num_experts", 0)
                      or getattr(cfg, "num_local_experts", 0) or 0)
        per_tok = int(getattr(cfg, "num_experts_per_tok", 0)
                      or getattr(cfg, "experts_per_tok", 0) or 0)
        inter = int(getattr(cfg, "moe_intermediate_size", 0)
                    or cfg.intermediate_size)
        return cls(num_layers=int(cfg.num_hidden_layers), hidden=h,
                   intermediate=inter, vocab=int(cfg.vocab_size), heads=nh,
                   kv_heads=int(getattr(cfg, "num_key_value_heads", nh)),
                   head_dim=h // nh, dtype_bytes=int(dtype_bytes),
                   num_experts=experts, experts_per_tok=per_tok)

    # ---- derived counts -------------------------------------------------
    @property
    def attn_params_per_layer(self) -> int:
        """Fused qkv + output projection."""
        return (self.hidden * (self.heads + 2 * self.kv_heads)
                * self.head_dim + self.heads * self.head_dim * self.hidden)

    @property
    def mlp_params_per_expert(self) -> int:
        """gate + up + down projections of one (dense or expert) MLP."""
        return 3 * self.hidden * self.intermediate

    @property
    def activated_params(self) -> int:
        """Weight parameters ONE token's forward multiplies against:
        attention + experts_per_tok MLPs (all of the dense MLP) + head."""
        e = self.experts_per_tok if self.num_experts else 1
        return (self.num_layers * (self.attn_params_per_layer
                                   + e * self.mlp_params_per_expert)
                + self.hidden * self.vocab)

    @property
    def resident_params(self) -> int:
        """Weight parameters a batched forward streams from HBM: every
        expert is resident (the batch routes across all of them)."""
        e = self.num_experts if self.num_experts else 1
        return (self.num_layers * (self.attn_params_per_layer
                                   + e * self.mlp_params_per_expert)
                + self.hidden * self.vocab)


def weight_bytes(geom: ModelGeometry) -> float:
    """Bytes of weights one jitted forward reads from HBM (honouring
    weight-only quantization when ``weight_dtype_bytes`` is set)."""
    return float(geom.resident_params) * (geom.weight_dtype_bytes
                                          or geom.dtype_bytes)


def kv_bytes_per_position(geom: ModelGeometry) -> float:
    """K + V bytes of ONE cached position across all layers; GQA head
    grouping makes this kv_heads/heads of the MHA figure. An int8 pool
    stores head_dim codes plus a per-(position, kv-head) scale."""
    per_head = (geom.head_dim * (geom.kv_dtype_bytes or geom.dtype_bytes)
                + geom.kv_scale_bytes)
    return float(geom.num_layers * 2 * geom.kv_heads * per_head)


def phase_flops(geom: ModelGeometry, tokens: float,
                kv_read_positions: float) -> float:
    """Forward FLOPs of a phase that computed ``tokens`` token positions
    attending ``kv_read_positions`` (query, cached-position) pairs in
    total: 2 × activated params per token (matmuls), plus the qk^T and
    p·v terms — 4 × heads × head_dim FLOPs per attended pair per layer
    (2 mult-adds). The attention term rides the PAIR count, so callers
    describe causal prefill (Σ ctx per query) and single-query decode
    (whole table per token) with the same argument."""
    matmul = 2.0 * geom.activated_params * tokens
    attn = 4.0 * geom.heads * geom.head_dim * kv_read_positions
    return matmul + attn


def phase_bytes(geom: ModelGeometry, *, tokens: float, weight_passes: float,
                kv_read_positions: float) -> float:
    """HBM bytes of a phase: weights once per jitted forward, KV reads
    per attended (query, position) pair, one KV write per computed
    token, and the f32 logits row per token."""
    w = weight_passes * weight_bytes(geom)
    kv_r = kv_read_positions * kv_bytes_per_position(geom)
    kv_w = tokens * kv_bytes_per_position(geom)
    logits = tokens * geom.vocab * 4.0
    total = w + kv_r + kv_w + logits
    if geom.cp > 1:
        # cross-shard partial merge per computed token: each member
        # psums an f32 (o [H, D], m [H], l [H]) triple per layer —
        # 2·(cp-1)/cp of it crosses the interconnect per member
        triple = geom.num_layers * geom.heads * (geom.head_dim + 2) * 4.0
        total += tokens * triple * 2.0 * (geom.cp - 1) / geom.cp
    return total


def arith_intensity(flops: float, nbytes: float) -> float:
    """FLOPs per HBM byte — the roofline x-axis."""
    return flops / nbytes if nbytes else 0.0


def roofline_verdict(intensity: float, peak_flops: float,
                     peak_hbm_bps: float) -> str:
    """Which roof the phase sits under: intensity below the machine
    balance (peak_flops / peak_hbm) means the bandwidth roof caps it."""
    if not peak_flops or not peak_hbm_bps:
        return "undefined"
    return ("compute-bound" if intensity >= peak_flops / peak_hbm_bps
            else "bandwidth-bound")


_MFU = METRICS.gauge(
    "serving_mfu",
    "per-phase model FLOPs utilisation vs the chip bf16 peak "
    "(0.0 = undefined off-TPU)", labelnames=("phase",))
_MBU = METRICS.gauge(
    "serving_mbu",
    "per-phase model bandwidth utilisation vs the chip HBM peak "
    "(0.0 = undefined off-TPU)", labelnames=("phase",))
_AI = METRICS.gauge(
    "serving_arith_intensity",
    "per-phase arithmetic intensity, FLOPs per HBM byte",
    labelnames=("phase",))

# last full report per phase, served verbatim at /roofline
_REPORTS: dict = {}
_REPORTS_LOCK = threading.Lock()


def record_serving_throughput(phase: str, *, seconds: float, tokens: float,
                              weight_passes: float, kv_read_positions: float,
                              geom: ModelGeometry, peak_flops: float = 0.0,
                              peak_hbm_bps: float = 0.0) -> dict:
    """Single choke point for serving utilisation: fold one phase's
    cumulative (seconds, tokens, weight passes, KV-read positions)
    through the analytic models, set the three per-phase gauges, stash
    the full report for ``/roofline``, and return it. Unknown peaks
    (CPU, mock backends) keep MFU/MBU at 0.0 — undefined, never
    fabricated — while intensity and the byte/FLOP tallies stay real."""
    if seconds <= 0.0 or tokens <= 0:
        return {}
    fl = phase_flops(geom, tokens, kv_read_positions)
    by = phase_bytes(geom, tokens=tokens, weight_passes=weight_passes,
                     kv_read_positions=kv_read_positions)
    ai = arith_intensity(fl, by)
    mfu_v = fl / seconds / peak_flops if peak_flops else 0.0
    mbu_v = by / seconds / peak_hbm_bps if peak_hbm_bps else 0.0
    report = {
        "phase": phase, "seconds": seconds, "tokens": tokens,
        "weight_passes": weight_passes,
        "kv_read_positions": kv_read_positions,
        "flops": fl, "bytes": by,
        "flops_per_sec": fl / seconds, "bytes_per_sec": by / seconds,
        "arith_intensity": ai, "mfu": mfu_v, "mbu": mbu_v,
        "bound": roofline_verdict(ai, peak_flops, peak_hbm_bps),
        "geometry": asdict(geom),
    }
    _MFU.set(mfu_v, phase=phase)
    _MBU.set(mbu_v, phase=phase)
    _AI.set(ai, phase=phase)
    with _REPORTS_LOCK:
        _REPORTS[phase] = report
        _REPORTS["_machine"] = {
            "peak_flops": peak_flops, "peak_hbm_bps": peak_hbm_bps,
            "balance_flops_per_byte": (peak_flops / peak_hbm_bps
                                       if peak_hbm_bps else 0.0),
        }
    return report


def serving_tick_anatomy() -> dict:
    """Overlap-aware tick anatomy (ISSUE 20): cumulative wall-seconds
    per tick phase from the breakdown histogram, with host time split
    into *exposed* (the breakdown's ``host`` remainder — device idle
    while the host works) and *hidden* (host work done under an
    in-flight async dispatch, ``serving_tick_host_hidden_seconds``;
    zero for synchronous engines). ``overlap_fraction`` is the share of
    total host work the pipeline hid."""
    def _hist_sum(name, **labels):
        m = METRICS.get(name)
        if m is None:
            return 0.0
        try:
            return float(m.value(**labels)["sum"])
        except (KeyError, TypeError):
            return 0.0

    phases = {p: _hist_sum("serving_tick_breakdown_seconds", phase=p)
              for p in ("prefill", "draft", "verify", "sample", "host")}
    hidden = _hist_sum("serving_tick_host_hidden_seconds")
    exposed = phases["host"]
    host_total = exposed + hidden
    return {
        "ticks_seconds": _hist_sum("serving_tick_seconds"),
        "phases_seconds": phases,
        "host_exposed_seconds": exposed,
        "host_hidden_seconds": hidden,
        "overlap_fraction": hidden / host_total if host_total else 0.0,
    }


def serving_roofline_report() -> dict:
    """The ``/roofline`` document: machine roofs + the last per-phase
    reports the choke point recorded + the overlap-aware tick anatomy."""
    with _REPORTS_LOCK:
        machine = _REPORTS.get("_machine", {
            "peak_flops": 0.0, "peak_hbm_bps": 0.0,
            "balance_flops_per_byte": 0.0})
        phases = {k: dict(v) for k, v in _REPORTS.items()
                  if k != "_machine"}
    return {"machine": machine, "phases": phases,
            "tick_anatomy": serving_tick_anatomy()}


def reset_serving_roofline():
    """Drop every stashed phase report (test hygiene)."""
    with _REPORTS_LOCK:
        _REPORTS.clear()
