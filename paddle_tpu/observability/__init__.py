"""Observability subsystem (ISSUE 2): metrics registry, trace spans,
and the shared FLOPs/MFU accounting.

Three layers, all host-side and CPU-safe:

  * :mod:`paddle_tpu.observability.metrics` — process-global
    Counter/Gauge/Histogram registry (:data:`METRICS`), exportable as
    one-line JSON and Prometheus text.
  * :mod:`paddle_tpu.observability.tracing` — :func:`span` context
    manager/decorator + :func:`instant` markers over the global
    :data:`TRACER`, exported as a Chrome-trace/Perfetto JSON timeline.
  * :mod:`paddle_tpu.observability.flops` — the peak-FLOPs table and
    :func:`record_throughput`, the single MFU choke point shared by the
    Trainer, ``utils.profiler.StepTimer``, and bench.py.

Built-in instrumentation (serving engine, Trainer, checkpoints, elastic
restarts, collectives, fault injection) emits through these singletons;
``metrics_snapshot()``/``dump()`` give a one-call export of everything.

The second layer (ISSUE 4) turns the registry into an operable
telemetry pipeline:

  * :mod:`paddle_tpu.observability.flight` — :data:`FLIGHT`, the
    bounded ring of structured runtime events, atomically dumped to
    ``flight_<step>.json`` on crash/give-up/watchdog trip.
  * :mod:`paddle_tpu.observability.compile` — :func:`instrumented_jit`,
    compile spans + cache hit/miss counters + cost_analysis FLOPs.
  * :mod:`paddle_tpu.observability.shipper` — the ``pt-metrics-shipper``
    thread appending registry snapshots (with deltas) to a rotating
    JSONL ring on disk.
  * :mod:`paddle_tpu.observability.health` — :data:`HEALTH`, declarative
    OK/WARN/CRIT rules served at ``/healthz`` (with ``/flight``) by the
    metrics HTTP server.

The request layer (ISSUE 9) adds per-request views on top of the
aggregates:

  * :mod:`paddle_tpu.observability.requests` — :data:`REQUESTS`, a
    bounded ring of per-request lifecycle timelines, stitched across
    serving replicas via TRACER flow events and served at ``/requests``.
  * :mod:`paddle_tpu.observability.goodput` — :data:`GOODPUT`, the
    useful-vs-wasted device-token ledger behind
    ``serving_goodput_tokens_total`` / ``serving_waste_total{why}``.

The memory layer (ISSUE 13) accounts for where the KV pool's blocks are:

  * :mod:`paddle_tpu.observability.memledger` — :class:`MemLedger`, the
    per-pool block-state ledger (active/parked/cow_pending/reserved/
    free, ``sum == num_blocks`` by construction) behind
    ``serving_kv_blocks{state}``, per-request peak attribution,
    admission-stall forensics, and the ``GET /memory`` endpoint
    (:func:`memory_doc`).

The SLO layer (ISSUE 19) turns the aggregates into objectives:

  * :mod:`paddle_tpu.observability.windows` — :class:`WindowedReads`,
    the delta-since-last-poll read machinery shared by the degradation
    ladder and the SLO tracker.
  * :mod:`paddle_tpu.observability.slo` — :class:`SLOTracker`,
    declarative per-tenant :class:`Objective` targets with SRE-style
    multi-window burn-rate alerting, plus :class:`CostLedger`, the
    usage-metering ledger attributing device-seconds, KV block-seconds
    and goodput/waste tokens to tenants (``GET /slo`` /
    ``GET /tenants``). ``PT_SLO=0`` kills the whole layer.

``python -m paddle_tpu.observability`` prints a generated reference of
every registered metric instrument.
"""
from __future__ import annotations

from paddle_tpu.observability.metrics import (Counter, Gauge, Histogram,
                                              METRICS, MetricsRegistry,
                                              DEFAULT_BUCKETS)
from paddle_tpu.observability.tracing import (TRACER, Tracer, span, instant,
                                              export_chrome_trace)
from paddle_tpu.observability.flops import (PEAK_BF16, chip_peak_flops, mfu,
                                            record_throughput)
from paddle_tpu.observability.roofline import (PEAK_HBM_BPS, ModelGeometry,
                                               chip_peak_hbm_bw,
                                               record_serving_throughput,
                                               serving_roofline_report)
from paddle_tpu.observability.httpd import (MetricsServer,
                                            start_metrics_server,
                                            stop_metrics_server)
from paddle_tpu.observability.flight import FLIGHT, FlightRecorder
from paddle_tpu.observability.compile import InstrumentedJit, instrumented_jit
from paddle_tpu.observability.shipper import (MetricsShipper,
                                              start_metrics_shipper,
                                              stop_metrics_shipper)
from paddle_tpu.observability.health import (HEALTH, HealthEvaluator,
                                             HealthRule,
                                             install_default_rules)
from paddle_tpu.observability.requests import REQUESTS, RequestTracker
from paddle_tpu.observability.goodput import GOODPUT, GoodputLedger
from paddle_tpu.observability.memledger import MemLedger, memory_doc
from paddle_tpu.observability.windows import WindowedReads
from paddle_tpu.observability.slo import (CostLedger, Objective, SLOTracker,
                                          default_objectives, slo_doc,
                                          slo_enabled, tenants_doc)

__all__ = [
    "METRICS", "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "DEFAULT_BUCKETS",
    "TRACER", "Tracer", "span", "instant", "export_chrome_trace",
    "PEAK_BF16", "chip_peak_flops", "mfu", "record_throughput",
    "PEAK_HBM_BPS", "ModelGeometry", "chip_peak_hbm_bw",
    "record_serving_throughput", "serving_roofline_report",
    "MetricsServer", "start_metrics_server", "stop_metrics_server",
    "FLIGHT", "FlightRecorder",
    "InstrumentedJit", "instrumented_jit",
    "MetricsShipper", "start_metrics_shipper", "stop_metrics_shipper",
    "HEALTH", "HealthEvaluator", "HealthRule", "install_default_rules",
    "REQUESTS", "RequestTracker", "GOODPUT", "GoodputLedger",
    "MemLedger", "memory_doc",
    "WindowedReads",
    "SLOTracker", "Objective", "CostLedger", "default_objectives",
    "slo_enabled", "slo_doc", "tenants_doc",
    "enable", "disable", "metrics_snapshot", "dump",
]


def enable(tracing: bool = True):
    """Turn the whole layer on (metrics are on by default; this also
    starts span collection when ``tracing``)."""
    METRICS.enable()
    if tracing:
        TRACER.enable()


def disable():
    """No-op every instrument and stop span collection."""
    METRICS.disable()
    TRACER.disable()


def metrics_snapshot() -> dict:
    return METRICS.snapshot()


def dump(prefix: str) -> dict:
    """Write ``<prefix>.metrics.json`` (one line), ``<prefix>.prom``
    (Prometheus text), and ``<prefix>.trace.json`` (Chrome trace);
    returns the three paths."""
    paths = {"json": prefix + ".metrics.json", "prom": prefix + ".prom",
             "trace": prefix + ".trace.json"}
    with open(paths["json"], "w") as f:
        f.write(METRICS.to_json() + "\n")
    with open(paths["prom"], "w") as f:
        f.write(METRICS.to_prometheus())
    TRACER.export_chrome_trace(paths["trace"])
    return paths
