"""Health/SLO evaluator (ISSUE 4): declarative rules over the registry.

The registry answers "how many"; operators need "is it healthy". A
:class:`HealthRule` names one scalar derived from registry values (a
counter ratio, a histogram quantile, a gauge) plus WARN/CRIT thresholds;
:class:`HealthEvaluator.evaluate` runs every rule and folds the per-rule
statuses into one overall ``OK``/``WARN``/``CRIT`` — what ``/healthz``
on :mod:`paddle_tpu.observability.httpd` serves (HTTP 503 on CRIT, so a
dumb TCP health checker needs zero JSON parsing).

Rules are *greater-is-worse*: value >= crit → CRIT, >= warn → WARN.
A rule with no data yet (empty histogram → NaN quantile, zero-count
ratio) reports OK — absence of traffic is not an incident. Getters
never raise out of ``evaluate``: a getter that throws marks its rule
CRIT with the error attached (a broken health probe IS unhealthy).

The module-global :data:`HEALTH` ships with the default rule set
(:func:`install_default_rules`): NaN-skip rate, serving queue-wait p95,
prefetch stall ratio, checkpoint CRC failures, elastic restart count,
and the goodput waste ratio (ISSUE 9).
"""
from __future__ import annotations

import math
import os
from typing import Callable, List, Optional, Sequence

from paddle_tpu.observability.metrics import METRICS, Histogram

__all__ = ["HEALTH", "HealthEvaluator", "HealthRule", "install_default_rules",
           "gauge_max",
           "counter_value", "gauge_value", "counter_ratio", "counter_share",
           "gauge_imbalance", "gauge_deficit", "histogram_quantile",
           "histogram_sum_ratio", "kv_parked_ratio"]

_ORDER = {"OK": 0, "WARN": 1, "CRIT": 2}


# ------------------------------------------------------------ getter factories
def _series_total(inst) -> float:
    """Sum of every label series of a counter/gauge (0.0 when absent)."""
    if inst is None:
        return 0.0
    return float(sum(cell[0] for cell in inst._series.values()))


def counter_value(name: str, registry=None) -> Callable[[], float]:
    """Current value of a counter, summed across label series."""
    def get():
        reg = registry if registry is not None else METRICS
        return _series_total(reg.get(name))
    return get


gauge_value = counter_value      # same read path for gauges


def counter_ratio(num: str, den: str, registry=None) -> Callable[[], float]:
    """num/den over two counters; 0.0 while the denominator is zero."""
    def get():
        reg = registry if registry is not None else METRICS
        d = _series_total(reg.get(den))
        return _series_total(reg.get(num)) / d if d else 0.0
    return get


def counter_share(part: str, whole: Sequence[str],
                  registry=None) -> Callable[[], float]:
    """part / sum(whole counters) — e.g. wasted device tokens over all
    accounted device tokens. NaN while the denominator is zero: no
    traffic is not an incident."""
    def get():
        reg = registry if registry is not None else METRICS
        d = sum(_series_total(reg.get(n)) for n in whole)
        return _series_total(reg.get(part)) / d if d else float("nan")
    return get


def gauge_imbalance(name: str, registry=None) -> Callable[[], float]:
    """Spread across a labeled gauge's series: (max - min) / max(mean, 1),
    e.g. per-replica outstanding-request counts — 0 when perfectly
    balanced, large when one series hoards the load. NaN (→ OK) with
    fewer than two series: imbalance needs something to compare."""
    def get():
        reg = registry if registry is not None else METRICS
        inst = reg.get(name)
        if inst is None or len(inst._series) < 2:
            return float("nan")
        vals = [float(cell[0]) for cell in inst._series.values()]
        mean = sum(vals) / len(vals)
        return (max(vals) - min(vals)) / max(mean, 1.0)
    return get


def gauge_max(name: str, registry=None, *,
              deficit: bool = False) -> Callable[[], float]:
    """Worst series of a labeled gauge — max over label series, e.g.
    the hottest tenant's SLO burn rate. ``deficit=True`` reads
    ``max(1 - v)`` instead (worst budget CONSUMED when the gauge stores
    budget remaining). NaN (→ OK) while the gauge is absent or empty."""
    def get():
        reg = registry if registry is not None else METRICS
        inst = reg.get(name)
        if inst is None or not inst._series:
            return float("nan")
        vals = [float(cell[0]) for cell in inst._series.values()]
        if deficit:
            vals = [1.0 - v for v in vals]
        return max(vals)
    return get


def histogram_quantile(name: str, q: float, registry=None,
                       **labels) -> Callable[[], float]:
    """q-quantile of a histogram series (label kwargs select the series
    of a labeled histogram, e.g. ``phase="host"``); NaN while
    empty/absent."""
    def get():
        reg = registry if registry is not None else METRICS
        h = reg.get(name)
        if not isinstance(h, Histogram):
            return float("nan")
        return h.quantile(q, **labels)
    return get


def gauge_deficit(name: str, registry=None, **labels) -> Callable[[], float]:
    """1 - gauge value — a greater-is-worse view of a utilisation gauge
    (MBU, goodput ratio). NaN while the series is absent OR reads <= 0:
    by this repo's convention a utilisation of 0.0 means "undefined"
    (unknown peak, e.g. CPU), and undefined is not an incident."""
    def get():
        reg = registry if registry is not None else METRICS
        inst = reg.get(name)
        if inst is None:
            return float("nan")
        try:
            v = float(inst.value(**labels))
        except Exception:
            return float("nan")
        return 1.0 - v if v > 0.0 else float("nan")
    return get


def kv_parked_ratio(registry=None) -> Callable[[], float]:
    """serving_kv_blocks{state="parked"} / serving_kv_pool_blocks — the
    reclaimable prefix-cache share of the pool. NaN (→ OK) while the
    radix cache is disabled (``PT_RADIX_CACHE=0`` — a flat-manager pool
    parking ~everything after a burst is normal LRU behavior, and with
    caching off entirely there is nothing to rule on) or while the pool
    gauges are absent/zero."""
    def get():
        if os.environ.get("PT_RADIX_CACHE", "1") == "0":
            return float("nan")
        reg = registry if registry is not None else METRICS
        inst = reg.get("serving_kv_blocks")
        pool = reg.get("serving_kv_pool_blocks")
        if inst is None or pool is None:
            return float("nan")
        try:
            denom = float(pool.value())
            if denom <= 0.0:
                return float("nan")
            return float(inst.value(state="parked")) / denom
        except Exception:
            return float("nan")
    return get


def histogram_sum_ratio(num: str, den: str,
                        registry=None) -> Callable[[], float]:
    """sum(num histogram) / sum(den histogram) — e.g. seconds stalled in
    prefetch per second spent stepping; 0.0 while the denominator is 0."""
    def get():
        reg = registry if registry is not None else METRICS
        def hsum(n):
            h = reg.get(n)
            if not isinstance(h, Histogram):
                return 0.0
            return float(sum(s.sum for s in h._series.values()))
        d = hsum(den)
        return hsum(num) / d if d else 0.0
    return get


# --------------------------------------------------------------------- rules
class HealthRule:
    """One named scalar + WARN/CRIT thresholds (greater is worse)."""

    def __init__(self, name: str, getter: Callable[[], float],
                 warn: float, crit: float, description: str = ""):
        if crit < warn:
            raise ValueError(
                f"rule {name!r}: crit ({crit}) must be >= warn ({warn})")
        self.name = name
        self.getter = getter
        self.warn = warn
        self.crit = crit
        self.description = description

    def evaluate(self) -> dict:
        try:
            v = float(self.getter())
        except Exception as e:        # a broken probe IS unhealthy
            return {"name": self.name, "value": None, "status": "CRIT",
                    "warn": self.warn, "crit": self.crit,
                    "error": f"{type(e).__name__}: {e}"}
        if math.isnan(v):             # no data yet — not an incident
            status, v_out = "OK", None
        elif v >= self.crit:
            status, v_out = "CRIT", v
        elif v >= self.warn:
            status, v_out = "WARN", v
        else:
            status, v_out = "OK", v
        return {"name": self.name, "value": v_out, "status": status,
                "warn": self.warn, "crit": self.crit}


class HealthEvaluator:
    """An ordered rule list + one ``evaluate()`` fold."""

    def __init__(self, rules: Optional[List[HealthRule]] = None):
        self.rules: List[HealthRule] = list(rules or [])

    def add_rule(self, rule: HealthRule) -> HealthRule:
        """Add (or replace, by name) one rule."""
        self.rules = [r for r in self.rules if r.name != rule.name]
        self.rules.append(rule)
        return rule

    def rule(self, name: str, getter, warn: float, crit: float,
             description: str = "") -> HealthRule:
        return self.add_rule(HealthRule(name, getter, warn, crit,
                                        description))

    def remove_rule(self, name: str):
        self.rules = [r for r in self.rules if r.name != name]

    def clear(self):
        self.rules = []

    def evaluate(self) -> dict:
        """{"status": worst-of-rules, "rules": [per-rule dicts]}.
        No rules installed → OK (an unconfigured probe must not page)."""
        results = [r.evaluate() for r in self.rules]
        worst = max((r["status"] for r in results),
                    key=_ORDER.__getitem__, default="OK")
        return {"status": worst, "rules": results}


def install_default_rules(ev: HealthEvaluator,
                          registry=None) -> HealthEvaluator:
    """The stock rule set. Thresholds are deliberately loose — they flag
    "clearly on fire", not "worth a look"; tighten per deployment via
    ``HEALTH.rule(...)`` (same name replaces)."""
    ev.rule("nan_skip_rate",
            counter_ratio("train_nan_skips_total", "train_steps_total",
                          registry),
            warn=0.05, crit=0.25,
            description="fraction of optimizer steps skipped on "
                        "non-finite loss")
    ev.rule("serving_queue_wait_p95_s",
            histogram_quantile("serving_queue_wait_seconds", 0.95, registry),
            warn=1.0, crit=5.0,
            description="p95 submission→admission wait")
    ev.rule("prefetch_stall_ratio",
            histogram_sum_ratio("io_prefetch_stall_seconds",
                                "train_step_seconds", registry),
            warn=0.2, crit=0.5,
            description="host seconds stalled waiting on the input "
                        "pipeline per second of stepping")
    ev.rule("ckpt_crc_failures",
            counter_value("ckpt_crc_failures_total", registry),
            warn=1, crit=3,
            description="array CRC mismatches caught on checkpoint load")
    ev.rule("elastic_restarts",
            counter_value("elastic_restarts_total", registry),
            warn=1, crit=3,
            description="elastic restarts taken after failures")
    ev.rule("serving_waste_ratio",
            counter_share("serving_waste_total",
                          ("serving_goodput_tokens_total",
                           "serving_waste_total"), registry),
            warn=0.6, crit=0.95,
            description="wasted device tokens / all accounted device "
                        "tokens (goodput ledger): spec rejects, replay "
                        "re-prefill, padding rows, capacity drops")
    ev.rule("serving_decode_mbu_collapse",
            gauge_deficit("serving_mbu", registry, phase="decode"),
            warn=0.95, crit=0.99,
            description="1 - serving_mbu{decode}: decode is bandwidth-"
                        "bound at continuous-batching sizes, so MBU "
                        "below ~5% on real hardware means the tick is "
                        "nowhere near the HBM roof (skipped while MBU "
                        "reads 0.0 = undefined, e.g. off-TPU)")
    ev.rule("serving_kv_fragmentation",
            gauge_value("serving_kv_fragmentation", registry),
            warn=0.25, crit=0.6,
            description="window-recycling holes / (holes + live KV "
                        "table entries): high means block tables are "
                        "mostly None placeholders — capacity burned on "
                        "positions nothing will ever attend again")
    ev.rule("serving_kv_parked_ratio",
            kv_parked_ratio(registry),
            warn=0.9, crit=0.995,
            description="radix-parked blocks / KV pool size: near 1.0 "
                        "the whole pool is cache residue and every "
                        "admission pays an eviction walk (skipped while "
                        "PT_RADIX_CACHE=0 or before the pool gauges "
                        "exist)")
    ev.rule("serving_tick_host_p95_s",
            histogram_quantile("serving_tick_breakdown_seconds", 0.95,
                               registry, phase="host"),
            warn=0.25, crit=2.5,
            description="p95 host-bookkeeping share of an engine tick "
                        "(the tick-anatomy remainder after prefill/"
                        "draft/verify/sample device phases)")
    ev.rule("serving_degrade_level",
            gauge_value("serving_degrade_level", registry),
            warn=2, crit=4,
            description="degradation-ladder rung: L2+ is shrinking "
                        "prefill budgets, L4 rejects new sessions. NOTE "
                        "this rule reads the gauge the controller "
                        "writes — never feed THIS evaluator back into "
                        "DegradationController(health=...), or the rung "
                        "becomes its own input and latches")
    ev.rule("serving_slo_burn_rate",
            gauge_max("serving_slo_burn_rate", registry),
            warn=6.0, crit=14.4,
            description="hottest tenant/objective short-window SLO "
                        "error-budget burn multiple (1.0 = spending "
                        "exactly the budget): 6x is the tracker's slow-"
                        "burn gate, 14.4x its fast-burn page threshold")
    ev.rule("serving_slo_budget_spent",
            gauge_max("serving_slo_budget_remaining", registry,
                      deficit=True),
            warn=0.8, crit=1.0,
            description="worst tenant/objective fraction of the "
                        "compliance-window error budget already "
                        "consumed (1 - serving_slo_budget_remaining)")
    ev.rule("router_hedge_rate",
            gauge_value("router_hedge_rate", registry),
            warn=0.2, crit=0.6,
            description="hedged / successful KV handoffs (lifetime): "
                        "sustained hedging means a straggling decode "
                        "replica or transport link")
    return ev


HEALTH = install_default_rules(HealthEvaluator())
