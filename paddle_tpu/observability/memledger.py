"""Per-pool KV MEMORY ledger (ISSUE 13 tentpole): every physical block
classified into exactly one state, reconciled against the block manager
by construction.

The serving stack measures time (tick anatomy), tokens (goodput ledger)
and the FLOPs/bytes roofline — this module measures *where the memory
is*. Each :class:`~paddle_tpu.models.paged.BlockManager` owns one
:class:`MemLedger`; the manager's own mutation choke points
(``allocate``/``free``/``free_prefix``/``adopt_prefix``/``_evict_one``/
``take_copy_plan`` — a ``test_lint`` rule enforces the list) notify it
with primitive transitions (``table_enter``/``table_exit``/``park``/
``unpark``/``pin``/``unpin``), so every call path — engine admission,
beam forks, radix adoption, preemption, KV extract/install — is covered
without any engine-side bookkeeping. The ledger folds the transitions
into five mutually-exclusive states:

    active        block referenced by at least one live block table
    parked        radix/prefix-cache resident, rc == 0, matchable
    cow_pending   adopted COW source pinned until the fused copy drains
    reserved      promised by the reservation ledger but not yet held
                  (carved out of free first, then parked — a promise
                  can only be kept by reclaimable blocks)
    free          none of the above

with ``sum(states) == num_blocks`` an identity, not an aspiration:
:meth:`MemLedger.reconcile` independently re-walks the manager's
``tables``/``_pending``/``_parked``/``_free`` and must agree
block-for-block — the chaos suites assert it after every tick (the same
design as the goodput↔token-counter reconciliation).

On top of the ledger: ``serving_kv_blocks{state}`` / occupancy /
fragmentation / bytes-per-token gauges, Chrome-trace counter events
(``"ph": "C"`` — Perfetto renders pool occupancy-by-state over time
next to the tick spans), per-request peak-block attribution
(:meth:`take_peak` → ``req.trace_summary["kv_peak_blocks"]``),
admission-stall forensics (:meth:`record_stall` →
``serving_kv_stall_total{blocked_on}``), and the ``GET /memory`` httpd
document (:func:`memory_doc`) + flight-dump excerpt
(:func:`flight_excerpt`) over a weak registry of live pools.

``PT_MEM_LEDGER=0`` (checked at construction, per pool — the
RequestTracker pattern) turns every hook into one boolean read and
restores bit-identical serving behavior.
"""
from __future__ import annotations

import itertools
import os
import threading
import weakref
from collections import Counter, OrderedDict

from paddle_tpu.observability.metrics import METRICS
from paddle_tpu.observability.tracing import TRACER

__all__ = ["MemLedger", "pools", "memory_doc", "flight_excerpt"]

_KV_STATE = METRICS.gauge(
    "serving_kv_blocks",
    "physical KV-pool blocks by ledger state (active / parked / "
    "cow_pending / reserved / free); the five states sum to the pool "
    "size by construction", labelnames=("state",))
_KV_POOL = METRICS.gauge(
    "serving_kv_pool_blocks",
    "total physical blocks in the serving KV pool (the ledger's "
    "denominator)")
_KV_OCC = METRICS.gauge(
    "serving_kv_occupancy",
    "fraction of pool blocks holding resident KV (active + parked + "
    "cow_pending) / pool size")
_KV_FRAG = METRICS.gauge(
    "serving_kv_fragmentation",
    "window-recycling holes / (holes + live table entries): the share "
    "of block-table positions that are None placeholders")
_KV_PARKED_RATIO = METRICS.gauge(
    "serving_kv_parked_ratio",
    "radix/prefix-cache parked blocks / pool size (reclaimable cache "
    "residency)")
_KV_BPT = METRICS.gauge(
    "serving_kv_bytes_per_token",
    "HBM bytes held by active KV blocks per resident token (block-"
    "rounding overhead included) — the baseline quantized KV benches "
    "against")
_KV_STALL = METRICS.counter(
    "serving_kv_stall_total",
    "admissions blocked at the headroom gate, by which ledger state "
    "holds the missing blocks (active / reserved / cow_pending / "
    "slots / capacity)", labelnames=("blocked_on",))

# every live ledger, for /memory and flight-dump excerpts; weak so an
# engine's pool dies with the engine
_LEDGERS: "weakref.WeakSet[MemLedger]" = weakref.WeakSet()
_SEQ = itertools.count(1)

# per-request peak attribution survives table_drop (preemption must not
# reset a lifetime max) but beam groups mint fresh sids every tick, so
# the peak map is LRU-bounded instead of dropped at free
_PEAK_CAP = 4096


class MemLedger:
    """Per-pool block-state ledger. Hooks are called by the block
    manager's own mutation choke points; every hook is gated on one
    enabled-bool read (``PT_MEM_LEDGER=0`` → no-op)."""

    STATES = ("active", "parked", "cow_pending", "reserved", "free")

    def __init__(self, num_blocks: int, block_size: int,
                 enabled: bool = None):
        if enabled is None:
            enabled = os.environ.get("PT_MEM_LEDGER", "1") != "0"
        self._enabled = bool(enabled)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._seq_no = next(_SEQ)
        self._lock = threading.Lock()
        self._table_refs: dict[int, int] = {}   # blk -> live table entries
        self._pin_refs: dict[int, int] = {}     # blk -> pending-COW src pins
        self._parked: set[int] = set()
        self._reserved = 0                      # mirror of KVManager.reserved
        self._req_live: dict = {}               # seq_id -> live table entries
        self._req_holes: dict = {}              # seq_id -> None placeholders
        self._req_peak: OrderedDict = OrderedDict()   # seq_id -> peak live
        self._live_total = 0                    # Σ live entries (all tables)
        self._holes_total = 0                   # Σ holes (all tables)
        self.stall_counts: dict[str, int] = {}  # blocked_on -> stalls
        self.peak_states = dict.fromkeys(self.STATES, 0)   # per-publish max
        self.bytes_per_token = 0.0
        self.peak_bytes_per_token = 0.0
        _LEDGERS.add(self)

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -------------------------------------------------- manager hooks
    # (each is one bool read when disabled — the kill-switch contract)
    def table_enter(self, seq_id, blk: int):
        """A block became (one more) live entry of ``seq_id``'s table."""
        if not self._enabled:
            return
        with self._lock:
            self._table_refs[blk] = self._table_refs.get(blk, 0) + 1
            self._live_total += 1
            live = self._req_live.get(seq_id, 0) + 1
            self._req_live[seq_id] = live
            if live > self._req_peak.get(seq_id, 0):
                self._req_peak[seq_id] = live
            self._req_peak.move_to_end(seq_id)
            while len(self._req_peak) > _PEAK_CAP:
                self._req_peak.popitem(last=False)

    def table_exit(self, seq_id, blk: int, hole: bool = False):
        """A table entry left ``seq_id``'s table; ``hole=True`` when the
        position stays behind as a None placeholder (window recycling)."""
        if not self._enabled:
            return
        with self._lock:
            n = self._table_refs.get(blk, 0) - 1
            if n > 0:
                self._table_refs[blk] = n
            else:
                self._table_refs.pop(blk, None)
            self._live_total -= 1
            self._req_live[seq_id] = self._req_live.get(seq_id, 1) - 1
            if hole:
                self._holes_total += 1
                self._req_holes[seq_id] = self._req_holes.get(seq_id, 0) + 1

    def table_drop(self, seq_id):
        """``seq_id``'s table is gone — retire its holes and live count
        (the peak survives: preemption/replay must not reset it)."""
        if not self._enabled:
            return
        with self._lock:
            self._req_live.pop(seq_id, None)
            self._holes_total -= self._req_holes.pop(seq_id, 0)

    def park(self, blk: int):
        if not self._enabled:
            return
        with self._lock:
            self._parked.add(blk)

    def unpark(self, blk: int):
        if not self._enabled:
            return
        with self._lock:
            self._parked.discard(blk)

    def pin(self, blk: int):
        """A pending-COW order pinned ``blk`` as its copy source."""
        if not self._enabled:
            return
        with self._lock:
            self._pin_refs[blk] = self._pin_refs.get(blk, 0) + 1

    def unpin(self, blk: int):
        if not self._enabled:
            return
        with self._lock:
            n = self._pin_refs.get(blk, 0) - 1
            if n > 0:
                self._pin_refs[blk] = n
            else:
                self._pin_refs.pop(blk, None)

    def set_reserved(self, n: int):
        """Mirror of the KVManager reservation count (blocks promised to
        in-flight requests but not yet materialised as table entries)."""
        if not self._enabled:
            return
        self._reserved = max(0, int(n))

    # ---------------------------------------------------------- reads
    def _classify_locked(self) -> dict:
        """The five-state breakdown from the transition mirrors.
        Precedence: a tabled block is active even while pinned (the COW
        source may still be live in its writer's table); a pinned block
        is cow_pending even while parked-by-history. ``reserved`` is a
        COUNT, not identified blocks — carved out of free first, then
        parked (both are what an unheld promise would be kept with), so
        the five states always sum to num_blocks."""
        active = len(self._table_refs)
        pinned = sum(1 for b in self._pin_refs if b not in self._table_refs)
        parked = sum(1 for b in self._parked
                     if b not in self._table_refs
                     and b not in self._pin_refs)
        free_raw = self.num_blocks - active - pinned - parked
        resv = max(0, min(self._reserved, free_raw + parked))
        r_free = min(resv, free_raw)
        r_parked = resv - r_free
        return {"active": active, "parked": parked - r_parked,
                "cow_pending": pinned, "reserved": resv,
                "free": free_raw - r_free}

    def counts(self) -> dict:
        """Current {state: blocks}; zeros while disabled."""
        if not self._enabled:
            return dict.fromkeys(self.STATES, 0)
        with self._lock:
            return self._classify_locked()

    def fragmentation(self) -> float:
        """Holes / (holes + live table entries) — the share of table
        positions window recycling left as None placeholders."""
        if not self._enabled:
            return 0.0
        with self._lock:
            denom = self._holes_total + self._live_total
            return self._holes_total / denom if denom else 0.0

    def take_peak(self, seq_id) -> int:
        """Pop and return ``seq_id``'s lifetime peak live-block count
        (0 when unknown). Works while disabled so finish paths can
        always call it for cleanup."""
        with self._lock:
            return self._req_peak.pop(seq_id, 0)

    def describe(self) -> str:
        """One-line state breakdown for assertion messages."""
        if not self._enabled:
            return "disabled (PT_MEM_LEDGER=0)"
        c = self.counts()
        body = " ".join(f"{s}={c[s]}" for s in self.STATES)
        return f"{body} (of {self.num_blocks})"

    def snapshot(self) -> dict:
        """JSON-safe pool document (/memory, flight dumps)."""
        c = self.counts()
        with self._lock:
            holders = sorted(self._req_live.items(),
                             key=lambda kv: -kv[1])[:8]
            top = [{"seq_id": str(s), "live": n,
                    "peak": self._req_peak.get(s, n)} for s, n in holders]
            stalls = dict(self.stall_counts)
        return {"pool": self._seq_no, "enabled": self._enabled,
                "num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "states": c, "reserved_promised": self._reserved,
                "fragmentation": round(self.fragmentation(), 6),
                "bytes_per_token": round(self.bytes_per_token, 3),
                "stalls": stalls, "top_holders": top}

    def flight_fields(self) -> dict:
        """kwargs for ``FLIGHT.record`` at alloc-failure/leak sites."""
        return {"states": self.counts(), "num_blocks": self.num_blocks,
                "reserved_promised": self._reserved,
                "fragmentation": round(self.fragmentation(), 6)}

    # ------------------------------------------------ stall forensics
    def record_stall(self, need: int, slots_short: bool = False):
        """An admission was blocked: attribute the missing blocks to the
        state holding them — the largest of active/reserved/cow_pending
        (parked and free blocks never block an admission: both count as
        free_blocks). ``slots_short`` marks a slot-limited (not block-
        limited) stall; an all-idle pool that is simply too small is
        ``capacity``."""
        if not self._enabled:
            return
        if slots_short:
            label = "slots"
        else:
            c = self.counts()
            holders = [(s, c[s]) for s in ("active", "reserved",
                                           "cow_pending")]
            label = (max(holders, key=lambda kv: kv[1])[0]
                     if any(v for _, v in holders) else "capacity")
        _KV_STALL.inc(blocked_on=label)
        with self._lock:
            self.stall_counts[label] = self.stall_counts.get(label, 0) + 1

    # --------------------------------------------------------- publish
    def publish(self, bytes_per_block: int = None,
                resident_tokens: int = None):
        """Fold the current breakdown into the gauges, the per-state
        peaks (bench columns), and a Chrome-trace counter event ("C") —
        Perfetto stacks the five series into an occupancy-by-state track
        next to the serving.step spans."""
        if not self._enabled:
            return
        c = self.counts()
        for s, v in c.items():
            _KV_STATE.set(v, state=s)
            if v > self.peak_states[s]:
                self.peak_states[s] = v
        _KV_POOL.set(self.num_blocks)
        _KV_OCC.set((c["active"] + c["parked"] + c["cow_pending"])
                    / max(self.num_blocks, 1))
        _KV_FRAG.set(self.fragmentation())
        _KV_PARKED_RATIO.set(c["parked"] / max(self.num_blocks, 1))
        if bytes_per_block:
            bpt = (c["active"] * bytes_per_block / resident_tokens
                   if resident_tokens else 0.0)
            _KV_BPT.set(bpt)
            self.bytes_per_token = bpt
            if bpt > self.peak_bytes_per_token:
                self.peak_bytes_per_token = bpt
        TRACER.counter("serving_kv_blocks",
                       **{s: float(v) for s, v in c.items()})

    # ------------------------------------------------- reconciliation
    def reconcile(self, mgr, reserved: int = None) -> dict:
        """Independently re-walk the block manager and diff it against
        the transition mirrors, block-for-block: table refs vs
        ``mgr.tables``, COW pins vs live ``mgr._pending`` orders, the
        parked set vs ``mgr._parked`` (radix) / ``mgr._evictable``
        (flat), and the raw free list vs the complement of all of the
        above. Then re-derive the five-state breakdown from the walk and
        require it to equal :meth:`counts` with ``sum == num_blocks``.
        Returns ``{"ok", "diffs", "counts", "walk"}``."""
        if not self._enabled:
            return {"ok": True, "skipped": True, "diffs": [],
                    "counts": self.counts(), "walk": None}
        diffs = []
        truth_tables: Counter = Counter()
        for t in mgr.tables.values():
            for b in t:
                if b is not None:
                    truth_tables[b] += 1
        truth_pins = Counter(e.src for e in getattr(mgr, "_pending", ())
                             if not e.dead)
        if hasattr(mgr, "_parked"):
            truth_parked = set(mgr._parked)
        elif hasattr(mgr, "_evictable"):
            truth_parked = set(mgr._evictable)
        else:
            truth_parked = set()
        with self._lock:
            led_tables = dict(self._table_refs)
            led_pins = dict(self._pin_refs)
            led_parked = set(self._parked)
            led_reserved = self._reserved
        for blk in sorted(set(truth_tables) | set(led_tables)):
            a, b = truth_tables.get(blk, 0), led_tables.get(blk, 0)
            if a != b:
                diffs.append(f"block {blk}: {a} table entries in the "
                             f"manager, {b} in the ledger")
        for blk in sorted(set(truth_pins) | set(led_pins)):
            a, b = truth_pins.get(blk, 0), led_pins.get(blk, 0)
            if a != b:
                diffs.append(f"block {blk}: {a} live COW pins in the "
                             f"manager, {b} in the ledger")
        for blk in sorted(truth_parked ^ led_parked):
            where = "manager" if blk in truth_parked else "ledger"
            diffs.append(f"block {blk}: parked only in the {where}")
        free = list(mgr._free)
        if len(free) != len(set(free)):
            diffs.append("free list contains duplicate blocks")
        expected_free = (set(range(self.num_blocks)) - set(truth_tables)
                         - set(truth_pins) - truth_parked)
        for blk in sorted(set(free) ^ expected_free):
            where = ("free list" if blk in set(free)
                     else "unaccounted (neither tabled, pinned, parked, "
                          "nor free)")
            diffs.append(f"block {blk}: {where}")
        if reserved is not None and led_reserved != max(0, reserved):
            diffs.append(f"reservation mirror: manager promises "
                         f"{reserved}, ledger mirrors {led_reserved}")
        # re-derive the published breakdown from the walk (same
        # precedence + reserved carve-out as _classify_locked)
        w_active = len(truth_tables)
        w_pinned = len(set(truth_pins) - set(truth_tables))
        w_parked = len(truth_parked - set(truth_tables) - set(truth_pins))
        w_free_raw = self.num_blocks - w_active - w_pinned - w_parked
        w_resv = max(0, min(led_reserved if reserved is None
                            else max(0, reserved),
                            w_free_raw + w_parked))
        w_r_free = min(w_resv, w_free_raw)
        walk = {"active": w_active, "parked": w_parked - (w_resv - w_r_free),
                "cow_pending": w_pinned, "reserved": w_resv,
                "free": w_free_raw - w_r_free}
        counts = self.counts()
        if walk != counts:
            diffs.append(f"state breakdown: walk {walk} != ledger {counts}")
        if sum(counts.values()) != self.num_blocks:
            diffs.append(f"sum(states) = {sum(counts.values())} != "
                         f"num_blocks = {self.num_blocks}")
        return {"ok": not diffs, "diffs": diffs[:20], "counts": counts,
                "walk": walk}


# ------------------------------------------------------- pool registry
def pools() -> list:
    """Live ledgers, oldest pool first."""
    return sorted(_LEDGERS, key=lambda led: led._seq_no)


def memory_doc() -> dict:
    """The ``GET /memory`` document: every live pool's snapshot plus
    per-device HBM stats (zeroed placeholders off-accelerator)."""
    doc = {"pools": [led.snapshot() for led in pools()]}
    try:
        from paddle_tpu.utils.profiler import device_memory_stats
        doc["device"] = device_memory_stats()
    except Exception as e:          # jax may be unimportable here
        doc["device"] = {"error": f"{type(e).__name__}: {e}"}
    return doc


def flight_excerpt() -> list:
    """What flight dumps embed on alloc failure / quiescence violation:
    the newest few pools' snapshots (dump paths must stay cheap)."""
    return [led.snapshot() for led in pools()[-4:]]
