"""``/metrics`` pull endpoint (ROADMAP open item; ISSUE 3 satellite).

A stdlib-only ``http.server`` running on a daemon thread, exposing the
process-global registry the way a Prometheus scraper expects:

  * ``GET /metrics``       → text exposition format 0.0.4
  * ``GET /metrics.json``  → the one-line JSON snapshot
  * ``GET /healthz``       → HEALTH.evaluate() JSON; HTTP 503 on CRIT so
    a TCP/status-code health checker needs zero JSON parsing
  * ``GET /flight``        → the flight recorder's current ring as JSON
  * ``GET /requests``      → the request tracker's recent per-request
    timelines + summaries (ISSUE 9); empty lists while tracking is off
  * ``GET /roofline``      → the serving roofline ledger's per-phase
    MFU/MBU/intensity reports + the machine roofs (ISSUE 12)
  * ``GET /memory``        → every live KV pool's memory-ledger snapshot
    (blocks by state, fragmentation, stalls, top holders) plus the
    per-device HBM stats (ISSUE 13)
  * ``GET /slo``           → every live SLO tracker's objectives, per-
    tenant burn rates / budget remaining and recent breaches (ISSUE 19)
  * ``GET /tenants``       → the usage-metering cost ledger: per-tenant
    device-seconds, KV block-seconds and goodput/waste/saved tokens
  * ``GET /profile?seconds=N`` → run ONE ``jax.profiler`` trace capture
    of N seconds (0 < N <= 600) into ``PT_PROFILE_DIR`` (default
    ``pt_profile``); 400 on a missing/bad ``seconds``, 409 while a
    capture is already running — at most one capture at a time
  * anything else          → 404

Usage::

    from paddle_tpu.observability import start_metrics_server
    srv = start_metrics_server(port=9100)    # port=0 picks a free port
    ...                                      # scrape http://host:srv.port/metrics
    srv.stop()

``start_metrics_server``/``stop_metrics_server`` also manage one
module-level default server so a training script can expose metrics in
two lines and not hold a handle. The serving thread is named
``pt-metrics-http`` (the test suite's leak fixture reaps strays).
"""
from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs

from paddle_tpu.observability.metrics import METRICS

__all__ = ["MetricsServer", "start_metrics_server", "stop_metrics_server"]

_PROM_CTYPE = "text/plain; version=0.0.4; charset=utf-8"

# one device-profiler capture at a time, process-wide: concurrent
# start_trace calls would corrupt each other's TraceMe nesting
_PROFILE_LOCK = threading.Lock()
_PROFILE_MAX_SECONDS = 600.0


def _run_profile_capture(seconds: float) -> dict:
    """One guarded ``jax.profiler`` capture into ``PT_PROFILE_DIR``.
    jax imports lazily — the metrics server itself must stay usable in
    processes that never touch a device."""
    out_dir = os.environ.get("PT_PROFILE_DIR", "pt_profile")
    import jax
    jax.profiler.start_trace(out_dir)
    try:
        time.sleep(seconds)
    finally:
        jax.profiler.stop_trace()
    return {"dir": out_dir, "seconds": seconds}


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0]
        status = 200
        if path == "/metrics":
            body = METRICS.to_prometheus().encode()
            ctype = _PROM_CTYPE
        elif path == "/metrics.json":
            body = (METRICS.to_json() + "\n").encode()
            ctype = "application/json"
        elif path == "/healthz":
            from paddle_tpu.observability.health import HEALTH
            report = HEALTH.evaluate()
            if report["status"] == "CRIT":
                status = 503
            body = (json.dumps(report, sort_keys=True) + "\n").encode()
            ctype = "application/json"
        elif path == "/flight":
            from paddle_tpu.observability.flight import FLIGHT
            doc = {"last_step": FLIGHT.last_step,
                   "capacity": FLIGHT.capacity,
                   "total_recorded": FLIGHT.total_recorded,
                   "events": FLIGHT.events()}
            body = (json.dumps(doc, sort_keys=True) + "\n").encode()
            ctype = "application/json"
        elif path == "/requests":
            from paddle_tpu.observability.requests import REQUESTS
            body = (json.dumps(REQUESTS.to_doc(), sort_keys=True)
                    + "\n").encode()
            ctype = "application/json"
        elif path == "/roofline":
            from paddle_tpu.observability.roofline import (
                serving_roofline_report)
            body = (json.dumps(serving_roofline_report(), sort_keys=True)
                    + "\n").encode()
            ctype = "application/json"
        elif path == "/memory":
            from paddle_tpu.observability.memledger import memory_doc
            body = (json.dumps(memory_doc(), sort_keys=True)
                    + "\n").encode()
            ctype = "application/json"
        elif path == "/slo":
            from paddle_tpu.observability.slo import slo_doc
            body = (json.dumps(slo_doc(), sort_keys=True) + "\n").encode()
            ctype = "application/json"
        elif path == "/tenants":
            from paddle_tpu.observability.slo import tenants_doc
            body = (json.dumps(tenants_doc(), sort_keys=True)
                    + "\n").encode()
            ctype = "application/json"
        elif path == "/profile":
            qs = parse_qs(self.path.partition("?")[2])
            raw = qs.get("seconds", [None])[0]
            try:
                seconds = float(raw)
            except (TypeError, ValueError):
                self.send_error(
                    400, "need /profile?seconds=N with numeric N")
                return
            if not 0.0 < seconds <= _PROFILE_MAX_SECONDS:
                self.send_error(
                    400, f"seconds must be in (0, "
                         f"{_PROFILE_MAX_SECONDS:.0f}], got {raw}")
                return
            if not _PROFILE_LOCK.acquire(blocking=False):
                self.send_error(
                    409, "a profiler capture is already running")
                return
            try:
                doc = _run_profile_capture(seconds)
            except Exception as e:     # noqa: BLE001 — report, don't die
                self.send_error(
                    500, f"profiler capture failed: "
                         f"{type(e).__name__}: {e}")
                return
            finally:
                _PROFILE_LOCK.release()
            body = (json.dumps(doc, sort_keys=True) + "\n").encode()
            ctype = "application/json"
        else:
            self.send_error(
                404, "try /metrics, /metrics.json, /healthz, /flight, "
                     "/requests, /roofline, /memory, /slo, /tenants or "
                     "/profile?seconds=N")
            return
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):   # scrapes must not spam stderr
        pass


class MetricsServer:
    """One bound listener + one daemon serve thread. ``port=0`` binds an
    ephemeral port; read it back from :attr:`port` (useful in tests and
    when several trainers share a host)."""

    def __init__(self, port: int = 9100, host: str = "0.0.0.0"):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pt-metrics-http",
            kwargs={"poll_interval": 0.1}, daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        host = "127.0.0.1" if self.host == "0.0.0.0" else self.host
        return f"http://{host}:{self.port}/metrics"

    def stop(self, timeout: float = 5.0):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


_default: Optional[MetricsServer] = None
_default_lock = threading.Lock()


def start_metrics_server(port: int = 9100, host: str = "0.0.0.0") -> MetricsServer:
    """Start (or return the already-running) module-default server."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsServer(port=port, host=host)
        return _default


def stop_metrics_server():
    """Stop the module-default server, if one is running."""
    global _default
    with _default_lock:
        srv, _default = _default, None
    if srv is not None:
        srv.stop()
