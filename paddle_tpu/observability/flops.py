"""Peak-FLOPs table + MFU accounting — the ONE copy bench.py, the
Trainer, and ``utils.profiler.StepTimer`` all read (ISSUE 2 satellite:
bench.py used to carry its own table and recompute MFU ad hoc).

Import-light on purpose: bench.py's orchestrator process must never pull
in jax, so nothing at this module's top level may import jax (or the
``paddle_tpu`` root package — this file is reached via
``paddle_tpu.observability.flops`` only from contexts that already paid
that import, or standalone through sys.modules tricks bench does not
need: ``from paddle_tpu.observability import flops`` inside the worker).
"""
from __future__ import annotations

from paddle_tpu.observability.metrics import METRICS

__all__ = ["PEAK_BF16", "chip_peak_flops", "mfu", "record_throughput"]

# Peak dense bf16 FLOP/s per chip, by device_kind prefix. (The serving
# and training MFU numbers, bench.py's vs_baseline, and the profiler's
# StepTimer all divide by THIS table.)
PEAK_BF16 = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6": 918e12,
}


def chip_peak_flops(dev=None, kind: str = None) -> float:
    """Peak bf16 FLOP/s for a jax device (or an explicit ``device_kind``
    string). Unknown TPU kinds assume v5e-class; non-TPU backends (cpu
    debugging runs) return 0.0 — callers treat 0 peak as "MFU undefined"
    rather than dividing by a made-up number. An EMPTY kind earns the
    v5e assumption only when the platform says ``tpu``: a mock/unknown
    device with neither attribute must read 0.0, not a fabricated peak
    (ISSUE 12 satellite)."""
    platform = None
    if kind is None:
        kind = getattr(dev, "device_kind", "") or ""
        platform = getattr(dev, "platform", "") or ""
        if platform and platform != "tpu":
            return 0.0
    for k, v in PEAK_BF16.items():
        if kind.startswith(k) or k in kind:
            return v
    if "TPU" in kind.upper():
        return 197e12          # some TPU, kind string unrecognised
    if kind == "" and platform == "tpu":
        return 197e12          # TPU platform, no kind string exposed
    return 0.0


def mfu(tokens_per_sec: float, flops_per_token: float,
        peak_flops: float) -> float:
    """Model FLOPs utilisation; 0.0 when the peak is unknown."""
    if not peak_flops or not flops_per_token:
        return 0.0
    return tokens_per_sec * flops_per_token / peak_flops


_TOKENS_PER_SEC = METRICS.gauge(
    "train_tokens_per_sec", "training throughput, tokens/sec")
_MFU = METRICS.gauge(
    "train_mfu", "model FLOPs utilisation vs the chip peak-bf16 table")
_MFU_OVERLAP = METRICS.gauge(
    "train_mfu_overlap", "MFU with host time hidden behind in-flight "
    "device steps subtracted from the wall-clock denominator")


def record_throughput(tokens_per_sec: float, flops_per_token: float = 0.0,
                      peak_flops: float = 0.0, hidden_host_s: float = 0.0,
                      window_s: float = 0.0) -> float:
    """Single choke point for throughput/MFU accounting: computes MFU
    from the shared table's peak, sets the ``train_tokens_per_sec`` and
    ``train_mfu`` gauges, returns the (naive) MFU. Trainer, StepTimer,
    and bench.py all land here — there is exactly one FLOPs model.

    ``hidden_host_s``/``window_s`` enable the overlap-aware variant
    (ROADMAP leftover): the pipelined trainer measures how much host
    input/dispatch time rode in the shadow of in-flight device steps
    during the ``window_s``-second logging window; that time belongs to
    neither the device nor the critical path, so the overlap-aware MFU
    removes it from the denominator —
    ``mfu(tps * window / (window - hidden), ...)``. With no overlap
    information (sync loop, StepTimer, bench baseline) the overlap gauge
    mirrors the naive value, so the two series are always comparable."""
    m = mfu(tokens_per_sec, flops_per_token, peak_flops)
    if window_s > 0.0 and 0.0 < hidden_host_s < window_s:
        m_ov = mfu(tokens_per_sec * window_s / (window_s - hidden_host_s),
                   flops_per_token, peak_flops)
    else:
        m_ov = m
    _TOKENS_PER_SEC.set(tokens_per_sec)
    _MFU.set(m)
    _MFU_OVERLAP.set(m_ov)
    return m
