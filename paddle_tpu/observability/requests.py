"""Request-scoped tracing (ISSUE 9 tentpole): a bounded ring of
per-request lifecycle timelines across the serving cluster.

The metrics registry is aggregate and the tracer is thread-scoped, so
once the router fans one request over prefill and decode replicas
neither can answer "where did request 1234's latency go?". The
:class:`RequestTracker` records, per traced request, an ordered event
timeline — submitted → queued → dispatched{replica} → admitted →
prefill chunks → KV handoff (extract/ship/install) → decode ticks →
preempted/replayed → finished{reason} — stamped on the tracker's own
monotonic clock, plus cheap per-request counters for the per-token hot
path (sampled / spec-proposed / spec-committed tokens).

A trace id is minted at ``Router.add_request``/``LLMEngine.add_request``
the first time a request is submitted while tracking is enabled, and
rides the :class:`~paddle_tpu.serving.types.Request` object itself —
through the scheduler, KV manager, executor, and the ``KVTransfer``
seam (a :class:`KVPayload` carries its ``req``) — so no serving API
changes shape. Hop events ("dispatched", "kv_install", "finished")
additionally emit Chrome-trace flow events through the global
:data:`~paddle_tpu.observability.tracing.TRACER` keyed by the trace id
and pinned to per-replica named tracks, which is what stitches one
request's spans on different replicas into a single Perfetto arrow.

The global :data:`REQUESTS` starts DISABLED and disabled is a real
no-op path: every recording method returns after one bool read, no
trace ids are minted, and requests therefore carry ``trace_id=None``
so even call sites that don't pre-check ``REQUESTS.enabled`` fall
through immediately. The ring is bounded (oldest timeline evicted),
each timeline's event list is bounded (drops counted), and at finish a
JSON-safe summary (TTFT, queue wait, replicas visited, spec
acceptance, preemptions, TTFT breakdown) is computed once, attached to
``req.trace_summary``, and served verbatim by the ``/requests`` httpd
endpoint and the flight recorder's slowest/failed excerpt.

Import-light on purpose: stdlib + :mod:`tracing` only, so the flight
recorder can lazily embed excerpts without an import cycle.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict

from paddle_tpu.observability.tracing import TRACER

__all__ = ["REQUESTS", "RequestTracker"]

_DEFAULT_CAPACITY = 256          # timelines kept before eviction
_DEFAULT_EVENT_CAP = 128         # events per timeline before drops

# finish reasons that are a normal end of life; anything else ("timeout",
# "cancelled", "replica_death", ...) counts as failed in excerpts
_OK_REASONS = frozenset({"eos", "length", "beam"})


class _Timeline:
    """One request's bounded event list + hot-path counters."""

    __slots__ = ("trace_id", "req_id", "t0", "events", "dropped_events",
                 "counters", "replicas", "flow_open", "await_decode",
                 "done", "summary")

    def __init__(self, trace_id: int, req_id, t0: float):
        self.trace_id = trace_id
        self.req_id = req_id
        self.t0 = t0
        self.events: list = []            # {"t": rel_s, "kind": ..., **fields}
        self.dropped_events = 0
        self.counters = {"tokens_sampled": 0, "spec_proposed": 0,
                         "spec_accepted": 0, "spec_committed": 0,
                         "preemptions": 0, "requeues": 0}
        self.replicas: list = []          # visit order, deduped
        self.flow_open = False            # first hop emits flow "s", rest "t"
        self.await_decode = False         # set at kv_install, cleared at
        self.done = False                 # the first post-handoff token
        self.summary = None

    def first(self, kind: str):
        """t of the first event of ``kind`` (None when absent)."""
        for ev in self.events:
            if ev["kind"] == kind:
                return ev["t"]
        return None


class RequestTracker:
    """Bounded ring of request timelines. Thread-safe; every mutator is
    gated on one enabled-bool read so the disabled tracker costs nothing
    on the per-token path."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY,
                 event_cap: int = _DEFAULT_EVENT_CAP):
        if capacity < 1:
            raise ValueError(f"tracker capacity must be >= 1, got {capacity}")
        self._capacity = capacity
        self.event_cap = event_cap
        self._lines: OrderedDict = OrderedDict()   # trace_id -> _Timeline
        self._lock = threading.Lock()
        self._enabled = False
        self._ids = itertools.count(1)
        self.evicted = 0

    # ------------------------------------------------------------ admin
    def enable(self):
        self._enabled = True

    def disable(self):
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def capacity(self) -> int:
        return self._capacity

    def set_capacity(self, capacity: int):
        """Resize the ring, evicting oldest timelines if shrinking."""
        if capacity < 1:
            raise ValueError(f"tracker capacity must be >= 1, got {capacity}")
        with self._lock:
            self._capacity = capacity
            while len(self._lines) > capacity:
                self._lines.popitem(last=False)
                self.evicted += 1

    def clear(self):
        with self._lock:
            self._lines.clear()
            self.evicted = 0

    def __len__(self):
        with self._lock:
            return len(self._lines)

    # -------------------------------------------------------- recording
    def submit(self, req, source: str = "engine"):
        """Mint a trace id for ``req`` (idempotent — a request the router
        already traced is not re-minted by the engine) and open its
        timeline. Returns the trace id, or None while disabled."""
        if not self._enabled:
            return None
        tid = getattr(req, "trace_id", None)
        if tid is not None and self._has(tid):
            return tid                     # already tracked (router → engine)
        if tid is None:
            tid = next(self._ids)
            req.trace_id = tid
        line = _Timeline(tid, getattr(req, "req_id", None),
                         time.monotonic())
        line.events.append({"t": 0.0, "kind": "submitted", "source": source,
                            "prompt_tokens": int(len(req.prompt))})
        with self._lock:
            self._lines[tid] = line
            while len(self._lines) > self._capacity:
                self._lines.popitem(last=False)
                self.evicted += 1
        return tid

    def _has(self, tid) -> bool:
        with self._lock:
            return tid in self._lines

    def _line(self, req):
        tid = getattr(req, "trace_id", None)
        if tid is None:
            return None
        with self._lock:
            return self._lines.get(tid)

    def event(self, req, kind: str, **fields):
        """Append one timeline event. Fields must be JSON-safe (call
        sites pass ints/strs). Hop kinds additionally emit TRACER flow
        events so replica crossings stitch in the Chrome trace."""
        if not self._enabled:
            return
        line = self._line(req)
        if line is None:
            return
        t = time.monotonic() - line.t0
        replica = fields.get("replica")
        with self._lock:
            if replica is not None and replica not in line.replicas:
                line.replicas.append(replica)
            if kind == "preempted":
                line.counters["preemptions"] += 1
            elif kind == "requeued":
                line.counters["requeues"] += 1
            elif kind == "kv_install":
                line.await_decode = True
            if len(line.events) >= self.event_cap:
                line.dropped_events += 1
                return
            line.events.append({"t": round(t, 6), "kind": kind, **fields})
        if kind in ("dispatched", "kv_install"):
            phase = "t" if line.flow_open else "s"
            line.flow_open = True
            TRACER.flow("request", line.trace_id, phase, track=replica,
                        rid=line.req_id, kind=kind, replica=replica)

    def tokens(self, req, n: int = 1, spec_committed: int = 0):
        """Per-token hot path: counter bumps only, no event append —
        except the one "decode_resume" marker after a KV handoff, which
        closes the TTFT handoff/first-decode breakdown."""
        if not self._enabled:
            return
        line = self._line(req)
        if line is None:
            return
        with self._lock:
            line.counters["tokens_sampled"] += n
            line.counters["spec_committed"] += spec_committed
            resume = line.await_decode
            line.await_decode = False
        if resume:
            self.event(req, "decode_resume")

    def spec(self, req, proposed: int, accepted: int):
        """Per-spec-commit counter bumps (no event append)."""
        if not self._enabled:
            return
        line = self._line(req)
        if line is None:
            return
        with self._lock:
            line.counters["spec_proposed"] += proposed
            line.counters["spec_accepted"] += accepted

    def finish(self, req, reason: str, replica: str = None):
        """Record the terminal event, compute the summary once, attach
        it to ``req.trace_summary``, and close the flow arrow."""
        if not self._enabled:
            return
        line = self._line(req)
        if line is None or line.done:
            return
        self.event(req, "finished", reason=str(reason), replica=replica)
        with self._lock:
            line.done = True
            line.summary = self._summarize(line, reason)
        req.trace_summary = line.summary
        if line.flow_open:
            TRACER.flow("request", line.trace_id, "f", track=replica,
                        rid=line.req_id, reason=str(reason))

    # ------------------------------------------------------- summaries
    @staticmethod
    def _summarize(line: _Timeline, reason) -> dict:
        """TTFT breakdown from first-occurrence event times: queue =
        submitted→admitted, prefill = admitted→first token, handoff =
        kv_extract→kv_install (0 colocated), first-decode = install→
        first post-handoff token (0 colocated)."""
        t_end = line.events[-1]["t"] if line.events else 0.0
        t_adm = line.first("admitted")
        t_tok = line.first("first_token")
        t_ext = line.first("kv_extract")
        t_ins = line.first("kv_install")
        t_res = line.first("decode_resume")

        def _delta(a, b):
            return round(max(0.0, b - a), 6) if (a is not None and
                                                 b is not None) else 0.0

        c = line.counters
        proposed = c["spec_proposed"]
        # peak KV blocks over the request's whole life — max over the
        # kv_peak events its finish paths stamped (preemption replays and
        # multi-replica hops each stamp one)
        peaks = [ev.get("blocks", 0) for ev in line.events
                 if ev["kind"] == "kv_peak"]
        return {
            "trace_id": line.trace_id,
            "req_id": line.req_id,
            "finish_reason": str(reason),
            "ok": str(reason) in _OK_REASONS,
            "tokens": c["tokens_sampled"],
            "total_s": round(t_end, 6),
            "queue_wait_s": _delta(0.0, t_adm),
            "ttft_s": _delta(0.0, t_tok),
            "breakdown": {
                "queue_s": _delta(0.0, t_adm),
                "prefill_s": _delta(t_adm, t_tok),
                "handoff_s": _delta(t_ext, t_ins),
                "first_decode_s": _delta(t_ins, t_res),
            },
            "kv_peak_blocks": max(peaks) if peaks else None,
            "replicas": list(line.replicas),
            "preemptions": c["preemptions"],
            "requeues": c["requeues"],
            "spec_proposed": proposed,
            "spec_accepted": c["spec_accepted"],
            "spec_acceptance": (round(c["spec_accepted"] / proposed, 6)
                                if proposed else None),
        }

    def _timeline_doc(self, line: _Timeline) -> dict:
        return {"trace_id": line.trace_id, "req_id": line.req_id,
                "done": line.done, "events": list(line.events),
                "dropped_events": line.dropped_events,
                "counters": dict(line.counters),
                "summary": line.summary}

    def timeline(self, trace_id) -> dict:
        """Full timeline doc for one trace id (None when evicted/unknown)."""
        with self._lock:
            line = self._lines.get(trace_id)
            return self._timeline_doc(line) if line is not None else None

    def summaries(self) -> list:
        """Summaries of finished timelines, oldest first."""
        with self._lock:
            return [line.summary for line in self._lines.values()
                    if line.summary is not None]

    def to_doc(self, timelines: int = 32) -> dict:
        """The ``/requests`` endpoint document: every tracked request's
        summary (or live progress) plus full timelines for the newest
        ``timelines`` of them."""
        with self._lock:
            lines = list(self._lines.values())
        reqs = []
        for line in lines:
            if line.summary is not None:
                reqs.append(line.summary)
            else:
                reqs.append({"trace_id": line.trace_id,
                             "req_id": line.req_id,
                             "finish_reason": None,
                             "tokens": line.counters["tokens_sampled"],
                             "replicas": list(line.replicas),
                             "events": len(line.events)})
        return {"enabled": self._enabled, "capacity": self._capacity,
                "tracked": len(lines), "evicted": self.evicted,
                "requests": reqs,
                "timelines": [self._timeline_doc(line)
                              for line in lines[-timelines:]]}

    def flight_excerpt(self, slowest: int = 3, failed: int = 5) -> dict:
        """What the flight recorder embeds in a dump: full timelines of
        the ``slowest`` finished requests (by total_s) and the newest
        ``failed`` ones (finish reason outside eos/length/beam)."""
        with self._lock:
            done = [line for line in self._lines.values()
                    if line.summary is not None]
        slow = sorted(done, key=lambda l: l.summary["total_s"],
                      reverse=True)[:slowest]
        bad = [line for line in done if not line.summary["ok"]][-failed:]
        return {"slowest": [self._timeline_doc(l) for l in slow],
                "failed": [self._timeline_doc(l) for l in bad]}


REQUESTS = RequestTracker()
