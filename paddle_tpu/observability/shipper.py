"""Periodic metrics-snapshot shipper (ROADMAP leftover; ISSUE 4).

The registry answers queries only while the process is alive and
someone is polling ``/metrics``. :class:`MetricsShipper` makes the
telemetry survive the process: a ``pt-metrics-shipper`` daemon thread
periodically appends one JSON line per snapshot to a size-capped
rotating ring of files on disk —

    <path>          newest lines
    <path>.1        previous segment
    ...
    <path>.<max_files-1>   oldest segment (deleted on the next rotation)

Each line carries the full snapshot PLUS per-series deltas of every
cumulative value (counters, histogram sums/counts) since the previous
ship, so a consumer can reconstruct rates from any single line without
the line before it — and a process restart (registry back to zero)
shows up as an empty ``deltas`` object instead of a negative rate.

Shipping must never take the host process down: the thread swallows
(and counts) per-ship errors and keeps going; ``stop()`` ships one
final snapshot so the tail of a run is on disk.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from paddle_tpu.observability.metrics import METRICS

__all__ = ["MetricsShipper", "start_metrics_shipper", "stop_metrics_shipper"]


class MetricsShipper:
    """One output ring + (optionally) one daemon ship thread."""

    def __init__(self, path: str, interval_s: float = 10.0,
                 max_bytes: int = 1 << 20, max_files: int = 3,
                 registry=None):
        if max_files < 1:
            raise ValueError(f"max_files must be >= 1, got {max_files}")
        self.path = str(path)
        self.interval_s = interval_s
        self.max_bytes = max_bytes
        self.max_files = max_files
        self._reg = registry if registry is not None else METRICS
        self._prev: Optional[dict] = None     # flat cumulative series
        self._prev_t: Optional[float] = None
        self._seq = 0
        self.shipped = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)

    # ------------------------------------------------------------- thread
    def start(self) -> "MetricsShipper":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="pt-metrics-shipper", daemon=True)
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self._ship_guarded()
        self._ship_guarded()      # final snapshot: the tail reaches disk

    def _ship_guarded(self):
        try:
            self.ship_now()
        except Exception:         # shipping never kills the host process
            self.errors += 1

    def stop(self, timeout: float = 5.0):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=timeout)

    def __enter__(self) -> "MetricsShipper":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # --------------------------------------------------------------- ship
    @staticmethod
    def _flat_cumulative(snap: dict) -> dict:
        """Every monotonically-increasing series as one flat dict —
        counters plus histogram sums/counts (gauges can go down, so they
        get no deltas)."""
        flat = dict(snap["counters"])
        for series, h in snap["histograms"].items():
            flat[series + "_sum"] = h["sum"]
            flat[series + "_count"] = h["count"]
        return flat

    def ship_now(self) -> dict:
        """Take one snapshot, append it as one JSONL line (rotating
        first when the current segment is over ``max_bytes``), and
        return the shipped record."""
        # HBM gauges must be fresh in every shipped snapshot — pull them
        # here rather than hoping an engine tick refreshed them recently
        # (jax may be unimportable in a metrics-only process: skip)
        try:
            from paddle_tpu.utils.profiler import device_memory_stats
            device_memory_stats()
        except Exception:
            pass
        snap = self._reg.snapshot()
        now = time.monotonic()
        flat = self._flat_cumulative(snap)
        deltas = {}
        if self._prev is not None:
            for k, v in flat.items():
                d = v - self._prev.get(k, 0.0)
                if d:
                    deltas[k] = d
        rec = {
            "seq": self._seq,
            "t_wall": time.time(),    # cross-process correlation timestamp
            "t_mono": now,
            "interval_s": (now - self._prev_t
                           if self._prev_t is not None else None),
            "snapshot": snap,
            "deltas": deltas,
        }
        self._seq += 1
        self._prev, self._prev_t = flat, now
        self._rotate_if_needed()
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True, separators=(",", ":"))
                    + "\n")
        self.shipped += 1
        return rec

    def _rotate_if_needed(self):
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size < self.max_bytes:
            return
        if self.max_files == 1:       # ring of one: rotation = truncation
            os.remove(self.path)
            return
        oldest = f"{self.path}.{self.max_files - 1}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.max_files - 1, 1, -1):
            src = f"{self.path}.{i - 1}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i}")
        os.replace(self.path, f"{self.path}.1")


_default: Optional[MetricsShipper] = None
_default_lock = threading.Lock()


def start_metrics_shipper(path: str, interval_s: float = 10.0,
                          **kw) -> MetricsShipper:
    """Start (or return the already-running) module-default shipper."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsShipper(path, interval_s=interval_s,
                                      **kw).start()
        return _default


def stop_metrics_shipper():
    """Stop the module-default shipper, if one is running."""
    global _default
    with _default_lock:
        shp, _default = _default, None
    if shp is not None:
        shp.stop()
