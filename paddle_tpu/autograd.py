"""Functional autograd API (ref: ``python/paddle/autograd/``:
``backward``-free functional surface — jacobian, hessian, jvp, vjp —
plus ``PyLayer`` for custom VJPs).

Thin re-exposure of JAX's tracing autodiff under the reference names.
Unlike the reference (tape-based double backward), everything here composes
with jit/vmap and compiles to one XLA program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["grad", "jacobian", "hessian", "jvp", "vjp", "vhp", "PyLayer",
           "no_grad"]

from paddle_tpu.jit import grad, no_grad  # noqa: F401  (reference namespace)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """Ref: paddle.autograd.jacobian — d func(xs) / d xs.

    xs may be one array or a tuple; returns the same structure of jacobians.
    """
    if isinstance(xs, (tuple, list)):
        return jax.jacobian(func, argnums=tuple(range(len(xs))))(*xs)
    return jax.jacobian(func)(xs)


def hessian(func, xs, create_graph=False, allow_unused=False):
    """Ref: paddle.autograd.hessian — d^2 func(xs) / d xs^2 (func scalar)."""
    if isinstance(xs, (tuple, list)):
        return jax.hessian(func, argnums=tuple(range(len(xs))))(*xs)
    return jax.hessian(func)(xs)


def jvp(func, xs, v=None):
    """Ref: paddle.incubate.autograd.jvp -> (func(xs), J @ v)."""
    xs = xs if isinstance(xs, (tuple, list)) else (xs,)
    if v is None:
        v = tuple(jnp.ones_like(x) for x in xs)
    v = v if isinstance(v, (tuple, list)) else (v,)
    out, tangent = jax.jvp(func, tuple(xs), tuple(v))
    return out, tangent


def vjp(func, xs, v=None):
    """Ref: paddle.incubate.autograd.vjp -> (func(xs), v @ J)."""
    xs = xs if isinstance(xs, (tuple, list)) else (xs,)
    out, pullback = jax.vjp(func, *xs)
    if v is None:
        v = jax.tree_util.tree_map(jnp.ones_like, out)
    grads = pullback(v)
    return out, grads if len(grads) > 1 else grads[0]


def vhp(func, xs, v=None):
    """vector-Hessian product: (func(xs), v @ H)."""
    xs_t = xs if isinstance(xs, (tuple, list)) else (xs,)
    if v is None:
        v = tuple(jnp.ones_like(x) for x in xs_t)
    v_t = v if isinstance(v, (tuple, list)) else (v,)
    g = jax.grad(func, argnums=tuple(range(len(xs_t))))
    out = func(*xs_t)
    _, hvp = jax.jvp(lambda *a: g(*a), tuple(xs_t), tuple(v_t))
    return out, hvp if len(hvp) > 1 else hvp[0]


class PyLayer:
    """Custom-VJP layer (ref: paddle.autograd.PyLayer).

    Subclass with static ``forward(ctx, *args)`` and
    ``backward(ctx, *grads)``; call via ``MyLayer.apply(*args)``.
    ``ctx.save_for_backward(*ts)`` stashes residuals.
    """

    class _Ctx:
        def __init__(self):
            self.saved = ()

        def save_for_backward(self, *ts):
            self.saved = ts

        def saved_tensor(self):
            return self.saved

    @classmethod
    def apply(cls, *args):
        @jax.custom_vjp
        def f(*xs):
            ctx = cls._Ctx()
            return cls.forward(ctx, *xs)

        def fwd(*xs):
            ctx = cls._Ctx()
            out = cls.forward(ctx, *xs)
            return out, ctx.saved

        def bwd(saved, g):
            ctx = cls._Ctx()
            ctx.saved = saved
            # multi-output forward -> tuple cotangent, unpacked per the
            # documented backward(ctx, *grads) signature
            grads = cls.backward(ctx, *g) if isinstance(g, tuple) \
                else cls.backward(ctx, g)
            return grads if isinstance(grads, tuple) else (grads,)

        f.defvjp(fwd, bwd)
        return f(*args)
