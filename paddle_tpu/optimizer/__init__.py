"""Optimizers (ref: ``python/paddle/optimizer/``).

Design: functional, optax-style. An optimizer owns no parameters; its state
is a pytree mirroring the param tree, so the whole (params, opt_state) pair
shards with the same PartitionSpecs — this is what makes ZeRO/GroupSharded
(paddle_tpu.distributed.sharded) fall out for free on the fsdp mesh axis.

Reference parity features kept:
  * ``multi_precision`` — fp32 master weights while params are bf16
    (ref: paddle.optimizer.AdamW(multi_precision=True))
  * ``grad_clip`` — ClipGradByValue / ByNorm / ByGlobalNorm objects
  * LRScheduler objects with ``step()``/``get_lr()``
  * param update API: ``opt.step(params, grads)`` returns new params
    (no in-place mutation under XLA; ``minimize`` drives value_and_grad).
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module, partition_trainable, value_and_grad
from paddle_tpu.optimizer.lr import (  # noqa: F401
    CosineAnnealingDecay,
    CyclicLR,
    ExponentialDecay,
    InverseTimeDecay,
    LambdaDecay,
    LinearWarmup,
    LRScheduler,
    MultiStepDecay,
    NaturalExpDecay,
    NoamDecay,
    OneCycleLR,
    PiecewiseDecay,
    PolynomialDecay,
    ReduceOnPlateau,
    StepDecay,
)

_FLOAT_TYPES = (jnp.float32, jnp.float16, jnp.bfloat16)


def _tree_map(f, *trees):
    return jax.tree_util.tree_map(
        f, *trees, is_leaf=lambda x: x is None)


def _map_params(f, params, *rest):
    """Map over float param leaves, passing through None / int leaves.
    A leaf whose companion (e.g. grad) is None — a non-trainable buffer —
    also passes through unchanged."""
    def g(p, *r):
        if p is None or not hasattr(p, "dtype") or not jnp.issubdtype(p.dtype, jnp.floating):
            return p
        if any(x is None for x in r):
            # no grad (non-trainable buffer): keep the param AND its slot
            # values unchanged, matching f's (p_new, *slots_new) convention
            return p if len(r) <= 1 else (p,) + tuple(r[1:])
        return f(p, *r)
    return _tree_map(g, params, *rest)



def _pluck(pairs, i):
    """Extract element i from tuple-leaves produced by a multi-output update."""
    return jax.tree_util.tree_map(
        lambda x: x[i] if isinstance(x, tuple) else x, pairs,
        is_leaf=lambda x: x is None or isinstance(x, tuple))

# -- grad clipping (ref python/paddle/nn/clip.py) ---------------------------

class ClipGradByValue:
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, grads):
        return _map_params(lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm:
    """Per-tensor norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        def clip(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(n, 1e-12))
            return (g * scale).astype(g.dtype)
        return _map_params(clip, grads)


class ClipGradByGlobalNorm:
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        leaves = [g for g in jax.tree_util.tree_leaves(grads)
                  if hasattr(g, "dtype") and jnp.issubdtype(g.dtype, jnp.floating)]
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(gnorm, 1e-12))
        return _map_params(lambda g: (g * scale).astype(g.dtype), grads)


def global_norm(grads):
    leaves = [g for g in jax.tree_util.tree_leaves(grads)
              if hasattr(g, "dtype") and jnp.issubdtype(g.dtype, jnp.floating)]
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))


# -- base -------------------------------------------------------------------

class Optimizer:
    """State layout: dict of pytrees, each mirroring the param tree."""

    def __init__(self, learning_rate=0.001, grad_clip=None, weight_decay=0.0,
                 multi_precision=False, apply_decay_param_fun=None):
        self.learning_rate = learning_rate
        self.grad_clip = grad_clip
        self.weight_decay = weight_decay
        self.multi_precision = multi_precision
        # ref: AdamW(apply_decay_param_fun=...) — name-based decay masking
        self.apply_decay_param_fun = apply_decay_param_fun

    # -- state --------------------------------------------------------------
    def init(self, params) -> dict:
        state = {"step": jnp.zeros((), jnp.int32)}
        if not isinstance(self.learning_rate, LRScheduler):
            # the lr is STATE, not a Python constant: inside a jitted train
            # step it is a traced array, so set_lr(..., state) takes effect
            # immediately without recompiling the step (ref Optimizer.set_lr
            # semantics; a folded-in float would freeze after first compile)
            state["lr"] = jnp.asarray(float(self.learning_rate), jnp.float32)
        if self.multi_precision:
            # master copies ONLY for reduced-precision float params — an
            # fp32 "copy" via astype (or a passthrough leaf) would alias the
            # param buffer, which breaks donation (same buffer donated
            # twice) and wastes HBM
            state["master"] = _tree_map(
                lambda p: p.astype(jnp.float32)
                if (p is not None and hasattr(p, "dtype")
                    and jnp.issubdtype(p.dtype, jnp.floating)
                    and p.dtype != jnp.float32) else None, params)
        state.update(self._init_slots(params))
        return state

    def _init_slots(self, params) -> dict:
        return {}

    # -- lr -----------------------------------------------------------------
    def _lr(self, state):
        lr = self.learning_rate
        if isinstance(lr, LRScheduler):
            return lr.value_at(state["step"])
        if "lr" in state:
            return state["lr"]
        return jnp.asarray(lr, jnp.float32)

    def set_lr(self, value, state=None):
        """Ref Optimizer.set_lr — override the current learning rate (only
        valid with a float lr, matching the reference's restriction).

        The lr lives in the optimizer state, so for a compiled train step
        pass that state and use the returned copy:
        ``state = opt.set_lr(3e-5, state)`` — the new value flows into the
        jitted step as data, no recompile. Called without ``state`` it
        updates future ``init()`` calls and the eager ``minimize`` state.
        """
        if isinstance(self.learning_rate, LRScheduler):
            raise RuntimeError(
                "set_lr is not allowed when the lr is an LRScheduler "
                "(reference behavior); mutate the scheduler instead")
        self.learning_rate = float(value)
        if state is not None:
            new = dict(state)
            new["lr"] = jnp.asarray(float(value), jnp.float32)
            return new
        if hasattr(self, "_eager_state") and "lr" in self._eager_state:
            self._eager_state["lr"] = jnp.asarray(float(value), jnp.float32)
        return None

    def get_lr(self, state=None):
        if isinstance(self.learning_rate, LRScheduler):
            if state is not None:
                return float(self.learning_rate.value_at(state["step"]))
            return self.learning_rate.get_lr()
        if state is not None and "lr" in state:
            return float(state["lr"])
        return self.learning_rate

    # -- update -------------------------------------------------------------
    def _owg_mask(self, params):
        """Bool tree marking overwrite-with-gradient leaves (fp8 delayed-
        scaling meta: their 'gradient' IS the new value). None when absent."""
        from paddle_tpu.amp.fp8 import FP8_META_MARKER
        from paddle_tpu.core.module import _path_to_str
        found = [False]

        def mark(path, leaf):
            hit = FP8_META_MARKER in _path_to_str(path)
            found[0] = found[0] or hit
            return hit

        mask = jax.tree_util.tree_map_with_path(
            mark, params, is_leaf=lambda x: x is None)
        return mask if found[0] else None

    def step(self, params, grads, state):
        """Returns (new_params, new_state). Pure — safe under jit/donation."""
        owg = self._owg_mask(params)
        owg_values = None
        if owg is not None:
            # fp8 meta leaves: stash the incoming "grads" (= new values),
            # zero them so clipping/update math never sees their magnitude,
            # and splice them into new_params at the end. (Meta tensors are
            # fp32 by construction, so no master-weight copy shadows them.)
            owg_values = grads
            grads = jax.tree_util.tree_map(
                lambda m, g: jnp.zeros_like(g) if (m and g is not None) else g,
                owg, grads, is_leaf=lambda x: x is None)
        if self.grad_clip is not None:
            grads = self.grad_clip(grads)
        lr = self._lr(state)
        if self.multi_precision:
            compute = _tree_map(
                lambda p, m: m if m is not None else p, params, state["master"])
        else:
            compute = params
        new_compute, new_state = self._update(compute, grads, state, lr)
        new_state["step"] = state["step"] + 1
        if self.multi_precision:
            # keep master only where one existed (non-fp32 params)
            new_state["master"] = _tree_map(
                lambda m, c: c if m is not None else None,
                state["master"], new_compute)
            new_params = _tree_map(
                lambda p, m, c: c if m is None else c.astype(p.dtype),
                params, state["master"], new_compute)
        else:
            new_params = new_compute
        if owg is not None:
            new_params = jax.tree_util.tree_map(
                lambda m, p, v: v if (m and v is not None) else p,
                owg, new_params, owg_values, is_leaf=lambda x: x is None)
        return new_params, new_state

    def _update(self, params, grads, state, lr):
        raise NotImplementedError

    # -- convenience: stateful eager API (reference ergonomics) -------------
    def minimize(self, loss_fn, module: Module, *args):
        if not hasattr(self, "_eager_state"):
            self._eager_state = self.init(module)
        loss, grads = value_and_grad(loss_fn)(module, *args)
        new_mod, self._eager_state = self.step(module, grads, self._eager_state)
        return loss, new_mod

    def _decay_mask(self, params):
        """weight-decay mask honouring apply_decay_param_fun (by param path)."""
        if self.apply_decay_param_fun is None:
            return None
        flat, treedef = jax.tree_util.tree_flatten_with_path(
            params, is_leaf=lambda x: x is None)
        from paddle_tpu.core.module import _path_to_str
        mask = [self.apply_decay_param_fun(_path_to_str(p)) for p, _ in flat]
        return jax.tree_util.tree_unflatten(treedef, mask)


# -- SGD / Momentum (ref sgd.py, momentum.py) -------------------------------

class SGD(Optimizer):
    def _update(self, params, grads, state, lr):
        def upd(p, g):
            u = g.astype(p.dtype)
            if self.weight_decay:
                u = u + self.weight_decay * p
            return (p - lr * u).astype(p.dtype)
        return _map_params(upd, params, grads), dict(state)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _init_slots(self, params):
        return {"velocity": _map_params(jnp.zeros_like, params)}

    def _update(self, params, grads, state, lr):
        mu = self.momentum

        def upd(p, g, v):
            g = g.astype(p.dtype)
            if self.weight_decay:
                g = g + self.weight_decay * p
            v_new = mu * v + g
            if self.use_nesterov:
                p_new = p - lr * (g + mu * v_new)
            else:
                p_new = p - lr * v_new
            return p_new.astype(p.dtype), v_new

        pairs = _map_params(lambda p, g, v: upd(p, g, v), params, grads, state["velocity"])
        return _pluck(pairs, 0), {**state, "velocity": _pluck(pairs, 1)}


# -- Adagrad / RMSProp / Adadelta -------------------------------------------

class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self.epsilon = epsilon
        self.init_acc = initial_accumulator_value

    def _init_slots(self, params):
        return {"moment": _map_params(
            lambda p: jnp.full_like(p, self.init_acc, dtype=jnp.float32), params)}

    def _update(self, params, grads, state, lr):
        def upd(p, g, m):
            g32 = g.astype(jnp.float32)
            m_new = m + g32 * g32
            p_new = p - lr * g32 / (jnp.sqrt(m_new) + self.epsilon)
            return p_new.astype(p.dtype), m_new

        pairs = _map_params(upd, params, grads, state["moment"])
        return _pluck(pairs, 0), {**state, "moment": _pluck(pairs, 1)}


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon, self.momentum, self.centered = rho, epsilon, momentum, centered

    def _init_slots(self, params):
        slots = {"mean_square": _map_params(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
                 "velocity": _map_params(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)}
        if self.centered:
            slots["mean_grad"] = _map_params(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return slots

    def _update(self, params, grads, state, lr):
        rho, eps, mu = self.rho, self.epsilon, self.momentum

        def upd(p, g, ms, v, mg=None):
            g32 = g.astype(jnp.float32)
            if self.weight_decay:
                g32 = g32 + self.weight_decay * p.astype(jnp.float32)
            ms_new = rho * ms + (1 - rho) * g32 * g32
            if self.centered:
                mg_new = rho * mg + (1 - rho) * g32
                denom = jnp.sqrt(ms_new - mg_new * mg_new + eps)
            else:
                mg_new = None
                denom = jnp.sqrt(ms_new + eps)
            v_new = mu * v + lr * g32 / denom
            return (p - v_new).astype(p.dtype), ms_new, v_new, mg_new

        if self.centered:
            pairs = _map_params(upd, params, grads, state["mean_square"],
                                state["velocity"], state["mean_grad"])
        else:
            pairs = _map_params(upd, params, grads, state["mean_square"], state["velocity"])
        get = lambda i: _pluck(pairs, i)
        new_state = {**state, "mean_square": get(1), "velocity": get(2)}
        if self.centered:
            new_state["mean_grad"] = get(3)
        return get(0), new_state


class Adadelta(Optimizer):
    def __init__(self, learning_rate=1.0, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon = rho, epsilon

    def _init_slots(self, params):
        z = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return {"avg_sq_grad": _map_params(z, params), "avg_sq_update": _map_params(z, params)}

    def _update(self, params, grads, state, lr):
        rho, eps = self.rho, self.epsilon

        def upd(p, g, asg, asu):
            g32 = g.astype(jnp.float32)
            asg_new = rho * asg + (1 - rho) * g32 * g32
            update = g32 * jnp.sqrt(asu + eps) / jnp.sqrt(asg_new + eps)
            asu_new = rho * asu + (1 - rho) * update * update
            return (p - lr * update).astype(p.dtype), asg_new, asu_new

        pairs = _map_params(upd, params, grads, state["avg_sq_grad"], state["avg_sq_update"])
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "avg_sq_grad": get(1), "avg_sq_update": get(2)}


# -- Adam family (ref adam.py / adamw.py / adamax.py / lamb.py) -------------

class Adam(Optimizer):
    decoupled_wd = False

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slots(self, params):
        z = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return {"moment1": _map_params(z, params), "moment2": _map_params(z, params)}

    def _update(self, params, grads, state, lr):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = state["step"].astype(jnp.float32) + 1.0
        bc1 = 1.0 - b1 ** t
        bc2 = 1.0 - b2 ** t
        mask = self._decay_mask(params)

        def upd(p, g, m, v, do_decay=True):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay and not self.decoupled_wd:
                g32 = g32 + self.weight_decay * p32
            m_new = b1 * m + (1 - b1) * g32
            v_new = b2 * v + (1 - b2) * g32 * g32
            update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if self.decoupled_wd and self.weight_decay and do_decay:
                update = update + self.weight_decay * p32
            return (p32 - lr * update).astype(p.dtype), m_new, v_new

        if mask is None:
            pairs = _map_params(upd, params, grads, state["moment1"], state["moment2"])
        else:
            pairs = _map_params(lambda p, g, m, v, dm: upd(p, g, m, v, dm),
                                params, grads, state["moment1"], state["moment2"], mask)
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "moment1": get(1), "moment2": get(2)}


class AdamW(Adam):
    """Decoupled weight decay (ref adamw.py). Default wd 0.01."""
    decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 weight_decay=0.01, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon,
                         weight_decay=weight_decay, **kw)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slots(self, params):
        z = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return {"moment": _map_params(z, params), "inf_norm": _map_params(z, params)}

    def _update(self, params, grads, state, lr):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = state["step"].astype(jnp.float32) + 1.0
        bc1 = 1.0 - b1 ** t

        def upd(p, g, m, u):
            g32 = g.astype(jnp.float32)
            m_new = b1 * m + (1 - b1) * g32
            u_new = jnp.maximum(b2 * u, jnp.abs(g32))
            p_new = p.astype(jnp.float32) - lr / bc1 * m_new / (u_new + eps)
            return p_new.astype(p.dtype), m_new, u_new

        pairs = _map_params(upd, params, grads, state["moment"], state["inf_norm"])
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "moment": get(1), "inf_norm": get(2)}


class Lamb(Optimizer):
    """Layer-wise adaptive moments for large-batch training (ref lamb.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-6,
                 lamb_weight_decay=0.01, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lamb_weight_decay = lamb_weight_decay

    def _init_slots(self, params):
        z = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return {"moment1": _map_params(z, params), "moment2": _map_params(z, params)}

    def _update(self, params, grads, state, lr):
        b1, b2, eps, wd = self.beta1, self.beta2, self.epsilon, self.lamb_weight_decay
        t = state["step"].astype(jnp.float32) + 1.0
        bc1, bc2 = 1.0 - b1 ** t, 1.0 - b2 ** t

        def upd(p, g, m, v):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1 - b1) * g32
            v_new = b2 * v + (1 - b2) * g32 * g32
            r = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps) + wd * p32
            w_norm = jnp.sqrt(jnp.sum(p32 * p32))
            r_norm = jnp.sqrt(jnp.sum(r * r))
            trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
            return (p32 - lr * trust * r).astype(p.dtype), m_new, v_new

        pairs = _map_params(upd, params, grads, state["moment1"], state["moment2"])
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "moment1": get(1), "moment2": get(2)}


class Lion(Optimizer):
    """Sign-momentum optimizer (ref paddle.incubate.optimizer). Half the
    optimizer memory of Adam — attractive on HBM-limited TPU training."""

    def __init__(self, learning_rate=1e-4, beta1=0.9, beta2=0.99, weight_decay=0.0, **kw):
        super().__init__(learning_rate, weight_decay=weight_decay, **kw)
        self.beta1, self.beta2 = beta1, beta2

    def _init_slots(self, params):
        return {"moment": _map_params(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)}

    def _update(self, params, grads, state, lr):
        b1, b2 = self.beta1, self.beta2

        def upd(p, g, m):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            update = jnp.sign(b1 * m + (1 - b1) * g32)
            if self.weight_decay:
                update = update + self.weight_decay * p32
            m_new = b2 * m + (1 - b2) * g32
            return (p32 - lr * update).astype(p.dtype), m_new

        pairs = _map_params(upd, params, grads, state["moment"])
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "moment": get(1)}


class Adafactor(Optimizer):
    """Factored-second-moment optimizer (Shazeer & Stern). The canonical
    low-memory choice for large TPU training runs: matrices keep row+col
    EMAs instead of a full second moment — O(r+c) slot memory vs Adam's
    O(r·c). (Reference capability: paddle.incubate optimizer family; this
    member is TPU-native rather than a port.)

    ``learning_rate=None`` enables the paper's relative-step schedule
    min(1e-2, 1/sqrt(t)) scaled by RMS(param).
    """

    def __init__(self, learning_rate=None, beta1=None, decay_rate=0.8,
                 epsilon1=1e-30, epsilon2=1e-3, clip_threshold=1.0,
                 scale_parameter=True, **kw):
        super().__init__(learning_rate if learning_rate is not None else 1.0, **kw)
        self.relative_step = learning_rate is None
        self.beta1 = beta1
        self.decay_rate = decay_rate
        self.eps1, self.eps2 = epsilon1, epsilon2
        self.clip_threshold = clip_threshold
        self.scale_parameter = scale_parameter

    @staticmethod
    def _factored(p):
        return p.ndim >= 2 and p.shape[-1] > 1 and p.shape[-2] > 1

    def _init_slots(self, params):
        def vr(p):
            if self._factored(p):
                return jnp.zeros(p.shape[:-1], jnp.float32)
            return jnp.zeros(p.shape, jnp.float32)  # full v for vectors

        def vc(p):
            if self._factored(p):
                return jnp.zeros(p.shape[:-2] + p.shape[-1:], jnp.float32)
            return jnp.zeros((1,), jnp.float32)     # unused placeholder

        slots = {"vr": _map_params(vr, params), "vc": _map_params(vc, params)}
        if self.beta1 is not None:
            slots["m"] = _map_params(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return slots

    def _update(self, params, grads, state, lr):
        t = state["step"].astype(jnp.float32) + 1.0
        rho = 1.0 - t ** (-self.decay_rate)
        eps1, eps2, d = self.eps1, self.eps2, self.clip_threshold
        ms = state.get("m")
        mask = self._decay_mask(params)

        def upd(p, g, vr, vc, m=None, do_decay=True):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            g2 = g32 * g32 + eps1
            if self._factored(p):
                vr_new = rho * vr + (1 - rho) * g2.mean(axis=-1)
                vc_new = rho * vc + (1 - rho) * g2.mean(axis=-2)
                # v̂_ij = vr_i vc_j / mean_i(vr) — rank-1 reconstruction
                denom = jnp.maximum(vr_new.mean(axis=-1, keepdims=True), eps1)
                u = g32 * jax.lax.rsqrt(
                    (vr_new / denom)[..., None] * vc_new[..., None, :] + eps1)
            else:
                vr_new = rho * vr + (1 - rho) * g2
                vc_new = vc
                u = g32 * jax.lax.rsqrt(vr_new + eps1)
            rms_u = jnp.sqrt(jnp.mean(u * u) + eps1)
            u = u / jnp.maximum(1.0, rms_u / d)
            if m is not None:
                b1 = self.beta1
                u = b1 * m + (1 - b1) * u
                m_new = u
            else:
                m_new = None
            if self.relative_step:
                step_lr = jnp.minimum(1e-2, 1.0 / jnp.sqrt(t))
            else:
                step_lr = lr
            if self.scale_parameter:
                step_lr = step_lr * jnp.maximum(eps2, jnp.sqrt(jnp.mean(p32 * p32)))
            if self.weight_decay and do_decay:
                p32 = p32 * (1.0 - step_lr * self.weight_decay)
            out = (p32 - step_lr * u).astype(p.dtype)
            return (out, vr_new, vc_new) if m_new is None else (out, vr_new, vc_new, m_new)

        if ms is not None and mask is not None:
            pairs = _map_params(lambda p, g, vr, vc, m, dm: upd(p, g, vr, vc, m, dm),
                                params, grads, state["vr"], state["vc"], ms, mask)
        elif ms is not None:
            pairs = _map_params(upd, params, grads, state["vr"], state["vc"], ms)
        elif mask is not None:
            pairs = _map_params(lambda p, g, vr, vc, dm: upd(p, g, vr, vc, None, dm),
                                params, grads, state["vr"], state["vc"], mask)
        else:
            pairs = _map_params(upd, params, grads, state["vr"], state["vc"])
        get = lambda i: _pluck(pairs, i)
        new_state = {**state, "vr": get(1), "vc": get(2)}
        if ms is not None:
            new_state["m"] = get(3)
        return get(0), new_state


class NAdam(Optimizer):
    """Adam with Nesterov momentum (ref nadam.py). Tracks the running
    product of the momentum-decay schedule mu_t in the state."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.momentum_decay = momentum_decay

    def _init_slots(self, params):
        z = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return {"moment1": _map_params(z, params),
                "moment2": _map_params(z, params),
                "mu_product": jnp.ones((), jnp.float32)}

    def _update(self, params, grads, state, lr):
        b1, b2, eps, psi = self.beta1, self.beta2, self.epsilon, self.momentum_decay
        t = state["step"].astype(jnp.float32) + 1.0
        mu_t = b1 * (1.0 - 0.5 * 0.96 ** (t * psi))
        mu_next = b1 * (1.0 - 0.5 * 0.96 ** ((t + 1.0) * psi))
        mu_prod = state["mu_product"] * mu_t
        mu_prod_next = mu_prod * mu_next
        bc2 = 1.0 - b2 ** t

        def upd(p, g, m, v):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay:
                g32 = g32 + self.weight_decay * p32
            m_new = b1 * m + (1 - b1) * g32
            v_new = b2 * v + (1 - b2) * g32 * g32
            m_hat = (mu_next * m_new / (1.0 - mu_prod_next)
                     + (1.0 - mu_t) * g32 / (1.0 - mu_prod))
            p_new = p32 - lr * m_hat / (jnp.sqrt(v_new / bc2) + eps)
            return p_new.astype(p.dtype), m_new, v_new

        pairs = _map_params(upd, params, grads, state["moment1"], state["moment2"])
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "moment1": get(1), "moment2": get(2),
                        "mu_product": mu_prod}


class RAdam(Optimizer):
    """Rectified Adam (ref radam.py): falls back to un-adapted momentum
    while the variance estimate is unreliable (rho_t <= 5); the branch is a
    traced ``where``, so the whole schedule stays one compiled program."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slots(self, params):
        z = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return {"moment1": _map_params(z, params),
                "moment2": _map_params(z, params)}

    def _update(self, params, grads, state, lr):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = state["step"].astype(jnp.float32) + 1.0
        bc1 = 1.0 - b1 ** t
        bc2 = 1.0 - b2 ** t
        rho_inf = 2.0 / (1.0 - b2) - 1.0
        rho_t = rho_inf - 2.0 * t * (b2 ** t) / bc2
        rect = jnp.sqrt(jnp.clip(
            ((rho_t - 4.0) * (rho_t - 2.0) * rho_inf)
            / jnp.maximum((rho_inf - 4.0) * (rho_inf - 2.0) * rho_t, eps),
            0.0))
        use_rect = rho_t > 5.0

        def upd(p, g, m, v):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay:
                g32 = g32 + self.weight_decay * p32
            m_new = b1 * m + (1 - b1) * g32
            v_new = b2 * v + (1 - b2) * g32 * g32
            m_hat = m_new / bc1
            adapted = rect * m_hat / (jnp.sqrt(v_new / bc2) + eps)
            p_new = p32 - lr * jnp.where(use_rect, adapted, m_hat)
            return p_new.astype(p.dtype), m_new, v_new

        pairs = _map_params(upd, params, grads, state["moment1"], state["moment2"])
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "moment1": get(1), "moment2": get(2)}


class ASGD(Optimizer):
    """Stochastic Average Gradient (ref asgd.py): keeps the last
    ``batch_num`` per-parameter gradients and steps with their mean. The
    history lives in a stacked leading axis; the rotating write is a
    ``dynamic_update_slice`` so it stays jit-compatible."""

    def __init__(self, learning_rate=0.001, batch_num=1, **kw):
        super().__init__(learning_rate, **kw)
        self.batch_num = batch_num

    def _init_slots(self, params):
        n = self.batch_num
        return {"d": _map_params(
                    lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
                "ys": _map_params(
                    lambda p: jnp.zeros((n,) + p.shape, jnp.float32), params)}

    def _update(self, params, grads, state, lr):
        n = self.batch_num
        idx = state["step"] % n

        def upd(p, g, d, ys):
            g32 = g.astype(jnp.float32)
            if self.weight_decay:
                g32 = g32 + self.weight_decay * p.astype(jnp.float32)
            y_old = jax.lax.dynamic_index_in_dim(ys, idx, 0, keepdims=False)
            d_new = d - y_old + g32
            ys_new = jax.lax.dynamic_update_index_in_dim(ys, g32, idx, 0)
            p_new = p.astype(jnp.float32) - lr * d_new / n
            return p_new.astype(p.dtype), d_new, ys_new

        pairs = _map_params(upd, params, grads, state["d"], state["ys"])
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "d": get(1), "ys": get(2)}


class Rprop(Optimizer):
    """Resilient backprop (ref rprop.py): per-element step sizes grown by
    ``eta+`` on sign agreement, shrunk by ``eta-`` on sign flip (update
    suppressed on flips). Full-batch method — sign logic is elementwise
    ``where``s, one fused XLA kernel per param."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 etas=(0.5, 1.2), **kw):
        super().__init__(learning_rate, **kw)
        self.lr_min, self.lr_max = learning_rate_range
        self.eta_minus, self.eta_plus = etas

    def _init_slots(self, params):
        lr0 = self.learning_rate if not isinstance(self.learning_rate, LRScheduler) \
            else self.learning_rate.get_lr()
        return {"prev_grad": _map_params(
                    lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
                "step_size": _map_params(
                    lambda p: jnp.full_like(p, lr0, dtype=jnp.float32), params)}

    def _update(self, params, grads, state, lr):
        def upd(p, g, gp, sz):
            g32 = g.astype(jnp.float32)
            sign = jnp.sign(g32 * gp)
            sz_new = jnp.clip(
                jnp.where(sign > 0, sz * self.eta_plus,
                          jnp.where(sign < 0, sz * self.eta_minus, sz)),
                self.lr_min, self.lr_max)
            g_eff = jnp.where(sign < 0, 0.0, g32)
            p_new = p.astype(jnp.float32) - jnp.sign(g_eff) * sz_new
            return p_new.astype(p.dtype), g_eff, sz_new

        pairs = _map_params(upd, params, grads, state["prev_grad"], state["step_size"])
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "prev_grad": get(1), "step_size": get(2)}


class LBFGS(Optimizer):
    """Limited-memory BFGS (ref lbfgs.py). Like the reference, an eager
    full-batch optimizer driven by a closure: ``minimize(loss_fn, module,
    *args)`` runs ``max_iter`` two-loop-recursion steps with Armijo
    backtracking line search. Params are flattened to one vector
    (``ravel_pytree``) so history is [m, n] — the value/grad evaluations
    are jitted; the tiny history algebra runs on host."""

    def __init__(self, learning_rate=1.0, max_iter=20, history_size=10,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 line_search_fn="armijo", **kw):
        super().__init__(learning_rate, **kw)
        self.max_iter = max_iter
        self.history_size = history_size
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.line_search_fn = line_search_fn

    def minimize(self, loss_fn, module, *args):
        from jax.flatten_util import ravel_pytree
        params, static = partition_trainable(module)
        x, unravel = ravel_pytree(
            _tree_map(lambda p: jnp.asarray(p, jnp.float32)
                      if p is not None and hasattr(p, "dtype") else p, params))

        def f(xv):
            from paddle_tpu.core.module import combine
            mod = combine(unravel(xv), static)
            return loss_fn(mod, *args)

        vg = jax.jit(jax.value_and_grad(f))
        loss, g = vg(x)
        s_hist, y_hist = [], []
        for _ in range(self.max_iter):
            if float(jnp.max(jnp.abs(g))) <= self.tolerance_grad:
                break
            # two-loop recursion
            q = g
            alphas = []
            for s, y in reversed(list(zip(s_hist, y_hist))):
                rho = 1.0 / jnp.vdot(y, s)
                a = rho * jnp.vdot(s, q)
                q = q - a * y
                alphas.append((a, rho))
            if s_hist:
                s, y = s_hist[-1], y_hist[-1]
                gamma = jnp.vdot(s, y) / jnp.vdot(y, y)
                q = gamma * q
            for (a, rho), (s, y) in zip(reversed(alphas), zip(s_hist, y_hist)):
                b = rho * jnp.vdot(y, q)
                q = q + (a - b) * s
            d = -q
            # Armijo backtracking
            t = float(self.learning_rate) if not isinstance(
                self.learning_rate, LRScheduler) else self.learning_rate.get_lr()
            gtd = float(jnp.vdot(g, d))
            for _ls in range(20):
                new_loss, new_g = vg(x + t * d)
                if float(new_loss) <= float(loss) + 1e-4 * t * gtd:
                    break
                t *= 0.5
            s_vec = t * d
            y_vec = new_g - g
            if float(jnp.max(jnp.abs(s_vec))) <= self.tolerance_change:
                x, loss, g = x + s_vec, new_loss, new_g
                break
            if float(jnp.vdot(s_vec, y_vec)) > 1e-10:
                s_hist.append(s_vec)
                y_hist.append(y_vec)
                if len(s_hist) > self.history_size:
                    s_hist.pop(0)
                    y_hist.pop(0)
            x, loss, g = x + s_vec, new_loss, new_g
        from paddle_tpu.core.module import combine
        new_params = unravel(x)
        cast = _tree_map(
            lambda p0, p: p.astype(p0.dtype)
            if p0 is not None and hasattr(p0, "dtype") else p0,
            params, new_params)
        return loss, combine(cast, static)


# -- incubate extras (ref python/paddle/incubate/optimizer/) -----------------

class LookAhead(Optimizer):
    """Ref: paddle.incubate.LookAhead — wraps an inner optimizer; every k
    steps the slow weights absorb the fast ones: slow += alpha*(fast-slow).
    Pure/jit-safe: the sync happens via a traced predicate."""

    def __init__(self, inner: Optimizer, alpha=0.5, k=5):
        super().__init__(learning_rate=inner.learning_rate)
        self.inner, self.alpha, self.k = inner, alpha, k

    def init(self, params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "inner": self.inner.init(params),
            # copy=True: an fp32 astype would alias the param buffer and
            # break donation (same-buffer-donated-twice)
            "slow": _map_params(
                lambda p: jnp.array(p, jnp.float32, copy=True), params),
        }

    def step(self, params, grads, state):
        fast, inner_state = self.inner.step(params, grads, state["inner"])
        la_step = state["step"] + 1
        sync = (la_step % self.k == 0)

        def merge(slow, f):
            if slow is None or f is None or not hasattr(f, "dtype") \
                    or not jnp.issubdtype(f.dtype, jnp.floating):
                return slow
            new_slow = slow + self.alpha * (f.astype(jnp.float32) - slow)
            return jnp.where(sync, new_slow, slow)

        new_slow = _tree_map(merge, state["slow"], fast)

        def pick(f, slow):
            if f is None or not hasattr(f, "dtype") \
                    or not jnp.issubdtype(f.dtype, jnp.floating):
                return f
            return jnp.where(sync, slow.astype(f.dtype), f)

        out = _tree_map(pick, fast, new_slow)
        # a multi_precision inner keeps its own fp32 master weights, and its
        # next step reads from THOSE — sync must land there too, or it is
        # overwritten immediately
        if getattr(self.inner, "multi_precision", False) and \
                "master" in inner_state:
            inner_state = {**inner_state, "master": _tree_map(
                lambda m, s: m if m is None or s is None
                else jnp.where(sync, s, m),
                inner_state["master"], new_slow)}
        return out, {"step": la_step, "inner": inner_state, "slow": new_slow}

    # see GradientMerge: lr state lives in the inner optimizer
    def set_lr(self, value, state=None):
        if state is not None:
            return {**state, "inner": self.inner.set_lr(value,
                                                        state["inner"])}
        self.inner.set_lr(value)
        self.learning_rate = self.inner.learning_rate
        return None

    def get_lr(self, state=None):
        return self.inner.get_lr(state["inner"] if state is not None
                                 else None)


class GradientMerge(Optimizer):
    """Ref: fleet ``DistributedStrategy.gradient_merge`` /
    ``paddle.incubate.optimizer.GradientMergeOptimizer`` — accumulate grads
    for ``k_steps`` calls and apply the inner optimizer once with the
    (averaged, when ``avg``) merged gradient. Pure/jit-safe: the inner step
    runs every call and a traced predicate selects whether its result or
    the unchanged params are kept, so the step has a single static shape."""

    def __init__(self, inner: Optimizer, k_steps: int = 1, avg: bool = True):
        super().__init__(learning_rate=inner.learning_rate)
        if k_steps < 1:
            raise ValueError(f"k_steps must be >= 1, got {k_steps}")
        self.inner, self.k_steps, self.avg = inner, int(k_steps), bool(avg)

    def init(self, params):
        if self._owg_mask(params) is not None:
            raise NotImplementedError(
                "GradientMerge cannot accumulate fp8 amax-history "
                "(overwrite-with-gradient) leaves — their 'gradient' is a "
                "value, not a summand; train fp8 without gradient_merge")
        # fp32 accumulators ONLY for float params (None elsewhere — a
        # passthrough leaf would alias the param buffer and break donation)
        return {"step": jnp.zeros((), jnp.int32),
                "inner": self.inner.init(params),
                "accum": _tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32)
                    if (p is not None and hasattr(p, "dtype")
                        and jnp.issubdtype(p.dtype, jnp.floating))
                    else None, params)}

    def step(self, params, grads, state):
        gm_step = state["step"] + 1
        apply_now = (gm_step % self.k_steps == 0)
        accum = _tree_map(
            lambda a, g: a if a is None or g is None
            else a + g.astype(jnp.float32), state["accum"], grads)
        scale = (1.0 / self.k_steps) if self.avg else 1.0
        merged = _tree_map(
            lambda a, g: g if a is None or g is None
            else (a * scale).astype(g.dtype), accum, grads)
        cand_params, cand_inner = self.inner.step(params, merged,
                                                 state["inner"])
        sel = lambda new, old: _tree_map(
            lambda n, o: n if n is None or o is None
            or not hasattr(n, "dtype") else jnp.where(apply_now, n, o),
            new, old)
        out_params = sel(cand_params, params)
        out_inner = sel(cand_inner, state["inner"])
        new_accum = _tree_map(
            lambda a: None if a is None
            else jnp.where(apply_now, jnp.zeros_like(a), a), accum)
        return out_params, {"step": gm_step, "inner": out_inner,
                            "accum": new_accum}

    # lr lives in the INNER optimizer's state — route there, or set_lr on
    # the wrapper would write a top-level "lr" nothing reads
    def set_lr(self, value, state=None):
        if state is not None:
            return {**state, "inner": self.inner.set_lr(value,
                                                        state["inner"])}
        self.inner.set_lr(value)
        self.learning_rate = self.inner.learning_rate
        return None

    def get_lr(self, state=None):
        return self.inner.get_lr(state["inner"] if state is not None
                                 else None)


class ExponentialMovingAverage:
    """Ref: paddle.incubate.ExponentialMovingAverage (functional flavour).

    shadow = ema.init(params); shadow = ema.update(shadow, params) each
    step; eval_params = ema.apply(shadow, params)."""

    def __init__(self, decay=0.999):
        self.decay = decay

    def init(self, params):
        # copy=True: see LookAhead.init — fp32 astype aliases the buffer
        return _map_params(
            lambda p: jnp.array(p, jnp.float32, copy=True), params)

    def update(self, shadow, params):
        d = self.decay

        def upd(s, p):
            if s is None or p is None or not hasattr(p, "dtype") \
                    or not jnp.issubdtype(p.dtype, jnp.floating):
                return s
            return d * s + (1 - d) * p.astype(jnp.float32)

        return _tree_map(upd, shadow, params)

    def apply(self, shadow, params):
        """Return params with EMA values (cast back to param dtypes)."""

        def pick(p, s):
            if p is None or s is None or not hasattr(p, "dtype") \
                    or not jnp.issubdtype(p.dtype, jnp.floating):
                return p
            return s.astype(p.dtype)

        return _tree_map(pick, params, shadow)
