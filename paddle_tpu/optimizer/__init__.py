"""Optimizers (ref: ``python/paddle/optimizer/``).

Design: functional, optax-style. An optimizer owns no parameters; its state
is a pytree mirroring the param tree, so the whole (params, opt_state) pair
shards with the same PartitionSpecs — this is what makes ZeRO/GroupSharded
(paddle_tpu.distributed.sharded) fall out for free on the fsdp mesh axis.

Reference parity features kept:
  * ``multi_precision`` — fp32 master weights while params are bf16
    (ref: paddle.optimizer.AdamW(multi_precision=True))
  * ``grad_clip`` — ClipGradByValue / ByNorm / ByGlobalNorm objects
  * LRScheduler objects with ``step()``/``get_lr()``
  * param update API: ``opt.step(params, grads)`` returns new params
    (no in-place mutation under XLA; ``minimize`` drives value_and_grad).
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module, partition_trainable, value_and_grad
from paddle_tpu.optimizer.lr import (  # noqa: F401
    CosineAnnealingDecay,
    CyclicLR,
    ExponentialDecay,
    InverseTimeDecay,
    LambdaDecay,
    LinearWarmup,
    LRScheduler,
    MultiStepDecay,
    NaturalExpDecay,
    NoamDecay,
    OneCycleLR,
    PiecewiseDecay,
    PolynomialDecay,
    ReduceOnPlateau,
    StepDecay,
)

_FLOAT_TYPES = (jnp.float32, jnp.float16, jnp.bfloat16)


def _tree_map(f, *trees):
    return jax.tree_util.tree_map(
        f, *trees, is_leaf=lambda x: x is None)


def _map_params(f, params, *rest):
    """Map over float param leaves, passing through None / int leaves.
    A leaf whose companion (e.g. grad) is None — a non-trainable buffer —
    also passes through unchanged."""
    def g(p, *r):
        if p is None or not hasattr(p, "dtype") or not jnp.issubdtype(p.dtype, jnp.floating):
            return p
        if any(x is None for x in r):
            # no grad (non-trainable buffer): keep the param AND its slot
            # values unchanged, matching f's (p_new, *slots_new) convention
            return p if len(r) <= 1 else (p,) + tuple(r[1:])
        return f(p, *r)
    return _tree_map(g, params, *rest)



def _pluck(pairs, i):
    """Extract element i from tuple-leaves produced by a multi-output update."""
    return jax.tree_util.tree_map(
        lambda x: x[i] if isinstance(x, tuple) else x, pairs,
        is_leaf=lambda x: x is None or isinstance(x, tuple))

# -- grad clipping (ref python/paddle/nn/clip.py) ---------------------------

class ClipGradByValue:
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, grads):
        return _map_params(lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm:
    """Per-tensor norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        def clip(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(n, 1e-12))
            return (g * scale).astype(g.dtype)
        return _map_params(clip, grads)


class ClipGradByGlobalNorm:
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        leaves = [g for g in jax.tree_util.tree_leaves(grads)
                  if hasattr(g, "dtype") and jnp.issubdtype(g.dtype, jnp.floating)]
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(gnorm, 1e-12))
        return _map_params(lambda g: (g * scale).astype(g.dtype), grads)


def global_norm(grads):
    leaves = [g for g in jax.tree_util.tree_leaves(grads)
              if hasattr(g, "dtype") and jnp.issubdtype(g.dtype, jnp.floating)]
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))


# -- base -------------------------------------------------------------------

class Optimizer:
    """State layout: dict of pytrees, each mirroring the param tree."""

    def __init__(self, learning_rate=0.001, grad_clip=None, weight_decay=0.0,
                 multi_precision=False, apply_decay_param_fun=None):
        self.learning_rate = learning_rate
        self.grad_clip = grad_clip
        self.weight_decay = weight_decay
        self.multi_precision = multi_precision
        # ref: AdamW(apply_decay_param_fun=...) — name-based decay masking
        self.apply_decay_param_fun = apply_decay_param_fun

    # -- state --------------------------------------------------------------
    def init(self, params) -> dict:
        state = {"step": jnp.zeros((), jnp.int32)}
        if self.multi_precision:
            # master copies ONLY for reduced-precision float params — an
            # fp32 "copy" via astype (or a passthrough leaf) would alias the
            # param buffer, which breaks donation (same buffer donated
            # twice) and wastes HBM
            state["master"] = _tree_map(
                lambda p: p.astype(jnp.float32)
                if (p is not None and hasattr(p, "dtype")
                    and jnp.issubdtype(p.dtype, jnp.floating)
                    and p.dtype != jnp.float32) else None, params)
        state.update(self._init_slots(params))
        return state

    def _init_slots(self, params) -> dict:
        return {}

    # -- lr -----------------------------------------------------------------
    def _lr(self, state):
        lr = self.learning_rate
        if isinstance(lr, LRScheduler):
            return lr.value_at(state["step"])
        return jnp.asarray(lr, jnp.float32)

    def get_lr(self, state=None):
        if isinstance(self.learning_rate, LRScheduler):
            if state is not None:
                return float(self.learning_rate.value_at(state["step"]))
            return self.learning_rate.get_lr()
        return self.learning_rate

    # -- update -------------------------------------------------------------
    def step(self, params, grads, state):
        """Returns (new_params, new_state). Pure — safe under jit/donation."""
        if self.grad_clip is not None:
            grads = self.grad_clip(grads)
        lr = self._lr(state)
        if self.multi_precision:
            compute = _tree_map(
                lambda p, m: m if m is not None else p, params, state["master"])
        else:
            compute = params
        new_compute, new_state = self._update(compute, grads, state, lr)
        new_state["step"] = state["step"] + 1
        if self.multi_precision:
            # keep master only where one existed (non-fp32 params)
            new_state["master"] = _tree_map(
                lambda m, c: c if m is not None else None,
                state["master"], new_compute)
            new_params = _tree_map(
                lambda p, m, c: c if m is None else c.astype(p.dtype),
                params, state["master"], new_compute)
        else:
            new_params = new_compute
        return new_params, new_state

    def _update(self, params, grads, state, lr):
        raise NotImplementedError

    # -- convenience: stateful eager API (reference ergonomics) -------------
    def minimize(self, loss_fn, module: Module, *args):
        if not hasattr(self, "_eager_state"):
            self._eager_state = self.init(module)
        loss, grads = value_and_grad(loss_fn)(module, *args)
        new_mod, self._eager_state = self.step(module, grads, self._eager_state)
        return loss, new_mod

    def _decay_mask(self, params):
        """weight-decay mask honouring apply_decay_param_fun (by param path)."""
        if self.apply_decay_param_fun is None:
            return None
        flat, treedef = jax.tree_util.tree_flatten_with_path(
            params, is_leaf=lambda x: x is None)
        from paddle_tpu.core.module import _path_to_str
        mask = [self.apply_decay_param_fun(_path_to_str(p)) for p, _ in flat]
        return jax.tree_util.tree_unflatten(treedef, mask)


# -- SGD / Momentum (ref sgd.py, momentum.py) -------------------------------

class SGD(Optimizer):
    def _update(self, params, grads, state, lr):
        def upd(p, g):
            u = g.astype(p.dtype)
            if self.weight_decay:
                u = u + self.weight_decay * p
            return (p - lr * u).astype(p.dtype)
        return _map_params(upd, params, grads), dict(state)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _init_slots(self, params):
        return {"velocity": _map_params(jnp.zeros_like, params)}

    def _update(self, params, grads, state, lr):
        mu = self.momentum

        def upd(p, g, v):
            g = g.astype(p.dtype)
            if self.weight_decay:
                g = g + self.weight_decay * p
            v_new = mu * v + g
            if self.use_nesterov:
                p_new = p - lr * (g + mu * v_new)
            else:
                p_new = p - lr * v_new
            return p_new.astype(p.dtype), v_new

        pairs = _map_params(lambda p, g, v: upd(p, g, v), params, grads, state["velocity"])
        return _pluck(pairs, 0), {**state, "velocity": _pluck(pairs, 1)}


# -- Adagrad / RMSProp / Adadelta -------------------------------------------

class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self.epsilon = epsilon
        self.init_acc = initial_accumulator_value

    def _init_slots(self, params):
        return {"moment": _map_params(
            lambda p: jnp.full_like(p, self.init_acc, dtype=jnp.float32), params)}

    def _update(self, params, grads, state, lr):
        def upd(p, g, m):
            g32 = g.astype(jnp.float32)
            m_new = m + g32 * g32
            p_new = p - lr * g32 / (jnp.sqrt(m_new) + self.epsilon)
            return p_new.astype(p.dtype), m_new

        pairs = _map_params(upd, params, grads, state["moment"])
        return _pluck(pairs, 0), {**state, "moment": _pluck(pairs, 1)}


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon, self.momentum, self.centered = rho, epsilon, momentum, centered

    def _init_slots(self, params):
        slots = {"mean_square": _map_params(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
                 "velocity": _map_params(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)}
        if self.centered:
            slots["mean_grad"] = _map_params(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return slots

    def _update(self, params, grads, state, lr):
        rho, eps, mu = self.rho, self.epsilon, self.momentum

        def upd(p, g, ms, v, mg=None):
            g32 = g.astype(jnp.float32)
            if self.weight_decay:
                g32 = g32 + self.weight_decay * p.astype(jnp.float32)
            ms_new = rho * ms + (1 - rho) * g32 * g32
            if self.centered:
                mg_new = rho * mg + (1 - rho) * g32
                denom = jnp.sqrt(ms_new - mg_new * mg_new + eps)
            else:
                mg_new = None
                denom = jnp.sqrt(ms_new + eps)
            v_new = mu * v + lr * g32 / denom
            return (p - v_new).astype(p.dtype), ms_new, v_new, mg_new

        if self.centered:
            pairs = _map_params(upd, params, grads, state["mean_square"],
                                state["velocity"], state["mean_grad"])
        else:
            pairs = _map_params(upd, params, grads, state["mean_square"], state["velocity"])
        get = lambda i: _pluck(pairs, i)
        new_state = {**state, "mean_square": get(1), "velocity": get(2)}
        if self.centered:
            new_state["mean_grad"] = get(3)
        return get(0), new_state


class Adadelta(Optimizer):
    def __init__(self, learning_rate=1.0, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon = rho, epsilon

    def _init_slots(self, params):
        z = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return {"avg_sq_grad": _map_params(z, params), "avg_sq_update": _map_params(z, params)}

    def _update(self, params, grads, state, lr):
        rho, eps = self.rho, self.epsilon

        def upd(p, g, asg, asu):
            g32 = g.astype(jnp.float32)
            asg_new = rho * asg + (1 - rho) * g32 * g32
            update = g32 * jnp.sqrt(asu + eps) / jnp.sqrt(asg_new + eps)
            asu_new = rho * asu + (1 - rho) * update * update
            return (p - lr * update).astype(p.dtype), asg_new, asu_new

        pairs = _map_params(upd, params, grads, state["avg_sq_grad"], state["avg_sq_update"])
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "avg_sq_grad": get(1), "avg_sq_update": get(2)}


# -- Adam family (ref adam.py / adamw.py / adamax.py / lamb.py) -------------

class Adam(Optimizer):
    decoupled_wd = False

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slots(self, params):
        z = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return {"moment1": _map_params(z, params), "moment2": _map_params(z, params)}

    def _update(self, params, grads, state, lr):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = state["step"].astype(jnp.float32) + 1.0
        bc1 = 1.0 - b1 ** t
        bc2 = 1.0 - b2 ** t
        mask = self._decay_mask(params)

        def upd(p, g, m, v, do_decay=True):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay and not self.decoupled_wd:
                g32 = g32 + self.weight_decay * p32
            m_new = b1 * m + (1 - b1) * g32
            v_new = b2 * v + (1 - b2) * g32 * g32
            update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if self.decoupled_wd and self.weight_decay and do_decay:
                update = update + self.weight_decay * p32
            return (p32 - lr * update).astype(p.dtype), m_new, v_new

        if mask is None:
            pairs = _map_params(upd, params, grads, state["moment1"], state["moment2"])
        else:
            pairs = _map_params(lambda p, g, m, v, dm: upd(p, g, m, v, dm),
                                params, grads, state["moment1"], state["moment2"], mask)
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "moment1": get(1), "moment2": get(2)}


class AdamW(Adam):
    """Decoupled weight decay (ref adamw.py). Default wd 0.01."""
    decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 weight_decay=0.01, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon,
                         weight_decay=weight_decay, **kw)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slots(self, params):
        z = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return {"moment": _map_params(z, params), "inf_norm": _map_params(z, params)}

    def _update(self, params, grads, state, lr):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = state["step"].astype(jnp.float32) + 1.0
        bc1 = 1.0 - b1 ** t

        def upd(p, g, m, u):
            g32 = g.astype(jnp.float32)
            m_new = b1 * m + (1 - b1) * g32
            u_new = jnp.maximum(b2 * u, jnp.abs(g32))
            p_new = p.astype(jnp.float32) - lr / bc1 * m_new / (u_new + eps)
            return p_new.astype(p.dtype), m_new, u_new

        pairs = _map_params(upd, params, grads, state["moment"], state["inf_norm"])
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "moment": get(1), "inf_norm": get(2)}


class Lamb(Optimizer):
    """Layer-wise adaptive moments for large-batch training (ref lamb.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-6,
                 lamb_weight_decay=0.01, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lamb_weight_decay = lamb_weight_decay

    def _init_slots(self, params):
        z = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return {"moment1": _map_params(z, params), "moment2": _map_params(z, params)}

    def _update(self, params, grads, state, lr):
        b1, b2, eps, wd = self.beta1, self.beta2, self.epsilon, self.lamb_weight_decay
        t = state["step"].astype(jnp.float32) + 1.0
        bc1, bc2 = 1.0 - b1 ** t, 1.0 - b2 ** t

        def upd(p, g, m, v):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1 - b1) * g32
            v_new = b2 * v + (1 - b2) * g32 * g32
            r = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps) + wd * p32
            w_norm = jnp.sqrt(jnp.sum(p32 * p32))
            r_norm = jnp.sqrt(jnp.sum(r * r))
            trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
            return (p32 - lr * trust * r).astype(p.dtype), m_new, v_new

        pairs = _map_params(upd, params, grads, state["moment1"], state["moment2"])
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "moment1": get(1), "moment2": get(2)}


class Lion(Optimizer):
    """Sign-momentum optimizer (ref paddle.incubate.optimizer). Half the
    optimizer memory of Adam — attractive on HBM-limited TPU training."""

    def __init__(self, learning_rate=1e-4, beta1=0.9, beta2=0.99, weight_decay=0.0, **kw):
        super().__init__(learning_rate, weight_decay=weight_decay, **kw)
        self.beta1, self.beta2 = beta1, beta2

    def _init_slots(self, params):
        return {"moment": _map_params(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)}

    def _update(self, params, grads, state, lr):
        b1, b2 = self.beta1, self.beta2

        def upd(p, g, m):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            update = jnp.sign(b1 * m + (1 - b1) * g32)
            if self.weight_decay:
                update = update + self.weight_decay * p32
            m_new = b2 * m + (1 - b2) * g32
            return (p32 - lr * update).astype(p.dtype), m_new

        pairs = _map_params(upd, params, grads, state["moment"])
        get = lambda i: _pluck(pairs, i)
        return get(0), {**state, "moment": get(1)}


class Adafactor(Optimizer):
    """Factored-second-moment optimizer (Shazeer & Stern). The canonical
    low-memory choice for large TPU training runs: matrices keep row+col
    EMAs instead of a full second moment — O(r+c) slot memory vs Adam's
    O(r·c). (Reference capability: paddle.incubate optimizer family; this
    member is TPU-native rather than a port.)

    ``learning_rate=None`` enables the paper's relative-step schedule
    min(1e-2, 1/sqrt(t)) scaled by RMS(param).
    """

    def __init__(self, learning_rate=None, beta1=None, decay_rate=0.8,
                 epsilon1=1e-30, epsilon2=1e-3, clip_threshold=1.0,
                 scale_parameter=True, **kw):
        super().__init__(learning_rate if learning_rate is not None else 1.0, **kw)
        self.relative_step = learning_rate is None
        self.beta1 = beta1
        self.decay_rate = decay_rate
        self.eps1, self.eps2 = epsilon1, epsilon2
        self.clip_threshold = clip_threshold
        self.scale_parameter = scale_parameter

    @staticmethod
    def _factored(p):
        return p.ndim >= 2 and p.shape[-1] > 1 and p.shape[-2] > 1

    def _init_slots(self, params):
        def vr(p):
            if self._factored(p):
                return jnp.zeros(p.shape[:-1], jnp.float32)
            return jnp.zeros(p.shape, jnp.float32)  # full v for vectors

        def vc(p):
            if self._factored(p):
                return jnp.zeros(p.shape[:-2] + p.shape[-1:], jnp.float32)
            return jnp.zeros((1,), jnp.float32)     # unused placeholder

        slots = {"vr": _map_params(vr, params), "vc": _map_params(vc, params)}
        if self.beta1 is not None:
            slots["m"] = _map_params(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return slots

    def _update(self, params, grads, state, lr):
        t = state["step"].astype(jnp.float32) + 1.0
        rho = 1.0 - t ** (-self.decay_rate)
        eps1, eps2, d = self.eps1, self.eps2, self.clip_threshold
        ms = state.get("m")
        mask = self._decay_mask(params)

        def upd(p, g, vr, vc, m=None, do_decay=True):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            g2 = g32 * g32 + eps1
            if self._factored(p):
                vr_new = rho * vr + (1 - rho) * g2.mean(axis=-1)
                vc_new = rho * vc + (1 - rho) * g2.mean(axis=-2)
                # v̂_ij = vr_i vc_j / mean_i(vr) — rank-1 reconstruction
                denom = jnp.maximum(vr_new.mean(axis=-1, keepdims=True), eps1)
                u = g32 * jax.lax.rsqrt(
                    (vr_new / denom)[..., None] * vc_new[..., None, :] + eps1)
            else:
                vr_new = rho * vr + (1 - rho) * g2
                vc_new = vc
                u = g32 * jax.lax.rsqrt(vr_new + eps1)
            rms_u = jnp.sqrt(jnp.mean(u * u) + eps1)
            u = u / jnp.maximum(1.0, rms_u / d)
            if m is not None:
                b1 = self.beta1
                u = b1 * m + (1 - b1) * u
                m_new = u
            else:
                m_new = None
            if self.relative_step:
                step_lr = jnp.minimum(1e-2, 1.0 / jnp.sqrt(t))
            else:
                step_lr = lr
            if self.scale_parameter:
                step_lr = step_lr * jnp.maximum(eps2, jnp.sqrt(jnp.mean(p32 * p32)))
            if self.weight_decay and do_decay:
                p32 = p32 * (1.0 - step_lr * self.weight_decay)
            out = (p32 - step_lr * u).astype(p.dtype)
            return (out, vr_new, vc_new) if m_new is None else (out, vr_new, vc_new, m_new)

        if ms is not None and mask is not None:
            pairs = _map_params(lambda p, g, vr, vc, m, dm: upd(p, g, vr, vc, m, dm),
                                params, grads, state["vr"], state["vc"], ms, mask)
        elif ms is not None:
            pairs = _map_params(upd, params, grads, state["vr"], state["vc"], ms)
        elif mask is not None:
            pairs = _map_params(lambda p, g, vr, vc, dm: upd(p, g, vr, vc, None, dm),
                                params, grads, state["vr"], state["vc"], mask)
        else:
            pairs = _map_params(upd, params, grads, state["vr"], state["vc"])
        get = lambda i: _pluck(pairs, i)
        new_state = {**state, "vr": get(1), "vc": get(2)}
        if ms is not None:
            new_state["m"] = get(3)
        return get(0), new_state


# -- incubate extras (ref python/paddle/incubate/optimizer/) -----------------

class LookAhead(Optimizer):
    """Ref: paddle.incubate.LookAhead — wraps an inner optimizer; every k
    steps the slow weights absorb the fast ones: slow += alpha*(fast-slow).
    Pure/jit-safe: the sync happens via a traced predicate."""

    def __init__(self, inner: Optimizer, alpha=0.5, k=5):
        super().__init__(learning_rate=inner.learning_rate)
        self.inner, self.alpha, self.k = inner, alpha, k

    def init(self, params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "inner": self.inner.init(params),
            # copy=True: an fp32 astype would alias the param buffer and
            # break donation (same-buffer-donated-twice)
            "slow": _map_params(
                lambda p: jnp.array(p, jnp.float32, copy=True), params),
        }

    def step(self, params, grads, state):
        fast, inner_state = self.inner.step(params, grads, state["inner"])
        la_step = state["step"] + 1
        sync = (la_step % self.k == 0)

        def merge(slow, f):
            if slow is None or f is None or not hasattr(f, "dtype") \
                    or not jnp.issubdtype(f.dtype, jnp.floating):
                return slow
            new_slow = slow + self.alpha * (f.astype(jnp.float32) - slow)
            return jnp.where(sync, new_slow, slow)

        new_slow = _tree_map(merge, state["slow"], fast)

        def pick(f, slow):
            if f is None or not hasattr(f, "dtype") \
                    or not jnp.issubdtype(f.dtype, jnp.floating):
                return f
            return jnp.where(sync, slow.astype(f.dtype), f)

        out = _tree_map(pick, fast, new_slow)
        # a multi_precision inner keeps its own fp32 master weights, and its
        # next step reads from THOSE — sync must land there too, or it is
        # overwritten immediately
        if getattr(self.inner, "multi_precision", False) and \
                "master" in inner_state:
            inner_state = {**inner_state, "master": _tree_map(
                lambda m, s: m if m is None or s is None
                else jnp.where(sync, s, m),
                inner_state["master"], new_slow)}
        return out, {"step": la_step, "inner": inner_state, "slow": new_slow}


class ExponentialMovingAverage:
    """Ref: paddle.incubate.ExponentialMovingAverage (functional flavour).

    shadow = ema.init(params); shadow = ema.update(shadow, params) each
    step; eval_params = ema.apply(shadow, params)."""

    def __init__(self, decay=0.999):
        self.decay = decay

    def init(self, params):
        # copy=True: see LookAhead.init — fp32 astype aliases the buffer
        return _map_params(
            lambda p: jnp.array(p, jnp.float32, copy=True), params)

    def update(self, shadow, params):
        d = self.decay

        def upd(s, p):
            if s is None or p is None or not hasattr(p, "dtype") \
                    or not jnp.issubdtype(p.dtype, jnp.floating):
                return s
            return d * s + (1 - d) * p.astype(jnp.float32)

        return _tree_map(upd, shadow, params)

    def apply(self, shadow, params):
        """Return params with EMA values (cast back to param dtypes)."""

        def pick(p, s):
            if p is None or s is None or not hasattr(p, "dtype") \
                    or not jnp.issubdtype(p.dtype, jnp.floating):
                return p
            return s.astype(p.dtype)

        return _tree_map(pick, params, shadow)
