"""LR schedulers (ref: ``python/paddle/optimizer/lr.py``).

Two usage modes:
  * jit-friendly: ``sched.value_at(step)`` — a pure function of the step
    counter carried in optimizer state (this is what Optimizer._lr uses, so
    the schedule compiles into the fused train step — no host sync).
  * reference-style stateful: ``sched.step()`` / ``sched.get_lr()``.
"""
from __future__ import annotations

import math

import jax.numpy as jnp


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.step()

    # stateful API -----------------------------------------------------------
    def step(self, metrics=None):
        self.last_epoch += 1
        self.last_lr = float(self.value_at(jnp.asarray(self.last_epoch)))

    def get_lr(self):
        return self.last_lr

    # pure API ---------------------------------------------------------------
    def value_at(self, step):
        raise NotImplementedError


class NoamDecay(LRScheduler):
    """lr = d^{-0.5} * min(t^{-0.5}, t * warmup^{-1.5}) (ref lr.py NoamDecay)."""

    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1):
        self.d_model, self.warmup_steps = d_model, warmup_steps
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        t = jnp.maximum(step.astype(jnp.float32), 1.0)
        return (self.base_lr * self.d_model ** -0.5 *
                jnp.minimum(t ** -0.5, t * self.warmup_steps ** -1.5))


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        return self.base_lr * self.gamma ** step.astype(jnp.float32)


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        return self.base_lr * jnp.exp(-self.gamma * step.astype(jnp.float32))


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        return self.base_lr / (1.0 + self.gamma * step.astype(jnp.float32))


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1):
        self.decay_steps, self.end_lr, self.power, self.cycle = decay_steps, end_lr, power, cycle
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        t = step.astype(jnp.float32)
        if self.cycle:
            div = jnp.maximum(jnp.ceil(t / self.decay_steps), 1.0)
            decay_steps = self.decay_steps * div
        else:
            decay_steps = self.decay_steps
            t = jnp.minimum(t, decay_steps)
        frac = (1.0 - t / decay_steps) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch)

    def value_at(self, step):
        t = step.astype(jnp.float32)
        out = jnp.asarray(self.values[-1], jnp.float32)
        for b, v in zip(reversed(self.boundaries), reversed(self.values[:-1])):
            out = jnp.where(t < b, v, out)
        return out


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0.0, last_epoch=-1):
        self.T_max, self.eta_min = T_max, eta_min
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        t = step.astype(jnp.float32)
        cos = jnp.cos(jnp.pi * jnp.minimum(t, self.T_max) / self.T_max)
        return self.eta_min + (self.base_lr - self.eta_min) * (1 + cos) / 2


class LinearWarmup(LRScheduler):
    """Linear warmup wrapping an inner schedule or constant (ref lr.py)."""

    def __init__(self, learning_rate, warmup_steps, start_lr=0.0, end_lr=None,
                 last_epoch=-1):
        self.inner = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr if end_lr is not None else (
            self.inner.base_lr if self.inner else float(learning_rate))
        base = self.inner.base_lr if self.inner else float(learning_rate)
        super().__init__(base, last_epoch)

    def value_at(self, step):
        t = step.astype(jnp.float32)
        warm = self.start_lr + (self.end_lr - self.start_lr) * jnp.minimum(
            t / max(self.warmup_steps, 1), 1.0)
        if self.inner is not None:
            after = self.inner.value_at(jnp.maximum(step - self.warmup_steps, 0))
        else:
            after = jnp.asarray(self.end_lr, jnp.float32)
        return jnp.where(t < self.warmup_steps, warm, after)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1):
        self.step_size, self.gamma = step_size, gamma
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        k = jnp.floor_divide(step, self.step_size).astype(jnp.float32)
        return self.base_lr * self.gamma ** k


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1):
        self.milestones, self.gamma = list(milestones), gamma
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        t = step.astype(jnp.float32)
        k = sum((t >= m).astype(jnp.float32) for m in self.milestones)
        return self.base_lr * self.gamma ** k


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        return self.base_lr * self.lr_lambda(step)


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3, last_epoch=-1):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.min_lr = end_learning_rate
        self.phase_pct = phase_pct
        super().__init__(max_learning_rate, last_epoch)

    def value_at(self, step):
        t = jnp.minimum(step.astype(jnp.float32), self.total_steps)
        up_steps = self.phase_pct * self.total_steps
        down_steps = self.total_steps - up_steps

        def cos_anneal(lo, hi, frac):
            return lo + (hi - lo) * (1 + jnp.cos(jnp.pi * frac)) / 2

        up = cos_anneal(self.max_lr, self.initial_lr, t / jnp.maximum(up_steps, 1))
        down = cos_anneal(self.min_lr, self.max_lr, (t - up_steps) / jnp.maximum(down_steps, 1))
        return jnp.where(t < up_steps, up, down)


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate, step_size_up,
                 step_size_down=None, last_epoch=-1):
        self.max_lr = max_learning_rate
        self.up = step_size_up
        self.down = step_size_down or step_size_up
        super().__init__(base_learning_rate, last_epoch)

    def value_at(self, step):
        t = step.astype(jnp.float32)
        cycle_len = self.up + self.down
        pos = jnp.mod(t, cycle_len)
        frac = jnp.where(pos < self.up, pos / self.up, 1.0 - (pos - self.up) / self.down)
        return self.base_lr + (self.max_lr - self.base_lr) * frac


class ReduceOnPlateau(LRScheduler):
    """Metric-driven: inherently host-side (ref lr.py ReduceOnPlateau).
    Use the stateful API; value_at returns the current lr."""

    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, min_lr=0.0, cooldown=0):
        self.mode, self.factor, self.patience = mode, factor, patience
        self.threshold, self.min_lr, self.cooldown = threshold, min_lr, cooldown
        self.best = None
        self.num_bad = 0
        self.cooldown_left = 0
        self.current = learning_rate
        self.base_lr = learning_rate
        self.last_epoch = -1
        self.last_lr = learning_rate

    def step(self, metrics=None):
        self.last_epoch += 1
        if metrics is None:
            return
        m = float(metrics)
        better = (self.best is None or
                  (m < self.best - self.threshold if self.mode == "min"
                   else m > self.best + self.threshold))
        if better:
            self.best = m
            self.num_bad = 0
        elif self.cooldown_left > 0:
            self.cooldown_left -= 1
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                self.current = max(self.current * self.factor, self.min_lr)
                self.num_bad = 0
                self.cooldown_left = self.cooldown
        self.last_lr = self.current

    def value_at(self, step):
        return jnp.asarray(self.current, jnp.float32)
