"""Graph-learning ops (ref: ``python/paddle/geometric/``).

Paddle's geometric package wraps CUDA scatter/gather kernels
(``paddle/phi/kernels/graph_send_recv_kernel.cu`` etc.). On TPU these are
segment reductions — XLA lowers ``jax.ops.segment_*`` to sorted-scatter,
which vectorises well; ``num_segments``/output size must be static under
jit (pass ``out_size``), matching the reference's ``out_size`` argument.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
    "sample_neighbors", "weighted_sample_neighbors",
]


def _num_segments(segment_ids, n):
    if n is not None:
        return int(n)
    # eager fallback — data-dependent, host sync (same as reference CPU path)
    return int(jax.device_get(jnp.max(segment_ids))) + 1 if segment_ids.size else 0


def segment_sum(data, segment_ids, num_segments=None):
    """Ref ``python/paddle/geometric/math.py:segment_sum``."""
    n = _num_segments(segment_ids, num_segments)
    return jax.ops.segment_sum(data, segment_ids, num_segments=n)


def segment_mean(data, segment_ids, num_segments=None):
    n = _num_segments(segment_ids, num_segments)
    s = jax.ops.segment_sum(data, segment_ids, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype),
                              segment_ids, num_segments=n)
    shape = (n,) + (1,) * (data.ndim - 1)
    return s / jnp.maximum(cnt.reshape(shape), 1)


def segment_min(data, segment_ids, num_segments=None):
    """Empty segments yield 0 like the reference (not +inf)."""
    n = _num_segments(segment_ids, num_segments)
    out = jax.ops.segment_min(data, segment_ids, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), jnp.int32),
                              segment_ids, num_segments=n)
    shape = (n,) + (1,) * (data.ndim - 1)
    return jnp.where(cnt.reshape(shape) > 0, out, 0)


def segment_max(data, segment_ids, num_segments=None):
    n = _num_segments(segment_ids, num_segments)
    out = jax.ops.segment_max(data, segment_ids, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), jnp.int32),
                              segment_ids, num_segments=n)
    shape = (n,) + (1,) * (data.ndim - 1)
    return jnp.where(cnt.reshape(shape) > 0, out, 0)


_REDUCERS = {"sum": segment_sum, "mean": segment_mean, "min": segment_min,
             "max": segment_max, "add": segment_sum}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None):
    """Gather source-node features along edges, reduce at destinations
    (ref ``python/paddle/geometric/message_passing/send_recv.py``)."""
    msgs = jnp.take(x, src_index, axis=0)
    n = out_size if out_size is not None else x.shape[0]
    return _REDUCERS[reduce_op](msgs, dst_index, n)


def _combine(xe, e, message_op):
    if message_op in ("add", "sum"):
        return xe + e
    if message_op == "sub":
        return xe - e
    if message_op == "mul":
        return xe * e
    if message_op == "div":
        return xe / e
    raise ValueError(f"unknown message_op {message_op!r}")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None):
    """Like :func:`send_u_recv` but combines edge features ``y`` into the
    message first (ref send_ue_recv). ``y``: [E, ...] broadcastable to x."""
    msgs = _combine(jnp.take(x, src_index, axis=0), jnp.asarray(y), message_op)
    n = out_size if out_size is not None else x.shape[0]
    return _REDUCERS[reduce_op](msgs, dst_index, n)


def send_uv(x, y, src_index, dst_index, message_op="add"):
    """Per-edge message from both endpoint features (ref send_uv): returns
    [E, ...] with no reduction."""
    return _combine(jnp.take(x, src_index, axis=0),
                    jnp.take(y, dst_index, axis=0), message_op)


def reindex_graph(x, neighbors, count):
    """Compact global node ids to local ids (ref reindex_graph). Host-side
    (hash-map semantics are inherently sequential) — pipeline glue, eager.

    Returns (reindexed_src, reindexed_dst, out_nodes): out_nodes is
    [x ∪ neighbors] unique-ordered, edges re-labelled into that space.
    """
    x_np = np.asarray(x)
    nbr = np.asarray(neighbors)
    cnt = np.asarray(count)
    uniq, first_pos = np.unique(np.concatenate([x_np, nbr]), return_index=True)
    # preserve first-appearance order like the reference
    order = np.argsort(first_pos, kind="stable")
    out_nodes = uniq[order]
    lookup = {int(v): i for i, v in enumerate(out_nodes)}
    src = np.array([lookup[int(v)] for v in nbr], np.int64)
    dst = np.repeat(np.arange(len(x_np)), cnt).astype(np.int64)
    return jnp.asarray(src), jnp.asarray(dst), jnp.asarray(out_nodes)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, *, seed=0):
    """Uniform neighbor sampling from CSC graph (ref sample_neighbors).
    Host-side numpy (data-dependent shapes); returns (neighbors, counts)."""
    rng = np.random.default_rng(seed)
    row_np, colptr_np = np.asarray(row), np.asarray(colptr)
    out, counts = [], []
    for v in np.asarray(input_nodes):
        lo, hi = int(colptr_np[v]), int(colptr_np[v + 1])
        nbrs = row_np[lo:hi]
        if 0 <= sample_size < len(nbrs):
            nbrs = rng.choice(nbrs, size=sample_size, replace=False)
        out.append(nbrs)
        counts.append(len(nbrs))
    cat = np.concatenate(out) if out else np.empty(0, row_np.dtype)
    return jnp.asarray(cat), jnp.asarray(np.array(counts, np.int64))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, *, seed=0):
    """Weight-proportional sampling without replacement (ref
    weighted_sample_neighbors)."""
    rng = np.random.default_rng(seed)
    row_np, colptr_np = np.asarray(row), np.asarray(colptr)
    w_np = np.asarray(edge_weight, np.float64)
    out, counts = [], []
    for v in np.asarray(input_nodes):
        lo, hi = int(colptr_np[v]), int(colptr_np[v + 1])
        nbrs = row_np[lo:hi]
        if 0 <= sample_size < len(nbrs):
            p = w_np[lo:hi]
            if np.count_nonzero(p) >= sample_size:
                nbrs = rng.choice(nbrs, size=sample_size, replace=False,
                                  p=p / p.sum())
            else:  # too few positive-weight edges: uniform fallback
                nbrs = rng.choice(nbrs, size=sample_size, replace=False)
        out.append(nbrs)
        counts.append(len(nbrs))
    cat = np.concatenate(out) if out else np.empty(0, row_np.dtype)
    return jnp.asarray(cat), jnp.asarray(np.array(counts, np.int64))
