"""Datasets (ref: ``python/paddle/io/dataloader/dataset.py``)."""
from __future__ import annotations

from typing import Sequence

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise TypeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, *tensors):
        assert all(len(t) == len(tensors[0]) for t in tensors)
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(np.asarray(t[idx]) for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class Subset(Dataset):
    def __init__(self, dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, seed=0):
    assert sum(lengths) == len(dataset)
    perm = np.random.RandomState(seed).permutation(len(dataset))
    out, ofs = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[ofs:ofs + n].tolist()))
        ofs += n
    return out


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        return tuple(d[idx] for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    """Ref dataset.py:ConcatDataset — end-to-end concatenation."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = []
        total = 0
        for d in self.datasets:
            total += len(d)
            self.cumulative_sizes.append(total)

    def __len__(self):
        return self.cumulative_sizes[-1] if self.cumulative_sizes else 0

    def __getitem__(self, idx):
        n = len(self)
        if idx < 0:
            idx += n
        if not 0 <= idx < n:
            raise IndexError(f"index {idx} out of range for length {n}")
        import bisect
        di = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[di - 1] if di else 0
        return self.datasets[di][idx - prev]
