"""DataLoader (ref: ``python/paddle/io/dataloader/dataloader_iter.py``).

The reference spawns multiprocessing workers feeding a pinned-memory queue.
TPU-native host pipeline: a thread pool (numpy collation releases the GIL
for the heavy copies) + a bounded prefetch queue, overlapping host batch
prep with device steps. For token-LM training prefer the native C++ reader
(paddle_tpu.io.token_bin.TokenBinDataset) which does mmap + prefetch in C++.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

import numpy as np

from paddle_tpu.io.dataset import Dataset, IterableDataset
from paddle_tpu.io.sampler import BatchSampler


def default_collate_fn(samples):
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return tuple(default_collate_fn([s[i] for s in samples])
                     for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate_fn([s[k] for s in samples]) for k in first}
    return np.stack([np.asarray(s) for s in samples], axis=0)


class DataLoader:
    def __init__(self, dataset: Dataset, batch_size: int = 1, shuffle: bool = False,
                 drop_last: bool = False, collate_fn: Optional[Callable] = None,
                 num_workers: int = 0, prefetch_factor: int = 2,
                 batch_sampler: Optional[BatchSampler] = None, seed=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.iterable = isinstance(dataset, IterableDataset)
        if self.iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_sampler = batch_sampler or BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last, seed=seed)

    def __len__(self):
        if self.iterable:
            raise TypeError("IterableDataset has no __len__")
        return len(self.batch_sampler)

    def _batches(self):
        if self.iterable:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def __iter__(self):
        if self.num_workers <= 0:
            yield from self._batches()
            return
        q: queue.Queue = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        _END = object()

        def producer():
            try:
                for b in self._batches():
                    q.put(b)
            finally:
                q.put(_END)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _END:
                break
            yield item
        t.join()
