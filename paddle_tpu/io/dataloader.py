"""DataLoader (ref: ``python/paddle/io/dataloader/dataloader_iter.py``).

The reference spawns multiprocessing workers feeding a pinned-memory queue.
Here (no CUDA pinned memory on the host→TPU path):

- map-style + ``num_workers>0`` → forked worker processes pulling
  index-batches from a task queue, results reassembled IN ORDER (the
  reference's ``_DataLoaderIterMultiProcess`` reordering), so determinism
  matches num_workers=0.
- iterable datasets → one producer thread with a bounded prefetch queue
  (numpy collation releases the GIL for the heavy copies).
- token-LM training → prefer the native C++ reader
  (``paddle_tpu.io.token_bin.TokenBinDataset``): mmap + prefetch in C++.
"""
from __future__ import annotations

import multiprocessing as mp
import queue
import threading
from typing import Callable, Optional

import numpy as np

from paddle_tpu.io.dataset import Dataset, IterableDataset
from paddle_tpu.io.sampler import BatchSampler

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id, num_workers, seed):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed


def get_worker_info():
    """Inside a worker: (id, num_workers, seed); None in the main process
    (ref ``paddle.io.get_worker_info``)."""
    return getattr(_worker_info, "info", None)


def _mp_worker(dataset, collate_fn, task_q, result_q, wid, num_workers, seed):
    _worker_info.info = WorkerInfo(wid, num_workers, seed)
    while True:
        task = task_q.get()
        if task is None:
            break
        seq, idxs = task
        try:
            batch = collate_fn([dataset[i] for i in idxs])
            result_q.put((seq, batch, None))
        except Exception as e:  # surface the real error in the parent
            result_q.put((seq, None, f"{type(e).__name__}: {e}"))


def default_collate_fn(samples):
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return tuple(default_collate_fn([s[i] for s in samples])
                     for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate_fn([s[k] for s in samples]) for k in first}
    return np.stack([np.asarray(s) for s in samples], axis=0)


class DataLoader:
    def __init__(self, dataset: Dataset, batch_size: int = 1, shuffle: bool = False,
                 drop_last: bool = False, collate_fn: Optional[Callable] = None,
                 num_workers: int = 0, prefetch_factor: int = 2,
                 batch_sampler: Optional[BatchSampler] = None, seed=None,
                 mp_start_method: str = "fork"):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.seed = seed
        self.mp_start_method = mp_start_method
        self.iterable = isinstance(dataset, IterableDataset)
        if self.iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_sampler = batch_sampler or BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last, seed=seed)

    def __len__(self):
        if self.iterable:
            raise TypeError("IterableDataset has no __len__")
        return len(self.batch_sampler)

    def _batches(self):
        if self.iterable:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def __iter__(self):
        if self.num_workers <= 0:
            yield from self._batches()
            return
        if self.iterable:
            yield from self._threaded_iter()
            return
        yield from self._mp_iter()

    def prefetch(self, depth: int = 2, sharding=None):
        """Host/device overlap: iterate this loader through a background
        thread that lands each batch on device (``jax.device_put``)
        ``depth`` batches ahead of the consumer — see
        :func:`paddle_tpu.io.prefetch.prefetch_to_device`. The returned
        object is a fresh iterator over ONE pass of the loader; close it
        (or exhaust it) to reap the producer thread."""
        from paddle_tpu.io.prefetch import prefetch_to_device
        return prefetch_to_device(iter(self), depth, sharding)

    def _threaded_iter(self):
        q: queue.Queue = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        _END = object()
        failure = []

        def producer():
            try:
                for b in self._batches():
                    q.put(b)
            except BaseException as e:  # re-raised in the consumer
                failure.append(e)
            finally:
                q.put(_END)

        t = threading.Thread(target=producer, name="pt-dataloader",
                             daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _END:
                break
            yield item
        t.join()
        if failure:
            raise failure[0]

    def _mp_iter(self):
        """Worker-process pool with in-order reassembly.

        Default start method is ``fork`` (cheap, no pickling — same choice as
        the reference loader on Linux). Workers must only run host/numpy code;
        if the dataset touches JAX, pass ``mp_start_method='spawn'`` — fork
        from a process with an initialized JAX runtime can deadlock.
        """
        ctx = mp.get_context(self.mp_start_method)
        task_q = ctx.Queue()
        result_q = ctx.Queue()
        nw = self.num_workers
        seed = self.seed or 0
        workers = [ctx.Process(target=_mp_worker,
                               args=(self.dataset, self.collate_fn, task_q,
                                     result_q, w, nw, seed + w), daemon=True)
                   for w in range(nw)]
        for w in workers:
            w.start()
        try:
            batches = iter(self.batch_sampler)
            inflight = 0
            seq_sent = 0
            for _ in range(nw * self.prefetch_factor):  # prime the pipeline
                try:
                    task_q.put((seq_sent, next(batches)))
                    seq_sent += 1
                    inflight += 1
                except StopIteration:
                    break
            pending = {}
            seq_want = 0
            while inflight:
                try:
                    seq, batch, err = result_q.get(timeout=5.0)
                except queue.Empty:
                    dead = [w for w in workers if not w.is_alive()]
                    if dead:
                        raise RuntimeError(
                            f"DataLoader worker exited unexpectedly (exitcode "
                            f"{dead[0].exitcode}) — killed by OOM or a crash "
                            f"in dataset code")
                    continue
                inflight -= 1
                if err is not None:
                    raise RuntimeError(f"DataLoader worker failed: {err}")
                pending[seq] = batch
                try:
                    task_q.put((seq_sent, next(batches)))
                    seq_sent += 1
                    inflight += 1
                except StopIteration:
                    pass
                while seq_want in pending:  # emit in submission order
                    yield pending.pop(seq_want)
                    seq_want += 1
        finally:
            for _ in workers:
                task_q.put(None)
            for w in workers:
                w.join(timeout=5)
                if w.is_alive():
                    w.terminate()
