"""Host→device prefetch (ref: ``tf.data`` prefetch-to-device / flax
``jax_utils.prefetch_to_device``; ISSUE 3 tentpole).

The synchronous train loop pays ``next(it)`` (host) and the device step
back-to-back; :func:`prefetch_to_device` overlaps them — a background
thread pulls batches from the iterator, lands them in device memory via
``jax.device_put`` (optionally with an explicit sharding), and parks
them in a bounded queue ``depth`` deep. The consumer side then sees
device-resident batches with near-zero latency while the host walks
ahead.

Contract:
  * ORDER preserved — batches come out exactly as the iterator yields
    them (one producer, FIFO queue).
  * EXCEPTIONS propagate — an error raised by the underlying iterator
    (or by ``device_put``) is captured and re-raised in the consumer at
    the point of ``next()``, after all batches produced before it.
  * CLEAN shutdown — :meth:`DevicePrefetch.close` unblocks and joins
    the producer; normal exhaustion joins it automatically. The
    producer thread is a daemon named ``pt-prefetch-*`` so the test
    suite's leak fixture can find strays.

Telemetry (through the global registry): ``io_prefetch_queue_depth``
(batches parked on device, sampled at each get) and
``io_prefetch_stall_seconds`` (host time blocked waiting for the next
batch — the residual host-boundedness the pipeline could not hide).
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Optional

import jax

from paddle_tpu.observability import METRICS

__all__ = ["DevicePrefetch", "prefetch_to_device"]

_QUEUE_DEPTH = METRICS.gauge(
    "io_prefetch_queue_depth", "device-resident batches waiting in the "
    "prefetch queue (sampled at each consumer get)")
_STALL_S = METRICS.histogram(
    "io_prefetch_stall_seconds", "host time blocked in next() waiting for "
    "the prefetch queue — residual host-boundedness")

_END = object()          # producer → consumer: iterator exhausted (or died)


def _land(batch: Any, sharding) -> Any:
    """Copy every array leaf of the batch onto device (async under the
    hood — device_put returns immediately with a future-backed Array)."""
    if sharding is None:
        return jax.tree_util.tree_map(jax.device_put, batch)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch)


class DevicePrefetch:
    """Iterator wrapper produced by :func:`prefetch_to_device`. Also a
    context manager — ``with prefetch_to_device(it, 2) as p:`` closes
    the producer on exit even when the consumer bails early."""

    def __init__(self, iterator: Iterable, depth: int, sharding=None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.depth = depth
        self._it = iter(iterator)
        self._sharding = sharding
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._closed = threading.Event()
        self._exc: Optional[BaseException] = None
        self._finished = False
        self._thread = threading.Thread(
            target=self._produce, name=f"pt-prefetch-{id(self):x}",
            daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ producer
    def _put(self, item) -> bool:
        """Bounded put that gives up when the consumer closed us (the
        timeout poll is what makes close() prompt instead of deadlocking
        against a full queue)."""
        while not self._closed.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self):
        try:
            for batch in self._it:
                landed = _land(batch, self._sharding)
                if not self._put(landed):
                    return               # closed mid-stream: just stop
                if self._closed.is_set():
                    return
        except BaseException as e:       # re-raised consumer-side, in order
            self._exc = e
        self._put(_END)

    # ------------------------------------------------------------ consumer
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        import time
        if self._finished:
            raise StopIteration
        t0 = time.monotonic()
        item = self._q.get()
        _STALL_S.observe(time.monotonic() - t0)
        _QUEUE_DEPTH.set(self._q.qsize())
        if item is _END:
            self._finished = True
            self._thread.join()
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            raise StopIteration
        return item

    def close(self, timeout: float = 5.0):
        """Stop the producer and join it. Idempotent; safe to call from
        the consumer at any point (mid-stream batches are discarded)."""
        self._closed.set()
        self._finished = True
        while True:                      # unblock a producer stuck in put
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=timeout)
        _QUEUE_DEPTH.set(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            if not self._finished:
                self.close(timeout=0.5)
        except Exception:
            pass


def prefetch_to_device(iterator: Iterable, depth: int = 2,
                       sharding=None) -> DevicePrefetch:
    """Wrap ``iterator`` so batches are landed on device ``depth`` ahead
    of consumption by a background thread. ``sharding`` (a
    ``jax.sharding.Sharding`` or device) is forwarded to ``device_put``
    for every array leaf; None lands on the default device.

    The returned object is an iterator AND a context manager; call
    :meth:`DevicePrefetch.close` (or exhaust it) to reap the producer
    thread."""
    return DevicePrefetch(iterator, depth, sharding)
