from paddle_tpu.io.dataset import (
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from paddle_tpu.io.sampler import (
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
from paddle_tpu.io.dataloader import (DataLoader, WorkerInfo,
                                      default_collate_fn, get_worker_info)
from paddle_tpu.io.prefetch import DevicePrefetch, prefetch_to_device
from paddle_tpu.io.token_bin import TokenBinDataset
