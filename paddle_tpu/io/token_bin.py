"""Native token-bin reader: ctypes binding over native/libfastloader.so
(the C++ mmap + prefetch-ring data runtime; see native/fastloader.cpp for
the reference mapping). Yields (input_ids, labels) int32 numpy batches.
"""
from __future__ import annotations

import ctypes
import os
from pathlib import Path

import numpy as np

from paddle_tpu.io.dataset import IterableDataset

_LIB = None


def _load_lib():
    global _LIB
    if _LIB is not None:
        return _LIB
    root = Path(__file__).resolve().parents[2]
    so = root / "native" / "libfastloader.so"
    if not so.exists():  # build on demand
        import subprocess
        subprocess.run(["make", "-C", str(root / "native")], check=True,
                       capture_output=True)
    lib = ctypes.CDLL(str(so))
    lib.fl_open.restype = ctypes.c_void_p
    lib.fl_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                            ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
    lib.fl_next.restype = ctypes.c_int
    lib.fl_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)]
    lib.fl_num_tokens.restype = ctypes.c_uint64
    lib.fl_num_tokens.argtypes = [ctypes.c_void_p]
    lib.fl_close.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return lib


class TokenBinDataset(IterableDataset):
    """Streams random (seq+1)-token windows from a binary token file.

    File format: flat little-endian uint16 (default) or uint32 token ids —
    the standard nanoGPT/megatron .bin layout.
    """

    def __init__(self, path: str, batch_size: int, seq_len: int, seed: int = 0,
                 token_width: int = 2, num_workers: int = 2, prefetch: int = 8,
                 num_batches: int | None = None,
                 shard: tuple[int, int] | None = None):
        """``shard=(rank, world)`` de-correlates the random-window stream
        across hosts (each host draws from a distinct seeded stream — the
        standard dp recipe for window-sampling loaders). ``shard=None``
        auto-detects from the launch env contract (PROCESS_ID /
        NUM_PROCESSES) or an ALREADY-INITIALIZED jax.distributed runtime;
        it never initializes the backend itself (constructing a dataset
        before ``launch.initialize_cluster()`` must stay side-effect-free),
        falling back to (0, 1)."""
        if shard is None:
            rank = int(os.environ.get("PROCESS_ID", "-1"))
            world = int(os.environ.get("NUM_PROCESSES", "-1"))
            if world > 0 and 0 <= rank < world:
                shard = (rank, world)
            else:
                try:
                    from jax._src import distributed as _jd
                    if _jd.global_state.client is not None:
                        import jax
                        shard = (jax.process_index(), jax.process_count())
                    else:
                        shard = (0, 1)
                except Exception:
                    shard = (0, 1)
        rank, world = shard
        if not (0 <= rank < world):
            raise ValueError(f"bad shard {shard}")
        self.shard = (rank, world)
        self.path = os.fspath(path)
        self.batch_size = batch_size
        self.seq_len = seq_len
        seed = seed * world + rank  # distinct stream per host
        self.seed = seed
        self.token_width = token_width
        self.num_workers = num_workers
        self.prefetch = prefetch
        self.num_batches = num_batches
        self._lib = _load_lib()
        self._handle = None

    def _open(self):
        h = self._lib.fl_open(self.path.encode(), self.token_width,
                              self.batch_size, self.seq_len, self.seed,
                              self.num_workers, self.prefetch)
        if not h:
            raise OSError(f"fastloader: cannot open {self.path}")
        return h

    @property
    def num_tokens(self) -> int:
        h = self._handle or self._open()
        n = int(self._lib.fl_num_tokens(h))
        if self._handle is None:
            self._lib.fl_close(h)
        return n

    def __iter__(self):
        h = self._open()
        window = self.seq_len + 1
        buf = np.empty((self.batch_size, window), dtype=np.int32)
        try:
            produced = 0
            while self.num_batches is None or produced < self.num_batches:
                rc = self._lib.fl_next(h, buf.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int32)))
                if rc != 0:
                    break
                yield buf[:, :-1].copy(), buf[:, 1:].copy()
                produced += 1
        finally:
            self._lib.fl_close(h)
