"""Samplers (ref: ``python/paddle/io/dataloader/sampler.py`` +
``batch_sampler.py`` incl. DistributedBatchSampler)."""
from __future__ import annotations

import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, seed=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)
        self.seed = seed
        self.epoch = 0

    def __iter__(self):
        rng = np.random.RandomState(
            None if self.seed is None else self.seed + self.epoch)
        n = len(self.data_source)
        if self.replacement:
            yield from rng.randint(0, n, self.num_samples).tolist()
        else:
            yield from rng.permutation(n)[:self.num_samples].tolist()
        self.epoch += 1

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False, seed=None):
        self.sampler = sampler or (
            RandomSampler(dataset, seed=seed) if shuffle else SequenceSampler(dataset))
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Per-host shard of the global batch (ref DistributedBatchSampler).
    On TPU each PROCESS feeds its local chips; global batch = world batches."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False, seed=0):
        import jax
        self.num_replicas = num_replicas if num_replicas is not None else jax.process_count()
        self.rank = rank if rank is not None else jax.process_index()
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            order = np.random.RandomState(self.seed + self.epoch).permutation(n)
        else:
            order = np.arange(n)
        # pad to a multiple of replicas so every rank sees equal batches
        total = ((n + self.num_replicas - 1) // self.num_replicas) * self.num_replicas
        order = np.concatenate([order, order[: total - n]])
        local = order[self.rank::self.num_replicas]
        batch = []
        for idx in local.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        local = (len(self.dataset) + self.num_replicas - 1) // self.num_replicas
        if self.drop_last:
            return local // self.batch_size
        return (local + self.batch_size - 1) // self.batch_size


class WeightedRandomSampler(Sampler):
    """Ref sampler.py:WeightedRandomSampler — sample indices with given
    per-index weights."""

    def __init__(self, weights, num_samples, replacement=True, seed=None):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement
        self.seed = seed
        self.epoch = 0

    def __iter__(self):
        rng = np.random.RandomState(
            None if self.seed is None else self.seed + self.epoch)
        p = self.weights / self.weights.sum()
        idx = rng.choice(len(self.weights), self.num_samples,
                         replace=self.replacement, p=p)
        self.epoch += 1
        yield from idx.tolist()

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    """Ref sampler.py:SubsetRandomSampler — permutation over given indices."""

    def __init__(self, indices, seed=None):
        self.indices = list(indices)
        self.seed = seed
        self.epoch = 0

    def __iter__(self):
        rng = np.random.RandomState(
            None if self.seed is None else self.seed + self.epoch)
        self.epoch += 1
        for i in rng.permutation(len(self.indices)):
            yield self.indices[i]

    def __len__(self):
        return len(self.indices)
