"""Parameter-efficient fine-tuning (ref: ``paddlenlp.peft`` —
LoRAConfig/LoRAModel).

TPU-first formulation: instead of wrapping layers with adapter modules
(the reference's nn.Layer surgery), LoRA lives as a SEPARATE small
pytree keyed by the dotted weight path, and ``lora_merge`` functionally
rebuilds the model with ``W + (alpha/r) * A @ B`` on the target weights
INSIDE the jitted loss — the base stays a closed-over constant, autodiff
reaches only the adapter tree, and XLA fuses the rank-r update into the
consuming matmul. Works on ANY model in the zoo (fused qkv_proj arrays
and Linear modules alike) because targeting is by path substring.

    lora = lora_init(model, rng, target_modules=("qkv_proj", "o_proj"))
    def loss_fn(lora):
        return lora_merge(model, lora).loss(x, y)      # grads: lora only
    merged = lora_merge(model, lora)                   # deployment merge
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import _path_to_str

# the reference's default LLaMA target set, extended with this zoo's
# fused projection names
DEFAULT_TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj", "qkv_proj",
                   "out_proj", "query_proj", "key_proj", "value_proj")


def _is_target(pstr: str, leaf, targets) -> bool:
    if not (hasattr(leaf, "ndim") and leaf.ndim == 2):
        return False
    last = pstr.split(".")[-2] if pstr.endswith(".weight") else \
        pstr.split(".")[-1]
    return any(t == last for t in targets)


def lora_targets(model, target_modules=DEFAULT_TARGETS):
    """Dotted paths of the 2-D weights LoRA will adapt."""
    flat, _ = jax.tree_util.tree_flatten_with_path(model)
    return [_path_to_str(p) for p, leaf in flat
            if _is_target(_path_to_str(p), leaf, tuple(target_modules))]


def lora_init(model, rng, r: int = 8, alpha: int = 16,
              target_modules=DEFAULT_TARGETS, dtype=jnp.float32):
    """Build the adapter tree: {path: {"a": [in, r], "b": [r, out]}}.
    ``b`` starts at zero (the reference convention), so the adapted model
    initially computes EXACTLY the base model."""
    flat, _ = jax.tree_util.tree_flatten_with_path(model)
    lora = {}
    for p, leaf in flat:
        pstr = _path_to_str(p)
        if not _is_target(pstr, leaf, tuple(target_modules)):
            continue
        rng, sub = jax.random.split(rng)
        fan_in = leaf.shape[0]
        lora[pstr] = {
            "a": (jax.random.normal(sub, (fan_in, r), dtype)
                  * (1.0 / jnp.sqrt(fan_in))),
            "b": jnp.zeros((r, leaf.shape[1]), dtype),
        }
    if not lora:
        raise ValueError(f"no 2-D weights matched {target_modules!r}")
    lora["_scale"] = jnp.asarray(alpha / r, jnp.float32)
    return lora


def lora_merge(model, lora):
    """Functionally rebuild ``model`` with ``W + scale * A @ B`` applied
    to every adapted weight. Differentiable w.r.t. ``lora``; the base
    weights pass through untouched (constant under jit)."""
    scale = lora["_scale"]
    flat, treedef = jax.tree_util.tree_flatten_with_path(model)
    leaves = []
    for p, leaf in flat:
        pstr = _path_to_str(p)
        ab = lora.get(pstr)
        if ab is None:
            leaves.append(leaf)
        else:
            delta = (ab["a"] @ ab["b"]).astype(jnp.float32) * scale
            leaves.append((leaf.astype(jnp.float32)
                           + delta).astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def lora_num_parameters(lora) -> int:
    return sum(int(v.size) for v in jax.tree_util.tree_leaves(lora))


def lora_state_dict(lora) -> dict:
    """Flat numpy state for checkpointing the adapters alone (the
    reference's lora_model.save_pretrained payload)."""
    import numpy as np
    out = {}
    for path, ab in lora.items():
        if path == "_scale":
            out["_scale"] = np.asarray(ab)
        else:
            out[path + ".lora_A"] = np.asarray(ab["a"])
            out[path + ".lora_B"] = np.asarray(ab["b"])
    return out


def lora_load_state_dict(lora, state: dict):
    """Inverse of ``lora_state_dict`` onto an existing adapter tree.

    Strict: the state's key set must match the adapter tree exactly.
    A tenant upload with a typo'd path, a stale target set, or extra
    tensors is rejected with a ``ValueError`` naming the offending keys
    (AdapterStore relies on this to bounce malformed uploads cleanly)."""
    expected = {"_scale"} | {p + sfx for p in lora if p != "_scale"
                             for sfx in (".lora_A", ".lora_B")}
    missing = sorted(expected - set(state))
    unexpected = sorted(set(state) - expected)
    if missing or unexpected:
        parts = []
        if missing:
            parts.append("missing keys: " + ", ".join(missing))
        if unexpected:
            parts.append("unexpected keys: " + ", ".join(unexpected))
        raise ValueError("lora_load_state_dict: state does not match the "
                         "adapter tree — " + "; ".join(parts))
    new = {}
    for path, ab in lora.items():
        if path == "_scale":
            new["_scale"] = jnp.asarray(state["_scale"], jnp.float32)
        else:
            new[path] = {"a": jnp.asarray(state[path + ".lora_A"],
                                          ab["a"].dtype),
                         "b": jnp.asarray(state[path + ".lora_B"],
                                          ab["b"].dtype)}
    return new


def make_lora_train_step(base_model, lora, optimizer, loss_fn):
    """Optimizer-integrated adapter-only training (the reference's
    LoRAModel + Trainer pairing): ONE jitted program computes the merged
    forward, adapter grads, and the optimizer update — the base model is
    a closed-over constant (frozen by construction; it is never donated
    or rewritten).

    ``loss_fn(merged_model, *batch) -> scalar``. Returns
    ``(step, lora, opt_state)`` with
    ``step(lora, opt_state, *batch) -> (lora, opt_state, loss)`` — the
    full adapter tree (``_scale`` included) flows in and out, so every
    other peft helper (``lora_merge``, ``lora_state_dict``) works on the
    trained tree directly; only the A/B leaves enter the optimizer (the
    ``_scale`` hyperparameter must not see weight decay). The returned
    ``lora`` is a COPY of the input leaves: the step donates its buffers,
    and a donating loop must never invalidate the caller's original tree
    (same rule as _pp_params(copy=True) in models/llama.py)."""
    scale = float(lora["_scale"])
    lora = jax.tree_util.tree_map(jnp.copy, lora)
    opt_state = optimizer.init(
        {k: v for k, v in lora.items() if k != "_scale"})

    def step(lora_tree, opt_state, *batch):
        adapters = {k: v for k, v in lora_tree.items() if k != "_scale"}

        def f(ad):
            merged = lora_merge(
                base_model,
                {**ad, "_scale": jnp.asarray(scale, jnp.float32)})
            return loss_fn(merged, *batch)

        loss, grads = jax.value_and_grad(f)(adapters)
        adapters, opt_state = optimizer.step(adapters, grads, opt_state)
        out = {**adapters, "_scale": jnp.asarray(scale, jnp.float32)}
        return out, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1)), lora, opt_state
