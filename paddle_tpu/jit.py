"""Graph capture (ref: ``python/paddle/jit/`` — ``to_static`` / SOT).

The reference converts dygraph Python into a static Program via AST
transforms and a bytecode tracer (SOT), then runs CINN. Under JAX the whole
dichotomy collapses: ``jax.jit`` traces the function once per input shape
and hands XLA the full graph. ``to_static`` is therefore a thin policy layer
over ``jax.jit``: static-argument marking, buffer donation, and HLO dump
hooks for debugging.
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax


def jit(fn: Callable = None, *, static_argnums=None, static_argnames=None,
        donate_argnums=None, device=None) -> Callable:
    if fn is None:
        return functools.partial(jit, static_argnums=static_argnums,
                                 static_argnames=static_argnames,
                                 donate_argnums=donate_argnums, device=device)
    return jax.jit(fn, static_argnums=static_argnums, static_argnames=static_argnames,
                   donate_argnums=donate_argnums)


def to_static(fn: Callable = None, **kwargs) -> Callable:
    """Reference-named alias (``paddle.jit.to_static``)."""
    return jit(fn, **kwargs)


def no_grad(fn: Callable = None):
    """Ref: ``paddle.no_grad`` — stop gradients through `fn` (or use as decorator)."""
    if fn is None:
        import contextlib

        @contextlib.contextmanager
        def ctx():
            yield
        return ctx()

    @functools.wraps(fn)
    def wrapped(*args, **kw):
        out = fn(*args, **kw)
        return jax.tree_util.tree_map(
            lambda x: jax.lax.stop_gradient(x) if isinstance(x, jax.Array) else x, out)
    return wrapped


def grad(fn: Callable, argnums=0, has_aux: bool = False) -> Callable:
    """Ref: ``paddle.grad`` — functional gradient transform."""
    return jax.grad(fn, argnums=argnums, has_aux=has_aux)


def dump_hlo(fn: Callable, *args, **kwargs) -> str:
    """Debug helper: lowered StableHLO text for `fn(*args)` (ref: Program.to_string)."""
    return jax.jit(fn).lower(*args, **kwargs).as_text()


def dump_jaxpr(fn: Callable, *args, **kwargs) -> str:
    return str(jax.make_jaxpr(fn)(*args, **kwargs))


def compiled_cost_analysis(fn: Callable, *args, **kwargs) -> dict:
    """FLOPs/bytes estimates from XLA for MFU accounting."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    try:
        return dict(compiled.cost_analysis())
    except Exception:
        return {}


class InputSpec:
    """Ref: paddle.static.InputSpec / paddle.jit input signatures.

    Under XLA a spec is a ShapeDtypeStruct; None dims mark varying axes
    (each distinct size triggers one retrace, same as the reference's
    bucketing)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def to_shape_struct(self, fill=1):
        import jax.numpy as jnp
        shape = tuple(fill if s is None else s for s in self.shape)
        return jax.ShapeDtypeStruct(shape, jnp.dtype(self.dtype))

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"
