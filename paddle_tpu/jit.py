"""Graph capture (ref: ``python/paddle/jit/`` — ``to_static`` / SOT).

The reference converts dygraph Python into a static Program via AST
transforms and a bytecode tracer (SOT), then runs CINN. Under JAX the whole
dichotomy collapses: ``jax.jit`` traces the function once per input shape
and hands XLA the full graph. ``to_static`` is therefore a thin policy layer
over ``jax.jit``: static-argument marking, buffer donation, and HLO dump
hooks for debugging.
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax


def jit(fn: Callable = None, *, static_argnums=None, static_argnames=None,
        donate_argnums=None, device=None, instrument: bool = False,
        name: str = None) -> Callable:
    if fn is None:
        return functools.partial(jit, static_argnums=static_argnums,
                                 static_argnames=static_argnames,
                                 donate_argnums=donate_argnums, device=device,
                                 instrument=instrument, name=name)
    if instrument:
        # compile introspection (ISSUE 4): trace/lower/compile spans,
        # compile_seconds histogram, cache hit/miss counters
        from paddle_tpu.observability.compile import instrumented_jit
        return instrumented_jit(fn, name=name,
                                static_argnums=static_argnums,
                                static_argnames=static_argnames,
                                donate_argnums=donate_argnums)
    return jax.jit(fn, static_argnums=static_argnums, static_argnames=static_argnames,
                   donate_argnums=donate_argnums)


def to_static(fn: Callable = None, **kwargs) -> Callable:
    """Reference-named alias (``paddle.jit.to_static``)."""
    return jit(fn, **kwargs)


def no_grad(fn: Callable = None):
    """Ref: ``paddle.no_grad`` — stop gradients through `fn` (or use as decorator)."""
    if fn is None:
        import contextlib

        @contextlib.contextmanager
        def ctx():
            yield
        return ctx()

    @functools.wraps(fn)
    def wrapped(*args, **kw):
        out = fn(*args, **kw)
        return jax.tree_util.tree_map(
            lambda x: jax.lax.stop_gradient(x) if isinstance(x, jax.Array) else x, out)
    return wrapped


def set_grad_enabled(mode: bool):
    """Ref: ``paddle.set_grad_enabled`` context manager. Autodiff here is a
    functional transform (``jax.grad`` traces on demand), so there is no
    global tape to switch off — with mode=False this marks intent only; use
    ``no_grad``/``stop_gradient`` to actually cut gradients at a value."""
    import contextlib

    @contextlib.contextmanager
    def ctx():
        yield
    return ctx()


def is_grad_enabled() -> bool:
    """Ref: ``paddle.is_grad_enabled`` — gradients are always available to
    a ``jax.grad`` trace; values opt out via stop_gradient."""
    return True


def grad(fn: Callable, argnums=0, has_aux: bool = False) -> Callable:
    """Ref: ``paddle.grad`` — functional gradient transform."""
    return jax.grad(fn, argnums=argnums, has_aux=has_aux)


def dump_hlo(fn: Callable, *args, **kwargs) -> str:
    """Debug helper: lowered StableHLO text for `fn(*args)` (ref: Program.to_string)."""
    return jax.jit(fn).lower(*args, **kwargs).as_text()


def dump_jaxpr(fn: Callable, *args, **kwargs) -> str:
    return str(jax.make_jaxpr(fn)(*args, **kwargs))


def compiled_cost_analysis(fn: Callable, *args, **kwargs) -> dict:
    """FLOPs/bytes estimates from XLA for MFU accounting."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    try:
        return dict(compiled.cost_analysis())
    except Exception:
        return {}


def save(fn_or_module, path: str, input_spec=None, example_args=None):
    """Serialize a traced program to disk (ref ``paddle.jit.save``: dygraph →
    inference Program + params). TPU-native form: ``jax.export`` serializes
    the StableHLO module + embedded weights — one artifact, loadable and
    runnable without the Python model class (the same deploy story as the
    reference's ``.pdmodel``/``.pdiparams`` pair).

    ``fn_or_module``: a Module (its ``__call__`` is exported) or a function.
    Provide ``input_spec`` (list of :class:`InputSpec`) or ``example_args``.
    """
    from jax import export as jexport

    import jax.numpy as jnp

    if example_args is None:
        if input_spec is None:
            raise ValueError("jit.save needs input_spec or example_args")
        # None dims become export symbols so the artifact accepts any size
        # along them (paddle InputSpec(None, ...) semantics)
        scope = jexport.SymbolicScope()
        example_args = []
        for i, s in enumerate(input_spec):
            dims = [f"_d{i}_{j}" if d is None else str(d)
                    for j, d in enumerate(s.shape)]
            shape = jexport.symbolic_shape(",".join(dims), scope=scope)
            example_args.append(jax.ShapeDtypeStruct(shape, jnp.dtype(s.dtype)))
        example_args = tuple(example_args)
    elif not isinstance(example_args, (tuple, list)):
        example_args = (example_args,)

    from paddle_tpu.core.module import Module
    if isinstance(fn_or_module, Module):
        mod = fn_or_module
        # snapshot per-layer modes: eval() mutates in place and the caller
        # may be mid-training
        modes = [m.training for m in mod.sublayers(include_self=True)]
        mod.eval()
        fn = lambda *xs: mod(*xs)
    else:
        mod, modes = None, None
        fn = fn_or_module
    try:
        exported = jexport.export(jax.jit(fn))(*example_args)
        data = exported.serialize()
    finally:
        if mod is not None:
            for m, was in zip(mod.sublayers(include_self=True), modes):
                object.__setattr__(m, "training", was)
    if not path.endswith(".stablehlo"):
        path = path + ".stablehlo"
    with open(path, "wb") as f:
        f.write(data)
    return path


def load(path: str):
    """Load a program saved by :func:`save`; returns a callable running the
    compiled artifact (ref ``paddle.jit.load``)."""
    from jax import export as jexport
    if not path.endswith(".stablehlo"):
        path = path + ".stablehlo"
    with open(path, "rb") as f:
        exported = jexport.deserialize(f.read())
    return jax.jit(exported.call)


class InputSpec:
    """Ref: paddle.static.InputSpec / paddle.jit input signatures.

    Under XLA a spec is a ShapeDtypeStruct; None dims mark varying axes
    (each distinct size triggers one retrace, same as the reference's
    bucketing)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def to_shape_struct(self, fill=1):
        import jax.numpy as jnp
        shape = tuple(fill if s is None else s for s in self.shape)
        return jax.ShapeDtypeStruct(shape, jnp.dtype(self.dtype))

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"
