"""Sparse tensors (ref: ``python/paddle/sparse/``).

Built on ``jax.experimental.sparse.BCOO`` — XLA's batched-COO format, the
only sparse representation with a TPU lowering. The reference's COO/CSR
creation API, elementwise ops, and matmul are provided; CSR inputs are
converted to BCOO (TPU kernels are gather/scatter based, so the distinction
is a storage detail, not a performance one, unlike cuSPARSE).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "is_sparse", "is_sparse_coo",
    "to_dense", "to_sparse_coo", "add", "subtract", "multiply", "divide",
    "matmul", "masked_matmul", "relu", "tanh", "sigmoid", "abs", "neg",
    "cast", "transpose", "sum", "nnz", "coalesce",
]


def sparse_coo_tensor(indices, values, shape=None, dtype=None):
    """Ref: paddle.sparse.sparse_coo_tensor — indices [ndim, nnz]."""
    indices = jnp.asarray(indices)
    values = jnp.asarray(values, dtype)
    if shape is None:
        shape = tuple(int(i) + 1 for i in indices.max(axis=1))
    return jsparse.BCOO((values, indices.T), shape=tuple(shape))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """Ref: paddle.sparse.sparse_csr_tensor — 2-D CSR, stored as BCOO."""
    crows = jnp.asarray(crows, jnp.int32)
    cols = jnp.asarray(cols, jnp.int32)
    values = jnp.asarray(values, dtype)
    n_rows = len(crows) - 1
    rows = jnp.repeat(jnp.arange(n_rows, dtype=jnp.int32),
                      jnp.diff(crows), total_repeat_length=values.shape[0])
    idx = jnp.stack([rows, cols], axis=1)
    return jsparse.BCOO((values, idx), shape=tuple(shape))


def is_sparse(x):
    return isinstance(x, jsparse.JAXSparse)


is_sparse_coo = is_sparse


def to_dense(x):
    return x.todense() if is_sparse(x) else x


def to_sparse_coo(x, sparse_dim=None):
    """sparse_dim leading dims sparse, the rest dense (reference hybrid
    layout → BCOO n_dense)."""
    n_dense = 0 if sparse_dim is None else jnp.ndim(x) - sparse_dim
    return jsparse.BCOO.fromdense(x, n_dense=n_dense)


def coalesce(x):
    return x.sum_duplicates(nse=int(x.nse))


def nnz(x):
    return x.nse


def _ew(fn, x, y=None):
    """Elementwise op on values (zero-preserving ops only)."""
    if y is None:
        return jsparse.BCOO((fn(x.data), x.indices), shape=x.shape)
    if not (is_sparse(x) and is_sparse(y)):
        # sparse x dense-array: dense result (reference returns dense too)
        return fn(to_dense(x), to_dense(y))
    # sparse-sparse: via dense with a STATIC nse bound so it stays jittable
    # (structural result pattern ⊆ union of operand patterns)
    nse = min(int(x.nse) + int(y.nse), int(np.prod(x.shape)))
    return jsparse.BCOO.fromdense(fn(to_dense(x), to_dense(y)), nse=nse)


def add(x, y):
    if is_sparse(x) and is_sparse(y):
        return _ew(jnp.add, x, y)
    return to_dense(x) + to_dense(y)


def subtract(x, y):
    return _ew(jnp.subtract, x, y) if is_sparse(x) and is_sparse(y) \
        else to_dense(x) - to_dense(y)


def multiply(x, y):
    if is_sparse(x) and not is_sparse(y) and jnp.ndim(y) == 0:
        return jsparse.BCOO((x.data * y, x.indices), shape=x.shape)
    return _ew(jnp.multiply, x, y)


def divide(x, y):
    if is_sparse(x) and not is_sparse(y) and jnp.ndim(y) == 0:
        return jsparse.BCOO((x.data / y, x.indices), shape=x.shape)
    if is_sparse(x) and is_sparse(y):
        # reference semantics: same-pattern value-wise quotient (densifying
        # would put 0/0 = NaN at every structural zero)
        xs = x.sum_duplicates(nse=int(x.nse))
        ys = y.sum_duplicates(nse=int(y.nse))
        if xs.indices.shape != ys.indices.shape:
            raise ValueError("sparse divide requires operands with the same "
                             "sparsity pattern (reference behaviour)")
        if not isinstance(xs.indices, jax.core.Tracer) and not bool(
                jnp.all(xs.indices == ys.indices)):
            # eager-only validation; under jit the same pattern is assumed
            raise ValueError("sparse divide requires operands with the same "
                             "sparsity pattern (reference behaviour)")
        return jsparse.BCOO((xs.data / ys.data, xs.indices), shape=x.shape)
    return _ew(jnp.divide, x, y)


def matmul(x, y):
    """sparse @ dense (or dense @ sparse) — BCOO dot_general on TPU;
    __matmul__/__rmatmul__ dispatch covers both operand orders."""
    return x @ y


def masked_matmul(x, y, mask):
    """Ref: paddle.sparse.masked_matmul — dense@dense sampled at mask's
    sparsity (SDDMM)."""
    rows = mask.indices[:, 0]
    cols = mask.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", x[rows, :], y[:, cols].T)
    return jsparse.BCOO((vals.astype(x.dtype), mask.indices), shape=mask.shape)


def relu(x):
    return _ew(jax.nn.relu, x)


def tanh(x):
    return _ew(jnp.tanh, x)


def sigmoid(x):
    # NOT zero-preserving; reference applies to stored values only
    return _ew(jax.nn.sigmoid, x)


def abs(x):
    return _ew(jnp.abs, x)


def neg(x):
    return _ew(jnp.negative, x)


def cast(x, dtype):
    return jsparse.BCOO((x.data.astype(dtype), x.indices), shape=x.shape)


def transpose(x, perm=(1, 0)):
    return jsparse.bcoo_transpose(x, permutation=tuple(perm))


def sum(x, axis=None, keepdim=False):
    if axis is None:
        out = jnp.sum(x.data)
        return out.reshape((1,) * x.ndim) if keepdim else out
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    axes = tuple(a % x.ndim for a in axes)
    out = jsparse.bcoo_reduce_sum(x, axes=axes)
    if keepdim:
        shape = tuple(1 if i in axes else s for i, s in enumerate(x.shape))
        out = jsparse.bcoo_reshape(out, new_sizes=shape)
    return out


def is_same_shape(x, y):
    """Ref sparse/unary.py:is_same_shape."""
    return tuple(x.shape) == tuple(y.shape)


class _SparseReLU:
    def __call__(self, x):
        return relu(x)


class _SparseReLU6:
    def __call__(self, x):
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse
        return jsparse.BCOO((jnp.clip(x.data, 0, 6), x.indices),
                            shape=x.shape)


from types import SimpleNamespace as _SNS  # noqa: E402

# ref paddle.sparse.nn — activations over sparse values
nn = _SNS(ReLU=_SparseReLU, ReLU6=_SparseReLU6)
