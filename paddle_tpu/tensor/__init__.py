"""Tensor op surface with reference naming/semantics.

Reference: ``python/paddle/tensor/`` (creation.py, math.py, manipulation.py,
linalg.py, search.py, logic.py, stat.py). Each op here keeps Paddle's name
and argument conventions (``axis=`` etc.) but lowers straight to jnp/lax so
XLA owns fusion and MXU tiling. Ops are pure functions of jax.Arrays — there
is deliberately no Tensor wrapper class: jax.Array IS the tensor type.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core import dtypes as _dt
from paddle_tpu.core.dtypes import get_default_dtype

# -- creation (ref python/paddle/tensor/creation.py) ------------------------

def to_tensor(data, dtype=None):
    return jnp.asarray(data, dtype=dtype)


def zeros(shape, dtype=None):
    return jnp.zeros(shape, dtype=dtype or get_default_dtype())


def ones(shape, dtype=None):
    return jnp.ones(shape, dtype=dtype or get_default_dtype())


def full(shape, fill_value, dtype=None):
    return jnp.full(shape, fill_value, dtype=dtype or get_default_dtype())


def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=dtype)


def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=dtype)


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=dtype)


def arange(start, end=None, step=1, dtype=None):
    return jnp.arange(start, end, step, dtype=dtype)


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, num, dtype=dtype or get_default_dtype())


def eye(num_rows, num_columns=None, dtype=None):
    return jnp.eye(num_rows, num_columns, dtype=dtype or get_default_dtype())


def diag(x, offset=0):
    return jnp.diag(x, k=offset)


def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def meshgrid(*args):
    return jnp.meshgrid(*args, indexing="ij")


def empty(shape, dtype=None):
    return jnp.zeros(shape, dtype=dtype or get_default_dtype())


def empty_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=dtype)


def clone(x):
    return jnp.array(x, copy=True)


def assign(x):
    return jnp.asarray(x)


# -- math (ref python/paddle/tensor/math.py) --------------------------------

add = jnp.add
subtract = jnp.subtract
multiply = jnp.multiply
divide = jnp.divide
floor_divide = jnp.floor_divide
mod = remainder = jnp.remainder
pow = jnp.power
negative = jnp.negative
abs = jnp.abs
sign = jnp.sign
sqrt = jnp.sqrt
rsqrt = lax.rsqrt
square = jnp.square
exp = jnp.exp
expm1 = jnp.expm1
log = jnp.log
log2 = jnp.log2
log10 = jnp.log10
log1p = jnp.log1p
sin = jnp.sin
cos = jnp.cos
tan = jnp.tan
asin = jnp.arcsin
acos = jnp.arccos
atan = jnp.arctan
atan2 = jnp.arctan2
sinh = jnp.sinh
cosh = jnp.cosh
tanh = jnp.tanh
asinh = jnp.arcsinh
acosh = jnp.arccosh
atanh = jnp.arctanh
ceil = jnp.ceil
floor = jnp.floor
round = jnp.round
trunc = jnp.trunc
reciprocal = jnp.reciprocal
erf = jax.scipy.special.erf
erfinv = jax.scipy.special.erfinv
lgamma = jax.scipy.special.gammaln
digamma = jax.scipy.special.digamma
isnan = jnp.isnan
isinf = jnp.isinf
isfinite = jnp.isfinite
maximum = jnp.maximum
minimum = jnp.minimum
fmax = jnp.fmax
fmin = jnp.fmin
hypot = jnp.hypot
nan_to_num = jnp.nan_to_num


def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


def multiply_(x, y):  # alias: no in-place under XLA, returns new array
    return jnp.multiply(x, y)


def lerp(x, y, weight):
    return x + weight * (y - x)


def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * (x @ y)


def outer(x, y):
    return jnp.outer(x, y)


def inner(x, y):
    return jnp.inner(x, y)


def logaddexp(x, y):
    return jnp.logaddexp(x, y)


def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


def frac(x):
    return x - jnp.trunc(x)


def rad2deg(x):
    return jnp.rad2deg(x)


def deg2rad(x):
    return jnp.deg2rad(x)


def gcd(x, y):
    return jnp.gcd(x, y)


def lcm(x, y):
    return jnp.lcm(x, y)


def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


def heaviside(x, y):
    return jnp.heaviside(x, y)


def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def kron(x, y):
    return jnp.kron(x, y)


# -- reductions (ref math.py / stat.py) -------------------------------------

def sum(x, axis=None, dtype=None, keepdim=False):
    return jnp.sum(x, axis=axis, dtype=dtype, keepdims=keepdim)


def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def max(x, axis=None, keepdim=False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim=False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=axis, keepdims=keepdim, dtype=dtype)


def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, q, axis=axis, keepdims=keepdim)


def iinfo(dtype):
    """Ref: paddle.iinfo — integer dtype limits."""
    return jnp.iinfo(dtype)


def finfo(dtype):
    """Ref: paddle.finfo — float dtype limits."""
    return jnp.finfo(dtype)


def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


def nanquantile(x, q, axis=None, keepdim=False):
    return jnp.nanquantile(x, q, axis=axis, keepdims=keepdim)


def as_strided(x, shape, stride, offset=0):
    """Strided view (ref manipulation.py:as_strided). JAX arrays have no
    raw-memory views, so this materialises the equivalent gather: index
    [i0..ik] reads flat element offset + sum(i*stride)."""
    flat = jnp.ravel(x)
    idx = jnp.asarray(offset)
    for n, s in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(n) * s
    return flat[idx]


def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def all(x, axis=None, keepdim=False):
    return jnp.all(x, axis=axis, keepdims=keepdim)


def any(x, axis=None, keepdim=False):
    return jnp.any(x, axis=axis, keepdims=keepdim)


def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


def nansum(x, axis=None, keepdim=False):
    return jnp.nansum(x, axis=axis, keepdims=keepdim)


def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=axis, keepdims=keepdim)


def cumsum(x, axis=None, dtype=None):
    return jnp.cumsum(x, axis=axis, dtype=dtype)


def cumprod(x, dim=None, dtype=None):
    return jnp.cumprod(x, axis=dim, dtype=dtype)


def cummax(x, axis=-1):
    return lax.associative_scan(jnp.maximum, x, axis=axis)


def cummin(x, axis=-1):
    return lax.associative_scan(jnp.minimum, x, axis=axis)


# -- linalg (ref python/paddle/tensor/linalg.py) ----------------------------

def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


def mm(x, y):
    return jnp.matmul(x, y)


def bmm(x, y):
    return jnp.matmul(x, y)


def dot(x, y):
    return jnp.sum(x * y, axis=-1)


def t(x):
    return jnp.swapaxes(x, -1, -2) if x.ndim >= 2 else x


def transpose(x, perm):
    return jnp.transpose(x, axes=perm)


def einsum(equation, *operands):
    return jnp.einsum(equation, *operands)


def norm(x, p=2, axis=None, keepdim=False):
    if p == "fro" or p == 2:
        return jnp.linalg.norm(x, ord=None if axis is None else 2, axis=axis, keepdims=keepdim)
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


def dist(x, y, p=2):
    return norm(x - y, p=p)


def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


def inverse(x):
    return jnp.linalg.inv(x)


def pinv(x, rcond=1e-15):
    return jnp.linalg.pinv(x, rtol=rcond)


def det(x):
    return jnp.linalg.det(x)


def slogdet(x):
    return jnp.linalg.slogdet(x)


def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


def cholesky(x, upper=False):
    c = jnp.linalg.cholesky(x)
    return jnp.swapaxes(c, -1, -2) if upper else c


def eigh(x):
    return jnp.linalg.eigh(x)


def solve(a, b):
    return jnp.linalg.solve(a, b)


def lstsq(a, b):
    return jnp.linalg.lstsq(a, b)


def triangular_solve(a, b, upper=True):
    return jax.scipy.linalg.solve_triangular(a, b, lower=not upper)


def matrix_rank(x, tol=None, hermitian=False):
    from paddle_tpu import linalg
    return linalg.matrix_rank(x, tol=tol, hermitian=hermitian)


def histogram(x, bins=100, min=0, max=0):
    rng = None if min == 0 and max == 0 else (min, max)
    return jnp.histogram(x, bins=bins, range=rng)[0]


def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength)


# -- manipulation (ref python/paddle/tensor/manipulation.py) ----------------

def reshape(x, shape):
    return jnp.reshape(x, shape)


def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if stop_axis < 0:
        stop_axis += nd
    if start_axis < 0:
        start_axis += nd
    shape = x.shape[:start_axis] + (-1,) + x.shape[stop_axis + 1:]
    return jnp.reshape(x, shape)


def concat(x, axis=0):
    return jnp.concatenate(x, axis=axis)


def stack(x, axis=0):
    return jnp.stack(x, axis=axis)


def split(x, num_or_sections, axis=0):
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    sections = list(num_or_sections)
    # paddle allows one -1 meaning "the rest"
    if -1 in sections:
        known = builtins_sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = x.shape[axis] - known
    idx = jnp.cumsum(jnp.array(sections))[:-1]
    return jnp.split(x, [int(i) for i in idx], axis=axis)


def builtins_sum(it):
    import builtins
    return builtins.sum(it)


def chunk(x, chunks, axis=0):
    return jnp.array_split(x, chunks, axis=axis)


def squeeze(x, axis=None):
    return jnp.squeeze(x, axis=axis)


def unsqueeze(x, axis):
    return jnp.expand_dims(x, axis)


def expand(x, shape):
    return jnp.broadcast_to(x, shape)


def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


def broadcast_to(x, shape):
    return jnp.broadcast_to(x, shape)


def tile(x, repeat_times):
    return jnp.tile(x, repeat_times)


def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


def flip(x, axis):
    return jnp.flip(x, axis=axis)


def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


def gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def gather_nd(x, index):
    return x[tuple(jnp.moveaxis(index, -1, 0))]


def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


def put_along_axis(x, indices, values, axis):
    return jnp.put_along_axis(x, indices, values, axis=axis, inplace=False)


def scatter(x, index, updates, overwrite=True):
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


def scatter_nd_add(x, index, updates):
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def index_add(x, index, axis, value):
    return _index_add(x, index, axis, value)


def _index_add(x, index, axis, value):
    x_m = jnp.moveaxis(x, axis, 0)
    v_m = jnp.moveaxis(value, axis, 0)
    out = x_m.at[index].add(v_m)
    return jnp.moveaxis(out, 0, axis)


def slice(x, axes, starts, ends):
    idx = [builtins_slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = builtins_slice(s, e)
    return x[tuple(idx)]


def builtins_slice(*a):
    import builtins
    return builtins.slice(*a)


def strided_slice(x, axes, starts, ends, strides):
    idx = [builtins_slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[ax] = builtins_slice(s, e, st)
    return x[tuple(idx)]


def unbind(x, axis=0):
    return [jnp.squeeze(s, axis=axis) for s in jnp.split(x, x.shape[axis], axis=axis)]


def unstack(x, axis=0):
    return unbind(x, axis)


def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


def swapaxes(x, axis1, axis2):
    return jnp.swapaxes(x, axis1, axis2)


def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def as_complex(x):
    return lax.complex(x[..., 0], x[..., 1])


def cast(x, dtype):
    return x.astype(dtype)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    """Paddle pad. Short form: (low, high) pairs apply from the LAST spatial
    axis backwards (torch convention adopted by paddle); channel-last
    formats (NLC/NHWC/NDHWC) skip the trailing C axis. Full form
    (len == 2*ndim): per-dim pairs in dim order."""
    pad = list(pad)
    if len(pad) == 2 * x.ndim:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        last = x.ndim - 2 if (data_format and data_format.endswith("C")
                              and x.ndim >= 3) else x.ndim - 1
        cfg = [(0, 0)] * x.ndim
        for i in range(len(pad) // 2):
            cfg[last - i] = (pad[2 * i], pad[2 * i + 1])
    if mode == "constant":
        return jnp.pad(x, cfg, constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, cfg, mode=jmode)


def where(condition, x=None, y=None):
    if x is None and y is None:
        return jnp.nonzero(condition)
    return jnp.where(condition, x, y)


def masked_select(x, mask):
    return x[mask]


def masked_fill(x, mask, value):
    return jnp.where(mask, value, x)


def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def diag_embed(x, offset=0):
    n = x.shape[-1] + builtins_abs(offset)
    base = jnp.zeros(x.shape[:-1] + (n, n), dtype=x.dtype)
    idx = jnp.arange(x.shape[-1])
    if offset >= 0:
        return base.at[..., idx, idx + offset].set(x)
    return base.at[..., idx - offset, idx].set(x)


def builtins_abs(v):
    import builtins
    return builtins.abs(v)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    return jnp.unique(x, return_index=return_index, return_inverse=return_inverse,
                      return_counts=return_counts, axis=axis)


def unique_consecutive(x, axis=None):
    if axis is None:
        x = x.ravel()
        keep = jnp.concatenate([jnp.array([True]), x[1:] != x[:-1]])
        return x[keep]
    raise NotImplementedError("axis != None requires static shapes")


# -- search / sort (ref python/paddle/tensor/search.py) ---------------------

def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim)
    return out.astype(_dt.canonical_int_dtype(dtype))


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim)
    return out.astype(_dt.canonical_int_dtype(dtype))


def argsort(x, axis=-1, descending=False, stable=True):
    out = jnp.argsort(x, axis=axis, stable=stable, descending=descending)
    return out


def sort(x, axis=-1, descending=False):
    return jnp.sort(x, axis=axis, descending=descending)


def topk(x, k, axis=-1, largest=True, sorted=True):
    if not largest:
        values, indices = lax.top_k(jnp.moveaxis(-x, axis, -1), k)
        values = -values
    else:
        values, indices = lax.top_k(jnp.moveaxis(x, axis, -1), k)
    return jnp.moveaxis(values, -1, axis), jnp.moveaxis(indices, -1, axis)


def kthvalue(x, k, axis=-1, keepdim=False):
    sorted_x = jnp.sort(x, axis=axis)
    idx = jnp.argsort(x, axis=axis)
    vals = jnp.take(sorted_x, k - 1, axis=axis)
    inds = jnp.take(idx, k - 1, axis=axis)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        inds = jnp.expand_dims(inds, axis)
    return vals, inds


def mode(x, axis=-1, keepdim=False):
    raise NotImplementedError("mode requires dynamic shapes; use host path")


def nonzero(x, as_tuple=False):
    nz = jnp.nonzero(x)
    if as_tuple:
        return nz
    return jnp.stack(nz, axis=-1)


def searchsorted(sorted_sequence, values, right=False):
    return jnp.searchsorted(sorted_sequence, values, side="right" if right else "left")


def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


# -- logic (ref python/paddle/tensor/logic.py) ------------------------------

equal = jnp.equal
not_equal = jnp.not_equal
greater_than = jnp.greater
greater_equal = jnp.greater_equal
less_than = jnp.less
less_equal = jnp.less_equal
logical_and = jnp.logical_and
logical_or = jnp.logical_or
logical_not = jnp.logical_not
logical_xor = jnp.logical_xor
bitwise_and = jnp.bitwise_and
bitwise_or = jnp.bitwise_or
bitwise_xor = jnp.bitwise_xor
bitwise_not = jnp.bitwise_not


def equal_all(x, y):
    return jnp.array_equal(x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def is_empty(x):
    return x.size == 0


# -- random sampling (ref python/paddle/tensor/random.py) -------------------
# Eager-mode convenience using the global seed; inside jit pass keys to the
# keyed variants (suffix `_with_key`).

def _k():
    from paddle_tpu.core.random import next_key
    return next_key()


def rand(shape, dtype=None):
    return jax.random.uniform(_k(), shape, dtype=dtype or get_default_dtype())


def randn(shape, dtype=None):
    return jax.random.normal(_k(), shape, dtype=dtype or get_default_dtype())


def randint(low, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return jax.random.randint(_k(), shape, low, high,
                              dtype=_dt.canonical_int_dtype(dtype))


def uniform(shape, dtype=None, min=-1.0, max=1.0):
    return jax.random.uniform(_k(), shape, dtype=dtype or get_default_dtype(),
                              minval=min, maxval=max)


def normal(mean=0.0, std=1.0, shape=(1,)):
    return mean + std * jax.random.normal(_k(), shape, dtype=get_default_dtype())


def randperm(n, dtype="int64"):
    return jax.random.permutation(_k(), n).astype(_dt.canonical_int_dtype(dtype))


def multinomial(x, num_samples=1, replacement=False):
    logits = jnp.log(jnp.clip(x, 1e-30, None))
    if replacement:
        return jax.random.categorical(_k(), logits, shape=x.shape[:-1] + (num_samples,))
    return jax.random.choice(_k(), x.shape[-1], shape=(num_samples,), replace=False,
                             p=x / x.sum())


def bernoulli(x):
    return jax.random.bernoulli(_k(), x).astype(get_default_dtype())


# -- misc -------------------------------------------------------------------

def numel(x):
    return x.size


def shape(x):
    return jnp.array(x.shape, dtype=jnp.int32)


def item(x):
    return x.item()


def increment(x, value=1.0):
    return x + value


def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


def bucketize(x, sorted_sequence, right=False):
    return jnp.searchsorted(sorted_sequence, x, side="right" if right else "left")


def tensordot(x, y, axes=2):
    return jnp.tensordot(x, y, axes=axes)


def mv(x, vec):
    return jnp.matmul(x, vec)


def cdist(x, y, p=2.0):
    diff = x[..., :, None, :] - y[..., None, :, :]
    return norm(diff, p=p, axis=-1)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot_ = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot_ / jnp.maximum(n1 * n2, eps)


# -- complex ops (ref python/paddle/tensor/attribute.py, creation.py) --------

conj = jnp.conj
real = jnp.real
imag = jnp.imag
angle = jnp.angle


def complex(real_part, imag_part):
    return jax.lax.complex(jnp.asarray(real_part, jnp.float32),
                           jnp.asarray(imag_part, jnp.float32))


def polar(abs_val, angle_val):
    return complex(abs_val * jnp.cos(angle_val), abs_val * jnp.sin(angle_val))


# -- misc math gap-fill (ref python/paddle/tensor/math.py) -------------------

copysign = jnp.copysign
signbit = jnp.signbit
ldexp = jnp.ldexp
nextafter = jnp.nextafter
i0 = jax.scipy.special.i0
i0e = jax.scipy.special.i0e
i1 = jax.scipy.special.i1
i1e = jax.scipy.special.i1e
gammaln = jax.scipy.special.gammaln
multigammaln = jax.scipy.special.multigammaln


def polygamma(x, n):
    return jax.scipy.special.polygamma(n, x)


def frexp(x):
    return jnp.frexp(x)


def trapezoid(y, x=None, dx=None, axis=-1):
    if x is not None:
        return jax.scipy.integrate.trapezoid(y, x=x, axis=axis)
    return jax.scipy.integrate.trapezoid(y, dx=1.0 if dx is None else dx, axis=axis)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1):
    """Running trapezoid integral (one fewer element along axis)."""
    y = jnp.asarray(y)
    n = y.shape[axis]
    y0 = jax.lax.slice_in_dim(y, 0, n - 1, axis=axis)
    y1 = jax.lax.slice_in_dim(y, 1, n, axis=axis)
    avg = (y0 + y1) * 0.5
    if x is not None:
        x = jnp.asarray(x)
        if x.ndim == 1:
            shape = [1] * y.ndim
            shape[axis] = n
            x = x.reshape(shape)
        d = jnp.diff(x, axis=axis)
        return jnp.cumsum(avg * d, axis=axis)
    return jnp.cumsum(avg * (1.0 if dx is None else dx), axis=axis)


def logcumsumexp(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.cumlogsumexp(x, axis=axis)


def renorm(x, p, axis, max_norm):
    """Clamp the p-norm of every slice along ``axis`` to ``max_norm``."""
    x = jnp.asarray(x)
    axes = tuple(i for i in range(x.ndim) if i != (axis % x.ndim))
    norms = jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12), 1.0)
    return x * factor


def vander(x, n=None, increasing=False):
    return jnp.vander(x, N=n, increasing=increasing)


def take(x, index, mode="raise"):
    """Flattened gather (ref math.py:take). mode: 'raise'|'wrap'|'clip' —
    'raise' clamps like 'clip' on device (no exceptions under jit)."""
    flat = jnp.asarray(x).reshape(-1)
    idx = jnp.asarray(index)
    if mode == "wrap":
        idx = idx % flat.shape[0]
    else:
        idx = jnp.clip(idx, -flat.shape[0], flat.shape[0] - 1)
    return flat[idx].reshape(idx.shape)


# -- split/shape gap-fill (ref python/paddle/tensor/manipulation.py) ---------

def tensor_split(x, num_or_indices, axis=0):
    return jnp.array_split(x, num_or_indices, axis=axis)


def hsplit(x, num_or_indices):
    return jnp.hsplit(x, num_or_indices)


def vsplit(x, num_or_indices):
    return jnp.vsplit(x, num_or_indices)


def dsplit(x, num_or_indices):
    return jnp.dsplit(x, num_or_indices)


atleast_1d = jnp.atleast_1d
atleast_2d = jnp.atleast_2d
atleast_3d = jnp.atleast_3d


def index_fill(x, index, axis, value):
    x = jnp.asarray(x)
    idx = jnp.asarray(index)
    moved = jnp.moveaxis(x, axis, 0)
    moved = moved.at[idx].set(value)
    return jnp.moveaxis(moved, 0, axis)


def masked_scatter(x, mask, value):
    """Fill True positions of ``mask`` with consecutive elements of ``value``
    (ref manipulation.py:masked_scatter). Static-shape formulation: the k-th
    True position (row-major) takes value.flatten()[k]."""
    x = jnp.asarray(x)
    m = jnp.broadcast_to(jnp.asarray(mask, bool), x.shape).reshape(-1)
    v = jnp.asarray(value).reshape(-1)
    pos = jnp.cumsum(m) - 1  # index into v for each True slot
    flat = x.reshape(-1)
    out = jnp.where(m, v[jnp.clip(pos, 0, v.shape[0] - 1)], flat)
    return out.reshape(x.shape)


bitwise_left_shift = jnp.left_shift
bitwise_right_shift = jnp.right_shift


def poisson(x):
    x = jnp.asarray(x)
    out = jax.random.poisson(_k(), x)
    # jnp.issubdtype, not dtype.kind: ml_dtypes (bfloat16) report kind 'V'
    return out.astype(x.dtype) if jnp.issubdtype(x.dtype, jnp.floating) else out


def standard_gamma(x):
    return jax.random.gamma(_k(), jnp.asarray(x))


def standard_normal(shape, dtype=None):
    return randn(shape, dtype)


def log_normal(mean=1.0, std=2.0, shape=(1,), dtype=None):
    return jnp.exp(jax.random.normal(_k(), shape,
                                     dtype=dtype or get_default_dtype()) * std + mean)


# -- top-level alias/gap-fill (ref python/paddle/tensor/ misc) ---------------

def add_n(inputs):
    """Ref math.py:add_n — elementwise sum of a tensor list."""
    if not isinstance(inputs, (list, tuple)):
        return inputs
    out = inputs[0]
    for t in inputs[1:]:
        out = out + t
    return out


arccos = jnp.arccos
arcsin = jnp.arcsin
arctan = jnp.arctan
arctan2 = jnp.arctan2
neg = jnp.negative
hstack = jnp.hstack
vstack = jnp.vstack


def floor_mod(x, y):
    return jnp.mod(x, y)


def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def broadcast_tensors(inputs):
    return list(jnp.broadcast_arrays(*inputs))


def crop(x, shape=None, offsets=None):
    """Ref creation.py:crop — slice an offset window; -1 extends to the
    end of that dim (after the offset)."""
    offsets = list(offsets) if offsets is not None else [0] * x.ndim
    shape = list(shape if shape is not None else x.shape)
    shape = [x.shape[i] - offsets[i] if s in (-1, None) else s
             for i, s in enumerate(shape)]
    for i, (off, size) in enumerate(zip(offsets, shape)):
        if isinstance(off, int) and isinstance(size, int) \
                and off + size > x.shape[i]:
            raise ValueError(
                f"crop: offsets[{i}]+shape[{i}] = {off + size} exceeds dim "
                f"{x.shape[i]} (dynamic_slice would silently clamp)")
    return jax.lax.dynamic_slice(x, offsets, shape)


def is_tensor(x):
    return isinstance(x, jax.Array)


def is_complex(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer)


def logspace(start, stop, num, base=10.0, dtype=None):
    return jnp.logspace(start, stop, int(num), base=base,
                        dtype=dtype or get_default_dtype())


def multiplex(inputs, index):
    """Ref math.py:multiplex — row r of the output comes from
    inputs[index[r]][r]."""
    stacked = jnp.stack(list(inputs), axis=0)  # [K, B, ...]
    idx = jnp.reshape(jnp.asarray(index), (-1,))
    return jnp.take_along_axis(
        stacked, idx[None, :].reshape((1, -1) + (1,) * (stacked.ndim - 2)),
        axis=0)[0]


def percentile(x, q, axis=None, keepdim=False):
    return jnp.percentile(x, q, axis=axis, keepdims=keepdim)


def randint_like(x, low, high=None, dtype=None):
    dtype = dtype or x.dtype
    if jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        # reference allows float outputs: integer values cast to float
        return randint(low, high, shape=x.shape, dtype="int64").astype(dtype)
    return randint(low, high, shape=x.shape, dtype=dtype)


def rank(x):
    return jnp.asarray(jnp.asarray(x).ndim)


def scatter_nd(index, updates, shape):
    """Ref manipulation.py:scatter_nd — zeros of `shape` with `updates`
    added at `index` (duplicate indices accumulate)."""
    zeros = jnp.zeros(tuple(shape), jnp.asarray(updates).dtype)
    return scatter_nd_add(zeros, index, updates)


def sgn(x):
    """Sign for real; unit-phase for complex (ref math.py:sgn)."""
    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, 0.0 + 0.0j, x / jnp.where(mag == 0, 1, mag))
    return jnp.sign(x)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Ref manipulation.py:shard_index — recode global ids into a shard's
    local range; ids outside this shard become ignore_value."""
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    hi = lo + shard_size
    inside = (input >= lo) & (input < hi)
    return jnp.where(inside, input - lo, ignore_value)


def tolist(x):
    import numpy as _np
    return _np.asarray(x).tolist()


def tril_indices(row, col=None, offset=0):
    col = col if col is not None else row
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return jnp.stack([r, c])


def triu_indices(row, col=None, offset=0):
    col = col if col is not None else row
    r, c = jnp.triu_indices(row, k=offset, m=col)
    return jnp.stack([r, c])


def unflatten(x, axis, shape):
    axis = axis % x.ndim
    shape = list(shape)
    if -1 in shape:  # one entry may be inferred
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        shape[shape.index(-1)] = x.shape[axis] // known
    return jnp.reshape(x, x.shape[:axis] + tuple(shape) + x.shape[axis + 1:])


def unfold(x, axis, size, step):
    """Ref manipulation.py:unfold — sliding windows along `axis` (torch
    Tensor.unfold semantics): windows of `size` every `step`, window dim
    appended last."""
    axis = axis % x.ndim
    if size > x.shape[axis]:
        raise ValueError(
            f"unfold: window size {size} exceeds axis length {x.shape[axis]}")
    n = (x.shape[axis] - size) // step + 1
    starts = jnp.arange(n) * step
    idx = starts[:, None] + jnp.arange(size)[None, :]  # [n, size]
    out = jnp.take(x, idx, axis=axis)  # axis -> (n, size)
    # move the window dim to the end
    return jnp.moveaxis(out, axis + 1, -1)


# -- final audit round (ref manipulation.py / creation.py) -------------------

import builtins as _builtins  # noqa: E402
builtins_max = _builtins.max
builtins_min = _builtins.min


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1):
    """Write ``y`` onto the given diagonal of ``x`` (ref
    manipulation.py:diagonal_scatter)."""
    xm = jnp.moveaxis(x, (axis1, axis2), (-2, -1))
    m, n = xm.shape[-2], xm.shape[-1]
    k = builtins_min(m, n - offset) if offset >= 0 else builtins_min(m + offset, n)
    r = jnp.arange(k) + builtins_max(-offset, 0)
    c = jnp.arange(k) + builtins_max(offset, 0)
    xm = xm.at[..., r, c].set(y)
    return jnp.moveaxis(xm, (-2, -1), (axis1, axis2))


def fill_diagonal(x, value, offset=0, wrap=False):
    """Functional fill_diagonal (returns a new array; no mutation under
    XLA). Matches the reference: ndim > 2 fills the GRAND diagonal
    x[i, i, ..., i] (all dims must be equal); 2-D supports ``offset`` and
    numpy-style ``wrap`` for tall matrices."""
    if x.ndim > 2:
        if len(set(x.shape)) != 1:
            raise ValueError(
                "fill_diagonal with ndim > 2 requires equal dims "
                f"(got {x.shape})")
        idx = (jnp.arange(x.shape[0]),) * x.ndim
        return x.at[idx].set(value)
    m, n = x.shape[-2], x.shape[-1]
    k = builtins_min(m, n - offset) if offset >= 0 \
        else builtins_min(m + offset, n)
    r = jnp.arange(k) + builtins_max(-offset, 0)
    c = jnp.arange(k) + builtins_max(offset, 0)
    out = x.at[..., r, c].set(value)
    if wrap and offset == 0 and m > n:  # numpy wrapped tall-matrix diagonal
        for start in range(n + 1, m, n + 1):
            kk = builtins_min(m - start, n)
            rr = jnp.arange(kk) + start
            cc = jnp.arange(kk)
            out = out.at[..., rr, cc].set(value)
    return out


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1):
    return diagonal_scatter(x, y, offset, dim1, dim2)


def index_put(x, indices, value, accumulate=False):
    """Ref manipulation.py:index_put — advanced-index write (functional)."""
    idx = tuple(indices)
    return x.at[idx].add(value) if accumulate else x.at[idx].set(value)


def take_along_dim(x, indices, dim):
    return jnp.take_along_axis(x, indices, axis=dim)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None):
    """Ref linalg.py:histogramdd — ``ranges`` is the reference's FLAT
    [min0, max0, min1, max1, ...] list; converted to numpy's per-dim
    pairs."""
    if ranges is not None:
        flat = list(ranges)
        if len(flat) != 2 * x.shape[-1]:
            raise ValueError(
                f"ranges must hold 2 values per dimension "
                f"({2 * x.shape[-1]}), got {len(flat)}")
        ranges = [(flat[2 * i], flat[2 * i + 1])
                  for i in range(len(flat) // 2)]
    h, edges = jnp.histogramdd(x, bins=bins, range=ranges, density=density,
                               weights=weights)
    return h, list(edges)


def histogram_bin_edges(x, bins=100, min=0, max=0):
    """Reference sentinel semantics (matching ``histogram`` above):
    min == max == 0 means use the data range."""
    rng = None if (min == 0 and max == 0) else (min, max)
    return jnp.histogram_bin_edges(x, bins=bins, range=rng)


def block_diag(*inputs):
    if len(inputs) == 1 and isinstance(inputs[0], (list, tuple)):
        inputs = tuple(inputs[0])
    return jax.scipy.linalg.block_diag(*inputs)


def column_stack(xs):
    return jnp.column_stack(tuple(xs))


def row_stack(xs):
    return jnp.vstack(tuple(xs))


def dstack(xs):
    return jnp.dstack(tuple(xs))


def positive(x):
    return +jnp.asarray(x)


def view(x, shape_or_dtype):
    """Ref manipulation.py:view — reshape or reinterpret-cast."""
    if isinstance(shape_or_dtype, (list, tuple)):
        return jnp.reshape(x, shape_or_dtype)
    return x.view(shape_or_dtype) if hasattr(x, "view") else \
        jnp.asarray(x).view(shape_or_dtype)


def view_as(x, other):
    return jnp.reshape(x, jnp.shape(other))
