"""Byte-level BPE tokenizer (capability ref: PaddleNLP FastTokenizer /
GPT-2-style BPE).

Training (offline) is Python; the per-text encode hot loop runs in
``native/libfastbpe.so`` via ctypes (calls release the GIL, so a thread
pool scales batch encoding across cores). A pure-Python encoder backs the
same algorithm for environments without the native build and for tests.
"""
from __future__ import annotations

import ctypes
import json
import os
from collections import Counter
from concurrent.futures import ThreadPoolExecutor

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")


def _load_native():
    path = os.path.join(_NATIVE_DIR, "libfastbpe.so")
    src = os.path.join(_NATIVE_DIR, "fast_bpe.cpp")
    stale = (os.path.exists(path) and os.path.exists(src)
             and os.path.getmtime(src) > os.path.getmtime(path))
    if (not os.path.exists(path) or stale) and os.path.exists(src):
        import subprocess
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR, "-B", "libfastbpe.so"],
                           check=True, capture_output=True)
        except Exception:
            if not os.path.exists(path):
                return None
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:  # wrong arch / platform: pure-Python fallback
        return None
    lib.bpe_new.restype = ctypes.c_void_p
    lib.bpe_new.argtypes = [ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
                            ctypes.POINTER(ctypes.c_int32)]
    lib.bpe_free.argtypes = [ctypes.c_void_p]
    lib.bpe_encode.restype = ctypes.c_int64
    lib.bpe_encode.argtypes = [ctypes.c_void_p,
                               ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                               ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
    return lib


_LIB = None


class BPETokenizer:
    """vocab: id -> bytes; merges: ordered list of (left_id, right_id)."""

    def __init__(self, merges, special_tokens=None, use_native=True):
        self.merges = [tuple(m) for m in merges]
        # ids 0..255 are the raw bytes; merged tokens follow in rank order
        self.vocab = {i: bytes([i]) for i in range(256)}
        self._ranks = {}
        for rank, (a, b) in enumerate(self.merges):
            new_id = 256 + rank
            self.vocab[new_id] = self.vocab[a] + self.vocab[b]
            self._ranks[(a, b)] = (rank, new_id)
        self.special_tokens = dict(special_tokens or {})  # str -> id
        for tok, tid in self.special_tokens.items():
            self.vocab[tid] = tok.encode("utf-8")
        self._handle = None
        if use_native:
            global _LIB
            if _LIB is None:
                _LIB = _load_native()
            if _LIB is not None:
                flat = np.asarray([[a, b, 256 + r] for r, (a, b)
                                   in enumerate(self.merges)],
                                  np.int32).reshape(-1)
                byte_ids = np.arange(256, dtype=np.int32)
                self._merges_buf = flat  # keep alive
                self._bytes_buf = byte_ids
                self._handle = _LIB.bpe_new(
                    flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    len(self.merges),
                    byte_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))

    def __del__(self):
        if getattr(self, "_handle", None) and _LIB is not None:
            _LIB.bpe_free(self._handle)
            self._handle = None

    @property
    def vocab_size(self):
        return 256 + len(self.merges) + len(self.special_tokens)

    # -- training ------------------------------------------------------------
    @classmethod
    def train(cls, texts, vocab_size=1024, special_tokens=("<pad>", "<eos>"),
              use_native=True):
        """Classic BPE training: repeatedly merge the most frequent pair.
        Words are whitespace-chunked (spaces kept with the following word,
        GPT-2 style) so merges never cross word boundaries."""
        words = Counter()
        for t in texts:
            for i, w in enumerate(t.split(" ")):
                words[(" " if i else "") + w] += 1
        seqs = {w: list(w.encode("utf-8")) for w in words}
        merges = []
        n_special = len(special_tokens)
        while 256 + len(merges) + n_special < vocab_size:
            pairs = Counter()
            for w, cnt in words.items():
                s = seqs[w]
                for i in range(len(s) - 1):
                    pairs[(s[i], s[i + 1])] += cnt
            if not pairs:
                break
            (a, b), freq = pairs.most_common(1)[0]
            if freq < 2:
                break
            new_id = 256 + len(merges)
            merges.append((a, b))
            for w in seqs:
                s = seqs[w]
                out, i = [], 0
                while i < len(s):
                    if i + 1 < len(s) and s[i] == a and s[i + 1] == b:
                        out.append(new_id)
                        i += 2
                    else:
                        out.append(s[i])
                        i += 1
                seqs[w] = out
        specials = {t: 256 + len(merges) + i
                    for i, t in enumerate(special_tokens)}
        return cls(merges, specials, use_native=use_native)

    # -- encoding ------------------------------------------------------------
    @staticmethod
    def _chunks(text):
        """Split like training (spaces bind to the following word): merges
        never cross these boundaries, so per-chunk encoding is byte-identical
        to whole-text encoding while keeping the greedy loop O(word²)."""
        for i, w in enumerate(text.split(" ")):
            c = (" " if i else "") + w
            if c:
                yield c

    def _encode_seq_py(self, chunk):
        ids = list(chunk.encode("utf-8"))
        while len(ids) >= 2:
            best = None
            for i in range(len(ids) - 1):
                r = self._ranks.get((ids[i], ids[i + 1]))
                if r is not None and (best is None or r[0] < best[0]):
                    best = (r[0], i, r[1])
            if best is None:
                break
            _, i, new_id = best
            ids[i:i + 2] = [new_id]
        return ids

    def _encode_seq_native(self, chunk):
        raw = chunk.encode("utf-8")
        buf_len = max(len(raw), 1)
        buf = np.empty(buf_len, np.int32)
        src = np.frombuffer(raw, np.uint8)
        n = _LIB.bpe_encode(
            self._handle,
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(raw),
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), buf_len)
        if n < 0:  # can't happen: output never exceeds input bytes
            raise RuntimeError("bpe_encode: output buffer too small")
        return buf[:n].tolist()

    def encode(self, text):
        enc = (self._encode_seq_native if self._handle is not None
               else self._encode_seq_py)
        out = []
        for chunk in self._chunks(text):
            out.extend(enc(chunk))
        return out

    def encode_batch(self, texts, num_threads=4):
        """Parallel batch encode — the native calls drop the GIL."""
        if self._handle is None or num_threads <= 1:
            return [self.encode(t) for t in texts]
        with ThreadPoolExecutor(num_threads) as ex:
            return list(ex.map(self.encode, texts))

    def decode(self, ids):
        return b"".join(self.vocab[int(i)] for i in ids).decode(
            "utf-8", errors="replace")

    # -- persistence ---------------------------------------------------------
    def save(self, path):
        with open(path, "w") as f:
            json.dump({"merges": self.merges,
                       "special_tokens": self.special_tokens}, f)

    @classmethod
    def load(cls, path, use_native=True):
        with open(path) as f:
            d = json.load(f)
        return cls(d["merges"], d["special_tokens"], use_native=use_native)
