"""Text datasets (ref: ``python/paddle/text/datasets/``).

File-backed parsers for the reference's dataset archives (zero egress:
``data_file`` must point at a local copy of the canonical archive — the
same file the reference's downloader would fetch). Formats:

* ``Imdb``       — aclImdb_v1.tar.gz (ref imdb.py)
* ``Imikolov``   — PTB simple-examples.tgz (ref imikolov.py)
* ``UCIHousing`` — housing.data whitespace table (ref uci_housing.py)
* ``Movielens``  — ml-1m.zip (ref movielens.py)
* ``Conll05st``  — conll05st tarball (ref conll05.py)
* ``WMT14`` / ``WMT16`` — tokenized dev+train tarballs (ref wmt14.py/wmt16.py)

All return numpy arrays ready for ``paddle_tpu.io.DataLoader``.
"""
from __future__ import annotations

import gzip
import io
import os
import re
import tarfile
import zipfile
from collections import Counter

import numpy as np

from paddle_tpu.io.dataset import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Movielens", "Conll05st",
           "WMT14", "WMT16"]


def _build_dict(counter, min_freq=0, extra=()):
    words = [w for w, c in counter.most_common() if c >= min_freq]
    vocab = {}
    for w in extra:
        vocab[w] = len(vocab)
    for w in words:
        if w not in vocab:
            vocab[w] = len(vocab)
    return vocab


class Imdb(Dataset):
    """IMDB sentiment (ref imdb.py). Tokenized docs as int arrays; label
    0=pos, 1=neg (reference convention). Vocabulary is built from the train
    split with ``cutoff`` min frequency and a trailing UNK id."""

    def __init__(self, data_file, mode="train", cutoff=150):
        self.mode = mode
        pat_doc = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        pat_train = re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$")
        tok = re.compile(r"[a-z]+")
        counter = Counter()
        docs_raw, labels = [], []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if not m.isfile():
                    continue
                is_train = pat_train.match(m.name)
                is_doc = pat_doc.match(m.name)
                if not (is_train or is_doc):
                    continue
                text = tf.extractfile(m).read().decode("utf-8", "ignore").lower()
                words = tok.findall(text)
                if is_train:
                    counter.update(words)
                if is_doc:
                    docs_raw.append(words)
                    labels.append(0 if is_doc.group(1) == "pos" else 1)
        self.word_idx = _build_dict(counter, cutoff)
        self.word_idx["<unk>"] = unk = len(self.word_idx)
        self.docs = [np.array([self.word_idx.get(w, unk) for w in d],
                              np.int64) for d in docs_raw]
        self.labels = np.array(labels, np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, idx):
        return self.docs[idx], int(self.labels[idx])


class Imikolov(Dataset):
    """PTB language-model dataset (ref imikolov.py). ``data_type='NGRAM'``
    yields fixed windows, ``'SEQ'`` whole sentences with <s>/<e> marks."""

    def __init__(self, data_file, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        member = {"train": "./simple-examples/data/ptb.train.txt",
                  "test": "./simple-examples/data/ptb.valid.txt"}[mode]
        counter = Counter()
        with tarfile.open(data_file) as tf:
            names = {m.name.lstrip("./"): m.name for m in tf.getmembers()}
            train_lines = tf.extractfile(
                names[member.lstrip("./")] if mode == "train"
                else names["simple-examples/data/ptb.train.txt"]
            ).read().decode().splitlines()
            lines = (train_lines if mode == "train" else tf.extractfile(
                names[member.lstrip("./")]).read().decode().splitlines())
        for ln in train_lines:
            counter.update(ln.split())
        counter["<unk>"] = -1  # reference drops raw <unk> from the dict build
        self.word_idx = _build_dict(counter, min_word_freq, extra=("<s>", "<e>"))
        self.word_idx["<unk>"] = unk = len(self.word_idx)
        s, e = self.word_idx["<s>"], self.word_idx["<e>"]
        self.data = []
        for ln in lines:
            ids = [s] + [self.word_idx.get(w, unk) for w in ln.split()] + [e]
            if data_type.upper() == "NGRAM":
                if len(ids) >= window_size:
                    for i in range(len(ids) - window_size + 1):
                        self.data.append(np.array(ids[i:i + window_size],
                                                  np.int64))
            else:
                self.data.append(np.array(ids, np.int64))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx]


class UCIHousing(Dataset):
    """Boston housing regression (ref uci_housing.py): 13 features
    normalised by (x - mean) / (max - min) over the full table; first 80%
    is train, rest test."""

    def __init__(self, data_file, mode="train"):
        raw = np.loadtxt(data_file).astype(np.float32)
        feats, target = raw[:, :-1], raw[:, -1:]
        mx, mn, avg = feats.max(0), feats.min(0), feats.mean(0)
        feats = (feats - avg) / (mx - mn)
        split = int(len(raw) * 0.8)
        sl = slice(0, split) if mode == "train" else slice(split, None)
        self.x, self.y = feats[sl], target[sl]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]


class Movielens(Dataset):
    """MovieLens-1M ratings (ref movielens.py). Each item: (user_id, gender,
    age_bucket, occupation, movie_id, category_ids, title_ids, rating)."""

    def __init__(self, data_file, mode="train", test_ratio=0.1, rand_seed=0):
        with zipfile.ZipFile(data_file) as zf:
            base = next(n for n in zf.namelist() if n.endswith("ratings.dat"))
            root = os.path.dirname(base)
            users = zf.read(f"{root}/users.dat").decode("latin1").splitlines()
            movies = zf.read(f"{root}/movies.dat").decode("latin1").splitlines()
            ratings = zf.read(f"{root}/ratings.dat").decode("latin1").splitlines()
        self.user_info, self.movie_info = {}, {}
        cats, title_words = {}, {}
        for ln in users:
            uid, gender, age, job, _ = ln.split("::")
            self.user_info[int(uid)] = (int(uid), 0 if gender == "M" else 1,
                                        int(age), int(job))
        for ln in movies:
            mid, title, genres = ln.split("::")
            cat_ids = [cats.setdefault(c, len(cats))
                       for c in genres.strip().split("|")]
            tw = [title_words.setdefault(w, len(title_words))
                  for w in re.sub(r"\(\d{4}\)$", "", title).strip().lower().split()]
            self.movie_info[int(mid)] = (int(mid), np.array(cat_ids, np.int64),
                                         np.array(tw, np.int64))
        self.max_movie_id = max(self.movie_info)
        self.categories_dict, self.title_dict = cats, title_words
        rng = np.random.RandomState(rand_seed)
        rows = []
        for ln in ratings:
            uid, mid, rating, _ = ln.split("::")
            if int(mid) not in self.movie_info:
                continue
            is_test = rng.rand() < test_ratio
            if (mode == "test") == is_test:
                rows.append((int(uid), int(mid), float(rating)))
        self.rows = rows

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, idx):
        uid, mid, rating = self.rows[idx]
        u = self.user_info[uid]
        m = self.movie_info[mid]
        return (*u, *m, np.float32(rating))


class Conll05st(Dataset):
    """CoNLL-2005 SRL (ref conll05.py). Parses the test-split tarball's
    ``words``/``props`` gz streams into (sentence, predicate, labels)
    triples with dicts built from the corpus."""

    def __init__(self, data_file):
        words_all, props_all = [], []
        with tarfile.open(data_file) as tf:
            wname = next(m.name for m in tf.getmembers()
                         if m.name.endswith("words.gz"))
            pname = next(m.name for m in tf.getmembers()
                         if m.name.endswith("props.gz"))
            words_txt = gzip.decompress(tf.extractfile(wname).read()).decode()
            props_txt = gzip.decompress(tf.extractfile(pname).read()).decode()
        sents = [s.split("\n") for s in words_txt.strip().split("\n\n")]
        props = [[ln.split() for ln in s.split("\n")]
                 for s in props_txt.strip().split("\n\n")]
        wdict, ldict = {}, {}
        self.samples = []
        for sent, prop in zip(sents, props):
            toks = [w.strip() for w in sent if w.strip()]
            if not prop or not prop[0]:
                continue
            preds = [r[0] for r in prop]
            n_frames = len(prop[0]) - 1
            for f in range(n_frames):
                tags = self._bio([r[1 + f] for r in prop])
                pred_pos = next((i for i, p in enumerate(preds)
                                 if p != "-" and tags[i].endswith("-V")), None)
                if pred_pos is None:
                    pred_pos = next(i for i, p in enumerate(preds) if p != "-")
                wids = np.array([wdict.setdefault(w.lower(), len(wdict))
                                 for w in toks], np.int64)
                lids = np.array([ldict.setdefault(t, len(ldict))
                                 for t in tags], np.int64)
                self.samples.append((wids, np.int64(pred_pos), lids))
        self.word_dict, self.label_dict = wdict, ldict

    @staticmethod
    def _bio(cols):
        """Convert bracketed props column ((A0* ... *) style) to BIO tags.
        Tokens may open several nested spans (e.g. ``(A1(V*)``) — all are
        pushed; the innermost (last-opened) names the B- tag, and each
        ``)`` pops one level."""
        tags, stack = [], []
        for c in cols:
            opens = re.findall(r"\(([^*()]+)", c)
            if opens:
                stack.extend(opens)
                tag = "B-" + opens[-1]
            elif stack:
                tag = "I-" + stack[-1]
            else:
                tag = "O"
            for _ in range(c.count(")")):
                if stack:
                    stack.pop()
            tags.append(tag)
        return tags

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        return self.samples[idx]


class _WMTBase(Dataset):
    src_lang = "en"

    def _finish(self, src_lines, trg_lines, src_dict_size, trg_dict_size=None):
        trg_dict_size = trg_dict_size or src_dict_size
        counter_src, counter_trg = Counter(), Counter()
        pairs_raw = []
        for s, t in zip(src_lines, trg_lines):
            sw, tw = s.split(), t.split()
            if not sw or not tw:
                continue
            counter_src.update(sw)
            counter_trg.update(tw)
            pairs_raw.append((sw, tw))
        specials = ("<s>", "<e>", "<unk>")
        def clip(counter, size):
            vocab = {w: i for i, w in enumerate(specials)}
            for w, _ in counter.most_common(max(size - len(specials), 0)):
                vocab.setdefault(w, len(vocab))
            return vocab
        self.src_dict = clip(counter_src, src_dict_size)
        self.trg_dict = clip(counter_trg, trg_dict_size)
        s_id, e_id, unk = 0, 1, 2
        self.pairs = []
        for sw, tw in pairs_raw:
            src = np.array([self.src_dict.get(w, unk) for w in sw], np.int64)
            # reference yields (src, trg_with_<s>_prefix, trg_with_<e>_suffix)
            trg_in = np.array([s_id] + [self.trg_dict.get(w, unk) for w in tw],
                              np.int64)
            trg_out = np.array([self.trg_dict.get(w, unk) for w in tw] + [e_id],
                               np.int64)
            self.pairs.append((src, trg_in, trg_out))

    def __len__(self):
        return len(self.pairs)

    def __getitem__(self, idx):
        return self.pairs[idx]


class WMT14(_WMTBase):
    """WMT'14 en→fr (ref wmt14.py): reads the preprocessed dev+train tgz of
    parallel ``\\t``-separated lines."""

    def __init__(self, data_file, mode="train", dict_size=30000):
        pat = {"train": "train/", "test": "test/", "gen": "gen/"}[mode]
        src, trg = [], []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if m.isfile() and pat in m.name:
                    for ln in tf.extractfile(m).read().decode(
                            "utf-8", "ignore").splitlines():
                        cols = ln.split("\t")
                        if len(cols) >= 2:
                            src.append(cols[0])
                            trg.append(cols[1])
        self._finish(src, trg, dict_size)


class WMT16(_WMTBase):
    """WMT'16 en↔de multimodal (ref wmt16.py): tarball with
    ``train/val/test`` split files per language."""

    def __init__(self, data_file, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en"):
        split = {"train": "train", "val": "val", "test": "test"}[mode]
        other = "de" if lang == "en" else "en"
        with tarfile.open(data_file) as tf:
            names = {m.name: m for m in tf.getmembers() if m.isfile()}
            def find(suffix):
                return next((n for n in names
                             if n.endswith(f"{split}.{suffix}")), None)
            sname, tname = find(lang), find(other)
            if sname is None or tname is None:
                raise FileNotFoundError(
                    f"no {split}.{lang}/{split}.{other} members in {data_file}")
            src = tf.extractfile(names[sname]).read().decode(
                "utf-8", "ignore").splitlines()
            trg = tf.extractfile(names[tname]).read().decode(
                "utf-8", "ignore").splitlines()
        self._finish(src, trg, src_dict_size, trg_dict_size)
