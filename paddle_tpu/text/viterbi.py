"""Viterbi decoding for linear-chain CRF tagging (ref:
``python/paddle/text/viterbi_decode.py`` — ViterbiDecoder / viterbi_decode).

The reference runs a custom CUDA kernel; here the forward DP and the
backtrace are both single ``lax.scan``s, so the whole decode is one XLA
program with [B, N, N] batched max-plus contractions on the vector unit.

Semantics match the reference: with ``include_bos_eos_tag=True`` the last
row of ``transitions`` is the start(BOS)->tag score and the second-to-last
column is the tag->stop(EOS) score.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.module import Module


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True):
    """Args: potentials [B, T, N] emission scores, transition_params [N, N],
    lengths [B] int. Returns (scores [B], paths [B, T] int32; positions past
    each sequence's length are 0)."""
    pot = jnp.asarray(potentials)
    trans = jnp.asarray(transition_params)
    lengths = jnp.asarray(lengths)
    b, t, n = pot.shape

    alpha = pot[:, 0]
    if include_bos_eos_tag:
        alpha = alpha + trans[-1][None, :]

    steps = jnp.arange(1, t)
    emis = jnp.moveaxis(pot[:, 1:], 1, 0)  # [T-1, B, N]

    def fwd(alpha, xs):
        step, em = xs
        # scores[b, i, j] = alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None]
        best_prev = jnp.argmax(scores, axis=1).astype(jnp.int32)  # [B, N]
        new_alpha = jnp.max(scores, axis=1) + em
        active = (step < lengths)[:, None]
        alpha = jnp.where(active, new_alpha, alpha)
        # identity pointer on inactive steps keeps the backtrace a no-op there
        best_prev = jnp.where(active, best_prev,
                              jnp.arange(n, dtype=jnp.int32)[None, :])
        return alpha, best_prev

    alpha, history = lax.scan(fwd, alpha, (steps, emis))  # history [T-1, B, N]

    if include_bos_eos_tag:
        alpha = alpha + trans[:, -2][None, :]
    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)  # [B]

    def back(tag, ptr):
        prev = jnp.take_along_axis(ptr, tag[:, None], axis=1)[:, 0]
        return prev, prev

    _, rest = lax.scan(back, last_tag, history[::-1])  # [T-1, B]
    paths = jnp.concatenate([rest[::-1], last_tag[None, :]], axis=0)  # [T, B]
    paths = jnp.moveaxis(paths, 0, 1)  # [B, T]
    valid = jnp.arange(t)[None, :] < lengths[:, None]
    return scores, jnp.where(valid, paths, 0)


class ViterbiDecoder(Module):
    """Layer wrapper (ref ViterbiDecoder): holds transitions, decodes batches."""

    def __init__(self, transitions, include_bos_eos_tag=True):
        super().__init__()
        self.transitions = jnp.asarray(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
