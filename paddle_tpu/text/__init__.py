"""Text/tokenization utilities (ref capability: PaddleNLP
``paddlenlp.transformers.*Tokenizer`` + ``paddle.text`` datasets).

Tokenization is host-side string processing — no TPU angle — so we provide:
 * a zero-dependency, reproducible ``SimpleTokenizer`` (whitespace/byte-level
   with a trainable vocab) for tests and self-contained pipelines;
 * ``AutoTokenizer`` which defers to the locally-installed ``transformers``
   library when a pretrained vocab is available on disk (no downloads).

Both return numpy int32 arrays shaped for ``paddle_tpu`` models
(``input_ids``, ``attention_mask``) and pad to fixed lengths so downstream
jit programs see static shapes.
"""
from __future__ import annotations

import collections
import re

import numpy as np

__all__ = ["SimpleTokenizer", "AutoTokenizer", "BPETokenizer", "pad_batch"]


def pad_batch(seqs, max_len=None, pad_id=0):
    """Pad a list of int lists to [B, max_len] + mask (static shapes for jit)."""
    max_len = max_len or max(len(s) for s in seqs)
    ids = np.full((len(seqs), max_len), pad_id, np.int32)
    mask = np.zeros((len(seqs), max_len), np.int32)
    for i, s in enumerate(seqs):
        s = s[:max_len]
        ids[i, :len(s)] = s
        mask[i, :len(s)] = 1
    return ids, mask


class SimpleTokenizer:
    """Regex word-level tokenizer with special tokens (ref: paddlenlp
    BasicTokenizer + vocab). Train on a corpus, encode/decode reversibly
    for in-vocab text."""

    PAT = re.compile(r"\w+|[^\w\s]")

    def __init__(self, vocab=None, unk_token="[UNK]", pad_token="[PAD]",
                 cls_token="[CLS]", sep_token="[SEP]", lowercase=True):
        self.lowercase = lowercase
        self.specials = [pad_token, unk_token, cls_token, sep_token]
        self.unk_token, self.pad_token = unk_token, pad_token
        self.cls_token, self.sep_token = cls_token, sep_token
        self.vocab = dict(vocab) if vocab else {
            t: i for i, t in enumerate(self.specials)}
        self.inv = {i: t for t, i in self.vocab.items()}

    # -- vocab ---------------------------------------------------------------
    @classmethod
    def train(cls, texts, vocab_size=30000, min_freq=1, **kw):
        tok = cls(**kw)
        counter = collections.Counter()
        for t in texts:
            counter.update(tok._tokens(t))
        for word, freq in counter.most_common(vocab_size - len(tok.specials)):
            if freq < min_freq:
                break
            if word not in tok.vocab:
                tok.vocab[word] = len(tok.vocab)
        tok.inv = {i: t for t, i in tok.vocab.items()}
        return tok

    @property
    def vocab_size(self):
        return len(self.vocab)

    @property
    def pad_token_id(self):
        return self.vocab[self.pad_token]

    @property
    def unk_token_id(self):
        return self.vocab[self.unk_token]

    # -- encode/decode -------------------------------------------------------
    def _tokens(self, text):
        if self.lowercase:
            text = text.lower()
        return self.PAT.findall(text)

    def encode(self, text, add_special_tokens=True, max_len=None):
        ids = [self.vocab.get(t, self.unk_token_id) for t in self._tokens(text)]
        if add_special_tokens:
            ids = [self.vocab[self.cls_token]] + ids + [self.vocab[self.sep_token]]
        if max_len is not None:
            if len(ids) > max_len:
                # truncation preserves the closing [SEP] (reference behaviour)
                if add_special_tokens:
                    ids = ids[:max_len - 1] + [self.vocab[self.sep_token]]
                else:
                    ids = ids[:max_len]
            ids = ids + [self.pad_token_id] * (max_len - len(ids))
        return ids

    def __call__(self, texts, max_len=None, add_special_tokens=True):
        if isinstance(texts, str):
            texts = [texts]
        seqs = [self.encode(t, add_special_tokens, max_len=None)
                for t in texts]
        if max_len is not None:
            sep = self.vocab[self.sep_token]
            seqs = [s if len(s) <= max_len else
                    (s[:max_len - 1] + [sep] if add_special_tokens
                     else s[:max_len]) for s in seqs]
        ids, mask = pad_batch(seqs, max_len, self.pad_token_id)
        return {"input_ids": ids, "attention_mask": mask}

    def decode(self, ids, skip_special_tokens=True):
        toks = []
        for i in np.asarray(ids).reshape(-1).tolist():
            t = self.inv.get(int(i), self.unk_token)
            if skip_special_tokens and t in self.specials:
                continue
            toks.append(t)
        return " ".join(toks)


class AutoTokenizer:
    """Ref: paddlenlp.transformers.AutoTokenizer — loads any pretrained
    tokenizer present on local disk via the installed ``transformers``."""

    @staticmethod
    def from_pretrained(path, **kw):
        from transformers import AutoTokenizer as _HFAuto
        return _HFAuto.from_pretrained(path, local_files_only=True, **kw)
from paddle_tpu.text.bpe import BPETokenizer
from paddle_tpu.text.viterbi import ViterbiDecoder, viterbi_decode
from paddle_tpu.text import datasets
