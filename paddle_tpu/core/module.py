"""Pytree-native Module system (the TPU-native answer to ``paddle.nn.Layer``).

Reference: ``python/paddle/nn/layer/layers.py`` (class ``Layer``) — dygraph
``Layer`` holds mutable parameters and an imperative forward. Here a Module
IS a JAX pytree: parameters/buffers/submodules are leaves/children, any
other attribute is static metadata. That makes every model directly usable
with ``jax.jit`` / ``jax.grad`` / ``jax.tree_util`` — no tape, no engine.

Key differences from the reference, by design:
  * functional: calling a module never mutates it; randomness (dropout) is
    passed in explicitly via ``rng=``.
  * sharding-aware: every parameter may carry a ``PartitionSpec`` in
    ``module.pspec(name)`` metadata, consumed by the distributed layer
    (see paddle_tpu/distributed/).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

_ARRAY_TYPES = (jax.Array, np.ndarray)


def _is_dynamic(value: Any) -> bool:
    """True if `value` participates in the pytree (array / module / container of)."""
    if isinstance(value, (Module, *_ARRAY_TYPES)):
        return True
    if isinstance(value, (list, tuple)):
        return any(_is_dynamic(v) for v in value)
    if isinstance(value, dict):
        return any(_is_dynamic(v) for v in value.values())
    return False


def _hashable_static(value: Any) -> Any:
    if isinstance(value, list):
        return tuple(_hashable_static(v) for v in value)
    if isinstance(value, dict):
        return tuple(sorted((k, _hashable_static(v)) for k, v in value.items()))
    if isinstance(value, set):
        return tuple(sorted(value))
    return value


class _Static:
    """Hashable wrapper for static attribute snapshots used as pytree aux data."""

    __slots__ = ("names", "values", "dyn_names", "buffers", "pspecs", "cls")

    def __init__(self, cls, names, values, dyn_names, buffers, pspecs):
        self.cls = cls
        self.names = names
        self.values = values
        self.dyn_names = dyn_names
        self.buffers = buffers
        self.pspecs = pspecs

    def _key(self):
        return (
            self.cls,
            self.names,
            tuple(_hashable_static(v) for v in self.values),
            self.dyn_names,
            self.buffers,
            self.pspecs,
        )

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return isinstance(other, _Static) and self._key() == other._key()


class Module:
    """Base class for all layers/models. Subclasses register as pytrees."""

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        jax.tree_util.register_pytree_with_keys_class(cls)

    # -- construction ------------------------------------------------------
    def __init__(self):
        object.__setattr__(self, "_buffers", set())
        object.__setattr__(self, "_pspecs", {})
        object.__setattr__(self, "_dyn_names", set())
        object.__setattr__(self, "training", True)

    def _ensure_meta(self):
        if not hasattr(self, "_buffers"):
            object.__setattr__(self, "_buffers", set())
            object.__setattr__(self, "_pspecs", {})
            object.__setattr__(self, "training", True)
        if not hasattr(self, "_dyn_names"):
            object.__setattr__(self, "_dyn_names", set())

    def register_buffer(self, name: str, value) -> None:
        """Non-trainable state (e.g. BatchNorm running stats). Ref Layer.register_buffer."""
        self._ensure_meta()
        self._buffers.add(name)
        setattr(self, name, value)

    def set_pspec(self, name: str, spec) -> None:
        """Attach a ``PartitionSpec`` (or axis-name tuple) to parameter `name`."""
        self._ensure_meta()
        self._pspecs[name] = tuple(spec) if isinstance(spec, (list, tuple)) else spec

    def pspec(self, name: str):
        return getattr(self, "_pspecs", {}).get(name)

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten_with_keys(self):
        self._ensure_meta()
        dyn_names, children, st_names, st_values = [], [], [], []
        for name, value in vars(self).items():
            if name in ("_buffers", "_pspecs", "_dyn_names"):
                continue
            # sticky classification: once an attr held a dynamic value, it
            # stays a pytree child even when a transform nulls it out, so
            # treedefs stay compatible across partition/combine.
            if _is_dynamic(value) or name in self._dyn_names:
                dyn_names.append(name)
                children.append(value)
            else:
                st_names.append(name)
                st_values.append(value)
        self._dyn_names.update(dyn_names)
        aux = _Static(
            type(self),
            tuple(st_names),
            tuple(st_values),
            tuple(dyn_names),
            tuple(sorted(self._buffers)),
            tuple(sorted((k, v) for k, v in self._pspecs.items())),
        )
        keyed = [(jax.tree_util.GetAttrKey(n), c) for n, c in zip(dyn_names, children)]
        return keyed, aux

    def tree_flatten(self):
        keyed, aux = self.tree_flatten_with_keys()
        return [c for _, c in keyed], aux

    @classmethod
    def tree_unflatten(cls, aux: _Static, children):
        obj = object.__new__(aux.cls)
        object.__setattr__(obj, "_buffers", set(aux.buffers))
        object.__setattr__(obj, "_pspecs", dict(aux.pspecs))
        object.__setattr__(obj, "_dyn_names", set(aux.dyn_names))
        for name, value in zip(aux.names, aux.values):
            object.__setattr__(obj, name, value)
        for name, child in zip(aux.dyn_names, children):
            object.__setattr__(obj, name, child)
        if not hasattr(obj, "training"):
            object.__setattr__(obj, "training", True)
        return obj

    # -- traversal ---------------------------------------------------------
    def _iter_named(self, prefix: str = "") -> Iterator[tuple[str, str, Any, "Module"]]:
        """Yield (path, attr_name, value, owner) for every array leaf."""
        for name, value in vars(self).items():
            if name in ("_buffers", "_pspecs", "_dyn_names"):
                continue
            path = f"{prefix}{name}"
            yield from _iter_value(path, name, value, self)

    def named_parameters(self, include_buffers: bool = False):
        for path, name, value, owner in self._iter_named():
            if isinstance(value, _ARRAY_TYPES):
                if include_buffers or name not in owner._buffers:
                    yield path, value

    def parameters(self):
        for _, v in self.named_parameters():
            yield v

    def sublayers(self, include_self: bool = True) -> Iterator["Module"]:
        if include_self:
            yield self
        for name, value in vars(self).items():
            if name in ("_buffers", "_pspecs", "_dyn_names"):
                continue
            yield from _iter_modules(value)

    def apply_to_sublayers(self, fn: Callable[["Module"], None]) -> "Module":
        for m in self.sublayers():
            fn(m)
        return self

    # -- train / eval ------------------------------------------------------
    def train(self) -> "Module":
        return self.apply_to_sublayers(lambda m: object.__setattr__(m, "training", True))

    def eval(self) -> "Module":
        return self.apply_to_sublayers(lambda m: object.__setattr__(m, "training", False))

    # -- state dict (ref Layer.state_dict / set_state_dict) ---------------
    def state_dict(self) -> dict[str, np.ndarray]:
        return {p: np.asarray(v) for p, v in self.named_parameters(include_buffers=True)}

    def set_state_dict(self, state: dict[str, Any]) -> None:
        """In-place load. Keys are dotted paths as produced by state_dict()."""
        remaining = dict(state)
        flat, treedef = jax.tree_util.tree_flatten_with_path(self)
        new_leaves = []
        for path, leaf in flat:
            pstr = _path_to_str(path)
            if isinstance(leaf, _ARRAY_TYPES) and pstr in remaining:
                new = jnp.asarray(remaining.pop(pstr), dtype=leaf.dtype)
                if new.shape != leaf.shape:
                    raise ValueError(f"shape mismatch for {pstr}: {new.shape} vs {leaf.shape}")
                new_leaves.append(new)
            else:
                new_leaves.append(leaf)
        if remaining:
            raise KeyError(f"unexpected keys in state_dict: {sorted(remaining)[:8]}")
        rebuilt = jax.tree_util.tree_unflatten(treedef, new_leaves)
        vars(self).update(vars(rebuilt))

    def num_parameters(self) -> int:
        return sum(int(np.prod(v.shape)) for _, v in self.named_parameters())

    def __repr__(self):
        return f"{type(self).__name__}(params={self.num_parameters():,})"


def _iter_modules(value):
    if isinstance(value, Module):
        yield from value.sublayers(include_self=True)
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _iter_modules(v)
    elif isinstance(value, dict):
        for v in value.values():
            yield from _iter_modules(v)


def _iter_value(path, name, value, owner):
    if isinstance(value, _ARRAY_TYPES):
        yield path, name, value, owner
    elif isinstance(value, Module):
        yield from value._iter_named(prefix=path + ".")
    elif isinstance(value, (list, tuple)):
        for i, v in enumerate(value):
            yield from _iter_value(f"{path}.{i}", name, v, owner)
    elif isinstance(value, dict):
        for k, v in value.items():
            yield from _iter_value(f"{path}.{k}", name, v, owner)


# ---------------------------------------------------------------------------
# filtering: split trainable params from everything else (eqx-style)
# ---------------------------------------------------------------------------

def partition_trainable(module: Module):
    """Split `module` into (params, skeleton): params has buffers/non-arrays
    as None; skeleton has trainable params as None. combine() re-merges."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(module)
    buffer_paths = _buffer_paths(module)
    params_leaves, skel_leaves = [], []
    for path, leaf in flat:
        path_str = _path_to_str(path)
        is_param = isinstance(leaf, _ARRAY_TYPES) and path_str not in buffer_paths
        params_leaves.append(leaf if is_param else None)
        skel_leaves.append(None if is_param else leaf)
    params = jax.tree_util.tree_unflatten(treedef, params_leaves)
    skel = jax.tree_util.tree_unflatten(treedef, skel_leaves)
    return params, skel


def combine(params: Module, skel: Module) -> Module:
    return jax.tree_util.tree_map(
        lambda a, b: a if a is not None else b, params, skel,
        is_leaf=lambda x: x is None)


def _buffer_paths(module: Module) -> set[str]:
    out = set()
    for path, name, value, owner in module._iter_named():
        if isinstance(value, _ARRAY_TYPES) and name in owner._buffers:
            out.add(path)
    return out


def _path_to_str(path) -> str:
    parts = []
    for p in path:
        if isinstance(p, jax.tree_util.GetAttrKey):
            parts.append(p.name)
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        elif isinstance(p, jax.tree_util.DictKey):
            parts.append(str(p.key))
        else:
            parts.append(str(p))
    return ".".join(parts)


def value_and_grad(fn, has_aux: bool = False):
    """Like jax.value_and_grad but differentiates only trainable leaves of a
    Module passed as the first argument."""

    def wrapped(module: Module, *args, **kwargs):
        params, skel = partition_trainable(module)

        def inner(p, *a, **k):
            return fn(combine(p, skel), *a, **k)

        return jax.value_and_grad(inner, has_aux=has_aux)(params, *args, **kwargs)

    return wrapped
