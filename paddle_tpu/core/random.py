"""Seeded, splittable RNG (TPU-native answer to ``paddle.seed`` / global RNG).

Reference: ``python/paddle/base/framework.py`` global generators. Paddle uses
stateful per-device generators; under XLA everything must be functional, so we
keep ONE host-side root key for eager convenience (`seed`, `next_key`) and an
explicit `RngStream` for use inside jitted training steps.
"""
from __future__ import annotations

import jax


class _GlobalRng:
    def __init__(self, seed: int = 0):
        self.key = jax.random.PRNGKey(seed)

    def split(self) -> jax.Array:
        self.key, sub = jax.random.split(self.key)
        return sub


# LAZY: creating a PRNGKey initializes the jax backend, and importing the
# package must stay computation-free (jax.distributed.initialize() has to
# run before ANY backend use — the multi-host launch contract).
_global = None


def _get_global() -> _GlobalRng:
    global _global
    if _global is None:
        _global = _GlobalRng()
    return _global


def seed(value: int) -> None:
    """Set the global seed (ref: ``paddle.seed``)."""
    global _global
    _global = _GlobalRng(value)


def next_key() -> jax.Array:
    """Eager-mode convenience: draw a fresh subkey from the global generator.

    Never call inside jit — pass keys explicitly there (RngStream).
    """
    return _get_global().split()


class RngStream:
    """Explicit key folder for jitted code: deterministic per (step, name)."""

    def __init__(self, key: jax.Array):
        self.key = key

    def fold(self, tag: int) -> "RngStream":
        return RngStream(jax.random.fold_in(self.key, tag))

    def take(self, n: int = 1):
        keys = jax.random.split(self.key, n + 1)
        self.key = keys[0]
        return keys[1] if n == 1 else keys[1:]
