"""Dtype registry & defaults (ref: ``python/paddle/framework/dtype.py``).

bfloat16 is a first-class citizen: it is the TPU compute dtype (MXU takes
bf16 inputs with fp32 accumulate). Default parameter dtype stays float32 for
reference parity; the AMP policy (paddle_tpu.amp) casts compute to bf16.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64

_DEFAULT = {"dtype": jnp.float32}


def set_default_dtype(dtype) -> None:
    _DEFAULT["dtype"] = jnp.dtype(dtype) if not hasattr(dtype, "dtype") else dtype


def get_default_dtype():
    return _DEFAULT["dtype"]


@contextlib.contextmanager
def default_dtype(dtype):
    old = _DEFAULT["dtype"]
    set_default_dtype(dtype)
    try:
        yield
    finally:
        _DEFAULT["dtype"] = old


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def canonical_int_dtype(dtype):
    """Platform-canonical integer dtype WITHOUT jax's truncation warning.

    The reference defaults index-producing ops (randint, argmax, ...) to
    int64; under jax without x64 those arrays are int32. Requesting int64
    would produce the same int32 array plus a per-call UserWarning — map it
    up front instead (deliberate, documented difference: MIGRATING.md).
    """
    import numpy as np
    try:
        import jax
        x64 = bool(jax.config.jax_enable_x64)
    except Exception:
        x64 = False
    if not x64 and np.dtype(dtype) in (np.dtype("int64"), np.dtype("uint64")):
        return jnp.int32 if np.dtype(dtype) == np.dtype("int64") else jnp.uint32
    return dtype
