"""Device / place management (ref: ``paddle.set_device``, ``paddle/phi/common/place.h``).

Paddle routes ops to a Place (CPUPlace/CUDAPlace/XPUPlace). Under JAX the
platform is process-global and arrays carry their sharding, so "set_device"
reduces to selecting the default platform and exposing topology queries used
by the distributed layer.
"""
from __future__ import annotations

import jax


def set_device(name: str) -> None:
    """Accepts 'tpu', 'cpu', 'gpu' (ref signature). Affects default backend only."""
    platform = {"xla": "tpu", "tpu": "tpu", "gpu": "gpu", "cpu": "cpu"}.get(name, name)
    try:
        jax.config.update("jax_default_device", jax.devices(platform)[0])
    except RuntimeError:
        pass  # platform not present (e.g. asking for tpu in CPU tests)


def get_device() -> str:
    return jax.default_backend()


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_tpu() -> bool:
    return jax.default_backend() == "tpu"



def is_compiled_with_cuda() -> bool:
    """Ref paddle.device.is_compiled_with_cuda — this build targets TPU."""
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str = "tpu") -> bool:
    """TPU is the custom device this framework is built for."""
    return device_type in ("tpu", "axon")


def get_all_device_type():
    try:
        return sorted({d.platform for d in jax.devices()})
    except Exception:
        return ["cpu"]


def synchronize(device=None):
    """Ref paddle.device.synchronize — block until pending work completes.
    XLA has no global stream, and ``block_until_ready`` is a no-op over the
    axon TPU tunnel, so the reliable fence is an actual host transfer of a
    freshly computed scalar (it cannot complete before prior dispatched
    work on that device)."""
    import jax.numpy as jnp
    devices = [device] if device is not None else jax.local_devices()
    for d in devices:
        float(jax.device_get(jax.device_put(jnp.zeros(()), d) + 0))
