from paddle_tpu.core import device, dtypes, random
from paddle_tpu.core.module import (
    Module,
    combine,
    partition_trainable,
    value_and_grad,
)
