"""Metrics (ref: ``python/paddle/metric/metrics.py`` — Metric, Accuracy,
Precision, Recall, Auc). Host-accumulated; updates accept jax or numpy."""
from __future__ import annotations

import numpy as np


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return type(self).__name__.lower()

    def compute(self, pred, label, *args):
        """Ref Metric.compute — pre-processing hook run inside the graph;
        default passthrough, outputs feed ``update``."""
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk=(1,)):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.reset()

    def reset(self):
        self.correct = np.zeros(len(self.topk))
        self.total = 0

    def update(self, pred, label):
        pred = np.asarray(pred)
        label = np.asarray(label).reshape(-1)
        k_max = max(self.topk)
        top = np.argsort(-pred, axis=-1)[..., :k_max].reshape(len(label), k_max)
        for i, k in enumerate(self.topk):
            self.correct[i] += (top[:, :k] == label[:, None]).any(axis=1).sum()
        self.total += len(label)
        return self.accumulate()

    def accumulate(self):
        acc = self.correct / max(self.total, 1)
        return float(acc[0]) if len(self.topk) == 1 else [float(a) for a in acc]


class Precision(Metric):
    def __init__(self):
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, pred, label):
        pred = (np.asarray(pred).reshape(-1) > 0.5).astype(np.int64)
        label = np.asarray(label).reshape(-1).astype(np.int64)
        self.tp += int(((pred == 1) & (label == 1)).sum())
        self.fp += int(((pred == 1) & (label == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self):
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, pred, label):
        pred = (np.asarray(pred).reshape(-1) > 0.5).astype(np.int64)
        label = np.asarray(label).reshape(-1).astype(np.int64)
        self.tp += int(((pred == 1) & (label == 1)).sum())
        self.fn += int(((pred == 0) & (label == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    """Riemann-sum ROC AUC over binned thresholds (ref Auc num_thresholds)."""

    def __init__(self, num_thresholds=4095):
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1)
        self._neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2:  # [N, 2] probs
            preds = preds[:, 1]
        labels = np.asarray(labels).reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64),
                      0, self.num_thresholds)
        np.add.at(self._pos, idx[labels == 1], 1)
        np.add.at(self._neg, idx[labels == 0], 1)

    def accumulate(self):
        tot_pos = self._pos.sum()
        tot_neg = self._neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tp = np.cumsum(self._pos[::-1])[::-1]
        fp = np.cumsum(self._neg[::-1])[::-1]
        tpr = np.concatenate([tp / tot_pos, [0.0]])
        fpr = np.concatenate([fp / tot_neg, [0.0]])
        return float(np.abs(np.trapezoid(tpr, fpr)))


def accuracy(pred, label, k=1):
    """Functional one-shot accuracy (ref paddle.metric.accuracy)."""
    m = Accuracy(topk=(k,))
    m.update(pred, label)
    return m.accumulate()
