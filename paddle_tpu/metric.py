"""Metrics (ref: ``python/paddle/metric/metrics.py`` — Metric, Accuracy,
Precision, Recall, Auc — plus the legacy ``paddle/fluid/metrics.py`` family:
CompositeMetric, ChunkEvaluator, EditDistance). Host-accumulated; updates
accept jax or numpy."""
from __future__ import annotations

import numpy as np


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return type(self).__name__.lower()

    def compute(self, pred, label, *args):
        """Ref Metric.compute — pre-processing hook run inside the graph;
        default passthrough, outputs feed ``update``."""
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk=(1,)):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.reset()

    def reset(self):
        self.correct = np.zeros(len(self.topk))
        self.total = 0

    def update(self, pred, label):
        pred = np.asarray(pred)
        label = np.asarray(label).reshape(-1)
        k_max = max(self.topk)
        top = np.argsort(-pred, axis=-1)[..., :k_max].reshape(len(label), k_max)
        for i, k in enumerate(self.topk):
            self.correct[i] += (top[:, :k] == label[:, None]).any(axis=1).sum()
        self.total += len(label)
        return self.accumulate()

    def accumulate(self):
        acc = self.correct / max(self.total, 1)
        return float(acc[0]) if len(self.topk) == 1 else [float(a) for a in acc]


class Precision(Metric):
    def __init__(self):
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, pred, label):
        pred = (np.asarray(pred).reshape(-1) > 0.5).astype(np.int64)
        label = np.asarray(label).reshape(-1).astype(np.int64)
        self.tp += int(((pred == 1) & (label == 1)).sum())
        self.fp += int(((pred == 1) & (label == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self):
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, pred, label):
        pred = (np.asarray(pred).reshape(-1) > 0.5).astype(np.int64)
        label = np.asarray(label).reshape(-1).astype(np.int64)
        self.tp += int(((pred == 1) & (label == 1)).sum())
        self.fn += int(((pred == 0) & (label == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    """Riemann-sum ROC AUC over binned thresholds (ref Auc num_thresholds)."""

    def __init__(self, num_thresholds=4095):
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1)
        self._neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2:  # [N, 2] probs
            preds = preds[:, 1]
        labels = np.asarray(labels).reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64),
                      0, self.num_thresholds)
        np.add.at(self._pos, idx[labels == 1], 1)
        np.add.at(self._neg, idx[labels == 0], 1)

    def accumulate(self):
        tot_pos = self._pos.sum()
        tot_neg = self._neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tp = np.cumsum(self._pos[::-1])[::-1]
        fp = np.cumsum(self._neg[::-1])[::-1]
        tpr = np.concatenate([tp / tot_pos, [0.0]])
        fpr = np.concatenate([fp / tot_neg, [0.0]])
        return float(np.abs(np.trapezoid(tpr, fpr)))


class CompositeMetric(Metric):
    """Ref ``fluid.metrics.CompositeMetric`` — evaluate several metrics on
    the same (pred, label) stream; ``accumulate`` returns their results in
    registration order."""

    def __init__(self, *metrics):
        self._metrics = list(metrics)

    def add_metric(self, metric):
        if not isinstance(metric, Metric):
            raise TypeError("add_metric expects a Metric instance")
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, pred, label):
        for m in self._metrics:
            m.update(pred, label)

    def accumulate(self):
        return [m.accumulate() for m in self._metrics]


def extract_chunks(tags, scheme: str = "IOB", num_chunk_types: int = None):
    """Decode a tag sequence into (start, end, type) chunks.

    Tag encoding follows the reference ChunkEvaluator: for scheme "IOB"
    tag = chunk_type * 2 + {0: B, 1: I}, and the last tag id (==
    num_chunk_types * 2) is O. "IOE" uses {0: E, 1: I}; "IOBES" uses
    tag = chunk_type * 4 + {0:B, 1:I, 2:E, 3:S}, O = num_chunk_types*4.
    """
    chunks = []
    n = len(tags)
    width = {"IOB": 2, "IOE": 2, "IOBES": 4}[scheme]
    start = None
    ctype = None

    def flush(end):
        nonlocal start, ctype
        if start is not None:
            chunks.append((start, end, ctype))
        start, ctype = None, None

    for i, t in enumerate(list(tags) + [None]):
        if t is None or (num_chunk_types is not None
                         and t >= num_chunk_types * width):
            flush(i - 1)  # O tag / end of sentence
            continue
        ct, pos = int(t) // width, int(t) % width
        if scheme == "IOB":
            if pos == 0:  # B
                flush(i - 1)
                start, ctype = i, ct
            else:  # I: continues only if same type is open
                if start is None or ctype != ct:
                    flush(i - 1)
                    start, ctype = i, ct  # tolerate I-start (common lenient)
        elif scheme == "IOE":
            if pos == 1:  # I
                if start is None or ctype != ct:
                    flush(i - 1)
                    start, ctype = i, ct
            else:  # E closes the chunk
                if start is None or ctype != ct:
                    start, ctype = i, ct
                flush(i)
        else:  # IOBES
            if pos == 0:  # B
                flush(i - 1)
                start, ctype = i, ct
            elif pos == 1:  # I
                if start is None or ctype != ct:
                    flush(i - 1)
                    start, ctype = i, ct
            elif pos == 2:  # E
                if start is None or ctype != ct:
                    start, ctype = i, ct
                flush(i)
            else:  # S: single-token chunk
                flush(i - 1)
                chunks.append((i, i, ct))
    return chunks


class ChunkEvaluator(Metric):
    """Ref ``fluid.metrics.ChunkEvaluator`` / chunk_eval op — micro-averaged
    precision/recall/F1 over decoded chunks (NER-style sequence labeling).

    ``update(preds, labels, seq_lens)`` takes int tag ids [B, T] and per-row
    valid lengths; ``accumulate`` returns (precision, recall, f1).
    """

    def __init__(self, num_chunk_types: int, chunk_scheme: str = "IOB"):
        if chunk_scheme not in ("IOB", "IOE", "IOBES"):
            raise ValueError(f"unsupported chunk_scheme {chunk_scheme!r}")
        if not isinstance(num_chunk_types, int) or num_chunk_types < 1:
            # without it O tags would decode as phantom chunk types and the
            # metric would be silently wrong (the reference requires it too)
            raise ValueError("num_chunk_types (a positive int) is required")
        self.num_chunk_types = num_chunk_types
        self.scheme = chunk_scheme
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, preds, labels, seq_lens=None):
        preds = np.asarray(preds)
        labels = np.asarray(labels)
        if preds.ndim == 1:
            preds, labels = preds[None], labels[None]
        if seq_lens is None:
            seq_lens = [preds.shape[1]] * preds.shape[0]
        for p_row, l_row, n in zip(preds, labels, np.asarray(seq_lens)):
            p_chunks = set(extract_chunks(p_row[:n], self.scheme,
                                          self.num_chunk_types))
            l_chunks = set(extract_chunks(l_row[:n], self.scheme,
                                          self.num_chunk_types))
            self.num_infer_chunks += len(p_chunks)
            self.num_label_chunks += len(l_chunks)
            self.num_correct_chunks += len(p_chunks & l_chunks)
        return self.accumulate()

    def accumulate(self):
        p = (self.num_correct_chunks / self.num_infer_chunks
             if self.num_infer_chunks else 0.0)
        r = (self.num_correct_chunks / self.num_label_chunks
             if self.num_label_chunks else 0.0)
        f1 = 2 * p * r / (p + r) if (p + r) else 0.0
        return p, r, f1


class EditDistance(Metric):
    """Ref ``fluid.metrics.EditDistance`` — average Levenshtein distance
    between predicted and reference sequences, optionally normalized by the
    reference length."""

    def __init__(self, normalized: bool = True):
        self.normalized = normalized
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    @staticmethod
    def _levenshtein(a, b):
        la, lb = len(a), len(b)
        prev = np.arange(lb + 1, dtype=np.int64)
        for i in range(1, la + 1):
            cur = np.empty(lb + 1, np.int64)
            cur[0] = i
            for j in range(1, lb + 1):
                cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                             prev[j - 1] + (a[i - 1] != b[j - 1]))
            prev = cur
        return int(prev[lb])

    def update(self, preds, labels):
        """preds/labels: lists of sequences (token-id lists or strings)."""
        if len(preds) != len(labels):
            raise ValueError(
                f"EditDistance.update: {len(preds)} preds vs "
                f"{len(labels)} labels (batch sizes must match)")
        for p, l in zip(preds, labels):
            p = list(np.asarray(p).reshape(-1)) if not isinstance(p, str) else p
            l = list(np.asarray(l).reshape(-1)) if not isinstance(l, str) else l
            d = self._levenshtein(p, l)
            if self.normalized:
                d = d / max(len(l), 1)
            self.total_distance += d
            self.seq_num += 1
            self.instance_error += int(d != 0)
        return self.accumulate()

    def accumulate(self):
        avg = self.total_distance / self.seq_num if self.seq_num else 0.0
        err = self.instance_error / self.seq_num if self.seq_num else 0.0
        return avg, err


def accuracy(pred, label, k=1):
    """Functional one-shot accuracy (ref paddle.metric.accuracy)."""
    m = Accuracy(topk=(k,))
    m.update(pred, label)
    return m.accumulate()
