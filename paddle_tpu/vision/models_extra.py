"""Vision zoo completion (ref: ``python/paddle/vision/models/``): LeNet,
AlexNet, SqueezeNet, DenseNet, GoogLeNet, InceptionV3, ShuffleNetV2,
MobileNetV1/V3.

All NCHW, pytree modules, pure calls; BatchNorm runs inference-style under
jit (running stats are buffers) exactly like the rest of the zoo.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layers import (
    AdaptiveAvgPool2D,
    AvgPool2D,
    BatchNorm2D,
    Conv2D,
    Dropout,
    Linear,
    MaxPool2D,
    ReLU,
    Sequential,
)

__all__ = [
    "LeNet", "AlexNet", "SqueezeNet", "DenseNet", "GoogLeNet", "InceptionV3",
    "ShuffleNetV2", "MobileNetV1", "MobileNetV3Small", "MobileNetV3Large",
    "alexnet", "squeezenet1_0", "squeezenet1_1", "densenet121", "densenet161",
    "densenet169", "densenet201", "densenet264", "googlenet", "inception_v3",
    "shufflenet_v2_x0_25", "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
    "shufflenet_v2_x1_5", "shufflenet_v2_x2_0", "shufflenet_v2_swish",
    "mobilenet_v1",
    "mobilenet_v3_small", "mobilenet_v3_large",
]


class _ConvBN(Module):
    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1, act="relu"):
        super().__init__()
        self.conv = Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                           groups=groups, bias_attr=False)
        self.bn = BatchNorm2D(out_c)
        self.act = act

    def __call__(self, x):
        x = self.bn(self.conv(x))
        if self.act == "relu":
            return F.relu(x)
        if self.act == "relu6":
            return F.relu6(x)
        if self.act == "hardswish":
            return F.hardswish(x)
        if self.act == "swish":
            return F.silu(x)
        return x


class LeNet(Module):
    """Ref: python/paddle/vision/models/lenet.py (28x28 inputs)."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(), MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1), ReLU(), MaxPool2D(2, 2))
        self.fc = Sequential(Linear(400, 120), Linear(120, 84),
                             Linear(84, num_classes))

    def __call__(self, x):
        x = self.features(x)
        return self.fc(x.reshape(x.shape[0], -1))


class AlexNet(Module):
    """Ref: python/paddle/vision/models/alexnet.py."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(), MaxPool2D(3, 2),
            Conv2D(64, 192, 5, padding=2), ReLU(), MaxPool2D(3, 2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(), MaxPool2D(3, 2))
        self.avgpool = AdaptiveAvgPool2D(6)
        self.classifier = Sequential(
            Dropout(0.5), Linear(256 * 6 * 6, 4096), ReLU(),
            Dropout(0.5), Linear(4096, 4096), ReLU(),
            Linear(4096, num_classes))

    def __call__(self, x, rng=None):
        x = self.avgpool(self.features(x))
        return self.classifier(x.reshape(x.shape[0], -1), rng=rng)


class _Fire(Module):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = Conv2D(in_c, squeeze, 1)
        self.expand1 = Conv2D(squeeze, e1, 1)
        self.expand3 = Conv2D(squeeze, e3, 3, padding=1)

    def __call__(self, x):
        x = F.relu(self.squeeze(x))
        return jnp.concatenate(
            [F.relu(self.expand1(x)), F.relu(self.expand3(x))], axis=1)


class SqueezeNet(Module):
    """Ref: python/paddle/vision/models/squeezenet.py (version 1.0/1.1)."""

    def __init__(self, version="1.0", num_classes=1000):
        super().__init__()
        version = str(version)
        self.version = version
        if version == "1.0":
            self.stem = Conv2D(3, 96, 7, stride=2)
            first_in = 96
            self.pool_before = (3, 7)  # maxpool precedes these block indices
        elif version == "1.1":
            self.stem = Conv2D(3, 64, 3, stride=2)
            first_in = 64
            self.pool_before = (2, 4)
        else:
            raise ValueError(f"SqueezeNet version must be '1.0' or '1.1', "
                             f"got {version!r}")
        self.blocks = [
            _Fire(first_in, 16, 64, 64), _Fire(128, 16, 64, 64),
            _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
            _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
            _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256)]
        self.dropout = Dropout(0.5)
        self.final_conv = Conv2D(512, num_classes, 1)
        self.pool = AdaptiveAvgPool2D(1)

    def __call__(self, x, rng=None):
        x = F.max_pool2d(F.relu(self.stem(x)), 3, 2)
        for i, b in enumerate(self.blocks):
            if i in self.pool_before:
                x = F.max_pool2d(x, 3, 2)
            x = b(x)
        x = self.dropout(x, rng=rng)
        x = self.pool(F.relu(self.final_conv(x)))
        return x.reshape(x.shape[0], -1)


class _DenseLayer(Module):
    def __init__(self, in_c, growth, bn_size):
        super().__init__()
        self.bn1 = BatchNorm2D(in_c)
        self.conv1 = Conv2D(in_c, bn_size * growth, 1, bias_attr=False)
        self.bn2 = BatchNorm2D(bn_size * growth)
        self.conv2 = Conv2D(bn_size * growth, growth, 3, padding=1, bias_attr=False)

    def __call__(self, x):
        y = self.conv1(F.relu(self.bn1(x)))
        y = self.conv2(F.relu(self.bn2(y)))
        return jnp.concatenate([x, y], axis=1)


class _Transition(Module):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = BatchNorm2D(in_c)
        self.conv = Conv2D(in_c, out_c, 1, bias_attr=False)

    def __call__(self, x):
        return F.avg_pool2d(self.conv(F.relu(self.bn(x))), 2, 2)


_DENSE_CFGS = {
    121: (32, (6, 12, 24, 16), 64), 161: (48, (6, 12, 36, 24), 96),
    169: (32, (6, 12, 32, 32), 64), 201: (32, (6, 12, 48, 32), 64),
    264: (32, (6, 12, 64, 48), 64),
}


class DenseNet(Module):
    """Ref: python/paddle/vision/models/densenet.py."""

    def __init__(self, layers=121, num_classes=1000, bn_size=4):
        super().__init__()
        growth, block_cfg, init_c = _DENSE_CFGS[layers]
        self.stem = Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False)
        self.stem_bn = BatchNorm2D(init_c)
        blocks = []
        c = init_c
        for bi, n in enumerate(block_cfg):
            for _ in range(n):
                blocks.append(_DenseLayer(c, growth, bn_size))
                c += growth
            if bi != len(block_cfg) - 1:
                blocks.append(_Transition(c, c // 2))
                c //= 2
        self.blocks = blocks
        self.final_bn = BatchNorm2D(c)
        self.pool = AdaptiveAvgPool2D(1)
        self.fc = Linear(c, num_classes)

    def __call__(self, x):
        x = F.max_pool2d(F.relu(self.stem_bn(self.stem(x))), 3, 2, padding=1)
        for b in self.blocks:
            x = b(x)
        x = self.pool(F.relu(self.final_bn(x)))
        return self.fc(x.reshape(x.shape[0], -1))


class _Inception(Module):
    """GoogLeNet inception block (1x1 / 3x3 / 5x5 / pool-proj branches)."""

    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _ConvBN(in_c, c1, 1)
        self.b3a = _ConvBN(in_c, c3r, 1)
        self.b3b = _ConvBN(c3r, c3, 3, padding=1)
        self.b5a = _ConvBN(in_c, c5r, 1)
        self.b5b = _ConvBN(c5r, c5, 5, padding=2)
        self.proj = _ConvBN(in_c, proj, 1)

    def __call__(self, x):
        return jnp.concatenate([
            self.b1(x), self.b3b(self.b3a(x)), self.b5b(self.b5a(x)),
            self.proj(F.max_pool2d(x, 3, 1, padding=1))], axis=1)


class GoogLeNet(Module):
    """Ref: python/paddle/vision/models/googlenet.py (aux heads omitted in
    eval; returns main logits)."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.stem = Sequential(
            _ConvBN(3, 64, 7, stride=2, padding=3), MaxPool2D(3, 2, padding=1),
            _ConvBN(64, 64, 1), _ConvBN(64, 192, 3, padding=1),
            MaxPool2D(3, 2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.pool = AdaptiveAvgPool2D(1)
        self.dropout = Dropout(0.2)
        self.fc = Linear(1024, num_classes)

    def __call__(self, x, rng=None):
        x = self.stem(x)
        x = self.i3b(self.i3a(x))
        x = F.max_pool2d(x, 3, 2, padding=1)
        x = self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(x)))))
        x = F.max_pool2d(x, 3, 2, padding=1)
        x = self.i5b(self.i5a(x))
        x = self.pool(x).reshape(x.shape[0], -1)
        return self.fc(self.dropout(x, rng=rng))


class _IncA(Module):
    def __init__(self, in_c, pool_c):
        super().__init__()
        self.b1 = _ConvBN(in_c, 64, 1)
        self.b5 = Sequential(_ConvBN(in_c, 48, 1), _ConvBN(48, 64, 5, padding=2))
        self.b3 = Sequential(_ConvBN(in_c, 64, 1), _ConvBN(64, 96, 3, padding=1),
                             _ConvBN(96, 96, 3, padding=1))
        self.bp = _ConvBN(in_c, pool_c, 1)

    def __call__(self, x):
        return jnp.concatenate([
            self.b1(x), self.b5(x), self.b3(x),
            self.bp(F.avg_pool2d(x, 3, 1, padding=1))], axis=1)


class _IncB(Module):  # grid reduction 35->17
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _ConvBN(in_c, 384, 3, stride=2)
        self.b3d = Sequential(_ConvBN(in_c, 64, 1), _ConvBN(64, 96, 3, padding=1),
                              _ConvBN(96, 96, 3, stride=2))

    def __call__(self, x):
        return jnp.concatenate([
            self.b3(x), self.b3d(x), F.max_pool2d(x, 3, 2)], axis=1)


class _IncC(Module):  # 17x17 factorised 7x7
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _ConvBN(in_c, 192, 1)
        self.b7 = Sequential(
            _ConvBN(in_c, c7, 1), _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = Sequential(
            _ConvBN(in_c, c7, 1), _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = _ConvBN(in_c, 192, 1)

    def __call__(self, x):
        return jnp.concatenate([
            self.b1(x), self.b7(x), self.b7d(x),
            self.bp(F.avg_pool2d(x, 3, 1, padding=1))], axis=1)


class _IncD(Module):  # grid reduction 17->8
    def __init__(self, in_c):
        super().__init__()
        self.b3 = Sequential(_ConvBN(in_c, 192, 1), _ConvBN(192, 320, 3, stride=2))
        self.b7 = Sequential(
            _ConvBN(in_c, 192, 1), _ConvBN(192, 192, (1, 7), padding=(0, 3)),
            _ConvBN(192, 192, (7, 1), padding=(3, 0)), _ConvBN(192, 192, 3, stride=2))

    def __call__(self, x):
        return jnp.concatenate([
            self.b3(x), self.b7(x), F.max_pool2d(x, 3, 2)], axis=1)


class _IncE(Module):  # 8x8 expanded
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _ConvBN(in_c, 320, 1)
        self.b3a = _ConvBN(in_c, 384, 1)
        self.b3b1 = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3b2 = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bda = Sequential(_ConvBN(in_c, 448, 1), _ConvBN(448, 384, 3, padding=1))
        self.bdb1 = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.bdb2 = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bp = _ConvBN(in_c, 192, 1)

    def __call__(self, x):
        a = self.b3a(x)
        d = self.bda(x)
        return jnp.concatenate([
            self.b1(x), self.b3b1(a), self.b3b2(a), self.bdb1(d), self.bdb2(d),
            self.bp(F.avg_pool2d(x, 3, 1, padding=1))], axis=1)


class InceptionV3(Module):
    """Ref: python/paddle/vision/models/inceptionv3.py (299x299 inputs)."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.stem = Sequential(
            _ConvBN(3, 32, 3, stride=2), _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1), MaxPool2D(3, 2),
            _ConvBN(64, 80, 1), _ConvBN(80, 192, 3), MaxPool2D(3, 2))
        self.blocks = [
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _IncB(288),
            _IncC(768, 128), _IncC(768, 160), _IncC(768, 160), _IncC(768, 192),
            _IncD(768),
            _IncE(1280), _IncE(2048)]
        self.pool = AdaptiveAvgPool2D(1)
        self.dropout = Dropout(0.5)
        self.fc = Linear(2048, num_classes)

    def __call__(self, x, rng=None):
        x = self.stem(x)
        for b in self.blocks:
            x = b(x)
        x = self.pool(x).reshape(x.shape[0], -1)
        return self.fc(self.dropout(x, rng=rng))


class _ShuffleUnit(Module):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 2:
            self.b1_dw = _ConvBN(in_c, in_c, 3, stride=2, padding=1,
                                 groups=in_c, act=None)
            self.b1_pw = _ConvBN(in_c, branch_c, 1, act=act)
            in_main = in_c
        else:
            in_main = in_c // 2
        self.b2_pw1 = _ConvBN(in_main, branch_c, 1, act=act)
        self.b2_dw = _ConvBN(branch_c, branch_c, 3, stride=stride, padding=1,
                             groups=branch_c, act=None)
        self.b2_pw2 = _ConvBN(branch_c, branch_c, 1, act=act)

    def __call__(self, x):
        if self.stride == 2:
            left = self.b1_pw(self.b1_dw(x))
            right = self.b2_pw2(self.b2_dw(self.b2_pw1(x)))
        else:
            left, right = jnp.split(x, 2, axis=1)
            right = self.b2_pw2(self.b2_dw(self.b2_pw1(right)))
        out = jnp.concatenate([left, right], axis=1)
        return F.channel_shuffle(out, 2)


_SHUFFLE_CFGS = {
    0.25: (24, 24, 48, 96, 512), 0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024), 1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}


class ShuffleNetV2(Module):
    """Ref: python/paddle/vision/models/shufflenetv2.py."""

    def __init__(self, scale=1.0, num_classes=1000, act="relu"):
        super().__init__()
        c0, c1, c2, c3, c_last = _SHUFFLE_CFGS[scale]
        self.stem = _ConvBN(3, c0, 3, stride=2, padding=1, act=act)
        blocks = []
        in_c = c0
        for c, n in ((c1, 4), (c2, 8), (c3, 4)):
            blocks.append(_ShuffleUnit(in_c, c, 2, act=act))
            for _ in range(n - 1):
                blocks.append(_ShuffleUnit(c, c, 1, act=act))
            in_c = c
        self.blocks = blocks
        self.head = _ConvBN(in_c, c_last, 1, act=act)
        self.pool = AdaptiveAvgPool2D(1)
        self.fc = Linear(c_last, num_classes)

    def __call__(self, x):
        x = F.max_pool2d(self.stem(x), 3, 2, padding=1)
        for b in self.blocks:
            x = b(x)
        x = self.pool(self.head(x))
        return self.fc(x.reshape(x.shape[0], -1))


class MobileNetV1(Module):
    """Ref: python/paddle/vision/models/mobilenetv1.py (dw-separable stacks)."""

    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__()
        def c(v):
            return max(8, int(v * scale))
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        self.stem = _ConvBN(3, c(32), 3, stride=2, padding=1)
        blocks = []
        for in_c, out_c, s in cfg:
            blocks.append(_ConvBN(c(in_c), c(in_c), 3, stride=s, padding=1,
                                  groups=c(in_c)))
            blocks.append(_ConvBN(c(in_c), c(out_c), 1))
        self.blocks = blocks
        self.pool = AdaptiveAvgPool2D(1)
        self.fc = Linear(c(1024), num_classes)

    def __call__(self, x):
        x = self.stem(x)
        for b in self.blocks:
            x = b(x)
        return self.fc(self.pool(x).reshape(x.shape[0], -1))


def _make_divisible(v, divisor=8):
    """Reference channel rounding (mobilenet make_divisible)."""
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _SEBlock(Module):
    def __init__(self, c, reduction=4):
        super().__init__()
        squeeze = _make_divisible(c // reduction, 8)
        self.fc1 = Conv2D(c, squeeze, 1)
        self.fc2 = Conv2D(squeeze, c, 1)

    def __call__(self, x):
        s = jnp.mean(x, axis=(2, 3), keepdims=True)
        s = F.hardsigmoid(self.fc2(F.relu(self.fc1(s))))
        return x * s


class _MBV3Block(Module):
    def __init__(self, in_c, exp_c, out_c, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        self.expand = _ConvBN(in_c, exp_c, 1, act=act) if exp_c != in_c else None
        self.dw = _ConvBN(exp_c, exp_c, k, stride=stride, padding=k // 2,
                          groups=exp_c, act=act)
        self.se = _SEBlock(exp_c) if use_se else None
        self.project = _ConvBN(exp_c, out_c, 1, act=None)

    def __call__(self, x):
        y = x if self.expand is None else self.expand(x)
        y = self.dw(y)
        if self.se is not None:
            y = self.se(y)
        y = self.project(y)
        return x + y if self.use_res else y


_MBV3_LARGE = [
    # k, exp, out, se, act, stride
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_MBV3_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class _MobileNetV3(Module):
    def __init__(self, cfg, last_exp, last_c, num_classes=1000):
        super().__init__()
        self.stem = _ConvBN(3, 16, 3, stride=2, padding=1, act="hardswish")
        blocks = []
        in_c = 16
        for k, exp, out, se, act, s in cfg:
            blocks.append(_MBV3Block(in_c, exp, out, k, s, se, act))
            in_c = out
        self.blocks = blocks
        self.head = _ConvBN(in_c, last_exp, 1, act="hardswish")
        self.pool = AdaptiveAvgPool2D(1)
        self.fc1 = Linear(last_exp, last_c)
        self.dropout = Dropout(0.2)
        self.fc2 = Linear(last_c, num_classes)

    def __call__(self, x, rng=None):
        x = self.stem(x)
        for b in self.blocks:
            x = b(x)
        x = self.pool(self.head(x)).reshape(x.shape[0], -1)
        return self.fc2(self.dropout(F.hardswish(self.fc1(x)), rng=rng))


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, num_classes=1000):
        super().__init__(_MBV3_LARGE, 960, 1280, num_classes)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, num_classes=1000):
        super().__init__(_MBV3_SMALL, 576, 1024, num_classes)


# -- factories (reference naming) --------------------------------------------

def alexnet(num_classes=1000):
    return AlexNet(num_classes)


def squeezenet1_0(num_classes=1000):
    return SqueezeNet("1.0", num_classes)


def squeezenet1_1(num_classes=1000):
    return SqueezeNet("1.1", num_classes)


def densenet121(num_classes=1000):
    return DenseNet(121, num_classes)


def densenet161(num_classes=1000):
    return DenseNet(161, num_classes)


def densenet169(num_classes=1000):
    return DenseNet(169, num_classes)


def densenet201(num_classes=1000):
    return DenseNet(201, num_classes)


def densenet264(num_classes=1000):
    return DenseNet(264, num_classes)


def googlenet(num_classes=1000):
    return GoogLeNet(num_classes)


def inception_v3(num_classes=1000):
    return InceptionV3(num_classes)


def shufflenet_v2_x0_25(num_classes=1000):
    return ShuffleNetV2(0.25, num_classes)


def shufflenet_v2_x0_5(num_classes=1000):
    return ShuffleNetV2(0.5, num_classes)


def shufflenet_v2_x1_0(num_classes=1000):
    return ShuffleNetV2(1.0, num_classes)


def shufflenet_v2_x1_5(num_classes=1000):
    return ShuffleNetV2(1.5, num_classes)


def shufflenet_v2_x2_0(num_classes=1000):
    return ShuffleNetV2(2.0, num_classes)


def shufflenet_v2_swish(num_classes=1000):
    """Ref shufflenetv2.py:shufflenet_v2_swish — x1.0 with swish acts."""
    return ShuffleNetV2(1.0, num_classes, act="swish")


def mobilenet_v1(scale=1.0, num_classes=1000):
    return MobileNetV1(scale, num_classes)


def mobilenet_v3_small(num_classes=1000):
    return MobileNetV3Small(num_classes)


def mobilenet_v3_large(num_classes=1000):
    return MobileNetV3Large(num_classes)
