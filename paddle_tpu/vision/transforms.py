"""Vision transforms (ref: ``python/paddle/vision/transforms/``).

Host-side numpy transforms (they run in the input pipeline, not on TPU);
Normalize/Resize also accept jax arrays for on-device use. Images are HWC
uint8/float; ToTensor converts to CHW float32 like the reference.
"""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        arr = arr.astype(np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        return np.transpose(arr, (2, 0, 1))


class Normalize:
    def __init__(self, mean, std, data_format="CHW"):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            return (img - self.mean[:, None, None]) / self.std[:, None, None]
        return (img - self.mean) / self.std


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[-1]
        if chw:
            h_axis, shape = 1, (arr.shape[0],) + self.size
        else:
            h_axis, shape = 0, self.size + (arr.shape[-1],) if arr.ndim == 3 else self.size
        method = {"bilinear": "linear", "nearest": "nearest", "bicubic": "cubic"}[self.interpolation]
        out = jax.image.resize(jnp.asarray(arr, jnp.float32), shape, method=method)
        return np.asarray(out)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[-1]
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        return arr[:, i:i + th, j:j + tw] if chw else arr[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=0, seed=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.rng = np.random.RandomState(seed)

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[-1]
        if self.padding:
            p = self.padding
            pad = ((0, 0), (p, p), (p, p)) if chw else ((p, p), (p, p), (0, 0))[:arr.ndim]
            arr = np.pad(arr, pad[:arr.ndim], mode="constant")
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i = self.rng.randint(0, h - th + 1)
        j = self.rng.randint(0, w - tw + 1)
        return arr[:, i:i + th, j:j + tw] if chw else arr[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, seed=None):
        self.prob = prob
        self.rng = np.random.RandomState(seed)

    def __call__(self, img):
        arr = np.asarray(img)
        if self.rng.rand() < self.prob:
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[-1]
            return arr[:, :, ::-1].copy() if chw else arr[:, ::-1].copy()
        return arr
