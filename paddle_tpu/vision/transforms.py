"""Vision transforms (ref: ``python/paddle/vision/transforms/``).

Host-side numpy transforms (they run in the input pipeline, not on TPU);
Normalize/Resize also accept jax arrays for on-device use. Images are HWC
uint8/float; ToTensor converts to CHW float32 like the reference.
"""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        arr = arr.astype(np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        return np.transpose(arr, (2, 0, 1))


class Normalize:
    def __init__(self, mean, std, data_format="CHW"):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            return (img - self.mean[:, None, None]) / self.std[:, None, None]
        return (img - self.mean) / self.std


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[-1]
        if chw:
            h_axis, shape = 1, (arr.shape[0],) + self.size
        else:
            h_axis, shape = 0, self.size + (arr.shape[-1],) if arr.ndim == 3 else self.size
        method = {"bilinear": "linear", "nearest": "nearest", "bicubic": "cubic"}[self.interpolation]
        out = np.asarray(jax.image.resize(jnp.asarray(arr, jnp.float32), shape,
                                          method=method))
        if arr.dtype == np.uint8:
            # preserve the dtype contract: uint8 in → uint8 out, so the
            # 0-255 vs 0-1 value-range question never depends on pipeline
            # position (reference behavior)
            out = np.clip(np.round(out), 0, 255).astype(np.uint8)
        return out


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[-1]
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        return arr[:, i:i + th, j:j + tw] if chw else arr[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=0, seed=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.rng = np.random.RandomState(seed)

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[-1]
        if self.padding:
            p = self.padding
            pad = ((0, 0), (p, p), (p, p)) if chw else ((p, p), (p, p), (0, 0))[:arr.ndim]
            arr = np.pad(arr, pad[:arr.ndim], mode="constant")
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i = self.rng.randint(0, h - th + 1)
        j = self.rng.randint(0, w - tw + 1)
        return arr[:, i:i + th, j:j + tw] if chw else arr[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, seed=None):
        self.prob = prob
        self.rng = np.random.RandomState(seed)

    def __call__(self, img):
        arr = np.asarray(img)
        if self.rng.rand() < self.prob:
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[-1]
            return arr[:, :, ::-1].copy() if chw else arr[:, ::-1].copy()
        return arr


def _is_chw(arr):
    return (arr.ndim == 3 and arr.shape[0] in (1, 3)
            and arr.shape[0] < arr.shape[-1])


def _to_hwc(arr):
    """→ (hwc_array, was_chw). 2-D stays [H, W, 1]."""
    if arr.ndim == 2:
        return arr[:, :, None], False
    if _is_chw(arr):
        return np.transpose(arr, (1, 2, 0)), True
    return arr, False


def _from_hwc(arr, was_chw, orig_ndim):
    if orig_ndim == 2:
        return arr[:, :, 0]
    return np.transpose(arr, (2, 0, 1)) if was_chw else arr


# -- functional mirror (ref python/paddle/vision/transforms/functional.py) ---

def hflip(img):
    arr = np.asarray(img)
    return arr[:, :, ::-1].copy() if _is_chw(arr) else arr[:, ::-1].copy()


def vflip(img):
    arr = np.asarray(img)
    return arr[:, ::-1].copy() if _is_chw(arr) else arr[::-1].copy()


def crop(img, top, left, height, width):
    arr = np.asarray(img)
    if _is_chw(arr):
        return arr[:, top:top + height, left:left + width]
    return arr[top:top + height, left:left + width]


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = np.asarray(img)
    if isinstance(padding, int):
        pl = pr = pt = pb = padding
    elif len(padding) == 2:
        (pl, pt), (pr, pb) = (padding[0], padding[1]), (padding[0], padding[1])
    else:
        pl, pt, pr, pb = padding
    hwc, was_chw = _to_hwc(arr)
    kw = {"mode": padding_mode}
    if padding_mode == "constant":
        kw["constant_values"] = fill
    out = np.pad(hwc, ((pt, pb), (pl, pr), (0, 0)), **kw)
    return _from_hwc(out, was_chw, arr.ndim)


def _value_ceiling(arr):
    """Dtype contract, never data-dependent: uint8 images live in 0-255,
    float images in 0-1 (ToTensor's output). Resize preserves uint8, so a
    pipeline never silently switches range mid-stream."""
    return 255.0 if arr.dtype == np.uint8 else 1.0


def adjust_brightness(img, factor):
    src = np.asarray(img)
    arr = src.astype(np.float32)
    out = np.clip(arr * factor, 0, _value_ceiling(src))
    return out.astype(src.dtype)


def adjust_contrast(img, factor):
    src = np.asarray(img)
    arr = src.astype(np.float32)
    hwc, _ = _to_hwc(arr)
    mean = _rgb_to_gray(hwc).mean()
    out = np.clip(mean + factor * (arr - mean), 0, _value_ceiling(src))
    return out.astype(src.dtype)


def adjust_saturation(img, factor):
    src = np.asarray(img)
    arr = src.astype(np.float32)
    hwc, was_chw = _to_hwc(arr)
    gray = _rgb_to_gray(hwc)[..., None]
    out = np.clip(gray + factor * (hwc - gray), 0, _value_ceiling(src))
    return _from_hwc(out, was_chw, arr.ndim).astype(src.dtype)


def adjust_hue(img, hue_factor):
    """hue_factor in [-0.5, 0.5] — rotate hue via HSV roundtrip. Grayscale
    images are returned unchanged (reference behavior)."""
    src = np.asarray(img)
    arr = src.astype(np.float32)
    scale = 255.0 if src.dtype == np.uint8 else 1.0
    if arr.ndim == 2 or _to_hwc(arr)[0].shape[-1] < 3:
        return src
    hwc, was_chw = _to_hwc(arr / scale)
    r, g, b = hwc[..., 0], hwc[..., 1], hwc[..., 2]
    mx, mn = hwc.max(-1), hwc.min(-1)
    diff = mx - mn + 1e-12
    h = np.where(mx == r, (g - b) / diff % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4)) / 6
    s = np.where(mx > 0, diff / (mx + 1e-12), 0)
    v = mx
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6)
    f = h * 6 - i
    p, q, t = v * (1 - s), v * (1 - f * s), v * (1 - (1 - f) * s)
    i = i.astype(np.int32) % 6
    out = np.empty_like(hwc)
    for k, (rr, gg, bb) in enumerate([(v, t, p), (q, v, p), (p, v, t),
                                      (p, q, v), (t, p, v), (v, p, q)]):
        m = i == k
        out[..., 0] = np.where(m, rr, out[..., 0]) if k else np.where(m, rr, 0)
        out[..., 1] = np.where(m, gg, out[..., 1]) if k else np.where(m, gg, 0)
        out[..., 2] = np.where(m, bb, out[..., 2]) if k else np.where(m, bb, 0)
    out = _from_hwc(out * scale, was_chw, arr.ndim)
    return np.clip(out, 0, scale).astype(src.dtype)


def _rgb_to_gray(hwc):
    if hwc.shape[-1] == 1:
        return hwc[..., 0]
    return (0.299 * hwc[..., 0] + 0.587 * hwc[..., 1] + 0.114 * hwc[..., 2])


def to_grayscale(img, num_output_channels=1):
    arr = np.asarray(img).astype(np.float32)
    hwc, was_chw = _to_hwc(arr)
    g = _rgb_to_gray(hwc)[..., None]
    out = np.repeat(g, num_output_channels, axis=-1)
    return _from_hwc(out, was_chw, 3).astype(np.asarray(img).dtype)


def rotate(img, angle, interpolation="bilinear", expand=False, fill=0.0):
    """Rotate around the image center (degrees CCW) — inverse-map bilinear
    sampling in numpy (host-side pipeline, like the reference's CPU path)."""
    src = np.asarray(img)
    arr = src.astype(np.float32)
    hwc, was_chw = _to_hwc(arr)
    h, w = hwc.shape[:2]
    # positive angle = counter-clockwise in image coords (y down), matching
    # the reference; the inverse map therefore rotates by -angle
    theta = -np.deg2rad(angle)
    c, s = np.cos(theta), np.sin(theta)
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    if expand:  # enlarge the canvas to hold the whole rotated image
        oh = int(np.ceil(abs(h * c) + abs(w * s)))
        ow = int(np.ceil(abs(w * c) + abs(h * s)))
    else:
        oh, ow = h, w
    ocy, ocx = (oh - 1) / 2.0, (ow - 1) / 2.0
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    # inverse rotation of output coords into source coords
    xs = c * (xx - ocx) + s * (yy - ocy) + cx
    ys = -s * (xx - ocx) + c * (yy - ocy) + cy
    if interpolation == "nearest":
        xi = np.round(xs).astype(np.int64)
        yi = np.round(ys).astype(np.int64)
        valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
        out = np.where(valid[..., None],
                       hwc[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)], fill)
    else:
        x0 = np.floor(xs).astype(np.int64)
        y0 = np.floor(ys).astype(np.int64)
        lx, ly = xs - x0, ys - y0
        out = 0.0
        for dy in (0, 1):
            for dx in (0, 1):
                yi, xi = y0 + dy, x0 + dx
                wgt = ((ly if dy else 1 - ly) * (lx if dx else 1 - lx))[..., None]
                inb = ((xi >= 0) & (xi < w) & (yi >= 0) & (yi < h))[..., None]
                v = hwc[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)]
                out = out + np.where(inb, wgt * v, wgt * fill)
    return _from_hwc(out, was_chw, src.ndim).astype(src.dtype)


def erase(img, i, j, h, w, v=0):
    arr = np.asarray(img).copy()
    if _is_chw(arr):
        arr[:, i:i + h, j:j + w] = v
    else:
        arr[i:i + h, j:j + w] = v
    return arr


def normalize(img, mean, std, data_format="CHW"):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


# -- transform classes (ref python/paddle/vision/transforms/transforms.py) ---

class RandomVerticalFlip:
    def __init__(self, prob=0.5, seed=None):
        self.prob = prob
        self.rng = np.random.RandomState(seed)

    def __call__(self, img):
        return vflip(img) if self.rng.rand() < self.prob else np.asarray(img)


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", seed=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation
        self.rng = np.random.RandomState(seed)

    def __call__(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _to_hwc(arr)
        h, w = hwc.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * self.rng.uniform(*self.scale)
            ar = np.exp(self.rng.uniform(np.log(self.ratio[0]),
                                         np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = self.rng.randint(0, h - ch + 1)
                j = self.rng.randint(0, w - cw + 1)
                patch = hwc[i:i + ch, j:j + cw]
                break
        else:  # fallback: center crop
            m = min(h, w)
            i, j = (h - m) // 2, (w - m) // 2
            patch = hwc[i:i + m, j:j + m]
        out = Resize(self.size, self.interpolation)(patch)
        return _from_hwc(np.asarray(out), was_chw, arr.ndim)


class ColorJitter:
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, seed=None):
        self.brightness, self.contrast = brightness, contrast
        self.saturation, self.hue = saturation, hue
        self.rng = np.random.RandomState(seed)

    def _factor(self, amt):
        return self.rng.uniform(max(0, 1 - amt), 1 + amt)

    def __call__(self, img):
        out = np.asarray(img)
        ops = []
        if self.brightness:
            ops.append(lambda x: adjust_brightness(x, self._factor(self.brightness)))
        if self.contrast:
            ops.append(lambda x: adjust_contrast(x, self._factor(self.contrast)))
        if self.saturation:
            ops.append(lambda x: adjust_saturation(x, self._factor(self.saturation)))
        if self.hue:
            ops.append(lambda x: adjust_hue(x, self.rng.uniform(-self.hue, self.hue)))
        self.rng.shuffle(ops)
        for op in ops:
            out = op(out)
        return out


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def __call__(self, img):
        return to_grayscale(img, self.num_output_channels)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def __call__(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation:
    def __init__(self, degrees, interpolation="bilinear", fill=0.0, seed=None):
        self.degrees = ((-degrees, degrees) if isinstance(degrees, (int, float))
                        else tuple(degrees))
        self.interpolation, self.fill = interpolation, fill
        self.rng = np.random.RandomState(seed)

    def __call__(self, img):
        angle = self.rng.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, fill=self.fill)


class RandomErasing:
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, seed=None):
        self.prob, self.scale, self.ratio, self.value = prob, scale, ratio, value
        self.rng = np.random.RandomState(seed)

    def __call__(self, img):
        arr = np.asarray(img)
        if self.rng.rand() >= self.prob:
            return arr
        chw = _is_chw(arr)
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        for _ in range(10):
            target = h * w * self.rng.uniform(*self.scale)
            ar = np.exp(self.rng.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i = self.rng.randint(0, h - eh + 1)
                j = self.rng.randint(0, w - ew + 1)
                return erase(arr, i, j, eh, ew, self.value)
        return arr
