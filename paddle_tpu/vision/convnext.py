"""ConvNeXt family (ref capability: PaddleClas ``ppcls/arch/backbone/
model_zoo/convnext.py``).

TPU notes: blocks run channels-LAST internally — the 7×7 depthwise conv and
the two pointwise matmuls then keep channels on the 128-lane axis, and the
LayerNorm over channels is a lane-wise reduce. Only the stem/downsample
convs see NCHW at the API boundary (reference layout).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Conv2D, LayerNorm, Linear

__all__ = ["ConvNeXt", "convnext_tiny", "convnext_small", "convnext_base",
           "convnext_large"]


class _Block(Module):
    """dwconv7x7 → LN → pw 4x → GELU → pw → layer-scale → residual."""

    def __init__(self, dim, layer_scale_init=1e-6, drop_path=0.0, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        self.dwconv = Conv2D(dim, dim, 7, padding=3, groups=dim, dtype=dtype)
        self.norm = LayerNorm(dim, epsilon=1e-6, dtype=dtype)
        self.pwconv1 = Linear(dim, 4 * dim, dtype=dtype)
        self.pwconv2 = Linear(4 * dim, dim, dtype=dtype)
        self.gamma = I.Constant(layer_scale_init)((dim,), dtype)
        self.drop_path = drop_path

    def __call__(self, x, rng=None):
        # x: NCHW
        y = self.dwconv(x)
        y = jnp.transpose(y, (0, 2, 3, 1))       # NHWC: lanes = channels
        y = self.norm(y)
        y = self.pwconv2(jax.nn.gelu(self.pwconv1(y)))
        y = (self.gamma.astype(y.dtype) * y)
        y = jnp.transpose(y, (0, 3, 1, 2))
        if self.drop_path > 0 and self.training and rng is not None:
            keep = 1.0 - self.drop_path
            mask = jax.random.bernoulli(rng, keep, (x.shape[0], 1, 1, 1))
            y = y * mask.astype(y.dtype) / keep
        return x + y


class ConvNeXt(Module):
    def __init__(self, in_chans=3, num_classes=1000, depths=(3, 3, 9, 3),
                 dims=(96, 192, 384, 768), drop_path_rate=0.0,
                 layer_scale_init=1e-6, class_num=None, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        num_classes = class_num if class_num is not None else num_classes
        self.stem = Conv2D(in_chans, dims[0], 4, stride=4, dtype=dtype)
        self.stem_norm = LayerNorm(dims[0], epsilon=1e-6, dtype=dtype)
        self.down_norms = []
        self.down_convs = []
        for i in range(3):
            self.down_norms.append(LayerNorm(dims[i], epsilon=1e-6, dtype=dtype))
            self.down_convs.append(Conv2D(dims[i], dims[i + 1], 2, stride=2,
                                          dtype=dtype))
        rates = [float(r) for r in
                 jnp.linspace(0, drop_path_rate, sum(depths))]
        self.stages = []
        k = 0
        for i, depth in enumerate(depths):
            self.stages.append([_Block(dims[i], layer_scale_init, rates[k + j],
                                       dtype=dtype) for j in range(depth)])
            k += depth
        self.head_norm = LayerNorm(dims[-1], epsilon=1e-6, dtype=dtype)
        self.head = Linear(dims[-1], num_classes, dtype=dtype)

    def _nhwc_norm(self, x, norm):
        x = jnp.transpose(x, (0, 2, 3, 1))
        x = norm(x)
        return jnp.transpose(x, (0, 3, 1, 2))

    def __call__(self, x, rng=None):
        x = self._nhwc_norm(self.stem(x), self.stem_norm)
        for i, stage in enumerate(self.stages):
            if i > 0:
                x = self.down_convs[i - 1](
                    self._nhwc_norm(x, self.down_norms[i - 1]))
            for j, blk in enumerate(stage):
                sub = (None if rng is None
                       else jax.random.fold_in(rng, i * 100 + j))
                x = blk(x, rng=sub)
        x = x.mean(axis=(2, 3))                   # global average pool
        return self.head(self.head_norm(x))


def convnext_tiny(**kw):
    return ConvNeXt(depths=(3, 3, 9, 3), dims=(96, 192, 384, 768), **kw)


def convnext_small(**kw):
    return ConvNeXt(depths=(3, 3, 27, 3), dims=(96, 192, 384, 768), **kw)


def convnext_base(**kw):
    return ConvNeXt(depths=(3, 3, 27, 3), dims=(128, 256, 512, 1024), **kw)


def convnext_large(**kw):
    return ConvNeXt(depths=(3, 3, 27, 3), dims=(192, 384, 768, 1536), **kw)
