"""Vision Transformer family (ref capability: PaddleClas ``ppcls/arch/
backbone/model_zoo/vision_transformer.py`` — ViT-Ti/S/B/L, DeiT variants).

TPU-first notes: patch embedding is one strided conv (maps to the MXU as an
im2col matmul); the token stream [B, 1+N, D] keeps D on the 128-lane axis;
encoder blocks are pre-LN (``normalize_before=True``) transformer layers
reused from ``paddle_tpu.nn`` so flash attention and AMP policies apply
unchanged.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Conv2D, Dropout, LayerNorm, Linear
from paddle_tpu.nn.transformer import TransformerEncoder, TransformerEncoderLayer

__all__ = ["VisionTransformer", "vit_tiny_patch16_224", "vit_small_patch16_224",
           "vit_base_patch16_224", "vit_base_patch32_224", "vit_large_patch16_224"]


class PatchEmbed(Module):
    """Image → patch tokens via one strided conv (im2col matmul on MXU)."""

    def __init__(self, img_size=224, patch_size=16, in_chans=3, embed_dim=768,
                 dtype=None):
        super().__init__()
        self.num_patches = (img_size // patch_size) ** 2
        self.proj = Conv2D(in_chans, embed_dim, patch_size, stride=patch_size,
                           dtype=dtype)

    def __call__(self, x):
        x = self.proj(x)                       # [B, D, H/p, W/p]
        b, d = x.shape[0], x.shape[1]
        return x.reshape(b, d, -1).transpose(0, 2, 1)  # [B, N, D]


class VisionTransformer(Module):
    def __init__(self, img_size=224, patch_size=16, in_chans=3,
                 num_classes=1000, embed_dim=768, depth=12, num_heads=12,
                 mlp_ratio=4.0, drop_rate=0.0, class_num=None, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        num_classes = class_num if class_num is not None else num_classes
        self.patch_embed = PatchEmbed(img_size, patch_size, in_chans,
                                      embed_dim, dtype=dtype)
        n = self.patch_embed.num_patches
        self.cls_token = I.TruncatedNormal(std=0.02)((1, 1, embed_dim), dtype)
        self.pos_embed = I.TruncatedNormal(std=0.02)((1, n + 1, embed_dim), dtype)
        self.pos_drop = Dropout(drop_rate)
        self.encoder = TransformerEncoder(
            lambda: TransformerEncoderLayer(
                embed_dim, num_heads, int(embed_dim * mlp_ratio),
                dropout=drop_rate, activation="gelu", normalize_before=True,
                dtype=dtype),
            depth)
        self.norm = LayerNorm(embed_dim, dtype=dtype)
        self.head = Linear(embed_dim, num_classes, dtype=dtype)

    def forward_features(self, x, rng=None):
        b = x.shape[0]
        x = self.patch_embed(x)
        cls = jnp.broadcast_to(self.cls_token, (b, 1, x.shape[-1]))
        x = jnp.concatenate([cls.astype(x.dtype), x], axis=1)
        x = self.pos_drop(x + self.pos_embed.astype(x.dtype), rng=rng)
        x = self.encoder(x, rng=rng)
        return self.norm(x)

    def __call__(self, x, rng=None):
        feats = self.forward_features(x, rng=rng)
        return self.head(feats[:, 0])          # classify on the cls token


def _vit(patch, dim, depth, heads, **kw):
    return VisionTransformer(patch_size=patch, embed_dim=dim, depth=depth,
                             num_heads=heads, **kw)


def vit_tiny_patch16_224(**kw):
    return _vit(16, 192, 12, 3, **kw)


def vit_small_patch16_224(**kw):
    return _vit(16, 384, 12, 6, **kw)


def vit_base_patch16_224(**kw):
    return _vit(16, 768, 12, 12, **kw)


def vit_base_patch32_224(**kw):
    return _vit(32, 768, 12, 12, **kw)


def vit_large_patch16_224(**kw):
    return _vit(16, 1024, 24, 16, **kw)
