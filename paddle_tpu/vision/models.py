"""Vision model zoo (ref: ``python/paddle/vision/models/``) — ResNet family
re-exported plus VGG and MobileNetV2."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.models.resnet import (  # noqa: F401
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    resnext50_32x4d,
    resnext101_32x4d,
    resnext101_64x4d,
    resnext152_32x4d,
    resnext152_64x4d,
    wide_resnet50_2,
    wide_resnet101_2,
)
from paddle_tpu.vision.models_extra import *  # noqa: F401,F403
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layers import (
    AdaptiveAvgPool2D,
    BatchNorm2D,
    Conv2D,
    Dropout,
    Linear,
    MaxPool2D,
    Sequential,
)

_VGG_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512, 512,
         "M", 512, 512, 512, 512, "M"],
}


class VGG(Module):
    def __init__(self, depth=16, num_classes=1000, batch_norm=True):
        super().__init__()
        layers = []
        in_c = 3
        for v in _VGG_CFGS[depth]:
            if v == "M":
                layers.append(MaxPool2D(2, 2))
            else:
                layers.append(Conv2D(in_c, v, 3, padding=1, bias_attr=not batch_norm))
                if batch_norm:
                    layers.append(BatchNorm2D(v))
                from paddle_tpu.nn.layers import ReLU
                layers.append(ReLU())
                in_c = v
        self.features = Sequential(*layers)
        self.avgpool = AdaptiveAvgPool2D(7)
        self.classifier = Sequential(
            Linear(512 * 7 * 7, 4096), _relu(), Dropout(0.5),
            Linear(4096, 4096), _relu(), Dropout(0.5),
            Linear(4096, num_classes))

    def __call__(self, x, rng=None):
        x = self.features(x)
        x = self.avgpool(x)
        return self.classifier(x.reshape(x.shape[0], -1), rng=rng)


def _relu():
    from paddle_tpu.nn.layers import ReLU
    return ReLU()


def vgg11(num_classes=1000, **kw):
    return VGG(11, num_classes, **kw)


def vgg13(num_classes=1000, **kw):
    return VGG(13, num_classes, **kw)


def vgg16(num_classes=1000, **kw):
    return VGG(16, num_classes, **kw)


def vgg19(num_classes=1000, **kw):
    return VGG(19, num_classes, **kw)


class _InvertedResidual(Module):
    def __init__(self, in_c, out_c, stride, expand):
        super().__init__()
        hidden = in_c * expand
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand != 1:
            layers += [Conv2D(in_c, hidden, 1, bias_attr=False), BatchNorm2D(hidden)]
        layers += [Conv2D(hidden, hidden, 3, stride=stride, padding=1,
                          groups=hidden, bias_attr=False), BatchNorm2D(hidden)]
        self.expand_layers = layers
        self.project = Conv2D(hidden, out_c, 1, bias_attr=False)
        self.project_bn = BatchNorm2D(out_c)

    def __call__(self, x):
        y = x
        i = 0
        layers = self.expand_layers
        while i < len(layers):
            y = layers[i](y)       # conv
            y = layers[i + 1](y)   # bn
            y = F.relu6(y)
            i += 2
        y = self.project_bn(self.project(y))
        return x + y if self.use_res else y


class MobileNetV2(Module):
    def __init__(self, num_classes=1000, width_mult=1.0):
        super().__init__()
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        c0 = int(32 * width_mult)
        self.stem = Conv2D(3, c0, 3, stride=2, padding=1, bias_attr=False)
        self.stem_bn = BatchNorm2D(c0)
        blocks = []
        in_c = c0
        for t, c, n, s in cfg:
            out_c = int(c * width_mult)
            for i in range(n):
                blocks.append(_InvertedResidual(in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        self.blocks = blocks
        last = int(1280 * max(1.0, width_mult))
        self.head = Conv2D(in_c, last, 1, bias_attr=False)
        self.head_bn = BatchNorm2D(last)
        self.pool = AdaptiveAvgPool2D(1)
        self.fc = Linear(last, num_classes)

    def __call__(self, x):
        x = F.relu6(self.stem_bn(self.stem(x)))
        for b in self.blocks:
            x = b(x)
        x = F.relu6(self.head_bn(self.head(x)))
        x = self.pool(x)
        return self.fc(x.reshape(x.shape[0], -1))


def mobilenet_v2(num_classes=1000, **kw):
    return MobileNetV2(num_classes, **kw)
