"""Vision datasets (ref: ``python/paddle/vision/datasets/``).

File-backed parsers for the reference's dataset formats (MNIST idx,
CIFAR pickle batches). No downloading — this environment has zero egress;
point ``*_path`` at local copies. ``FakeData`` generates deterministic
synthetic batches for pipeline tests (reference uses it the same way).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from paddle_tpu.io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad idx image magic {magic}"
        data = np.frombuffer(f.read(), np.uint8)
    return data.reshape(n, rows, cols)


def _read_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad idx label magic {magic}"
        return np.frombuffer(f.read(), np.uint8)


class MNIST(Dataset):
    """Ref: paddle.vision.datasets.MNIST — idx-format reader.

    ``image_path``/``label_path`` point at (optionally gzipped) idx files.
    """

    def __init__(self, image_path, label_path, transform=None,
                 backend="numpy"):
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None]  # [1, H, W]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])


class FashionMNIST(MNIST):
    """Same idx container as MNIST."""


class Cifar10(Dataset):
    """Ref: paddle.vision.datasets.Cifar10 — python-pickle tar reader."""

    _train_members = [f"data_batch_{i}" for i in range(1, 6)]
    _test_members = ["test_batch"]
    _label_key = b"labels"

    def __init__(self, data_file, mode="train", transform=None):
        members = self._train_members if mode == "train" else self._test_members
        images, labels = [], []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                if base in members:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    images.append(d[b"data"])
                    labels.extend(d[self._label_key])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])


class Cifar100(Cifar10):
    _train_members = ["train"]
    _test_members = ["test"]
    _label_key = b"fine_labels"


class FakeData(Dataset):
    """Deterministic synthetic dataset for pipeline tests."""

    def __init__(self, size=100, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rs = np.random.RandomState(self.seed + idx)
        img = rs.randn(*self.image_shape).astype(np.float32)
        label = int(rs.randint(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label
