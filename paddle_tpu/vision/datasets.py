"""Vision datasets (ref: ``python/paddle/vision/datasets/``).

File-backed parsers for the reference's dataset formats (MNIST idx,
CIFAR pickle batches). No downloading — this environment has zero egress;
point ``*_path`` at local copies. ``FakeData`` generates deterministic
synthetic batches for pipeline tests (reference uses it the same way).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from paddle_tpu.io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData",
           "DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def _pil_loader(path):
    from PIL import Image
    with Image.open(path) as img:
        return np.asarray(img.convert("RGB"))  # HWC uint8


def _scan_files(root, valid):
    """Sorted recursive scan of files under ``root`` passing ``valid``."""
    out = []
    for dirpath, _, files in sorted(os.walk(root)):
        for fname in sorted(files):
            p = os.path.join(dirpath, fname)
            if valid(p):
                out.append(p)
    return out


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad idx image magic {magic}"
        data = np.frombuffer(f.read(), np.uint8)
    return data.reshape(n, rows, cols)


def _read_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad idx label magic {magic}"
        return np.frombuffer(f.read(), np.uint8)


class MNIST(Dataset):
    """Ref: paddle.vision.datasets.MNIST — idx-format reader.

    ``image_path``/``label_path`` point at (optionally gzipped) idx files.
    """

    def __init__(self, image_path, label_path, transform=None,
                 backend="numpy"):
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None]  # [1, H, W]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])


class FashionMNIST(MNIST):
    """Same idx container as MNIST."""


class Cifar10(Dataset):
    """Ref: paddle.vision.datasets.Cifar10 — python-pickle tar reader."""

    _train_members = [f"data_batch_{i}" for i in range(1, 6)]
    _test_members = ["test_batch"]
    _label_key = b"labels"

    def __init__(self, data_file, mode="train", transform=None):
        members = self._train_members if mode == "train" else self._test_members
        images, labels = [], []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                if base in members:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    images.append(d[b"data"])
                    labels.extend(d[self._label_key])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])


class Cifar100(Cifar10):
    _train_members = ["train"]
    _test_members = ["test"]
    _label_key = b"fine_labels"


class FakeData(Dataset):
    """Deterministic synthetic dataset for pipeline tests."""

    def __init__(self, size=100, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rs = np.random.RandomState(self.seed + idx)
        img = rs.randn(*self.image_shape).astype(np.float32)
        label = int(rs.randint(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class DatasetFolder(Dataset):
    """Ref: paddle.vision.datasets.DatasetFolder — ``root/class_x/img.ext``
    directory scanner. Classes are sorted subdirectory names."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _pil_loader
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        valid = is_valid_file or (
            lambda p: p.lower().endswith(exts))
        self.samples = []
        for c in classes:
            for p in _scan_files(os.path.join(root, c), valid):
                self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"found no valid files under {root}")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target


class ImageFolder(Dataset):
    """Ref: paddle.vision.datasets.ImageFolder — flat (unlabelled) image list;
    ``__getitem__`` returns ``[img]`` like the reference."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.loader = loader or _pil_loader
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
        valid = is_valid_file or (lambda p: p.lower().endswith(exts))
        self.samples = _scan_files(root, valid)
        if not self.samples:
            raise RuntimeError(f"found no valid files under {root}")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]


class Flowers(Dataset):
    """Ref: paddle.vision.datasets.Flowers (Oxford 102). Reads the jpg
    tarball + ``imagelabels.mat`` + ``setid.mat`` (scipy) from local files."""

    _splits = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file, label_file, setid_file, mode="train",
                 transform=None):
        from scipy.io import loadmat
        self.labels = loadmat(label_file)["labels"][0]
        ids = loadmat(setid_file)[self._splits[mode]][0]
        self.indexes = np.sort(ids)
        self.transform = transform
        self._tar_path = data_file
        self._tar = None
        with tarfile.open(data_file) as tf:
            self._names = {os.path.basename(m.name): m.name
                           for m in tf.getmembers() if m.isfile()}

    def __len__(self):
        return len(self.indexes)

    def __getitem__(self, idx):
        from PIL import Image
        import io as _io
        flower_id = int(self.indexes[idx])
        if self._tar is None:  # lazy per-process open (worker-pool safe)
            self._tar = tarfile.open(self._tar_path)
        name = self._names[f"image_{flower_id:05d}.jpg"]
        data = self._tar.extractfile(name).read()
        img = np.asarray(Image.open(_io.BytesIO(data)).convert("RGB"))
        if self.transform is not None:
            img = self.transform(img)
        # raw 1-based label, matching the reference's .mat passthrough
        return img, int(self.labels[flower_id - 1])

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_tar"] = None
        return state


class VOC2012(Dataset):
    """Ref: paddle.vision.datasets.VOC2012 — segmentation pairs
    (image, mask) from the VOCtrainval tar."""

    _list = {"train": "ImageSets/Segmentation/train.txt",
             "valid": "ImageSets/Segmentation/val.txt",
             "trainval": "ImageSets/Segmentation/trainval.txt"}

    def __init__(self, data_file, mode="train", transform=None):
        self.transform = transform
        self._tar_path = data_file
        self._tar = None
        with tarfile.open(data_file) as tf:
            names = [m.name for m in tf.getmembers() if m.isfile()]
            list_name = next(n for n in names
                             if n.endswith(self._list[mode]))
            names = tf.extractfile(list_name).read().decode().split()
            root = list_name.split("ImageSets/")[0]
        self.pairs = [(f"{root}JPEGImages/{n}.jpg",
                       f"{root}SegmentationClass/{n}.png") for n in names]

    def __len__(self):
        return len(self.pairs)

    def __getitem__(self, idx):
        from PIL import Image
        import io as _io
        if self._tar is None:
            self._tar = tarfile.open(self._tar_path)
        ipath, mpath = self.pairs[idx]
        img = np.asarray(Image.open(
            _io.BytesIO(self._tar.extractfile(ipath).read())).convert("RGB"))
        mask = np.asarray(Image.open(
            _io.BytesIO(self._tar.extractfile(mpath).read())))
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_tar"] = None
        return state
