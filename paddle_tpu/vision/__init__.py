from paddle_tpu.vision import (convnext, datasets, models, models_extra, ops,
                               swin, transforms, vit)
