from paddle_tpu.vision import models, transforms
