from paddle_tpu.vision import models, transforms
from paddle_tpu.vision import models_extra
from paddle_tpu.vision.models_extra import *  # noqa: F401,F403
