from paddle_tpu.vision import (datasets, models, models_extra, ops, transforms,
                               vit)
