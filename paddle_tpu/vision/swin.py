"""Swin Transformer (ref capability: PaddleClas ``ppcls/arch/backbone/
model_zoo/swin_transformer.py``).

TPU notes: window partition is pure reshape/transpose (no gather); windowed
attention batches all windows into one [B·nW, w², C] attention call so the
MXU sees one large batched matmul; the shifted-window mask is precomputed
per stage resolution (static shapes) and added to logits.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Conv2D, Linear
from paddle_tpu.nn.layers import LayerNorm

__all__ = ["SwinTransformer", "swin_tiny_patch4_window7_224",
           "swin_small_patch4_window7_224", "swin_base_patch4_window7_224"]


def window_partition(x, w):
    """[B, H, W, C] → [B*nW, w*w, C] (reshape/transpose only)."""
    b, h, wd, c = x.shape
    x = x.reshape(b, h // w, w, wd // w, w, c)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(-1, w * w, c)


def window_reverse(x, w, h, wd):
    b = x.shape[0] // ((h // w) * (wd // w))
    x = x.reshape(b, h // w, wd // w, w, w, -1)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(b, h, wd, -1)


def _relative_index(w):
    coords = np.stack(np.meshgrid(np.arange(w), np.arange(w), indexing="ij"))
    flat = coords.reshape(2, -1)
    rel = flat[:, :, None] - flat[:, None, :]          # [2, w², w²]
    rel = rel.transpose(1, 2, 0) + (w - 1)
    return (rel[..., 0] * (2 * w - 1) + rel[..., 1]).astype(np.int32)


class WindowAttention(Module):
    def __init__(self, dim, num_heads, window, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        self.qkv = Linear(dim, 3 * dim, dtype=dtype)
        self.proj = Linear(dim, dim, dtype=dtype)
        self.rel_bias = I.TruncatedNormal(std=0.02)(
            ((2 * window - 1) ** 2, num_heads), dtype)
        self.register_buffer("rel_index", jnp.asarray(_relative_index(window)))
        self.num_heads = num_heads
        self.window = window

    def __call__(self, x, mask=None):
        bnw, n, c = x.shape
        nh = self.num_heads
        qkv = self.qkv(x).reshape(bnw, n, 3, nh, c // nh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]   # [bnw, n, nh, d]
        bias = self.rel_bias[self.rel_index.reshape(-1)]
        bias = jnp.transpose(bias.reshape(n, n, nh), (2, 0, 1))  # [nh, n, n]
        attn_mask = bias[None].astype(jnp.float32)           # [1, nh, n, n]
        if mask is not None:                                  # [nW, n, n]
            nw = mask.shape[0]
            m = jnp.tile(mask, (bnw // nw, 1, 1))[:, None]   # [bnw, 1, n, n]
            attn_mask = attn_mask + m.astype(jnp.float32)
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask)
        return self.proj(out.reshape(bnw, n, c))


class SwinBlock(Module):
    def __init__(self, dim, num_heads, window, shift, resolution,
                 mlp_ratio=4.0, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        self.norm1 = LayerNorm(dim, dtype=dtype)
        self.attn = WindowAttention(dim, num_heads, window, dtype=dtype)
        self.norm2 = LayerNorm(dim, dtype=dtype)
        self.fc1 = Linear(dim, int(dim * mlp_ratio), dtype=dtype)
        self.fc2 = Linear(int(dim * mlp_ratio), dim, dtype=dtype)
        self.window, self.shift = window, shift
        self.resolution = resolution
        if shift > 0:
            self.register_buffer("attn_mask",
                                 jnp.asarray(self._shift_mask(resolution)))
        else:
            self.attn_mask = None

    def _shift_mask(self, res):
        """Additive mask isolating the wrapped regions after cyclic shift
        (precomputed on host: static per resolution)."""
        h = w = res
        ws, sh = self.window, self.shift
        img = np.zeros((1, h, w, 1), np.float32)
        cnt = 0
        for hs in (slice(0, -ws), slice(-ws, -sh), slice(-sh, None)):
            for wsl in (slice(0, -ws), slice(-ws, -sh), slice(-sh, None)):
                img[:, hs, wsl, :] = cnt
                cnt += 1
        win = np.asarray(window_partition(jnp.asarray(img), ws))[:, :, 0]
        diff = win[:, None, :] - win[:, :, None]
        return np.where(diff != 0, -1e9, 0.0).astype(np.float32)

    def __call__(self, x):
        # x: [B, H*W, C] at this stage's resolution
        h = w = self.resolution
        b, _, c = x.shape
        shortcut = x
        y = self.norm1(x).reshape(b, h, w, c)
        if self.shift > 0:
            y = jnp.roll(y, (-self.shift, -self.shift), axis=(1, 2))
        wins = window_partition(y, self.window)
        wins = self.attn(wins, mask=self.attn_mask)
        y = window_reverse(wins, self.window, h, w)
        if self.shift > 0:
            y = jnp.roll(y, (self.shift, self.shift), axis=(1, 2))
        x = shortcut + y.reshape(b, h * w, c)
        return x + self.fc2(jax.nn.gelu(self.fc1(self.norm2(x))))


class PatchMerging(Module):
    def __init__(self, dim, resolution, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        self.norm = LayerNorm(4 * dim, dtype=dtype)
        self.reduction = Linear(4 * dim, 2 * dim, bias_attr=False, dtype=dtype)
        self.resolution = resolution

    def __call__(self, x):
        h = w = self.resolution
        b, _, c = x.shape
        x = x.reshape(b, h // 2, 2, w // 2, 2, c)
        x = jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(b, (h // 2) * (w // 2),
                                                         4 * c)
        return self.reduction(self.norm(x))


class SwinTransformer(Module):
    def __init__(self, img_size=224, patch_size=4, in_chans=3,
                 num_classes=1000, embed_dim=96, depths=(2, 2, 6, 2),
                 num_heads=(3, 6, 12, 24), window_size=7, mlp_ratio=4.0,
                 class_num=None, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        num_classes = class_num if class_num is not None else num_classes
        self.patch_embed = Conv2D(in_chans, embed_dim, patch_size,
                                  stride=patch_size, dtype=dtype)
        self.patch_norm = LayerNorm(embed_dim, dtype=dtype)
        res = img_size // patch_size
        self.stages = []
        self.mergers = []
        dim = embed_dim
        for i, depth in enumerate(depths):
            # reference behavior: when the stage fits in one window, use
            # window=resolution and NO shift (shifting a single window would
            # mask genuinely-adjacent tokens)
            win = min(window_size, res)
            shift = 0 if res <= window_size else window_size // 2
            if res % win != 0:
                raise ValueError(
                    f"stage {i}: resolution {res} is not a multiple of "
                    f"window {win}; pick img_size/patch_size so every stage "
                    f"resolution divides the window (e.g. 224/4 with window 7)")
            blocks = [SwinBlock(dim, num_heads[i], win,
                                0 if j % 2 == 0 else shift,
                                res, mlp_ratio, dtype=dtype)
                      for j in range(depth)]
            self.stages.append(blocks)
            if i < len(depths) - 1:
                self.mergers.append(PatchMerging(dim, res, dtype=dtype))
                dim *= 2
                res //= 2
        self.norm = LayerNorm(dim, dtype=dtype)
        self.head = Linear(dim, num_classes, dtype=dtype)

    def __call__(self, x):
        x = self.patch_embed(x)                    # [B, C, H/p, W/p]
        b, c = x.shape[0], x.shape[1]
        x = x.reshape(b, c, -1).transpose(0, 2, 1)
        x = self.patch_norm(x)
        for i, blocks in enumerate(self.stages):
            for blk in blocks:
                x = blk(x)
            if i < len(self.stages) - 1:
                x = self.mergers[i](x)
        x = self.norm(x).mean(axis=1)
        return self.head(x)


def swin_tiny_patch4_window7_224(**kw):
    return SwinTransformer(depths=(2, 2, 6, 2), embed_dim=96, **kw)


def swin_small_patch4_window7_224(**kw):
    return SwinTransformer(depths=(2, 2, 18, 2), embed_dim=96, **kw)


def swin_base_patch4_window7_224(**kw):
    return SwinTransformer(depths=(2, 2, 18, 2), embed_dim=128,
                           num_heads=(4, 8, 16, 32), **kw)
