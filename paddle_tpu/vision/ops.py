"""Detection / region ops (ref: ``python/paddle/vision/ops.py`` and the PHI
kernels ``paddle/phi/kernels/{nms,roi_align,roi_pool,psroi_pool,
deformable_conv,box_coder,yolo_box}_kernel.cc``).

TPU-first design notes:
- ``roi_align``/``roi_pool``/``psroi_pool`` are pure gather/masked-reduce
  formulations (no scatter), jit-safe with static output sizes.
- ``deform_conv2d`` lowers to bilinear gathers + ONE grouped matmul so the
  FLOPs land on the MXU (im2col of the deformed samples), instead of the
  reference's per-pixel CUDA kernel.
- ``nms`` keeps the O(N^2) IoU matrix on device and runs the greedy pass as a
  ``lax.fori_loop`` over the score-sorted boxes; the variable-length index
  list is materialised on host (eager API, like the reference's CPU/GPU
  kernel which also returns a dynamic shape).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I
from paddle_tpu.core.dtypes import get_default_dtype

__all__ = [
    "box_iou", "nms", "roi_align", "roi_pool", "psroi_pool",
    "deform_conv2d", "box_coder", "yolo_box", "distribute_fpn_proposals",
    "DeformConv2D", "RoIAlign", "RoIPool", "PSRoIPool",
]


def _norm2(v):
    return (v, v) if isinstance(v, int) else tuple(v)


# -- IoU / NMS ---------------------------------------------------------------

def box_iou(boxes1, boxes2):
    """Pairwise IoU of [N,4] and [M,4] xyxy boxes → [N,M]."""
    b1, b2 = jnp.asarray(boxes1, jnp.float32), jnp.asarray(boxes2, jnp.float32)
    a1 = jnp.maximum(b1[:, 2] - b1[:, 0], 0) * jnp.maximum(b1[:, 3] - b1[:, 1], 0)
    a2 = jnp.maximum(b2[:, 2] - b2[:, 0], 0) * jnp.maximum(b2[:, 3] - b2[:, 1], 0)
    lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
    rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = a1[:, None] + a2[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@jax.jit
def _nms_keep_mask(boxes, iou_threshold):
    """Greedy suppression over boxes already sorted by descending score.

    Returns a bool keep-mask. jit-safe: fori_loop over rows of the IoU
    matrix (the reference kernel's doubly-nested loop, with the inner loop
    vectorised across the lane dimension).
    """
    n = boxes.shape[0]
    iou = box_iou(boxes, boxes)
    sup = iou > iou_threshold

    def body(i, keep):
        # if box i survives, kill every later box it overlaps
        kill = keep[i] & sup[i] & (jnp.arange(n) > i)
        return keep & ~kill

    return lax.fori_loop(0, n, body, jnp.ones((n,), bool))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Reference-parity NMS (``python/paddle/vision/ops.py:nms``).

    Eager API — returns a variable-length int64 index array of kept boxes in
    descending-score order (score order = input order when ``scores`` is
    None). Multi-class mode offsets boxes per category so classes never
    suppress each other (batched-NMS trick, same result as the reference's
    per-category loop).
    """
    boxes = jnp.asarray(boxes)
    n = boxes.shape[0]
    if n == 0:
        return jnp.zeros((0,), jnp.int32)
    if scores is None:
        order = jnp.arange(n)
    else:
        order = jnp.argsort(-jnp.asarray(scores), stable=True)
    order = np.asarray(order)
    if category_idxs is not None and categories is not None:
        # reference iterates only the listed categories — drop the rest
        allowed = np.isin(np.asarray(category_idxs), np.asarray(list(categories)))
        order = order[allowed[order]]
        if order.size == 0:
            return jnp.zeros((0,), jnp.int32)
    sorted_boxes = boxes[order]
    if category_idxs is not None:
        # offset each category into its own disjoint coordinate region
        cat = jnp.asarray(category_idxs)[order].astype(jnp.float32)
        span = jnp.max(sorted_boxes) - jnp.min(sorted_boxes) + 1.0
        sorted_boxes = sorted_boxes + (cat * span)[:, None]
    keep = np.asarray(_nms_keep_mask(sorted_boxes, jnp.float32(iou_threshold)))
    kept = np.asarray(order)[keep]
    if top_k is not None:
        kept = kept[:top_k]
    return jnp.asarray(kept, jnp.int32)


# -- RoI ops -----------------------------------------------------------------

def _roi_batch_index(boxes_num, num_rois):
    """[R] image index for each roi from per-image counts (ref boxes_num)."""
    bn = np.asarray(boxes_num)
    return jnp.asarray(np.repeat(np.arange(bn.shape[0]), bn), jnp.int32)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoI Align (ref ``paddle/phi/kernels/roi_align_kernel``).

    x: [N,C,H,W]; boxes: [R,4] xyxy in input-image coords; boxes_num: [N]
    rois per image. Bilinear-samples a fixed grid per bin and averages.
    ``sampling_ratio=-1`` uses ceil(roi_size/out_size) per roi like the
    reference — that is data-dependent, so it is computed on host (eager);
    pass a positive ``sampling_ratio`` for use under jit.
    """
    ph, pw = _norm2(output_size)
    x = jnp.asarray(x)
    boxes = jnp.asarray(boxes, jnp.float32)
    R = boxes.shape[0]
    C = x.shape[1]
    H, W = x.shape[2], x.shape[3]
    bidx = _roi_batch_index(boxes_num, R)

    off = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * spatial_scale - off
    y1 = boxes[:, 1] * spatial_scale - off
    x2 = boxes[:, 2] * spatial_scale - off
    y2 = boxes[:, 3] * spatial_scale - off
    roi_w = x2 - x1
    roi_h = y2 - y1
    if not aligned:  # reference clamps to min size 1
        roi_w = jnp.maximum(roi_w, 1.0)
        roi_h = jnp.maximum(roi_h, 1.0)
    bin_w = roi_w / pw
    bin_h = roi_h / ph

    if sampling_ratio > 0:
        sh = sw = int(sampling_ratio)
        sh_arr = jnp.full((R,), sh, jnp.int32)
        sw_arr = jnp.full((R,), sw, jnp.int32)
        max_sh, max_sw = sh, sw
    else:
        # per-roi adaptive counts — host-side (eager only), padded to max
        rh = np.asarray(roi_h)
        rw = np.asarray(roi_w)
        sh_np = np.maximum(np.ceil(rh / ph), 1).astype(np.int32)
        sw_np = np.maximum(np.ceil(rw / pw), 1).astype(np.int32)
        sh_arr, sw_arr = jnp.asarray(sh_np), jnp.asarray(sw_np)
        max_sh = int(sh_np.max()) if R else 1
        max_sw = int(sw_np.max()) if R else 1

    iy = jnp.arange(max_sh)
    ix = jnp.arange(max_sw)
    # sample centers: y1 + (p*bin_h) + (i+0.5)*bin_h/count, padded entries masked
    ys = (y1[:, None, None] + bin_h[:, None, None] *
          (jnp.arange(ph)[None, :, None] +
           (iy[None, None, :] + 0.5) / sh_arr[:, None, None]))  # [R, ph, max_sh]
    xs = (x1[:, None, None] + bin_w[:, None, None] *
          (jnp.arange(pw)[None, :, None] +
           (ix[None, None, :] + 0.5) / sw_arr[:, None, None]))  # [R, pw, max_sw]
    my = (iy[None, None, :] < sh_arr[:, None, None])
    mx = (ix[None, None, :] < sw_arr[:, None, None])

    def bilinear(img, yy, xx, valid):
        # img [C,H,W]; yy/xx [...]; ref kernel: samples fully outside → 0,
        # coords clamped into the last row/col band like the CUDA kernel
        out_of_range = (yy < -1.0) | (yy > H) | (xx < -1.0) | (xx > W)
        yy = jnp.clip(yy, 0.0, H - 1)
        xx = jnp.clip(xx, 0.0, W - 1)
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, H - 1)
        x1i = jnp.minimum(x0 + 1, W - 1)
        ly = yy - y0
        lx = xx - x0
        v00 = img[:, y0, x0]
        v01 = img[:, y0, x1i]
        v10 = img[:, y1i, x0]
        v11 = img[:, y1i, x1i]
        val = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx +
               v10 * ly * (1 - lx) + v11 * ly * lx)
        return jnp.where(valid & ~out_of_range, val, 0.0)

    def one_roi(b, yy, xx, myy, mxx, cnt_h, cnt_w):
        img = x[b].astype(jnp.float32)                     # [C,H,W]
        # grid [ph, max_sh, pw, max_sw]
        Y = yy[:, :, None, None]
        X = xx[None, None, :, :]
        V = myy[:, :, None, None] & mxx[None, None, :, :]
        vals = bilinear(img, jnp.broadcast_to(Y, (ph, max_sh, pw, max_sw)),
                        jnp.broadcast_to(X, (ph, max_sh, pw, max_sw)), V)
        s = vals.sum(axis=(2, 4))                          # [C, ph, pw]
        return s / (cnt_h * cnt_w).astype(jnp.float32)

    out = jax.vmap(one_roi)(bidx, ys, xs, my, mx, sh_arr, sw_arr)
    return out.astype(x.dtype)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """RoI max-pool with quantised bins (ref roi_pool kernel).

    Mask-based: each output bin max-reduces a row/col membership mask over
    the feature map — jit-safe, no dynamic shapes.
    """
    ph, pw = _norm2(output_size)
    x = jnp.asarray(x)
    boxes = jnp.asarray(boxes, jnp.float32)
    R = boxes.shape[0]
    H, W = x.shape[2], x.shape[3]
    bidx = _roi_batch_index(boxes_num, R)

    x1 = jnp.round(boxes[:, 0] * spatial_scale)
    y1 = jnp.round(boxes[:, 1] * spatial_scale)
    x2 = jnp.round(boxes[:, 2] * spatial_scale)
    y2 = jnp.round(boxes[:, 3] * spatial_scale)
    roi_h = jnp.maximum(y2 - y1 + 1, 1.0)
    roi_w = jnp.maximum(x2 - x1 + 1, 1.0)
    bin_h = roi_h / ph
    bin_w = roi_w / pw

    def bounds(start, bin_sz, nbins, size):
        i = jnp.arange(nbins, dtype=jnp.float32)
        lo = jnp.clip(jnp.floor(i[None, :] * bin_sz[:, None]) + start[:, None], 0, size)
        hi = jnp.clip(jnp.ceil((i[None, :] + 1) * bin_sz[:, None]) + start[:, None], 0, size)
        return lo.astype(jnp.int32), hi.astype(jnp.int32)   # [R, nbins]

    hlo, hhi = bounds(y1, bin_h, ph, H)
    wlo, whi = bounds(x1, bin_w, pw, W)
    hs = jnp.arange(H)
    ws = jnp.arange(W)
    hmask = (hs[None, None, :] >= hlo[:, :, None]) & (hs[None, None, :] < hhi[:, :, None])  # [R,ph,H]
    wmask = (ws[None, None, :] >= wlo[:, :, None]) & (ws[None, None, :] < whi[:, :, None])  # [R,pw,W]

    def one_roi(args):
        b, hm, wm = args
        img = x[b].astype(jnp.float32)                     # [C,H,W]
        # separable masked max: rows then cols — peak intermediate is
        # [ph,C,H,W] for ONE roi (lax.map keeps R out of the memory bound)
        rows = jnp.where(hm[:, None, :, None], img[None], -jnp.inf).max(axis=2)  # [ph,C,W]
        val = jnp.where(wm[None, None, :, :], rows[:, :, None, :],
                        -jnp.inf).max(axis=-1)             # [ph,C,pw]
        val = jnp.moveaxis(val, 1, 0)                      # [C,ph,pw]
        empty = ~(hm.any(-1)[:, None] & wm.any(-1)[None, :])  # [ph,pw]
        return jnp.where(empty[None], 0.0, val)

    out = lax.map(one_roi, (bidx, hmask, wmask))
    return out.astype(x.dtype)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Position-sensitive RoI average pool (ref psroi_pool kernel).

    x channels = C_out * ph * pw; bin (i,j) of output channel c reads input
    channel c*ph*pw + i*pw + j and average-pools its quantised window.
    """
    ph, pw = _norm2(output_size)
    x = jnp.asarray(x)
    boxes = jnp.asarray(boxes, jnp.float32)
    R = boxes.shape[0]
    C_in, H, W = x.shape[1], x.shape[2], x.shape[3]
    assert C_in % (ph * pw) == 0, "psroi_pool: channels must divide ph*pw"
    C_out = C_in // (ph * pw)
    bidx = _roi_batch_index(boxes_num, R)

    x1 = jnp.round(boxes[:, 0]) * spatial_scale
    y1 = jnp.round(boxes[:, 1]) * spatial_scale
    x2 = jnp.round(boxes[:, 2] + 1) * spatial_scale
    y2 = jnp.round(boxes[:, 3] + 1) * spatial_scale
    roi_h = jnp.maximum(y2 - y1, 0.1)
    roi_w = jnp.maximum(x2 - x1, 0.1)
    bin_h = roi_h / ph
    bin_w = roi_w / pw

    i = jnp.arange(ph, dtype=jnp.float32)
    j = jnp.arange(pw, dtype=jnp.float32)
    hlo = jnp.clip(jnp.floor(y1[:, None] + i[None] * bin_h[:, None]), 0, H).astype(jnp.int32)
    hhi = jnp.clip(jnp.ceil(y1[:, None] + (i[None] + 1) * bin_h[:, None]), 0, H).astype(jnp.int32)
    wlo = jnp.clip(jnp.floor(x1[:, None] + j[None] * bin_w[:, None]), 0, W).astype(jnp.int32)
    whi = jnp.clip(jnp.ceil(x1[:, None] + (j[None] + 1) * bin_w[:, None]), 0, W).astype(jnp.int32)
    hs = jnp.arange(H)
    ws = jnp.arange(W)
    hmask = (hs[None, None] >= hlo[:, :, None]) & (hs[None, None] < hhi[:, :, None])  # [R,ph,H]
    wmask = (ws[None, None] >= wlo[:, :, None]) & (ws[None, None] < whi[:, :, None])  # [R,pw,W]

    def one_roi(b, hm, wm):
        img = x[b].astype(jnp.float32).reshape(C_out, ph, pw, H, W)
        hf = hm.astype(jnp.float32)                        # [ph,H]
        wf = wm.astype(jnp.float32)                        # [pw,W]
        # window sums as two small matmuls (MXU) — never materialises a
        # [ph,pw,H,W] mask; HIGHEST keeps the mean exact
        s = jnp.einsum("ih,cijhw,jw->cij", hf, img, wf,
                       precision=lax.Precision.HIGHEST)    # [C_out,ph,pw]
        cnt = hm.sum(-1)[:, None] * wm.sum(-1)[None, :]    # [ph,pw]
        return jnp.where(cnt[None] > 0, s / jnp.maximum(cnt[None], 1), 0.0)

    out = lax.map(lambda a: one_roi(*a), (bidx, hmask, wmask))
    return out.astype(x.dtype)


# -- deformable conv ---------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """Deformable convolution v1/v2 (ref deformable_conv kernel;
    ``python/paddle/vision/ops.py:deform_conv2d``).

    x [N,C,H,W]; offset [N, 2*dg*kh*kw, Ho, Wo] with per-tap (dy, dx) pairs;
    mask [N, dg*kh*kw, Ho, Wo] for v2 modulation; weight [Cout, C//groups,
    kh, kw].

    TPU formulation: bilinear-gather the deformed im2col columns, then one
    grouped matmul [Cout, C/g*kh*kw] × [C/g*kh*kw, N*Ho*Wo] on the MXU.
    """
    x = jnp.asarray(x)
    weight = jnp.asarray(weight)
    N, C, H, W = x.shape
    Cout, Cg, kh, kw = weight.shape
    sh, sw = _norm2(stride)
    ph_, pw_ = _norm2(padding)
    dh, dw = _norm2(dilation)
    dg = deformable_groups
    Ho = (H + 2 * ph_ - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw_ - dw * (kw - 1) - 1) // sw + 1
    K = kh * kw

    off = offset.reshape(N, dg, K, 2, Ho, Wo).astype(jnp.float32)
    # base sampling positions p0 + pk (in un-padded input coords)
    oy = (jnp.arange(Ho) * sh - ph_)[:, None] + jnp.zeros((Wo,))[None, :]
    ox = (jnp.arange(Wo) * sw - pw_)[None, :] + jnp.zeros((Ho,))[:, None]
    ky = (jnp.arange(kh) * dh)[:, None].repeat(kw, 1).reshape(K)
    kx = (jnp.arange(kw) * dw)[None, :].repeat(kh, 0).reshape(K)
    # sample coords [N, dg, K, Ho, Wo]
    yy = oy[None, None, None] + ky[None, None, :, None, None] + off[:, :, :, 0]
    xx = ox[None, None, None] + kx[None, None, :, None, None] + off[:, :, :, 1]

    xg = x.reshape(N, dg, C // dg, H, W).astype(jnp.float32)

    def bilinear(img, yy, xx):
        # img [Cdg,H,W], coords [...] — zeros outside
        valid = (yy > -1.0) & (yy < H) & (xx > -1.0) & (xx < W)
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        ly = yy - y0
        lx = xx - x0

        def tap(yi, xi, w):
            inb = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            v = img[:, jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
            return jnp.where(inb, v * w, 0.0)

        val = (tap(y0, x0, (1 - ly) * (1 - lx)) + tap(y0, x0 + 1, (1 - ly) * lx) +
               tap(y0 + 1, x0, ly * (1 - lx)) + tap(y0 + 1, x0 + 1, ly * lx))
        return jnp.where(valid, val, 0.0)

    # columns [N, dg, Cdg, K, Ho, Wo]
    cols = jax.vmap(jax.vmap(bilinear))(xg, yy, xx)
    if mask is not None:
        m = mask.reshape(N, dg, 1, K, Ho, Wo).astype(jnp.float32)
        cols = cols * m
    cols = cols.reshape(N, C, K, Ho, Wo)

    # grouped matmul on the MXU
    wmat = weight.reshape(groups, Cout // groups, Cg * K).astype(jnp.float32)
    cols = cols.reshape(N, groups, Cg * K, Ho * Wo)
    out = jnp.einsum("gok,ngkp->ngop", wmat, cols)
    out = out.reshape(N, Cout, Ho, Wo)
    if bias is not None:
        out = out + bias.reshape(1, Cout, 1, 1)
    return out.astype(x.dtype)


# -- box utilities -----------------------------------------------------------

def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0):
    """Encode/decode boxes against priors (ref box_coder kernel).

    encode: target [M,4] vs priors [N,4] → [M,N,4] deltas.
    decode: target [N,M,4] deltas vs priors [N,4] → [N,M,4] boxes (axis=0);
    axis=1 broadcasts priors along dim1.
    """
    prior = jnp.asarray(prior_box, jnp.float32)
    target = jnp.asarray(target_box, jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = prior[:, 2] - prior[:, 0] + norm
    ph = prior[:, 3] - prior[:, 1] + norm
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((4,), jnp.float32)
    else:
        var = jnp.asarray(prior_box_var, jnp.float32)

    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + norm
        th = target[:, 3] - target[:, 1] + norm
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None]) / pw[None]
        dy = (tcy[:, None] - pcy[None]) / ph[None]
        dw = jnp.log(jnp.abs(tw[:, None] / pw[None]))
        dh = jnp.log(jnp.abs(th[:, None] / ph[None]))
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        if var.ndim == 2:  # per-prior variance [N,4]
            out = out / var[None]
        else:
            out = out / var.reshape(1, 1, 4)
        return out
    # decode: target [N,M,4]; axis=0 → prior [M,4] broadcast over dim 0,
    # axis=1 → prior [N,4] broadcast over dim 1 (reference semantics)
    if axis == 0:
        pcx_b, pcy_b = pcx[None, :], pcy[None, :]
        pw_b, ph_b = pw[None, :], ph[None, :]
        var_b = var[None, :] if var.ndim == 2 else var.reshape(1, 1, 4)
    else:
        pcx_b, pcy_b = pcx[:, None], pcy[:, None]
        pw_b, ph_b = pw[:, None], ph[:, None]
        var_b = var[:, None] if var.ndim == 2 else var.reshape(1, 1, 4)
    d = target * var_b
    cx = d[..., 0] * pw_b + pcx_b
    cy = d[..., 1] * ph_b + pcy_b
    w = jnp.exp(d[..., 2]) * pw_b
    h = jnp.exp(d[..., 3]) * ph_b
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=-1)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode a YOLO detection head (ref yolo_box kernel).

    x [N, an*(5+cls), H, W] → (boxes [N, an*H*W, 4], scores [N, an*H*W, cls]),
    anchor-major flattening like the reference kernel's
    ``box_idx = j*stride + k*w + l``. With ``iou_aware`` the input grows a
    leading block of ``an`` IoU channels: [N, an + an*(5+cls), H, W] (ref
    yolo_box kernel ``GetIoUIndex``). Boxes below ``conf_thresh`` are zeroed
    like the reference (shapes stay static — TPU-friendly).
    """
    x = jnp.asarray(x, jnp.float32)
    N, _, H, W = x.shape
    an = len(anchors) // 2
    anchors_a = jnp.asarray(anchors, jnp.float32).reshape(an, 2)
    if iou_aware:
        iou_p = jax.nn.sigmoid(x[:, :an].reshape(N, an, H, W))
        feat = x[:, an:].reshape(N, an, 5 + class_num, H, W)
    else:
        feat = x.reshape(N, an, 5 + class_num, H, W)
    tx, ty, tw, th, tconf = feat[:, :, 0], feat[:, :, 1], feat[:, :, 2], feat[:, :, 3], feat[:, :, 4]
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    bias_xy = 0.5 * (scale_x_y - 1.0)
    cx = (jax.nn.sigmoid(tx) * scale_x_y - bias_xy + gx) / W
    cy = (jax.nn.sigmoid(ty) * scale_x_y - bias_xy + gy) / H
    input_h = downsample_ratio * H
    input_w = downsample_ratio * W
    bw = jnp.exp(tw) * anchors_a[None, :, 0, None, None] / input_w
    bh = jnp.exp(th) * anchors_a[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(tconf)
    if iou_aware:
        conf = conf ** (1 - iou_aware_factor) * iou_p ** iou_aware_factor
    probs = jax.nn.sigmoid(feat[:, :, 5:]) * conf[:, :, None]

    img_h = jnp.asarray(img_size, jnp.float32)[:, 0].reshape(N, 1, 1, 1)
    img_w = jnp.asarray(img_size, jnp.float32)[:, 1].reshape(N, 1, 1, 1)
    x1 = (cx - bw * 0.5) * img_w
    y1 = (cy - bh * 0.5) * img_h
    x2 = (cx + bw * 0.5) * img_w
    y2 = (cy + bh * 0.5) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)          # [N,an,H,W,4]
    keep = (conf >= conf_thresh)[..., None]
    boxes = jnp.where(keep, boxes, 0.0)
    probs = jnp.where(keep, probs.transpose(0, 1, 3, 4, 2), 0.0)
    # anchor-major flatten (reference ordering: anchor, then h, then w)
    boxes = boxes.reshape(N, an * H * W, 4)
    scores = probs.reshape(N, an * H * W, class_num)
    return boxes, scores


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None):
    """Assign RoIs to FPN levels (ref distribute_fpn_proposals op). Eager,
    host-side — this is pipeline glue, not device compute."""
    rois = np.asarray(fpn_rois, np.float32)
    w = np.maximum(rois[:, 2] - rois[:, 0], 0)
    h = np.maximum(rois[:, 3] - rois[:, 1], 0)
    scale = np.sqrt(w * h)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi_rois, restore = [], np.empty(len(rois), np.int64)
    order = []
    for L in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == L)[0]
        multi_rois.append(jnp.asarray(rois[idx]))
        order.append(idx)
    order = np.concatenate(order) if order else np.empty(0, np.int64)
    restore[order] = np.arange(len(rois))
    out_num = None
    if rois_num is not None:
        bidx = np.repeat(np.arange(len(rois_num)), np.asarray(rois_num))
        out_num = [jnp.asarray(np.bincount(bidx[lvl == L], minlength=len(rois_num)).astype(np.int32))
                   for L in range(min_level, max_level + 1)]
    return multi_rois, jnp.asarray(restore), out_num


# -- layer wrappers (ref python/paddle/vision/ops.py layer classes) ----------

class DeformConv2D(Module):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 bias_attr=True):
        super().__init__()
        kh, kw = _norm2(kernel_size)
        dtype = get_default_dtype()
        fan_in = in_channels * kh * kw
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = I.Uniform(-bound, bound)(
            (out_channels, in_channels // groups, kh, kw), dtype)
        self.bias = I.Constant(0.0)((out_channels,), dtype) if bias_attr else None
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.deformable_groups, self.groups = deformable_groups, groups

    def __call__(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self.stride,
                             self.padding, self.dilation,
                             self.deformable_groups, self.groups, mask)


class RoIAlign(Module):
    def __init__(self, output_size, spatial_scale=1.0, sampling_ratio=-1,
                 aligned=True):
        super().__init__()
        self.output_size, self.spatial_scale = output_size, spatial_scale
        self.sampling_ratio, self.aligned = sampling_ratio, aligned

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, self.sampling_ratio, self.aligned)


class RoIPool(Module):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size, self.spatial_scale = output_size, spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


class PSRoIPool(Module):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size, self.spatial_scale = output_size, spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)
