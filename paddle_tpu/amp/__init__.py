"""Mixed precision (ref: ``python/paddle/amp/`` — auto_cast, GradScaler).

TPU-native stance: bf16 is the native MXU input dtype and needs NO loss
scaling (same exponent range as fp32). So:
  * O1 ("auto_cast"): cast op inputs to bf16 for allow-listed ops — here a
    Policy object that casts params/activations at module boundaries.
  * O2 ("pure"): hold params in bf16, master fp32 weights in the optimizer
    (``multi_precision=True``) — the reference's O2 + master-grad recipe.
  * GradScaler: full state machine kept for fp16 parity (scale, growth,
    inf-skip), a no-op in bf16 mode.
"""
from __future__ import annotations

import contextlib
from typing import Any

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module

_FP = (jnp.float32, jnp.float16, jnp.bfloat16)


class Policy:
    """Dtype policy: param/compute/output dtypes (jmp-style, reference O-levels)."""

    def __init__(self, param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
                 output_dtype=None):
        self.param_dtype = jnp.dtype(param_dtype)
        self.compute_dtype = jnp.dtype(compute_dtype)
        self.output_dtype = jnp.dtype(output_dtype) if output_dtype else self.compute_dtype

    def cast_to_compute(self, tree):
        return _cast_floats(tree, self.compute_dtype)

    def cast_to_param(self, tree):
        return _cast_floats(tree, self.param_dtype)

    def cast_to_output(self, tree):
        return _cast_floats(tree, self.output_dtype)


def O1(dtype=jnp.bfloat16) -> Policy:
    return Policy(param_dtype=jnp.float32, compute_dtype=dtype)


def O2(dtype=jnp.bfloat16) -> Policy:
    return Policy(param_dtype=dtype, compute_dtype=dtype)


def _cast_floats(tree, dtype):
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(cast, tree, is_leaf=lambda x: x is None)


def decorate(model: Module, level: str = "O1", dtype=jnp.bfloat16) -> Module:
    """Ref: ``paddle.amp.decorate`` — O2 casts the model's params."""
    if level == "O2":
        return _cast_floats(model, dtype)
    return model


@contextlib.contextmanager
def auto_cast(enable=True, level="O1", dtype="bfloat16"):
    """Reference context-manager API. Under a functional framework the cast
    happens on values, so this sets the default dtype for the block."""
    from paddle_tpu.core.dtypes import default_dtype
    if not enable:
        yield
        return
    with default_dtype(jnp.dtype(dtype) if level == "O2" else jnp.float32):
        yield


class GradScaler:
    """Dynamic loss scaling (ref: ``python/paddle/amp/grad_scaler.py``).

    Functional: carry ``scaler.init()`` state through the train step.
    In bf16 (enable=False) every method is the identity.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5,
                 incr_every_n_steps=2000, decr_every_n_nan_or_inf=1):
        self.enable = enable
        self.init_scale = init_loss_scaling
        self.incr_ratio, self.decr_ratio = incr_ratio, decr_ratio
        self.incr_every = incr_every_n_steps
        self.decr_every = decr_every_n_nan_or_inf

    def init(self):
        return {"scale": jnp.asarray(self.init_scale, jnp.float32),
                "good_steps": jnp.zeros((), jnp.int32),
                "bad_steps": jnp.zeros((), jnp.int32)}

    def scale(self, loss, state):
        if not self.enable:
            return loss
        return loss * state["scale"]

    def unscale(self, grads, state):
        if not self.enable:
            return grads
        inv = 1.0 / state["scale"]
        return jax.tree_util.tree_map(
            lambda g: g * inv if g is not None and hasattr(g, "dtype")
            and jnp.issubdtype(g.dtype, jnp.floating) else g,
            grads, is_leaf=lambda x: x is None)

    def found_inf(self, grads):
        leaves = [g for g in jax.tree_util.tree_leaves(grads)
                  if hasattr(g, "dtype") and jnp.issubdtype(g.dtype, jnp.floating)]
        if not leaves:
            return jnp.asarray(False)
        return jnp.logical_not(
            jnp.all(jnp.stack([jnp.all(jnp.isfinite(g)) for g in leaves])))

    def update(self, state, found_inf):
        """Returns new scaler state (pure, jit-safe)."""
        if not self.enable:
            return state
        good = jnp.where(found_inf, 0, state["good_steps"] + 1)
        bad = jnp.where(found_inf, state["bad_steps"] + 1, 0)
        scale = state["scale"]
        scale = jnp.where(bad >= self.decr_every, scale * self.decr_ratio, scale)
        bad = jnp.where(bad >= self.decr_every, 0, bad)
        scale = jnp.where(good >= self.incr_every, scale * self.incr_ratio, scale)
        good = jnp.where(good >= self.incr_every, 0, good)
        return {"scale": scale, "good_steps": good, "bad_steps": bad}

    def step_or_skip(self, params, new_params, found_inf):
        """Skip the update when grads overflowed (ref: scaler.step skips)."""
        if not self.enable:
            return new_params
        return jax.tree_util.tree_map(
            lambda old, new: jnp.where(found_inf, old, new)
            if old is not None and hasattr(old, "dtype") else old,
            params, new_params, is_leaf=lambda x: x is None)
from paddle_tpu.amp import debugging


def is_bfloat16_supported(device=None):
    """Ref amp helpers: TPUs are bf16-native; CPU XLA also executes bf16."""
    return True


def is_float16_supported(device=None):
    import jax
    # fp16 matmuls execute everywhere but TPUs upcast — keep parity: True
    return jax.default_backend() in ("tpu", "gpu", "cpu")
