"""fp8 training with delayed scaling (ref capability: the reference stack's
fp8 path — PaddleNLP llm fp8 + PHI fp8 GEMM; design follows the public
TransformerEngine/flax recipe re-thought for a functional TPU stack).

Core pieces:
  * e4m3 forward operands / e5m2 gradients, with per-tensor scales derived
    from a rolling amax HISTORY (delayed scaling: the scale used at step t
    comes from steps < t, so quantization adds no serial amax-reduction
    dependency before the matmul).
  * ``fp8_matmul(x, w, meta)`` — a ``jax.custom_vjp`` whose backward ALSO
    returns the UPDATED meta (amax histories rolled, scales recomputed) as
    the meta's "cotangent". Meta tensors live in the module tree under the
    ``fp8_meta`` name marker; the optimizer OVERWRITES them with this
    "gradient" instead of applying an update rule (flax's
    overwrite-with-gradient pattern — the idiomatic way to thread mutable
    scaling state through a pure ``jit(grad(...))`` training step).
  * On hardware without native fp8 MXU support XLA computes the quantized
    matmul by upcasting — numerics (and tests) are identical; the speedup
    arrives on fp8-capable chips with the same code.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

E4M3_MAX = 448.0
E5M2_MAX = 57344.0
FP8_META_MARKER = "fp8_meta"  # path substring the optimizer overwrites


def new_fp8_meta(history_len: int = 16):
    """Delayed-scaling state for one matmul: one amax history per operand
    role (x = activation, w = weight, g = upstream gradient). Scales are
    DERIVED from the history at use time (_compute_scale) — no duplicate
    scale state to drift out of sync."""
    return {f"amax_{role}": jnp.zeros((history_len,), jnp.float32)
            for role in ("x", "w", "g")}


def _compute_scale(amax_history, fp8_max, margin: float = 0.0):
    """TransformerEngine-style: scale so that amax maps to fp8_max."""
    amax = jnp.max(amax_history)
    scale = fp8_max / jnp.maximum(amax, 1e-12) / (2.0 ** margin)
    # no history yet (amax == 0): keep scale 1
    return jnp.where(amax > 0, scale, 1.0)


def _roll(history, amax_now):
    return jnp.concatenate([amax_now[None].astype(history.dtype),
                            history[:-1]])


def _quant(x, scale, dtype, fp8_max):
    scaled = x.astype(jnp.float32) * scale
    return jnp.clip(scaled, -fp8_max, fp8_max).astype(dtype)


@jax.custom_vjp
def fp8_matmul(x, w, meta):
    """x @ w with e4m3 operands under delayed scaling. x: [..., K],
    w: [K, N]. The backward pass quantizes the upstream gradient to e5m2
    and returns the rolled/rescaled meta as meta's cotangent."""
    y, _ = _fp8_fwd(x, w, meta)
    return y


def _fp8_fwd(x, w, meta):
    sx = _compute_scale(meta["amax_x"], E4M3_MAX)
    sw = _compute_scale(meta["amax_w"], E4M3_MAX)
    qx = _quant(x, sx, jnp.float8_e4m3fn, E4M3_MAX)
    qw = _quant(w, sw, jnp.float8_e4m3fn, E4M3_MAX)
    y = jnp.matmul(qx.astype(jnp.bfloat16), qw.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32)
    y = (y / (sx * sw)).astype(x.dtype)
    # residuals keep only the fp8 copies + scalar amaxes (the memory saving
    # IS the point); zero-sized sentinels carry the primal dtypes
    ax, aw = jnp.max(jnp.abs(x)), jnp.max(jnp.abs(w))
    res = (qx, qw, sx, sw, ax, aw, meta,
           jnp.zeros((), x.dtype), jnp.zeros((), w.dtype))
    return y, res


def _fp8_bwd(res, g):
    qx, qw, sx, sw, ax, aw, meta, x_dt, w_dt = res
    sg = _compute_scale(meta["amax_g"], E5M2_MAX)
    qg = _quant(g, sg, jnp.float8_e5m2, E5M2_MAX)
    gb = qg.astype(jnp.bfloat16)
    # dx = g @ w^T, dw = x^T @ g — both from quantized operands
    dx = jnp.matmul(gb, qw.astype(jnp.bfloat16).T,
                    preferred_element_type=jnp.float32)
    dx = (dx / (sg * sw)).astype(x_dt.dtype)
    x2 = qx.reshape(-1, qx.shape[-1])
    g2 = gb.reshape(-1, gb.shape[-1])
    dw = jnp.matmul(x2.astype(jnp.bfloat16).T, g2,
                    preferred_element_type=jnp.float32)
    dw = (dw / (sx * sg)).astype(w_dt.dtype)
    # meta "cotangent" = UPDATED meta (overwrite-with-gradient)
    new_meta = dict(meta)
    new_meta["amax_x"] = _roll(meta["amax_x"], ax)
    new_meta["amax_w"] = _roll(meta["amax_w"], aw)
    new_meta["amax_g"] = _roll(meta["amax_g"], jnp.max(jnp.abs(g)))
    return dx, dw, new_meta


fp8_matmul.defvjp(lambda x, w, m: _fp8_fwd(x, w, m), _fp8_bwd)


def is_fp8_meta_path(path_str: str) -> bool:
    return FP8_META_MARKER in path_str


from paddle_tpu.core.module import Module as _Module


class Fp8Linear(_Module):
    """Linear layer computing through ``fp8_matmul`` (delayed scaling).

    A drop-in for ``nn.Linear`` in fp8-trained blocks: weight/bias train
    normally; the ``fp8_meta`` attribute holds the scaling state, which the
    optimizer overwrites from its custom-vjp "gradient" (see module
    docstring)."""

    def __init__(self, in_features, out_features, bias_attr=True,
                 history_len: int = 16, dtype=jnp.bfloat16):
        super().__init__()
        from paddle_tpu.nn import initializer as I
        self.weight = I.XavierUniform()((in_features, out_features), dtype)
        self.bias = (jnp.zeros((out_features,), dtype)
                     if bias_attr else None)
        self.fp8_meta = new_fp8_meta(history_len)

    def __call__(self, x):
        y = fp8_matmul(x, self.weight, self.fp8_meta)
        return y if self.bias is None else y + self.bias
