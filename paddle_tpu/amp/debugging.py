"""AMP debugging utilities (ref: ``python/paddle/amp/debugging.py`` —
check_numerics, operator stats collection, accuracy comparison).

TPU-native: op statistics come from the lowered StableHLO (the compiled
truth about which ops run in which dtype — the reference instruments the
dygraph op stream instead), and numeric checks are host-side over pytrees.
"""
from __future__ import annotations

import re
from collections import Counter

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["check_numerics", "collect_operator_stats", "compare_accuracy",
           "count_nonfinite"]


def count_nonfinite(tree):
    """(n_nan, n_inf) across every float leaf."""
    n_nan = n_inf = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            # native dtype: a float32 downcast would overflow finite fp64
            # (and ml_dtypes handle isnan/isinf natively)
            a = np.asarray(leaf)
            n_nan += int(np.isnan(a).sum())
            n_inf += int(np.isinf(a).sum())
    return n_nan, n_inf


def check_numerics(tree, name="tensor", raise_on_error=True):
    """Raise (or warn) if any float leaf contains nan/inf (ref
    ``paddle.amp.debugging.check_numerics``). Host-side, eager."""
    n_nan, n_inf = count_nonfinite(tree)
    if n_nan or n_inf:
        msg = f"check_numerics({name}): {n_nan} NaN, {n_inf} Inf values"
        if raise_on_error:
            raise FloatingPointError(msg)
        import warnings
        warnings.warn(msg)
        return False
    return True


_OP_RE = re.compile(r"stablehlo\.(\w+)")
_TYPE_RE = re.compile(r"tensor<[^>]*?(f32|f16|bf16|f64|i32|i8|i64)>")


def collect_operator_stats(fn, *args, print_fn=print, **kwargs):
    """Count ops per (op_kind, result dtype) in the lowered program (ref
    ``paddle.amp.debugging.collect_operator_stats``). Answers the AMP
    question 'which matmuls stayed fp32?' from the compiled truth."""
    text = jax.jit(fn).lower(*args, **kwargs).as_text()
    stats: Counter = Counter()
    for line in text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        t = _TYPE_RE.search(line.split("->")[-1] if "->" in line else line)
        stats[(m.group(1), t.group(1) if t else "?")] += 1
    if print_fn:
        width = max((len(k[0]) for k in stats), default=4)
        for (op, dt), n in sorted(stats.items(), key=lambda kv: -kv[1]):
            print_fn(f"{op:<{width}}  {dt:>5}  x{n}")
    return dict(stats)


def compare_accuracy(run_fp32, run_low, *args, atol=1e-2, rtol=1e-2,
                     print_fn=print):
    """Run the same computation in two precisions and report per-leaf max
    abs/rel error (ref ``paddle.amp.debugging.compare_accuracy``)."""
    out_hi = run_fp32(*args)
    out_lo = run_low(*args)
    flat_hi = jax.tree_util.tree_leaves(out_hi)
    flat_lo = jax.tree_util.tree_leaves(out_lo)
    if len(flat_hi) != len(flat_lo):
        raise ValueError(
            f"fp32/low-precision outputs have different structures "
            f"({len(flat_hi)} vs {len(flat_lo)} leaves); cannot compare")
    report = []
    ok = True
    for i, (a, b) in enumerate(zip(flat_hi, flat_lo)):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        abs_err = float(np.max(np.abs(a - b))) if a.size else 0.0
        rel_err = float(np.max(np.abs(a - b) / (np.abs(a) + 1e-9))) if a.size else 0.0
        good = abs_err <= atol or rel_err <= rtol
        ok &= good
        report.append({"leaf": i, "abs_err": abs_err, "rel_err": rel_err,
                       "ok": good})
        if print_fn:
            print_fn(f"leaf {i}: abs {abs_err:.3e} rel {rel_err:.3e} "
                     f"{'OK' if good else 'MISMATCH'}")
    return ok, report
