"""Discrete Fourier transforms (ref: ``python/paddle/fft.py``).

Thin, norm-convention-faithful lowering onto ``jnp.fft`` — XLA has a native
TPU FFT. The reference's namespace and argument order are preserved
(``x, n, axis, norm``).
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def fft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.fft(x, n=n, axis=axis, norm=norm)


def ifft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=norm)


def rfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=norm)


def irfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=norm)


def hfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.hfft(x, n=n, axis=axis, norm=norm)


def ihfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ihfft(x, n=n, axis=axis, norm=norm)


def fft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.fft2(x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.ifft2(x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.rfft2(x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.irfft2(x, s=s, axes=axes, norm=norm)


def fftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=norm)


def ifftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=norm)


def rfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.rfftn(x, s=s, axes=axes, norm=norm)


def irfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=norm)


def fftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.fftfreq(n, d=d)
    return out.astype(dtype) if dtype is not None else out


def rfftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.rfftfreq(n, d=d)
    return out.astype(dtype) if dtype is not None else out


def fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


def ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)
