"""Continuous-batching LLM serving engine.

Ref capability: PaddleNLP ``llm/predict/predictor.py`` block-attention
serving (request queue + block KV cache + ``fused_multi_transformer``'s
block cache ops). TPU-native split:

  * DEVICE — two fixed-shape jitted programs from ``models/paged.py``:
    slot-aware prefill (admitted prompts written into their cache slots
    while other slots keep decoding state) and the fused decode tick
    (incremental block-table update + paged attention + on-device
    sampling). Shapes never change across ticks, so nothing recompiles.
  * HOST — this module: FCFS request queue, slot assignment, block
    reservation/allocation (BlockManager), streaming outputs. All per-tick
    bookkeeping is vectorised numpy; the only per-tick device→host
    traffic is the [num_slots] sampled-token fetch.

Capacity discipline: a request is admitted only when the pool can cover
its WHOLE worst case (prompt + max_new_tokens) net of other in-flight
reservations — blocks are still allocated lazily (pool usage ≈ Σ live
lengths), but an admitted request can never hit an out-of-blocks
condition mid-decode (there is no preemption to recover with).
"""
from __future__ import annotations

import itertools
import os
import time
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models.decoding import KVCache, _sample_rows
from paddle_tpu.models.paged import (PagedKVCache, PrefixCachingBlockManager,
                                     _beam_finalize, _BEAM_GROUP_UPDATE_JIT,
                                     _BEAM_SELECT_JIT, _PREFILL_CHUNK_JIT,
                                     _PREFILL_JIT, _REWIND_LENS_JIT,
                                     _TICK_JIT, _VERIFY_CHUNK_JIT,
                                     greedy_accept_length, is_moe_model,
                                     stochastic_accept_row)
from paddle_tpu.models.speculative import _FWD_ROWS_JIT
from paddle_tpu.observability import METRICS, span as _span
from paddle_tpu.observability.flight import FLIGHT
from paddle_tpu.utils.faults import fault_point

# module-level so its compile cache persists across admissions
_SAMPLE_ROWS_JIT = jax.jit(_sample_rows, static_argnums=(4,))

# ---------------------------------------------------------- telemetry
# Engine metrics (ISSUE 2). Request-relative timings (TTFT, inter-token
# latency, queue wait) use the ENGINE clock — the swappable ``clock``
# ctor arg — so deadline tests driving a fake clock see deterministic
# histograms; host work timings (tick, drain) use the real monotonic
# clock. All instruments live in the process-global registry: a serve
# loop exports them with ``paddle_tpu.observability.dump(prefix)``.
_ADMITTED = METRICS.counter(
    "serving_admissions_total", "requests admitted into cache slots")
_PREEMPTED = METRICS.counter(
    "serving_preemptions_total", "requests evicted and re-queued")
_TIMEOUTS = METRICS.counter(
    "serving_timeouts_total", "requests expired (deadline_s/max_queue_s)")
_CANCELLED = METRICS.counter(
    "serving_cancellations_total", "requests cancelled by the caller")
_REJECTED = METRICS.counter(
    "serving_rejections_total", "admissions refused at intake",
    labelnames=("reason",))
_TOKENS = METRICS.counter(
    "serving_tokens_total", "tokens sampled and emitted")
_FINISHED = METRICS.counter(
    "serving_finished_total", "requests finished, by finish_reason",
    labelnames=("reason",))
_QUEUE_DEPTH = METRICS.gauge(
    "serving_queue_depth", "requests waiting for admission")
_ACTIVE_SLOTS = METRICS.gauge(
    "serving_active_slots", "cache slots actively decoding")
_KV_IN_USE = METRICS.gauge(
    "serving_kv_blocks_in_use", "paged KV blocks currently allocated")
_KV_UTIL = METRICS.gauge(
    "serving_kv_block_utilization", "allocated fraction of the KV pool")
_TTFT = METRICS.histogram(
    "serving_ttft_seconds", "submission → first token (engine clock)")
_TOK_LAT = METRICS.histogram(
    "serving_token_latency_seconds", "inter-token gap (engine clock)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5))
_QUEUE_WAIT = METRICS.histogram(
    "serving_queue_wait_seconds", "submission → admission (engine clock)")
_TICK = METRICS.histogram(
    "serving_tick_seconds", "wall time of one engine tick")
_DRAIN = METRICS.histogram(
    "serving_drain_seconds", "wall time of graceful drain",
    buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0))
# speculative decoding (ISSUE 5): proposal/acceptance accounting plus the
# per-tick commit size — tokens_per_tick > 1 is the whole point
_SPEC_PROPOSED = METRICS.counter(
    "serving_spec_proposed_total", "draft tokens proposed for verification")
_SPEC_ACCEPTED = METRICS.counter(
    "serving_spec_accepted_total", "draft tokens accepted by the target")
_SPEC_FALLBACKS = METRICS.counter(
    "serving_spec_fallbacks_total",
    "spec ticks abandoned before verify (fault injection) — the engine "
    "fell back to the one-token tick")
_SPEC_RATE = METRICS.gauge(
    "serving_spec_acceptance_rate",
    "cumulative accepted/proposed draft-token ratio")
_SPEC_TOKENS = METRICS.histogram(
    "serving_spec_tokens_per_tick",
    "tokens committed per slot per speculative tick",
    buckets=(1, 2, 3, 4, 5, 6, 8, 12, 16))
# prefix cache: cumulative adopt/evict counts exported from the block
# manager's cache_stats (deltas pushed each gauge refresh), plus the
# lifetime hit rate (blocks adopted / blocks prefill would have written)
_PREFIX_HITS = METRICS.counter(
    "serving_prefix_hit_blocks_total",
    "prompt blocks adopted from the prefix cache instead of prefilled")
_PREFIX_EVICTIONS = METRICS.counter(
    "serving_prefix_evictions_total",
    "parked prefix blocks evicted to satisfy new allocations")
_PREFIX_HIT_RATE = METRICS.gauge(
    "serving_prefix_hit_rate",
    "prefix-cache hit blocks / prompt blocks requested (lifetime)")
# MoE serving: routing choices dropped by expert-capacity overflow
# (always 0 for dropless models — Mixtral/Qwen2-MoE serve with
# capacity_factor=None)
_MOE_DROPPED = METRICS.counter(
    "moe_dropped_tokens_total",
    "MoE routing assignments dropped at expert capacity")


class QueueFullError(RuntimeError):
    """Admission queue at ``max_queue_len`` — backpressure: the caller
    should shed load or retry later, NOT buffer unboundedly here."""


class EngineDrainingError(RuntimeError):
    """``drain()`` was called — the engine finishes in-flight work but
    admits nothing new."""


@dataclass
class Request:
    """One generation request. ``stream`` (optional) is called as
    ``stream(request, token)`` the tick each new token is sampled.
    ``num_beams > 1``: beam search — the request occupies num_beams cache
    slots, selection mirrors ``decoding.beam_search`` exactly, and the
    BEST hypothesis lands in ``tokens`` when the request finishes (no
    streaming; tail past a hypothesis' first EOS is EOS-filled)."""
    prompt: object                       # 1-D int tokens
    max_new_tokens: int = 32
    req_id: int = None
    stream: object = None
    num_beams: int = 1
    length_penalty: float = 1.0
    # per-request sampling overrides (None = the engine's defaults):
    temperature: float = None
    top_p: float = None
    # robustness knobs (None = unbounded):
    #   deadline_s    total wall-clock budget from submission — expired
    #                 requests finish with finish_reason="timeout"
    #                 (whatever tokens were generated stay available)
    #   max_queue_s   max time WAITING for admission; a request that
    #                 can't enter a slot in time also times out
    deadline_s: float = None
    max_queue_s: float = None
    # filled by the engine:
    tokens: list = field(default_factory=list)   # generated tokens
    done: bool = False
    finish_reason: str = None
    _submit_t: float = None              # engine clock at add_request
    _first_tok_t: float = None           # engine clock at first token (TTFT)
    _last_tok_t: float = None            # engine clock at newest token
    beam_score: float = None
    # set on preemption: prompt + tokens generated so far — the resume
    # prefill recomputes the whole sequence (prefix-cache hits make the
    # recompute cheap when its old blocks are still parked)
    _resume: object = None

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)


@dataclass
class _BeamGroup:
    """Engine-side state of one in-flight beam request (K cache slots +
    the device-resident selection state shared with paged_beam_search)."""
    req: Request
    slots: list
    s: int                                # prompt length
    i: int = 0                            # selects done
    sid: dict = field(default_factory=dict)   # beam j -> BlockManager key
    running_lp: object = None
    seqs: object = None
    fin_seqs: object = None
    fin_scores: object = None
    logp: object = None                   # [K, vocab] device, pre-select


class LLMEngine:
    """Continuous-batching engine over a shared paged KV pool.

    ``num_slots`` concurrent sequences; queued requests are admitted
    MID-FLIGHT into slots freed by finished ones (prefill interleaves with
    decode ticks). ``step()`` is one engine tick; ``run()`` drains
    everything and returns {req_id: full token list}.
    """

    def __init__(self, model, *, num_slots=8, block_size=16,
                 max_prompt_len=128, max_seq_len=None, num_blocks=None,
                 eos_token_id=None, temperature=0.0, top_k=None, top_p=None,
                 seed=0, prefix_caching=True, preemption=False,
                 max_queue_len=None, clock=None, draft_model=None,
                 spec_k=4, spec_adaptive=True):
        cfg = model.cfg
        self.model = model
        self.num_slots = num_slots
        self.block_size = block_size
        self.max_prompt_len = max_prompt_len
        self.max_seq_len = max_seq_len or (max_prompt_len + 256)
        self.max_blocks_per_seq = -(-self.max_seq_len // block_size)
        if num_blocks is None:
            num_blocks = num_slots * self.max_blocks_per_seq
        # refcounted + content-hashed: beam groups share prompt blocks
        # copy-on-write; requests with equal prompt prefixes share the
        # prefix blocks outright (prefill only runs on the uncached
        # suffix); with no sharing it behaves exactly like BlockManager
        self.mgr = PrefixCachingBlockManager(num_blocks, block_size)
        self._prefix_pushed = dict(self.mgr.cache_stats)
        # MoE models route tokens through expert all_to_alls inside the
        # tick — give chaos a hook at that boundary (dead expert shard)
        self._is_moe = is_moe_model(model)
        self.eos_token_id = eos_token_id
        # engine defaults; each request may override temperature/top_p
        # (top_k stays engine-global — it is a static compile parameter)
        self.default_temp = float(temperature)
        self.default_top_p = 1.0 if top_p is None else float(top_p)
        self.top_k = top_k
        self.temps = np.zeros(num_slots, np.float32)
        self.top_ps = np.ones(num_slots, np.float32)
        self.rng = jax.random.PRNGKey(seed)
        # sliding-window models: blocks entirely below cur - window are
        # never attended again (the paged kernel KEEPS only positions
        # >= lens - window, masking everything below) — recycle them,
        # bounding live blocks per sequence by O(window), not O(length)
        self.window = getattr(cfg, "sliding_window", None)
        self._dyn_rope = (getattr(cfg, "rope_scaling", None)
                          or {}).get("type") == "dynamic"
        # prefix caching is sound only when a block's KV is a function of
        # its token prefix alone: windowed recycling punches holes in the
        # table, and dynamic-NTK makes KV depend on the FULL prompt length
        self.prefix_caching = bool(prefix_caching) and self.window is None \
            and not self._dyn_rope
        # preemption: admit optimistically (no worst-case reservation for
        # greedy requests; beams keep theirs) and, on out-of-blocks,
        # preempt the youngest greedy slot — it re-queues with
        # resume-prompt = prompt + generated-so-far and recomputes
        self.preemption = bool(preemption)

        # ---- speculative decoding (ISSUE 5): draft-and-verify tick ----
        # ``draft_model`` enables it; each eligible slot drafts up to
        # spec_k tokens through a per-slot dense draft cache, then ONE
        # batched target chunk forward verifies them through the paged
        # pool. PT_SPEC_DECODE=0 is the kill switch (checked every tick,
        # so it also disables a live engine); beam slots always take the
        # one-token path.
        self.draft_model = draft_model
        self.spec_k = int(spec_k)
        self.spec_adaptive = bool(spec_adaptive)
        if draft_model is not None:
            if self.spec_k < 1:
                raise ValueError("spec_k must be >= 1")
            if self.window is not None or \
                    getattr(draft_model.cfg, "sliding_window", None):
                raise NotImplementedError(
                    "speculative decoding needs full (un-windowed) caches "
                    "on both models — rewind relies on masked stale KV")
            if self._dyn_rope:
                raise NotImplementedError(
                    "speculative decoding with dynamic-NTK rope is not "
                    "supported (the verify chunk shares the chunked-"
                    "prefill forward, which refuses per-chunk bases)")
            if draft_model.cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_model.cfg.vocab_size} != target "
                    f"vocab {cfg.vocab_size}")

        self.cache = PagedKVCache.init(
            cfg.num_hidden_layers, num_blocks, block_size,
            cfg.num_key_value_heads,
            cfg.hidden_size // cfg.num_attention_heads,
            num_slots, self.max_blocks_per_seq, cfg.dtype)

        # host mirrors (vectorised bookkeeping — no per-token python loops)
        self.slot_req = np.full(num_slots, -1, np.int64)   # req_id or -1
        self.active = np.zeros(num_slots, bool)
        self.cur = np.zeros(num_slots, np.int64)     # tokens stored in cache
        self.gen = np.zeros(num_slots, np.int64)     # tokens generated
        self.max_gen = np.zeros(num_slots, np.int64)
        self.table_len = np.zeros(num_slots, np.int64)
        self.last_tok = np.zeros(num_slots, np.int32)

        # spec-decode per-slot state (allocated tiny even when spec is
        # off, so reset sites need no guards). ``draft_cur``: committed-
        # sequence positions 0..draft_cur-1 are in the draft cache — 0
        # means empty, which is how eviction "frees" a draft cache and
        # replay rebuilds it (the re-admitted slot re-feeds from scratch).
        self.draft_cur = np.zeros(num_slots, np.int64)
        self.slot_k = np.full(num_slots, self.spec_k, np.int64)
        self._acc_ema = np.ones(num_slots, np.float64)
        self._draft_cache = None
        if draft_model is not None:
            dcfg = draft_model.cfg
            self._draft_cache = KVCache.init(
                dcfg.num_hidden_layers, num_slots,
                self.max_seq_len + self.spec_k + 2,
                dcfg.num_key_value_heads,
                dcfg.hidden_size // dcfg.num_attention_heads, dcfg.dtype)
            # host RNG for draft sampling + accept/reject (temperature>0):
            # the accept rule preserves the target distribution for any
            # uniform source, so this stream need not match the engine key
            self._spec_rs = np.random.RandomState((seed ^ 0x5eed) & 0x7fffffff)

        self.is_beam = np.zeros(num_slots, bool)
        self.groups: dict[int, _BeamGroup] = {}
        self._sid_counter = 0        # unique fork keys: (req_id, counter)
        # chunked prefill (prompts > max_prompt_len): rid -> (slot,
        # tokens consumed); slots stay inactive until the last chunk
        self.prefilling: dict[int, tuple] = {}

        self.queue: deque[Request] = deque()
        self.requests: dict[int, Request] = {}
        self._ids = itertools.count()
        self._reserved = 0           # blocks promised to in-flight requests
        self._staged_admits = frozenset()   # this tick's pre-scatter rows
        self._resv: dict[int, int] = {}    # req_id -> outstanding reserve
        self._need: dict[int, int] = {}    # req_id -> worst-case blocks
        # host-vs-device split of decode ticks (admission ticks excluded):
        # stats["host_s"] is scheduling/bookkeeping, stats["device_s"] the
        # jitted tick incl. the [num_slots] token fetch
        self.stats = {"host_s": 0.0, "device_s": 0.0, "ticks": 0,
                      "preemptions": 0, "timeouts": 0, "cancelled": 0,
                      "rejected": 0, "spec_ticks": 0, "spec_proposed": 0,
                      "spec_accepted": 0, "spec_fallbacks": 0}
        self._adm_counter = 0                # admission recency, per slot
        self.adm_order = np.zeros(num_slots, np.int64)
        # robustness: bounded admission queue (None = unbounded), a
        # swappable clock (tests drive deadlines deterministically), and
        # the drain flag (graceful shutdown: finish in-flight, admit
        # nothing new)
        self.max_queue_len = max_queue_len
        self._clock = clock if clock is not None else time.monotonic
        self._draining = False
        self._has_deadlines = False

    # ------------------------------------------------------------- intake
    def add_request(self, req: Request) -> int:
        if self._draining:
            self.stats["rejected"] += 1
            _REJECTED.inc(reason="draining")
            raise EngineDrainingError(
                "engine is draining — finishing in-flight requests, "
                "admitting nothing new")
        if (self.max_queue_len is not None
                and len(self.queue) >= self.max_queue_len):
            # reject-on-full backpressure: push the load signal to the
            # caller instead of buffering an unbounded deque
            self.stats["rejected"] += 1
            _REJECTED.inc(reason="queue_full")
            raise QueueFullError(
                f"admission queue full ({self.max_queue_len} waiting) — "
                "shed load or retry later")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (the prefill "
                             "itself produces the first token)")
        if req.num_beams < 1:
            raise ValueError("num_beams must be >= 1")
        if req.num_beams > 1:
            if req.num_beams > self.num_slots:
                raise ValueError(f"num_beams {req.num_beams} exceeds "
                                 f"num_slots={self.num_slots}")
            if self.window is not None:
                raise NotImplementedError(
                    "beam search + sliding-window block recycling are not "
                    "combined (a recycled parent block may be needed by a "
                    "forked child)")
            if req.stream is not None:
                raise ValueError("streaming is not supported for beam "
                                 "requests (tokens are only known at the "
                                 "final selection)")
        if len(req.prompt) < 1:
            raise ValueError("prompt must contain at least one token "
                             "(an empty row has no logit to sample from)")
        if len(req.prompt) > self.max_prompt_len and req.num_beams > 1:
            raise ValueError(f"prompt length {len(req.prompt)} exceeds "
                             f"max_prompt_len={self.max_prompt_len} "
                             "(chunked prefill does not combine with "
                             "beam search)")
        if len(req.prompt) > self.max_prompt_len and self.window is not None:
            raise NotImplementedError(
                "chunked prefill + sliding-window recycling not combined")
        if len(req.prompt) > self.max_prompt_len and \
                (getattr(self.model.cfg, "rope_scaling", None)
                 or {}).get("type") == "dynamic":
            # refuse HERE: a trace-time raise inside step() would leave
            # the slot claimed and the request wedged in self.prefilling
            raise NotImplementedError(
                "chunked prefill with dynamic-NTK rope is not supported")
        if len(req.prompt) + req.max_new_tokens > self.max_seq_len:
            raise ValueError("prompt + max_new_tokens exceeds max_seq_len")
        if self._worst_case_blocks(req) > self.mgr.num_blocks:
            raise ValueError(
                "request worst case exceeds the WHOLE block pool — it "
                "could never be admitted (raise num_blocks)")
        if req.req_id is None:
            req.req_id = next(self._ids)
        else:
            if req.req_id in self.requests:
                # a duplicate id would alias the BlockManager table AND
                # the reservation ledger of the in-flight request
                raise ValueError(f"req_id {req.req_id} already exists")
            # keep auto ids from ever colliding with explicit ones
            self._ids = itertools.count(
                max(req.req_id + 1, next(self._ids)))
        req._submit_t = self._clock()
        if req.deadline_s is not None or req.max_queue_s is not None:
            self._has_deadlines = True
        self.requests[req.req_id] = req
        self.queue.append(req)
        _QUEUE_DEPTH.set(len(self.queue))
        return req.req_id

    def pop_finished(self) -> dict:
        """Remove and return completed requests ({req_id: Request}) — call
        periodically from a long-running serve loop so the engine does not
        retain every finished request's token list forever."""
        done = {rid: r for rid, r in self.requests.items() if r.done}
        for rid in done:
            del self.requests[rid]
        return done

    def generate(self, prompt, **kw) -> int:
        return self.add_request(Request(prompt, **kw))

    def has_work(self) -> bool:
        return (bool(self.queue) or bool(self.active.any())
                or bool(self.groups) or bool(self.prefilling))

    # --------------------------------------------- cancellation/deadlines
    def _release_ledger(self, rid: int):
        self._reserved -= self._resv.pop(rid, 0)
        self._need.pop(rid, None)

    def cancel(self, req_id: int, reason: str = "cancelled") -> bool:
        """Terminate a request wherever it currently lives — queued,
        chunk-prefilling, decoding, or mid-beam — freeing its blocks,
        reservation, and slot(s). Exception-atomic: every mutation below
        is a host dict/array op ordered so a failure cannot strand
        half-released state. Safe between ``step()`` calls (and from
        stream callbacks: an emptied slot is skipped by ``_emit``).
        Returns False for unknown/finished requests."""
        req = self.requests.get(req_id)
        if req is None or req.done:
            return False
        released = False
        for i, q in enumerate(self.queue):          # still waiting
            if q.req_id == req_id:
                del self.queue[i]
                released = True
                break
        if not released and req_id in self.prefilling:
            slot, _ = self.prefilling.pop(req_id)
            self.mgr.free(req_id)
            self.slot_req[slot] = -1
            released = True
        if not released and req_id in self.groups:
            g = self.groups.pop(req_id)
            for sid in g.sid.values():
                self.mgr.free(sid)
            for slot in g.slots:
                self.active[slot] = False
                self.is_beam[slot] = False
                self.slot_req[slot] = -1
            released = True
        if not released:
            slots = np.nonzero(self.slot_req == req_id)[0]
            if not len(slots):
                return False                        # mid-transition: punt
            slot = int(slots[0])
            self.mgr.free(req_id)
            self.active[slot] = False
            self.slot_req[slot] = -1
            released = True
        self._release_ledger(req_id)
        req.done = True
        req.finish_reason = reason
        self.stats["timeouts" if reason == "timeout" else "cancelled"] += 1
        (_TIMEOUTS if reason == "timeout" else _CANCELLED).inc()
        _FINISHED.inc(reason=reason)
        FLIGHT.record("serving.timeout" if reason == "timeout"
                      else "serving.cancel", rid=req_id)
        return True

    def _expire(self):
        """Finish requests whose wall-clock budget ran out: absolute
        ``deadline_s`` for everyone, ``max_queue_s`` additionally for
        requests still waiting for admission. Runs at the top of every
        tick — an expired request frees its slot/blocks THIS tick, so
        deadlines double as livelock bounds."""
        if not self._has_deadlines or not self.requests:
            return
        now = self._clock()
        queued = {r.req_id for r in self.queue}
        for rid, r in list(self.requests.items()):
            if r.done or r._submit_t is None:
                continue
            age = now - r._submit_t
            if ((r.deadline_s is not None and age >= r.deadline_s)
                    or (rid in queued and r.max_queue_s is not None
                        and age >= r.max_queue_s)):
                self.cancel(rid, reason="timeout")

    def drain(self, cancel_queued: bool = False) -> dict:
        """Graceful shutdown: stop admitting (``add_request`` raises
        EngineDrainingError) but finish everything in flight; returns
        {req_id: tokens} like ``run``. ``cancel_queued=True`` also
        cancels requests still waiting for admission instead of running
        them to completion."""
        from time import monotonic
        t0 = monotonic()
        with _span("serving.drain", cancel_queued=cancel_queued):
            self._draining = True
            if cancel_queued:
                for r in list(self.queue):
                    self.cancel(r.req_id)
            while self.has_work():
                self.step()
        _DRAIN.observe(monotonic() - t0)
        return {rid: r.tokens for rid, r in self.requests.items()}

    def assert_quiescent(self):
        """Invariant check once idle: every block is back in the pool
        (prefix-cache parked blocks count — they are reclaimable), no
        standing reservations, no per-sequence tables. Chaos tests call
        this after driving fault schedules: any leak in a recovery path
        shows up here as missing blocks."""
        assert not self.has_work(), "engine still has work"
        assert self.mgr.free_blocks == self.mgr.num_blocks, (
            f"block leak: {self.mgr.num_blocks - self.mgr.free_blocks} "
            f"of {self.mgr.num_blocks} blocks unaccounted for")
        assert self._reserved == 0, f"reservation leak: {self._reserved}"
        assert not self._resv and not self._need, (
            f"ledger leak: resv={self._resv} need={self._need}")
        assert not self.mgr.tables, f"table leak: {list(self.mgr.tables)}"

    def _pr(self, req) -> np.ndarray:
        """Effective prompt: the resume form (original prompt + tokens
        generated before a preemption), the original prompt otherwise."""
        return req.prompt if req._resume is None else req._resume

    def _remaining(self, req) -> int:
        """max_new_tokens still to generate (tokens survive preemption)."""
        return req.max_new_tokens - len(req.tokens)

    def _worst_case_blocks(self, req) -> int:
        """Blocks a request can ever hold at once. Windowed models recycle
        below-window blocks, so the live span is bounded by the window
        (plus the write-frontier block) — but prefill scatters the WHOLE
        prompt before any recycling, so that is a floor.

        Beam requests (K slots): shared prompt blocks once, plus per beam
        the generated span (straddling ≤ ceil(new/bs)+1 blocks), plus 2
        per beam for the copy-on-write partial forks (one held, one
        transient while the new fork exists before the parent is freed)."""
        p = len(self._pr(req))
        if req.num_beams > 1:
            k = req.num_beams
            return (self.mgr.blocks_needed(p)
                    + k * (self.mgr.blocks_needed(
                        req.max_new_tokens + self.block_size) + 2))
        total = p + self._remaining(req)
        if self.window is None:
            return self.mgr.blocks_needed(total)
        live = self.mgr.blocks_needed(
            min(total, self.window + 2 * self.block_size))
        return max(self.mgr.blocks_needed(p), live)

    # ---------------------------------------------------------- admission
    def _admit(self):
        """FCFS: move queued requests into free slots while the pool can
        cover their worst case; returns (greedy (slot, req) pairs,
        beam (slots, req) pairs). A beam request needs num_beams slots."""
        free_slots = list(np.nonzero(self.slot_req < 0)[0])
        admits, beam_admits = [], []
        while self.queue and free_slots:
            req = self.queue[0]
            k = req.num_beams
            p = self._pr(req)
            # prefix-cache lookup BEFORE the capacity gate: shared blocks
            # cost nothing, so a mostly-cached prompt admits under
            # pressure an uncached one would wait out
            cached = (self.mgr.match_prefix(p)
                      if self.prefix_caching and k == 1 else [])
            ct = len(cached) * self.block_size
            if self.preemption and k == 1:
                # optimistic: cover only the first prefill chunk (+1
                # decode-headroom block); out-of-blocks later preempts
                need = (self.mgr.blocks_needed(
                    min(len(p), ct + self.max_prompt_len)) - len(cached) + 1)
            else:
                need = self._worst_case_blocks(req)
            if (k > len(free_slots)
                    or need > self.mgr.free_blocks - self._reserved):
                break                      # FCFS: do not starve the head
            self.queue.popleft()
            _ADMITTED.inc()
            if req._submit_t is not None:
                _QUEUE_WAIT.observe(max(0.0, self._clock() - req._submit_t))
            if self.preemption and k == 1:
                need = 0                   # no standing reservation
            self._need[req.req_id] = need
            self._resv[req.req_id] = 0
            if k == 1:
                slot = int(free_slots.pop(0))
                if cached:
                    self.mgr.adopt_prefix(req.req_id, cached)
                if cached or len(p) > self.max_prompt_len:
                    # chunk-prefill path from offset ct: claims the slot
                    # INACTIVE; blocks allocate chunk-by-chunk against
                    # the reservation. (Cached short prompts ride it too —
                    # the chunk program is the one that prefills from an
                    # arbitrary offset over the slot's pool prefix.)
                    self._reserved += need
                    self._resv[req.req_id] = need
                    self.slot_req[slot] = req.req_id
                    # admission recency stamped at slot-claim: preemption
                    # victim selection keys on THIS, not on req_id (user
                    # ids need not be monotonic with admission)
                    self._adm_counter += 1
                    self.adm_order[slot] = self._adm_counter
                    self.prefilling[req.req_id] = (slot, ct)
                    continue
                self.mgr.allocate(req.req_id, len(p))
                if self.prefix_caching:
                    self.mgr.commit_prefix(req.req_id, p)
                self._update_resv(req.req_id)
                admits.append((slot, req))
            else:
                slots = [int(free_slots.pop(0)) for _ in range(k)]
                # full worst-case reservation up front; relaxed to
                # (need - live) as the group's blocks materialise
                self._reserved += need
                self._resv[req.req_id] = need
                beam_admits.append((slots, req))
        return admits, beam_admits

    def _live_blocks(self, rid: int) -> int:
        return sum(b is not None for b in self.mgr.tables.get(rid, []))

    def _update_resv(self, rid: int):
        """Outstanding reserve = worst case minus blocks currently held
        (recycling under a sliding window RETURNS headroom)."""
        new = max(0, self._need[rid] - self._live_blocks(rid))
        self._reserved += new - self._resv[rid]
        self._resv[rid] = new

    def _recycle_window(self, slots):
        """Free blocks entirely below cur - window for the given slots —
        live blocks per sequence stay O(window). Host-only: the paged
        kernel masks every position BELOW lens - window, so stale table
        entries pointing at recycled (even reused) blocks are never
        read."""
        for slot in slots:
            rid = int(self.slot_req[slot])
            dead = int(max(0, self.cur[slot] - self.window)
                       ) // self.block_size
            if dead > 0 and self.mgr.free_prefix(rid, dead):
                self._update_resv(rid)

    def _prefill(self, admits, beam_admits=()):
        """ONE padded prefill forward for every prompt admitted this tick —
        greedy prompts in rows 0..n-1, each beam request's prompt as one
        more row (written into its beam-0 slot; the forks are installed
        after, in ``_beam_init``)."""
        if not admits and not beam_admits:
            # nothing admitted: never pay the full (num_slots,
            # max_prompt_len) padded forward on all-sentinel rows
            return []
        a_cap = self.num_slots           # one compiled admission shape
        ids = np.zeros((a_cap, self.max_prompt_len), np.int32)
        lens = np.zeros(a_cap, np.int32)
        slots = np.full(a_cap, self.num_slots, np.int32)   # sentinel = drop
        rows = np.full((a_cap, self.max_blocks_per_seq),
                       self.mgr.num_blocks, np.int32)
        for i, (slot, req) in enumerate(admits):
            p = self._pr(req)
            ids[i, :len(p)] = p
            lens[i] = len(p)
            slots[i] = slot
            t = self.mgr.tables[req.req_id]
            rows[i, :len(t)] = t
            self.slot_req[slot] = req.req_id
            self.active[slot] = True
            self.cur[slot] = len(p)
            self.gen[slot] = 0
            self.max_gen[slot] = self._remaining(req)
            self._adm_counter += 1
            self.adm_order[slot] = self._adm_counter
            self.table_len[slot] = len(t)
            self.temps[slot] = (self.default_temp if req.temperature is None
                                else req.temperature)
            self.top_ps[slot] = (self.default_top_p if req.top_p is None
                                 else req.top_p)
            # fresh draft state: an evicted slot's draft cache was "freed"
            # by zeroing this frontier — replay rebuilds it from scratch
            self.draft_cur[slot] = 0
            self.slot_k[slot] = self.spec_k
            self._acc_ema[slot] = 1.0
        n = len(admits)
        beams = []
        self._staged_admits = frozenset(r.req_id for _, r in admits)
        for bi, (bslots, req) in enumerate(beam_admits):
            g, grows, csrc, cdst = self._beam_alloc(bslots, req)
            i = n + bi                   # every admit holds >= 1 slot, so
            ids[i, :g.s] = req.prompt    # greedy + beam rows fit in a_cap
            lens[i] = g.s
            slots[i] = bslots[0]
            rows[i] = grows[0]
            beams.append((g, grows, csrc, cdst))
        logits, self.cache = _PREFILL_JIT(
            self.model, jnp.asarray(ids), jnp.asarray(lens),
            self.cache, jnp.asarray(slots), jnp.asarray(rows))
        self._staged_admits = frozenset()   # scatter landed: evictable again
        self.rng, sub = jax.random.split(self.rng)
        row_temps = np.zeros(a_cap, np.float32)
        row_tps = np.ones(a_cap, np.float32)
        for i, (slot, req) in enumerate(admits):
            row_temps[i] = self.temps[slot]
            row_tps[i] = self.top_ps[slot]
        first = np.asarray(_SAMPLE_ROWS_JIT(
            logits.astype(jnp.float32), sub, jnp.asarray(row_temps),
            jnp.asarray(row_tps), self.top_k))
        if self.window is not None:
            # a long prompt's below-window blocks die the moment prefill
            # has scattered them — and from here on the sequence can never
            # hold more than the window live bound, so relax its
            # reservation too (the prompt-size floor only mattered DURING
            # prefill)
            self._recycle_window([slot for slot, _ in admits])
            live_bound = self.mgr.blocks_needed(
                self.window + 2 * self.block_size)
            for slot, req in admits:
                rid = req.req_id
                self._need[rid] = min(self._need[rid], live_bound)
                self._update_resv(rid)
        emitted = []
        for i, (slot, req) in enumerate(admits):
            emitted += self._emit(slot, int(first[i]))
        for bi, (g, grows, csrc, cdst) in enumerate(beams):
            emitted += self._beam_init(g, grows, csrc, cdst, logits[n + bi])
        return emitted

    # ------------------------------------------------------------ beams
    def _group_live_blocks(self, g: _BeamGroup) -> int:
        """Distinct pool blocks held by the whole group (shared prompt
        blocks appear in several beams' tables — count them once)."""
        return len({b for sid in g.sid.values()
                    for b in self.mgr.tables.get(sid, []) if b is not None})

    def _update_resv_group(self, rid: int):
        g = self.groups[rid]
        new = max(0, self._need[rid] - self._group_live_blocks(g))
        self._reserved += new - self._resv[rid]
        self._resv[rid] = new

    def _new_sid(self, rid):
        self._sid_counter += 1
        return (rid, self._sid_counter)

    def _beam_alloc(self, slots, req: Request):
        """Host/manager phase of beam admission: allocate the prompt under
        beam 0's key and fork the other beams copy-on-write. Returns the
        group plus the fork data; the prompt itself rides as ONE row of
        the shared admission prefill."""
        k, s, rid = req.num_beams, len(req.prompt), req.req_id
        nb, max_b = self.mgr.num_blocks, self.max_blocks_per_seq
        g = _BeamGroup(req=req, slots=list(slots), s=s)
        g.sid = {j: self._new_sid(rid) for j in range(k)}
        # protect same-tick greedy admits: their prefill rows are staged
        # but the scatter hasn't run yet (this is called mid-_prefill)
        prot = self._staged_admits
        self._mgr_retry(self.mgr.allocate, g.sid[0], s, protect=prot)
        rows = np.full((k, max_b), nb, np.int32)
        copy_src = np.full(k, nb, np.int32)
        copy_dst = np.full(k, nb, np.int32)
        for j in range(1, k):
            pair = self._mgr_retry(self.mgr.fork, g.sid[0], g.sid[j], s,
                                   protect=prot)
            if pair is not None:
                copy_src[j], copy_dst[j] = pair
        for j in range(k):
            t = self.mgr.tables[g.sid[j]]
            rows[j, :len(t)] = t
        return g, rows, copy_src, copy_dst

    def _beam_init(self, g: _BeamGroup, rows, copy_src, copy_dst,
                   logits_row):
        """Device-state phase after the shared prefill: install the forked
        tables, init the selection state from the prompt's last logits,
        then run the group's FIRST select so its slots enter this tick's
        forward with real beam tokens."""
        req, s, rid, k = g.req, g.s, g.req.req_id, g.req.num_beams
        self.cache = _BEAM_GROUP_UPDATE_JIT(
            self.cache, jnp.asarray(g.slots, jnp.int32), jnp.asarray(rows),
            jnp.asarray(s, jnp.int32), jnp.asarray(copy_src),
            jnp.asarray(copy_dst))
        neg = jnp.float32(-1e9)
        vocab = self.model.cfg.vocab_size
        logp0 = jax.nn.log_softmax(logits_row.astype(jnp.float32))
        g.logp = jnp.broadcast_to(logp0[None], (k, vocab))
        g.running_lp = jnp.asarray([0.0] + [float(neg)] * (k - 1),
                                   jnp.float32)
        max_len = s + req.max_new_tokens
        g.seqs = jnp.zeros((k, max_len), jnp.int32).at[:, :s].set(
            jnp.asarray(req.prompt)[None])
        g.fin_seqs = jnp.zeros_like(g.seqs)
        g.fin_scores = jnp.full((k,), neg, jnp.float32)

        for slot in g.slots:
            self.slot_req[slot] = rid
            self.active[slot] = True
            self.is_beam[slot] = True
            self.cur[slot] = s
            self.temps[slot] = 0.0       # beam tokens come from select
            self.top_ps[slot] = 1.0
        self.groups[rid] = g
        self._update_resv_group(rid)
        return self._beam_advance(rid, g)

    def _beam_advance(self, rid: int, g: _BeamGroup):
        """One beam select over the group's pending logp; fork the caches
        along the chosen parents (or finalize at the last select).
        Selection/fork math mirrors ``paged_beam_search`` exactly."""
        k = g.req.num_beams
        (g.running_lp, g.seqs, g.fin_seqs, g.fin_scores, new_beam,
         new_tok) = _BEAM_SELECT_JIT(
            g.running_lp, g.seqs, g.fin_seqs, g.fin_scores, g.logp,
            jnp.int32(g.i), g.s, self.eos_token_id,
            float(g.req.length_penalty))
        if g.i == g.req.max_new_tokens - 1:
            return self._finalize_beam(rid, g)
        parents = np.asarray(new_beam)
        toks = np.asarray(new_tok)
        cur = g.s + g.i                       # tokens stored per beam
        nb, max_b = self.mgr.num_blocks, self.max_blocks_per_seq
        rows = np.full((k, max_b), nb, np.int32)
        copy_src = np.full(k, nb, np.int32)
        copy_dst = np.full(k, nb, np.int32)
        new_sids = {}
        for j in range(k):
            dst = self._new_sid(rid)
            pair = self._mgr_retry(self.mgr.fork,
                                   g.sid[int(parents[j])], dst, cur)
            if pair is not None:
                copy_src[j], copy_dst[j] = pair
            new_sids[j] = dst
        for j in range(k):
            self.mgr.free(g.sid[j])
        g.sid = new_sids
        for j in range(k):
            t = self._mgr_retry(                      # room for the write
                self.mgr.allocate, g.sid[j], cur + 1)
            rows[j, :len(t)] = t
        self.cache = _BEAM_GROUP_UPDATE_JIT(
            self.cache, jnp.asarray(g.slots, jnp.int32), jnp.asarray(rows),
            jnp.asarray(cur, jnp.int32), jnp.asarray(copy_src),
            jnp.asarray(copy_dst))
        self._update_resv_group(rid)
        for j, slot in enumerate(g.slots):
            self.last_tok[slot] = toks[j]
        g.i += 1
        return []

    def _finalize_beam(self, rid: int, g: _BeamGroup):
        req = g.req
        best_seq, best_score = _beam_finalize(
            g.running_lp, g.seqs, g.fin_seqs, g.fin_scores, g.s,
            req.max_new_tokens, self.eos_token_id,
            float(req.length_penalty))
        req.tokens = [int(t) for t in np.asarray(best_seq)[g.s:]]
        req.beam_score = float(best_score)
        req.done = True
        req.finish_reason = "beam"
        _FINISHED.inc(reason="beam")
        _TOKENS.inc(len(req.tokens))
        for sid in g.sid.values():
            self.mgr.free(sid)
        for slot in g.slots:
            self.active[slot] = False
            self.is_beam[slot] = False
            self.slot_req[slot] = -1
        self._reserved -= self._resv.pop(rid, 0)
        self._need.pop(rid, None)
        del self.groups[rid]
        return [(rid, t) for t in req.tokens]

    def _prefill_chunks(self):
        """One chunk (≤ max_prompt_len tokens) for every in-flight
        chunked prefill — vLLM-style: long prompts stream in across
        ticks while other slots keep decoding. The final chunk samples
        the request's first token and activates its slot."""
        if not self.prefilling:
            return []
        a_cap = self.num_slots
        cap = self.max_prompt_len
        nb, max_b = self.mgr.num_blocks, self.max_blocks_per_seq
        ids = np.zeros((a_cap, cap), np.int32)
        lens = np.zeros(a_cap, np.int32)
        offs = np.zeros(a_cap, np.int32)
        slots = np.full(a_cap, self.num_slots, np.int32)
        rows = np.full((a_cap, max_b), nb, np.int32)
        batch = list(self.prefilling.items())[:a_cap]
        progressed = False
        staged = set()       # rows already in the jitted batch: their KV
        for i, (rid, (slot, consumed)) in enumerate(batch):
            if rid not in self.prefilling:   # scatter is pending — a later
                continue     # row's preemption must never evict them
            req = self.requests[rid]
            chunk = self._pr(req)[consumed: consumed + cap]
            t = self._allocate_or_preempt(rid, consumed + len(chunk),
                                          protect=staged)
            if t is None:
                continue         # no blocks this tick: row stays queued
            progressed = True
            staged.add(rid)
            self._update_resv(rid)
            ids[i, :len(chunk)] = chunk
            lens[i] = len(chunk)
            offs[i] = consumed
            slots[i] = slot
            rows[i, :len(t)] = t
        if (not progressed and not self.active.any() and not self.groups):
            # nothing decoded this tick and no prefill row got blocks even
            # though preemption could evict every OTHER prefill: the pool
            # cannot fit one chunk of the sole remaining request — no
            # future tick can differ, so raise instead of spinning
            raise MemoryError(
                "paged pool cannot fit one prefill chunk of the remaining "
                "request(s) even after preemption — increase num_blocks or "
                "reduce max_prompt_len (chunk size)")
        if not progressed:
            # every prefilling row is starved of blocks this tick (decode
            # keeps the engine alive): the batch is all-sentinel, so the
            # padded chunk forward would scatter nothing — skip it
            return []
        logits, self.cache = _PREFILL_CHUNK_JIT(
            self.model, jnp.asarray(ids), jnp.asarray(lens),
            jnp.asarray(offs), self.cache, jnp.asarray(slots),
            jnp.asarray(rows))
        emitted = []
        done_rows = []
        for i, (rid, (slot, consumed)) in enumerate(batch):
            if rid not in self.prefilling:
                continue     # evicted mid-batch: must not re-add its row
            req = self.requests[rid]
            consumed += int(lens[i])
            if consumed < len(self._pr(req)):
                self.prefilling[rid] = (slot, consumed)
                continue
            done_rows.append((i, rid, slot))
        if done_rows:
            self.rng, sub = jax.random.split(self.rng)
            row_t = np.zeros(a_cap, np.float32)
            row_p = np.ones(a_cap, np.float32)
            for i, rid, slot in done_rows:
                req = self.requests[rid]
                row_t[i] = (self.default_temp if req.temperature is None
                            else req.temperature)
                row_p[i] = (self.default_top_p if req.top_p is None
                            else req.top_p)
            first = np.asarray(_SAMPLE_ROWS_JIT(
                logits.astype(jnp.float32), sub, jnp.asarray(row_t),
                jnp.asarray(row_p), self.top_k))
            for i, rid, slot in done_rows:
                req = self.requests[rid]
                del self.prefilling[rid]
                p = self._pr(req)
                if self.prefix_caching:
                    self.mgr.commit_prefix(rid, p)
                t = self.mgr.tables[rid]
                self.active[slot] = True
                self.cur[slot] = len(p)
                self.gen[slot] = 0
                self.max_gen[slot] = self._remaining(req)
                self._adm_counter += 1
                self.adm_order[slot] = self._adm_counter
                self.table_len[slot] = len(t)
                self.temps[slot] = row_t[i]
                self.top_ps[slot] = row_p[i]
                self.draft_cur[slot] = 0
                self.slot_k[slot] = self.spec_k
                self._acc_ema[slot] = 1.0
                emitted += self._emit(slot, int(first[i]))
        return emitted

    # --------------------------------------------------------- preemption
    def _preempt(self, protect_rid=None) -> bool:
        """Evict the YOUNGEST active greedy request (LIFO — vLLM's policy:
        the oldest in-flight work is closest to completion) to free its
        blocks. The victim re-queues at the queue head with resume-prompt
        = prompt + generated-so-far; on re-admission the resume prefill
        recomputes its KV (prefix-cache hits cover whatever of its old
        blocks survived). When no active slot qualifies, falls back to
        evicting a CHUNK-PREFILLING request (slot inactive, blocks held):
        without this, two long prompts mid-prefill on a dry pool would
        spin forever — neither active nor evictable. Returns False when
        nothing is preemptible."""
        protect = self._protect(protect_rid)
        cand = [int(s) for s in np.nonzero(self.active & ~self.is_beam)[0]
                if int(self.slot_req[s]) not in protect]
        if self._preempt_from(cand):
            return True
        return self._preempt_prefilling(protect_rid)

    @staticmethod
    def _protect(protect_rid):
        """Normalise the protect argument to a set of req_ids (a single
        rid, an iterable of rids, or None)."""
        if protect_rid is None:
            return frozenset()
        if isinstance(protect_rid, (set, frozenset, list, tuple)):
            return frozenset(protect_rid)
        return frozenset((protect_rid,))

    def _preempt_prefilling(self, protect_rid=None) -> bool:
        """Evict the youngest in-flight chunked prefill — youngest by
        ADMISSION order (``adm_order`` stamped at slot-claim), not by
        req_id: ids may be user-supplied and non-monotonic, and evicting
        an explicitly-numbered old request as if youngest would churn the
        work closest to completion. Free its blocks and re-queue it at
        the head; consumed chunks are recomputed on re-admission —
        prefill is deterministic, so this only costs work, never
        correctness. Rows already STAGED into this tick's chunk batch must
        ride in ``protect_rid`` — the jitted scatter would otherwise write
        their KV into blocks just handed to someone else."""
        protect = self._protect(protect_rid)
        cand = [rid for rid in self.prefilling if rid not in protect]
        if not cand:
            return False
        rid = max(cand, key=lambda r: self.adm_order[self.prefilling[r][0]])
        slot, _ = self.prefilling.pop(rid)
        req = self.requests[rid]
        self.mgr.free(rid)
        self._reserved -= self._resv.pop(rid, 0)
        self._need.pop(rid, None)
        self.slot_req[slot] = -1
        self.queue.appendleft(req)
        self.stats["preemptions"] += 1
        _PREEMPTED.inc()
        FLIGHT.record("serving.preempt", rid=rid, slot=int(slot),
                      phase="prefill")
        return True

    def _preempt_from(self, cand) -> bool:
        if self.window is not None or self._dyn_rope:
            # the resume prefill rides the chunk path, which refuses
            # window-recycling and dynamic-NTK for long prompts — only
            # slots whose resume form fits one plain prefill qualify
            cand = [s for s in cand
                    if len(self.requests[int(self.slot_req[s])].prompt)
                    + len(self.requests[int(self.slot_req[s])].tokens)
                    <= self.max_prompt_len]
        if not cand:
            return False
        slot = max(cand, key=lambda s: self.adm_order[s])
        rid = int(self.slot_req[slot])
        req = self.requests[rid]
        req._resume = (np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
            if req.tokens else req.prompt)
        self.mgr.free(rid)
        self._reserved -= self._resv.pop(rid, 0)
        self._need.pop(rid, None)
        self.active[slot] = False
        self.slot_req[slot] = -1
        self.draft_cur[slot] = 0     # draft cache freed with the slot
        self.queue.appendleft(req)
        self.stats["preemptions"] += 1
        _PREEMPTED.inc()
        FLIGHT.record("serving.preempt", rid=rid, slot=int(slot),
                      phase="decode")
        return True

    def _allocate_or_preempt(self, rid: int, n_tokens: int, protect=None):
        """mgr.allocate with out-of-blocks recovery: preempt greedy slots
        (never ``rid`` itself, nor anything in ``protect`` — rows already
        staged into this tick's jitted batch) until the allocation fits.
        Returns the table, or None when preemption is off / nothing could
        be freed (caller skips this row for the tick — progress resumes
        when blocks free up).

        Respects OTHER requests' standing reservations: a greedy request
        (which carries none under preemption) must preempt before dipping
        into blocks a beam group's worst-case reservation counts on —
        otherwise a later beam select can raise MemoryError out of
        ``step()`` mid-update, corrupting engine state."""
        protect = self._protect(protect) | {rid}
        while True:
            others = self._reserved - self._resv.get(rid, 0)
            # need mirrors mgr.allocate: table POSITIONS — including the
            # None placeholders window recycling leaves — already cover
            # their token span; counting only live blocks would inflate
            # need without bound as a windowed sequence recycles
            # (spurious preemption storm, then a crash)
            need = (self.mgr.blocks_needed(n_tokens)
                    - len(self.mgr.tables.get(rid, [])))
            try:
                # chaos hook: an injected MemoryError exercises the same
                # preempt-and-retry recovery a genuinely dry pool would
                fault_point("serving.alloc", rid=rid, engine=self)
                if need > self.mgr.free_blocks - max(0, others):
                    raise MemoryError("allocation would dip into blocks "
                                      "reserved by other requests")
                return self.mgr.allocate(rid, n_tokens)
            except MemoryError:
                if not self.preemption or not self._preempt(
                        protect_rid=protect):
                    if self.preemption:
                        return None
                    raise

    def _mgr_retry(self, fn, *a, protect=None):
        """Beam-group block growth with out-of-blocks recovery: route
        through greedy preemption instead of letting MemoryError escape
        ``step()`` mid-cache-update. The group's worst-case reservation
        (+2 transient fork blocks per beam) should make this unreachable
        now that greedy growth respects reservations; this is the
        belt-and-braces path. ``protect``: req_ids whose prefill rows are
        staged but not yet scattered (evicting one would corrupt the KV
        writes about to land)."""
        while True:
            try:
                return fn(*a)
            except MemoryError:
                if not self.preemption or not self._preempt(
                        protect_rid=protect):
                    raise

    # ------------------------------------------------- speculative decode
    def _spec_probs(self, logits_row, temp, top_p):
        """Host mirror of ``decoding._sample_rows``'s filtered target
        distribution for one row (temperature > 0): temperature scale →
        static top_k cut → nucleus (top_p) cut → renormalise. The accept
        rule must compare proposals against EXACTLY the distribution the
        non-spec tick samples from, or speculation would change the
        output law."""
        scaled = np.asarray(logits_row, np.float64) / temp
        if self.top_k is not None and self.top_k > 0:
            kth = np.sort(scaled)[-self.top_k]
            scaled = np.where(scaled < kth, -1e30, scaled)
        srt = np.sort(scaled)[::-1]
        e = np.exp(srt - srt[0])
        cum = np.cumsum(e / e.sum())
        cutoff = srt[int((cum < top_p).sum())]
        scaled = np.where(scaled < cutoff, -1e30, scaled)
        e = np.exp(scaled - scaled.max())
        return e / e.sum()

    def _committed_seq(self, slot: int) -> np.ndarray:
        """The slot's committed sequence: effective prompt + tokens
        generated SINCE activation (earlier generations are already baked
        into the resume prompt). Its last token is ``last_tok`` — sampled
        but not yet written to the target cache — so len == cur + 1."""
        req = self.requests[int(self.slot_req[slot])]
        g = int(self.gen[slot])
        toks = np.asarray(req.tokens[len(req.tokens) - g:], np.int32)
        return np.concatenate([self._pr(req), toks])

    def _spec_draft(self, staged, seqs):
        """Draft phase: catch each staged slot's draft cache up to its
        committed frontier (chunked, for freshly admitted/replayed slots
        whose draft cache is empty), then autoregressively propose up to
        k_eff tokens per slot. Returns (props, qs) keyed by slot; qs[slot]
        is None for greedy rows, else the per-proposal draft
        distributions the accept rule needs."""
        ns = self.num_slots
        draft = self.draft_model
        kmax = max(k for _, _, k in staged)
        all_greedy = all(float(self.temps[s]) == 0.0 for s, _, _ in staged)
        Cs = self.spec_k + 1

        # ---- catch-up: wide chunks until every pending suffix fits the
        # steady feed (pending >= 1 always — last_tok is never in cache)
        CH = max(self.max_prompt_len, Cs)
        while True:
            pend_len = {s: len(seqs[s]) - int(self.draft_cur[s])
                        for s, _, _ in staged}
            if max(pend_len.values()) <= Cs:
                break
            ids = np.zeros((ns, CH), np.int32)
            cl = np.zeros(ns, np.int32)
            rp = np.zeros(ns, np.int32)
            for s, _, _ in staged:
                if pend_len[s] <= Cs:
                    continue               # already caught up: no writes
                n = min(pend_len[s] - 1, CH)   # keep >= 1 for the steady feed
                dc = int(self.draft_cur[s])
                ids[s, :n] = seqs[s][dc: dc + n]
                cl[s] = n
                rp[s] = dc
            _, self._draft_cache = _FWD_ROWS_JIT(
                draft, jnp.asarray(ids), self._draft_cache,
                jnp.asarray(rp, jnp.int32), None,
                jnp.asarray(cl, jnp.int32))
            for s, _, _ in staged:
                self.draft_cur[s] += int(cl[s])

        # ---- steady feed: the pending suffix (<= k+1 tokens) in one
        # fixed-width chunk; its last logit seeds the first proposal
        ids = np.zeros((ns, Cs), np.int32)
        cl = np.zeros(ns, np.int32)
        rp = np.zeros(ns, np.int32)
        for s, _, _ in staged:
            dc = int(self.draft_cur[s])
            pend = seqs[s][dc:]
            ids[s, :len(pend)] = pend
            cl[s] = len(pend)
            rp[s] = dc
        dl, self._draft_cache = _FWD_ROWS_JIT(
            draft, jnp.asarray(ids), self._draft_cache,
            jnp.asarray(rp, jnp.int32), None, jnp.asarray(cl, jnp.int32))
        for s, _, _ in staged:
            self.draft_cur[s] += int(cl[s])      # == cur + 1 now
        dlast = jnp.take_along_axis(
            dl, jnp.maximum(jnp.asarray(cl, jnp.int32) - 1,
                            0)[:, None, None], axis=1)[:, 0]

        props = {s: [] for s, _, _ in staged}
        qs = {s: (None if float(self.temps[s]) == 0.0 else [])
              for s, _, _ in staged}

        def pick(slot, row):
            temp = float(self.temps[slot])
            if temp == 0.0:
                return int(np.argmax(row))
            z = np.asarray(row, np.float64) / temp
            e = np.exp(z - z.max())
            q = e / e.sum()
            qs[slot].append(q)
            return int(self._spec_rs.choice(q.size, p=q))

        def pick_all(logits_2d, rows_feeding):
            if all_greedy:       # fetch [ns] ints, never the [ns, V] block
                am = np.asarray(jnp.argmax(
                    logits_2d.astype(jnp.float32), axis=-1))
                for s in rows_feeding:
                    props[s].append(int(am[s]))
            else:
                full = np.asarray(logits_2d.astype(jnp.float32))
                for s in rows_feeding:
                    props[s].append(pick(s, full[s]))

        pick_all(dlast, [s for s, _, _ in staged])
        # ---- autoregressive proposal rounds (single-token feeds)
        for r in range(1, kmax):
            feeding = [s for s, _, k in staged if k > r]
            if not feeding:
                break
            ids1 = np.zeros((ns, 1), np.int32)
            cl1 = np.zeros(ns, np.int32)
            rp1 = np.zeros(ns, np.int32)
            for s in feeding:
                ids1[s, 0] = props[s][-1]
                cl1[s] = 1
                rp1[s] = int(self.draft_cur[s])
            dl1, self._draft_cache = _FWD_ROWS_JIT(
                draft, jnp.asarray(ids1), self._draft_cache,
                jnp.asarray(rp1, jnp.int32), None,
                jnp.asarray(cl1, jnp.int32))
            for s in feeding:
                self.draft_cur[s] += 1           # == cur + r + 1
            pick_all(dl1[:, 0], feeding)
        return props, qs

    def _spec_tick(self, elig):
        """One draft-and-verify round for the eligible slots. Returns
        (handled mask, emitted): handled slots advanced up to k_eff+1
        tokens and skip this tick's one-token path.

        Staging allocates verify coverage (cur + k_eff + 1 tokens) per
        slot BEFORE any device work, protecting already-staged rows from
        preemption — mirrors ``_prefill_chunks``. The ``serving.spec_verify``
        fault point fires before the donating verify jit, so an injected
        exception aborts with the cache, tables, and ledgers exactly as
        the staging left them (staged blocks live in request tables — the
        normal free path reclaims them) and the tick falls back to
        one-token decode for every slot."""
        handled = np.zeros(self.num_slots, bool)
        emitted: list = []
        ns = self.num_slots
        # ---- stage: clamp k, allocate coverage for the worst case ----
        staged = []                        # (slot, rid, k_eff)
        staged_rids: set = set()
        for slot in np.nonzero(elig)[0]:
            slot = int(slot)
            if not self.active[slot]:
                continue                   # evicted by an earlier staging
            rid = int(self.slot_req[slot])
            k_cap = int(self.slot_k[slot]) if self.spec_adaptive \
                else self.spec_k
            k_eff = min(k_cap, int(self.max_gen[slot] - self.gen[slot]) - 1)
            if k_eff < 1:
                continue
            t = self._allocate_or_preempt(
                rid, int(self.cur[slot]) + k_eff + 1, protect=staged_rids)
            if t is None:
                continue                   # dry pool: one-token path today
            self._update_resv(rid)
            self.table_len[slot] = len(t)
            staged.append((slot, rid, k_eff))
            staged_rids.add(rid)
        staged = [(s, r, k) for s, r, k in staged if self.active[s]]
        if not staged:
            return handled, emitted

        seqs = {s: self._committed_seq(s) for s, _, _ in staged}
        with _span("serving.draft", slots=len(staged)):
            props, qs = self._spec_draft(staged, seqs)

        # ---- verify: ONE batched target chunk over (slots, k_eff+1) ----
        C = self.spec_k + 1
        ids = np.zeros((ns, C), np.int32)
        clens = np.zeros(ns, np.int32)
        offs = np.zeros(ns, np.int32)
        slot_ids = np.full(ns, ns, np.int32)
        rows = np.full((ns, self.max_blocks_per_seq), self.mgr.num_blocks,
                       np.int32)
        for slot, rid, k_eff in staged:
            ids[slot, 0] = self.last_tok[slot]
            ids[slot, 1: 1 + k_eff] = props[slot][:k_eff]
            clens[slot] = k_eff + 1
            offs[slot] = self.cur[slot]
            slot_ids[slot] = slot
            t = self.mgr.tables[rid]
            rows[slot, :len(t)] = t
        try:
            # chaos hook BEFORE the donating jit: an exception here must
            # leave self.cache intact (exception atomicity) — after the
            # donation there is no cache to fall back to
            fault_point("serving.spec_verify", engine=self,
                        slots=[s for s, _, _ in staged])
        except Exception as e:
            self.stats["spec_fallbacks"] += 1
            _SPEC_FALLBACKS.inc()
            FLIGHT.record("serving.spec_fallback",
                          error=f"{type(e).__name__}: {e}")
            # draft frontiers ran ahead of the commit that never came;
            # roll them back so the next round re-feeds from the frontier
            for slot, _, _ in staged:
                self.draft_cur[slot] = min(int(self.draft_cur[slot]),
                                           int(self.cur[slot]) + 1)
            return np.zeros(self.num_slots, bool), []
        t_dev = time.perf_counter()
        with _span("serving.verify", slots=len(staged)):
            logits, self.cache = _VERIFY_CHUNK_JIT(
                self.model, jnp.asarray(ids), jnp.asarray(clens),
                jnp.asarray(offs), self.cache, jnp.asarray(slot_ids),
                jnp.asarray(rows))
            logits = np.asarray(logits.astype(jnp.float32))
        self.stats["device_s"] += time.perf_counter() - t_dev

        # ---- accept/commit per slot; ONE batched length rewind after ----
        rw_slots = np.full(ns, ns, np.int32)
        rw_lens = np.zeros(ns, np.int32)
        for slot, rid, k_eff in staged:
            temp = float(self.temps[slot])
            row = logits[slot]                        # [C, V]
            if temp == 0.0:
                vs = row[: k_eff + 1].argmax(axis=-1)
                n_acc = int(greedy_accept_length(vs[:k_eff],
                                                 props[slot][:k_eff]))
                new = [int(x) for x in props[slot][:n_acc]] \
                    + [int(vs[n_acc])]
            else:
                ps = [self._spec_probs(row[i], temp,
                                       float(self.top_ps[slot]))
                      for i in range(k_eff + 1)]
                new, n_acc = stochastic_accept_row(
                    props[slot][:k_eff], qs[slot], ps, self._spec_rs)
            cur0 = int(self.cur[slot])
            cur1 = cur0 + n_acc + 1
            self.cur[slot] = cur1
            rw_slots[slot] = slot
            rw_lens[slot] = cur1
            # draft frontier rolls back past rejected positions (stale
            # entries are overwritten by the next round's feed)
            self.draft_cur[slot] = min(int(self.draft_cur[slot]), cur1)
            if self.spec_adaptive:
                self._acc_ema[slot] = (0.5 * self._acc_ema[slot]
                                       + 0.5 * (n_acc / k_eff))
                self.slot_k[slot] = int(np.clip(
                    round(self._acc_ema[slot] * self.spec_k), 1,
                    self.spec_k))
            self.stats["spec_proposed"] += k_eff
            self.stats["spec_accepted"] += n_acc
            _SPEC_PROPOSED.inc(k_eff)
            _SPEC_ACCEPTED.inc(n_acc)
            _SPEC_TOKENS.observe(len(new))
            handled[slot] = True
            for tok in new:
                emitted += self._emit(slot, int(tok))
                if self.slot_req[slot] < 0:
                    break      # EOS/length finished the request mid-list:
                    #            the rest of the accepted tokens is moot
        if self.stats["spec_proposed"]:
            _SPEC_RATE.set(self.stats["spec_accepted"]
                           / self.stats["spec_proposed"])
        # one rewind for all staged rows: length pointers only — verify
        # wrote k_eff+1 positions, the commit kept n_acc+1 of them
        self.cache = _REWIND_LENS_JIT(self.cache, jnp.asarray(rw_slots),
                                      jnp.asarray(rw_lens))
        self.stats["spec_ticks"] += 1
        return handled, emitted

    # ------------------------------------------------------------- decode
    def _grow_tables(self, mask=None):
        """At most one new block per slot per tick; returns the incremental
        (rows, cols, vals) update triple (sentinel-padded, fixed shape).
        ``mask`` restricts growth to those slots (spec-handled slots skip
        the normal tick, so their updates must not ride a tick that may
        never run — their tables grow in the verify staging instead)."""
        rows = np.full(self.num_slots, self.num_slots, np.int32)
        cols = np.zeros(self.num_slots, np.int32)
        vals = np.zeros(self.num_slots, np.int32)
        base = (self.active & ~self.is_beam) if mask is None else mask
        crossing = base & (
            self.cur // self.block_size >= self.table_len)
        for slot in np.nonzero(crossing)[0]:     # ≤ once per bs ticks/slot
            if not self.active[slot]:
                continue                 # preempted earlier in this loop
            rid = int(self.slot_req[slot])
            t = self._allocate_or_preempt(rid, int(self.cur[slot]) + 1)
            if t is None:
                # nothing else to evict: preempt THIS slot (it re-queues
                # with its progress and resumes when blocks free up)
                if not self._preempt_from([int(slot)]):
                    raise MemoryError(
                        "paged cache out of blocks and the growing slot "
                        "is not preemptible (windowed/dynamic-rope resume "
                        "exceeds max_prompt_len)")
                continue
            self._update_resv(rid)
            rows[slot] = slot
            cols[slot] = len(t) - 1
            vals[slot] = t[-1]
            self.table_len[slot] = len(t)
        if self.window is not None:
            self._recycle_window(np.nonzero(self.active & ~self.is_beam)[0])
        return rows, cols, vals

    def _emit(self, slot: int, token: int):
        """Record one sampled token for the request in ``slot``; finish on
        EOS or length. Returns [(req_id, token)]."""
        rid = int(self.slot_req[slot])
        if rid < 0:
            return []        # slot emptied mid-tick (stream-side cancel)
        req = self.requests[rid]
        req.tokens.append(token)
        _TOKENS.inc()
        now = self._clock()
        if req._first_tok_t is None:
            req._first_tok_t = now
            if req._submit_t is not None:
                _TTFT.observe(max(0.0, now - req._submit_t))
        elif req._last_tok_t is not None:
            _TOK_LAT.observe(max(0.0, now - req._last_tok_t))
        req._last_tok_t = now
        if req.stream is not None:
            req.stream(req, token)
        self.last_tok[slot] = token
        self.gen[slot] += 1
        eos = self.eos_token_id is not None and token == self.eos_token_id
        if eos or self.gen[slot] >= self.max_gen[slot]:
            req.done = True
            req.finish_reason = "eos" if eos else "length"
            _FINISHED.inc(reason=req.finish_reason)
            self.mgr.free(rid)
            self._reserved -= self._resv.pop(rid, 0)
            self._need.pop(rid, None)
            self.active[slot] = False
            self.slot_req[slot] = -1
        return [(rid, token)]

    def _refresh_gauges(self):
        """Point-in-time engine state → gauges (queue depth, active
        slots, KV-pool utilization). Called after every tick and intake
        mutation; cheap enough to never matter."""
        _QUEUE_DEPTH.set(len(self.queue))
        _ACTIVE_SLOTS.set(int(self.active.sum()))
        used = self.mgr.num_blocks - self.mgr.free_blocks
        _KV_IN_USE.set(used)
        _KV_UTIL.set(used / self.mgr.num_blocks if self.mgr.num_blocks
                     else 0.0)
        stats = getattr(self.mgr, "cache_stats", None)
        if stats is not None:
            # counters are process-global and cumulative; the manager's
            # stats are per-engine — push only what this engine added
            # since the last refresh
            _PREFIX_HITS.inc(stats["hit_blocks"]
                             - self._prefix_pushed["hit_blocks"])
            _PREFIX_EVICTIONS.inc(stats["evictions"]
                                  - self._prefix_pushed["evictions"])
            self._prefix_pushed = dict(stats)
            _PREFIX_HIT_RATE.set(stats["hit_blocks"]
                                 / max(stats["lookup_blocks"], 1))

    def step(self):
        """One engine tick — see :meth:`_step_impl`. Wrapped here so the
        tick lands in the trace timeline and the tick-duration histogram
        even when a chaos rule or a dry pool raises out of the middle."""
        from time import monotonic
        t0 = monotonic()
        try:
            with _span("serving.step"):
                return self._step_impl()
        finally:
            _TICK.observe(monotonic() - t0)
            self._refresh_gauges()

    def _step_impl(self):
        """One engine tick: advance in-flight beam groups (select + fork,
        or their final selection), admit waiting requests into free slots
        (their prefill runs now, interleaved with decode), then one decode
        tick for every active slot. Returns [(req_id, new_token), ...]
        (a finishing beam request emits its whole best hypothesis)."""
        from time import perf_counter
        # chaos hooks: serving.tick may raise/stall; serving.preempt rules
        # receive the engine and typically call engine._preempt() to
        # induce a preemption the pool never asked for
        fault_point("serving.tick", engine=self)
        fault_point("serving.preempt", engine=self)
        self._expire()
        emitted = []
        for rid in list(self.groups):
            emitted += self._beam_advance(rid, self.groups[rid])
        admits, beam_admits = self._admit()
        if admits or beam_admits:
            emitted += self._prefill(admits, beam_admits)
        emitted += self._prefill_chunks()
        if not self.active.any():
            return emitted
        # speculative draft-and-verify for eligible slots; the plain
        # one-token tick then covers only what speculation did not handle
        # (beam slots, final-token slots, fallback after an injected
        # verify fault). PT_SPEC_DECODE=0 kills the whole path.
        spec_handled = np.zeros(self.num_slots, bool)
        if (self.draft_model is not None
                and os.environ.get("PT_SPEC_DECODE", "1") != "0"):
            elig = (self.active & ~self.is_beam
                    & (self.max_gen - self.gen >= 2))
            if elig.any():
                spec_handled, spec_emitted = self._spec_tick(elig)
                emitted += spec_emitted
        run_mask = self.active & ~spec_handled
        if not run_mask.any():
            # every active slot advanced speculatively: the whole point —
            # this tick paid ONE target forward for k+1 positions per slot
            return emitted
        t0 = perf_counter()
        rows, cols, vals = self._grow_tables(run_mask & ~self.is_beam)
        # growth may have preempted slots — recompute the mask after it
        run_mask = self.active & ~spec_handled
        self.rng, sub = jax.random.split(self.rng)
        if self._is_moe:
            # chaos: a dead expert shard fails the token all_to_all. Fires
            # BEFORE the donating tick jit, so an injected exception aborts
            # the tick with the cache intact and every grown block still
            # owned by its request's table — cancel/free reclaims them and
            # assert_quiescent stays clean (exception-atomic).
            fault_point("serving.moe_dispatch", engine=self,
                        slots=np.nonzero(run_mask)[0])
        t1 = perf_counter()
        nxt, logp, self.cache = _TICK_JIT(
            self.model, jnp.asarray(self.last_tok), self.cache,
            jnp.asarray(run_mask), jnp.asarray(rows), jnp.asarray(cols),
            jnp.asarray(vals), sub, jnp.asarray(self.temps),
            jnp.asarray(self.top_ps), self.top_k, bool(self.groups))
        was_active = run_mask.copy()
        nxt = np.asarray(nxt)                 # the one per-tick host fetch
        t2 = perf_counter()
        for g in self.groups.values():        # device-resident, lazy gather
            g.logp = logp[np.asarray(g.slots)]
        self.cur += was_active                # vectorised mirrors
        for slot in np.nonzero(was_active & ~self.is_beam)[0]:
            emitted += self._emit(slot, int(nxt[slot]))
        t3 = perf_counter()
        self.stats["host_s"] += (t1 - t0) + (t3 - t2)
        self.stats["device_s"] += t2 - t1
        self.stats["ticks"] += 1
        return emitted

    def run(self) -> dict:
        """Drain queue + slots; returns {req_id: generated token list}."""
        while self.has_work():
            self.step()
        return {rid: r.tokens for rid, r in self.requests.items()}
