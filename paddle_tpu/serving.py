"""Continuous-batching LLM serving engine.

Ref capability: PaddleNLP ``llm/predict/predictor.py`` block-attention
serving (request queue + block KV cache + ``fused_multi_transformer``'s
block cache ops). TPU-native split:

  * DEVICE — two fixed-shape jitted programs from ``models/paged.py``:
    slot-aware prefill (admitted prompts written into their cache slots
    while other slots keep decoding state) and the fused decode tick
    (incremental block-table update + paged attention + on-device
    sampling). Shapes never change across ticks, so nothing recompiles.
  * HOST — this module: FCFS request queue, slot assignment, block
    reservation/allocation (BlockManager), streaming outputs. All per-tick
    bookkeeping is vectorised numpy; the only per-tick device→host
    traffic is the [num_slots] sampled-token fetch.

Capacity discipline: a request is admitted only when the pool can cover
its WHOLE worst case (prompt + max_new_tokens) net of other in-flight
reservations — blocks are still allocated lazily (pool usage ≈ Σ live
lengths), but an admitted request can never hit an out-of-blocks
condition mid-decode (there is no preemption to recover with).
"""
from __future__ import annotations

import itertools
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models.decoding import _sample
from paddle_tpu.models.paged import (BlockManager, PagedKVCache,
                                     _PREFILL_JIT, _TICK_JIT)

# module-level so its compile cache persists across admissions
_SAMPLE_JIT = jax.jit(_sample, static_argnums=(2, 3, 4))


@dataclass
class Request:
    """One generation request. ``stream`` (optional) is called as
    ``stream(request, token)`` the tick each new token is sampled."""
    prompt: object                       # 1-D int tokens
    max_new_tokens: int = 32
    req_id: int = None
    stream: object = None
    # filled by the engine:
    tokens: list = field(default_factory=list)   # generated tokens
    done: bool = False
    finish_reason: str = None

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)


class LLMEngine:
    """Continuous-batching engine over a shared paged KV pool.

    ``num_slots`` concurrent sequences; queued requests are admitted
    MID-FLIGHT into slots freed by finished ones (prefill interleaves with
    decode ticks). ``step()`` is one engine tick; ``run()`` drains
    everything and returns {req_id: full token list}.
    """

    def __init__(self, model, *, num_slots=8, block_size=16,
                 max_prompt_len=128, max_seq_len=None, num_blocks=None,
                 eos_token_id=None, temperature=0.0, top_k=None, top_p=None,
                 seed=0):
        cfg = model.cfg
        self.model = model
        self.num_slots = num_slots
        self.block_size = block_size
        self.max_prompt_len = max_prompt_len
        self.max_seq_len = max_seq_len or (max_prompt_len + 256)
        self.max_blocks_per_seq = -(-self.max_seq_len // block_size)
        if num_blocks is None:
            num_blocks = num_slots * self.max_blocks_per_seq
        self.mgr = BlockManager(num_blocks, block_size)
        self.eos_token_id = eos_token_id
        self.sampling = (float(temperature), top_k, top_p)
        self.rng = jax.random.PRNGKey(seed)
        # sliding-window models: blocks entirely below cur - window are
        # never attended again (the paged kernel KEEPS only positions
        # >= lens - window, masking everything below) — recycle them,
        # bounding live blocks per sequence by O(window), not O(length)
        self.window = getattr(cfg, "sliding_window", None)

        self.cache = PagedKVCache.init(
            cfg.num_hidden_layers, num_blocks, block_size,
            cfg.num_key_value_heads,
            cfg.hidden_size // cfg.num_attention_heads,
            num_slots, self.max_blocks_per_seq, cfg.dtype)

        # host mirrors (vectorised bookkeeping — no per-token python loops)
        self.slot_req = np.full(num_slots, -1, np.int64)   # req_id or -1
        self.active = np.zeros(num_slots, bool)
        self.cur = np.zeros(num_slots, np.int64)     # tokens stored in cache
        self.gen = np.zeros(num_slots, np.int64)     # tokens generated
        self.max_gen = np.zeros(num_slots, np.int64)
        self.table_len = np.zeros(num_slots, np.int64)
        self.last_tok = np.zeros(num_slots, np.int32)

        self.queue: deque[Request] = deque()
        self.requests: dict[int, Request] = {}
        self._ids = itertools.count()
        self._reserved = 0           # blocks promised to in-flight requests
        self._resv: dict[int, int] = {}    # req_id -> outstanding reserve
        self._need: dict[int, int] = {}    # req_id -> worst-case blocks
        # host-vs-device split of decode ticks (admission ticks excluded):
        # stats["host_s"] is scheduling/bookkeeping, stats["device_s"] the
        # jitted tick incl. the [num_slots] token fetch
        self.stats = {"host_s": 0.0, "device_s": 0.0, "ticks": 0}

    # ------------------------------------------------------------- intake
    def add_request(self, req: Request) -> int:
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (the prefill "
                             "itself produces the first token)")
        if len(req.prompt) < 1:
            raise ValueError("prompt must contain at least one token "
                             "(an empty row has no logit to sample from)")
        if len(req.prompt) > self.max_prompt_len:
            raise ValueError(f"prompt length {len(req.prompt)} exceeds "
                             f"max_prompt_len={self.max_prompt_len}")
        if len(req.prompt) + req.max_new_tokens > self.max_seq_len:
            raise ValueError("prompt + max_new_tokens exceeds max_seq_len")
        if self._worst_case_blocks(req) > self.mgr.num_blocks:
            raise ValueError(
                "request worst case exceeds the WHOLE block pool — it "
                "could never be admitted (raise num_blocks)")
        if req.req_id is None:
            req.req_id = next(self._ids)
        else:
            if req.req_id in self.requests:
                # a duplicate id would alias the BlockManager table AND
                # the reservation ledger of the in-flight request
                raise ValueError(f"req_id {req.req_id} already exists")
            # keep auto ids from ever colliding with explicit ones
            self._ids = itertools.count(
                max(req.req_id + 1, next(self._ids)))
        self.requests[req.req_id] = req
        self.queue.append(req)
        return req.req_id

    def pop_finished(self) -> dict:
        """Remove and return completed requests ({req_id: Request}) — call
        periodically from a long-running serve loop so the engine does not
        retain every finished request's token list forever."""
        done = {rid: r for rid, r in self.requests.items() if r.done}
        for rid in done:
            del self.requests[rid]
        return done

    def generate(self, prompt, **kw) -> int:
        return self.add_request(Request(prompt, **kw))

    def has_work(self) -> bool:
        return bool(self.queue) or bool(self.active.any())

    def _worst_case_blocks(self, req) -> int:
        """Blocks a request can ever hold at once. Windowed models recycle
        below-window blocks, so the live span is bounded by the window
        (plus the write-frontier block) — but prefill scatters the WHOLE
        prompt before any recycling, so that is a floor."""
        total = len(req.prompt) + req.max_new_tokens
        if self.window is None:
            return self.mgr.blocks_needed(total)
        live = self.mgr.blocks_needed(
            min(total, self.window + 2 * self.block_size))
        return max(self.mgr.blocks_needed(len(req.prompt)), live)

    # ---------------------------------------------------------- admission
    def _admit(self):
        """FCFS: move queued requests into free slots while the pool can
        cover their worst case; returns the admitted (slot, req) pairs."""
        free_slots = np.nonzero(self.slot_req < 0)[0]
        admits = []
        for slot in free_slots:
            if not self.queue:
                break
            req = self.queue[0]
            need = self._worst_case_blocks(req)
            if need > self.mgr.free_blocks - self._reserved:
                break                      # FCFS: do not starve the head
            self.queue.popleft()
            self.mgr.allocate(req.req_id, len(req.prompt))
            self._need[req.req_id] = need
            self._resv[req.req_id] = 0
            self._update_resv(req.req_id)
            admits.append((int(slot), req))
        return admits

    def _live_blocks(self, rid: int) -> int:
        return sum(b is not None for b in self.mgr.tables.get(rid, []))

    def _update_resv(self, rid: int):
        """Outstanding reserve = worst case minus blocks currently held
        (recycling under a sliding window RETURNS headroom)."""
        new = max(0, self._need[rid] - self._live_blocks(rid))
        self._reserved += new - self._resv[rid]
        self._resv[rid] = new

    def _recycle_window(self, slots):
        """Free blocks entirely below cur - window for the given slots —
        live blocks per sequence stay O(window). Host-only: the paged
        kernel masks every position BELOW lens - window, so stale table
        entries pointing at recycled (even reused) blocks are never
        read."""
        for slot in slots:
            rid = int(self.slot_req[slot])
            dead = int(max(0, self.cur[slot] - self.window)
                       ) // self.block_size
            if dead > 0 and self.mgr.free_prefix(rid, dead):
                self._update_resv(rid)

    def _prefill(self, admits):
        a_cap = self.num_slots           # one compiled admission shape
        ids = np.zeros((a_cap, self.max_prompt_len), np.int32)
        lens = np.zeros(a_cap, np.int32)
        slots = np.full(a_cap, self.num_slots, np.int32)   # sentinel = drop
        rows = np.full((a_cap, self.max_blocks_per_seq),
                       self.mgr.num_blocks, np.int32)
        for i, (slot, req) in enumerate(admits):
            ids[i, :len(req.prompt)] = req.prompt
            lens[i] = len(req.prompt)
            slots[i] = slot
            t = self.mgr.tables[req.req_id]
            rows[i, :len(t)] = t
            self.slot_req[slot] = req.req_id
            self.active[slot] = True
            self.cur[slot] = len(req.prompt)
            self.gen[slot] = 0
            self.max_gen[slot] = req.max_new_tokens
            self.table_len[slot] = len(t)
        logits, self.cache = _PREFILL_JIT(
            self.model, jnp.asarray(ids), jnp.asarray(lens),
            self.cache, jnp.asarray(slots), jnp.asarray(rows))
        self.rng, sub = jax.random.split(self.rng)
        first = np.asarray(_SAMPLE_JIT(logits.astype(jnp.float32), sub,
                                       *self.sampling))
        if self.window is not None:
            # a long prompt's below-window blocks die the moment prefill
            # has scattered them — and from here on the sequence can never
            # hold more than the window live bound, so relax its
            # reservation too (the prompt-size floor only mattered DURING
            # prefill)
            self._recycle_window([slot for slot, _ in admits])
            live_bound = self.mgr.blocks_needed(
                self.window + 2 * self.block_size)
            for slot, req in admits:
                rid = req.req_id
                self._need[rid] = min(self._need[rid], live_bound)
                self._update_resv(rid)
        emitted = []
        for i, (slot, req) in enumerate(admits):
            emitted += self._emit(slot, int(first[i]))
        return emitted

    # ------------------------------------------------------------- decode
    def _grow_tables(self):
        """At most one new block per slot per tick; returns the incremental
        (rows, cols, vals) update triple (sentinel-padded, fixed shape)."""
        rows = np.full(self.num_slots, self.num_slots, np.int32)
        cols = np.zeros(self.num_slots, np.int32)
        vals = np.zeros(self.num_slots, np.int32)
        crossing = self.active & (self.cur // self.block_size
                                  >= self.table_len)
        for slot in np.nonzero(crossing)[0]:     # ≤ once per bs ticks/slot
            rid = int(self.slot_req[slot])
            t = self.mgr.allocate(rid, int(self.cur[slot]) + 1)
            self._update_resv(rid)
            rows[slot] = slot
            cols[slot] = len(t) - 1
            vals[slot] = t[-1]
            self.table_len[slot] = len(t)
        if self.window is not None:
            self._recycle_window(np.nonzero(self.active)[0])
        return rows, cols, vals

    def _emit(self, slot: int, token: int):
        """Record one sampled token for the request in ``slot``; finish on
        EOS or length. Returns [(req_id, token)]."""
        rid = int(self.slot_req[slot])
        req = self.requests[rid]
        req.tokens.append(token)
        if req.stream is not None:
            req.stream(req, token)
        self.last_tok[slot] = token
        self.gen[slot] += 1
        eos = self.eos_token_id is not None and token == self.eos_token_id
        if eos or self.gen[slot] >= self.max_gen[slot]:
            req.done = True
            req.finish_reason = "eos" if eos else "length"
            self.mgr.free(rid)
            self._reserved -= self._resv.pop(rid, 0)
            self._need.pop(rid, None)
            self.active[slot] = False
            self.slot_req[slot] = -1
        return [(rid, token)]

    def step(self):
        """One engine tick: admit waiting requests into free slots (their
        prefill runs now, interleaved with decode), then one decode tick
        for every active slot. Returns [(req_id, new_token), ...]."""
        from time import perf_counter
        emitted = []
        admits = self._admit()
        if admits:
            emitted += self._prefill(admits)
        if not self.active.any():
            return emitted
        t0 = perf_counter()
        rows, cols, vals = self._grow_tables()
        self.rng, sub = jax.random.split(self.rng)
        t1 = perf_counter()
        nxt, self.cache = _TICK_JIT(
            self.model, jnp.asarray(self.last_tok), self.cache,
            jnp.asarray(self.active), jnp.asarray(rows), jnp.asarray(cols),
            jnp.asarray(vals), sub, *self.sampling)
        was_active = self.active.copy()
        nxt = np.asarray(nxt)                 # the one per-tick host fetch
        t2 = perf_counter()
        self.cur += was_active                # vectorised mirrors
        for slot in np.nonzero(was_active)[0]:
            emitted += self._emit(slot, int(nxt[slot]))
        t3 = perf_counter()
        self.stats["host_s"] += (t1 - t0) + (t3 - t2)
        self.stats["device_s"] += t2 - t1
        self.stats["ticks"] += 1
        return emitted

    def run(self) -> dict:
        """Drain queue + slots; returns {req_id: generated token list}."""
        while self.has_work():
            self.step()
        return {rid: r.tokens for rid, r in self.requests.items()}
