"""ERNIE-M (ref: PaddleNLP ``paddlenlp/transformers/ernie_m/modeling.py``
— Baidu's multilingual ERNIE, cross-lingual aligned pretraining).

Post-LN encoder with the ERNIE-M embedding quirk: NO token-type stream,
and positions offset by +2 (the PaddleNLP convention the HF port
mimics). Same MultiHeadAttention blocks as the rest of the encoder zoo.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Embedding, LayerNorm, Linear
from paddle_tpu.nn.transformer import MultiHeadAttention


@dataclass
class ErnieMConfig:
    vocab_size: int = 250002
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 514
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    @staticmethod
    def tiny(**kw):
        return ErnieMConfig(**{**dict(vocab_size=128, hidden_size=32,
                                      num_hidden_layers=2,
                                      num_attention_heads=2,
                                      intermediate_size=64,
                                      max_position_embeddings=66), **kw})


class ErnieMLayer(Module):
    def __init__(self, cfg: ErnieMConfig):
        super().__init__()
        h = cfg.hidden_size
        self.self_attn = MultiHeadAttention(h, cfg.num_attention_heads,
                                            dtype=cfg.dtype)
        self.norm1 = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                               dtype=cfg.dtype)
        self.linear1 = Linear(h, cfg.intermediate_size, dtype=cfg.dtype)
        self.linear2 = Linear(cfg.intermediate_size, h, dtype=cfg.dtype)
        self.norm2 = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                               dtype=cfg.dtype)

    def __call__(self, x, attn_mask=None):
        x = self.norm1(x + self.self_attn(x, attn_mask=attn_mask))
        return self.norm2(x + self.linear2(F.gelu(self.linear1(x))))


class ErnieMModel(Module):
    def __init__(self, cfg: ErnieMConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        h = cfg.hidden_size
        self.word_embeddings = Embedding(cfg.vocab_size, h,
                                         weight_init=init, dtype=cfg.dtype)
        self.position_embeddings = Embedding(cfg.max_position_embeddings, h,
                                             weight_init=init,
                                             dtype=cfg.dtype)
        self.emb_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.layers = [ErnieMLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]
        self.pooler = Linear(h, h, dtype=cfg.dtype)

    def __call__(self, input_ids, attention_mask=None):
        s = input_ids.shape[1]
        if attention_mask is not None:
            attention_mask = (1.0 - attention_mask[:, None, None, :]
                              .astype(jnp.float32)) * -1e9
        # the PaddleNLP +2 position offset (no token-type stream)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(jnp.arange(2, s + 2)[None, :]))
        x = self.emb_norm(x)
        for lyr in self.layers:
            x = lyr(x, attn_mask=attention_mask)
        pooled = jnp.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieMForSequenceClassification(Module):
    def __init__(self, cfg: ErnieMConfig, num_classes: int = 2):
        super().__init__()
        self.ernie_m = ErnieMModel(cfg)
        self.classifier = Linear(cfg.hidden_size, num_classes,
                                 dtype=cfg.dtype)

    def __call__(self, input_ids, attention_mask=None):
        _, pooled = self.ernie_m(input_ids, attention_mask)
        return self.classifier(pooled)
