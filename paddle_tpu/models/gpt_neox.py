"""GPT-NeoX decoder LM (ref capability: PaddleNLP ``gpt_neox`` /
Pythia-family checkpoints; ``paddlenlp.transformers`` GPTNeoX classes).

The partial-rotary, parallel-residual member of the model zoo:
  * rope covers only the first ``rotary_pct`` of each head's dims
    (Pythia: 25%); the rest pass through unrotated.
  * ``use_parallel_residual``: attention and MLP both read the SAME block
    input through their own LayerNorms and their outputs are summed with
    the residual in one step — one residual add per block, not two. (The
    sequential form is also supported for the few non-parallel configs.)
  * fused head-interleaved QKV in HF ([nh, 3, d] out-dim layout),
    re-laid out to [q|k|v] blocks at load (convert.py), untied embed_out.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import LayerNorm
from paddle_tpu.ops import attention as A


@dataclass
class GPTNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    rotary_pct: float = 0.25
    rotary_emb_base: float = 10000.0
    max_position_embeddings: int = 2048
    use_parallel_residual: bool = True
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    dtype: object = None
    remat: bool = True

    def __post_init__(self):
        if self.dtype is None:
            self.dtype = get_default_dtype()

    @staticmethod
    def tiny(**kw):
        return GPTNeoXConfig(**{**dict(vocab_size=128, hidden_size=32,
                                       num_hidden_layers=2,
                                       num_attention_heads=4,
                                       intermediate_size=64,
                                       max_position_embeddings=64,
                                       dtype=jnp.float32, remat=False),
                                **kw})


def _rope_partial(x, cos, sin, rot_dims):
    """Rotate only the first ``rot_dims`` of the head dim (NeoX partial
    rotary); the tail passes through."""
    rot, rest = x[..., :rot_dims], x[..., rot_dims:]
    return jnp.concatenate([A.apply_rope(rot, cos, sin), rest], axis=-1)


class GPTNeoXLayer(Module):
    def __init__(self, cfg: GPTNeoXConfig):
        super().__init__()
        h = cfg.hidden_size
        init = I.Normal(0.0, cfg.initializer_range)
        self.input_layernorm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                         dtype=cfg.dtype)
        self.post_attention_layernorm = LayerNorm(
            h, epsilon=cfg.layer_norm_eps, dtype=cfg.dtype)
        # our layout: [h, 3h] columns = [q all heads | k | v]
        self.qkv = init((h, 3 * h), cfg.dtype)
        self.qkv_bias = jnp.zeros((3 * h,), cfg.dtype)
        self.dense = init((h, h), cfg.dtype)
        self.dense_bias = jnp.zeros((h,), cfg.dtype)
        self.h_to_4h = init((h, cfg.intermediate_size), cfg.dtype)
        self.h_to_4h_bias = jnp.zeros((cfg.intermediate_size,), cfg.dtype)
        self.four_h_to_h = init((cfg.intermediate_size, h), cfg.dtype)
        self.four_h_to_h_bias = jnp.zeros((h,), cfg.dtype)
        self.n_head = cfg.num_attention_heads
        self.parallel = cfg.use_parallel_residual

    def _attn(self, h, cos, sin, rot_dims):
        b, s, hd = h.shape
        nh = self.n_head
        d = hd // nh
        qkv = h @ self.qkv + self.qkv_bias
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = _rope_partial(q.reshape(b, s, nh, d), cos, sin, rot_dims)
        k = _rope_partial(k.reshape(b, s, nh, d), cos, sin, rot_dims)
        att = A.scaled_dot_product_attention(q, k, v.reshape(b, s, nh, d),
                                             is_causal=True)
        return att.reshape(b, s, hd) @ self.dense + self.dense_bias

    def _mlp(self, h):
        m = jax.nn.gelu(h @ self.h_to_4h + self.h_to_4h_bias,
                        approximate=False)
        return m @ self.four_h_to_h + self.four_h_to_h_bias

    def __call__(self, x, cos, sin, rot_dims):
        att = self._attn(self.input_layernorm(x), cos, sin, rot_dims)
        if self.parallel:
            return x + att + self._mlp(self.post_attention_layernorm(x))
        x = x + att
        return x + self._mlp(self.post_attention_layernorm(x))


class GPTNeoXForCausalLM(Module):
    def __init__(self, cfg: GPTNeoXConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        h = cfg.hidden_size
        self.embed_in = init((cfg.vocab_size, h), cfg.dtype)
        self.layers = [GPTNeoXLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]
        self.final_layer_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                          dtype=cfg.dtype)
        self.embed_out = init((h, cfg.vocab_size), cfg.dtype)  # untied

    def __call__(self, input_ids):
        cfg = self.cfg
        s = input_ids.shape[1]
        d = cfg.hidden_size // cfg.num_attention_heads
        rot = int(d * cfg.rotary_pct)
        cos, sin = A.rope_cos_sin(s, rot, base=cfg.rotary_emb_base)
        x = jnp.take(self.embed_in, input_ids, axis=0)
        blk = (jax.checkpoint(lambda lyr, h: lyr(h, cos, sin, rot))
               if cfg.remat else (lambda lyr, h: lyr(h, cos, sin, rot)))
        for lyr in self.layers:
            x = blk(lyr, x)
        x = self.final_layer_norm(x)
        return x @ self.embed_out

    def loss(self, input_ids, labels):
        logits = self(input_ids).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, jnp.maximum(labels, 0)[..., None],
                                 axis=-1)[..., 0]
        mask = (labels >= 0).astype(jnp.float32)
        return -jnp.sum(ll * mask) / jnp.maximum(mask.sum(), 1.0)
