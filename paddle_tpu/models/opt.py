"""OPT decoder LM (ref capability: PaddleNLP ``opt`` model family /
``paddlenlp.transformers.OPTForCausalLM``).

The learned-position member of the model zoo: no rotary/ALiBi — positions
come from a trained embedding table read at ``position + 2`` (the HF
offset convention, inherited from fairseq's padding index). Architecture
(HF ``OPTModel``): word embeddings (optionally projected in/out when
``word_embed_proj_dim != hidden_size``, the 350m shape), blocks of
[LN -> MHA -> LN -> fc1 relu fc2] — pre-norm when
``do_layer_norm_before`` (everything except 350m), post-norm otherwise —
final LN (pre-norm only), lm head tied to the word embeddings.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import LayerNorm
from paddle_tpu.ops import attention as A


@dataclass
class OPTConfig:
    vocab_size: int = 50272
    hidden_size: int = 768
    ffn_dim: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 2048
    do_layer_norm_before: bool = True
    word_embed_proj_dim: int = None      # != hidden_size only for 350m
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    dtype: object = None
    remat: bool = True

    def __post_init__(self):
        if self.dtype is None:
            self.dtype = get_default_dtype()
        if self.word_embed_proj_dim is None:
            self.word_embed_proj_dim = self.hidden_size

    @staticmethod
    def tiny(**kw):
        return OPTConfig(**{**dict(vocab_size=128, hidden_size=32,
                                   ffn_dim=64, num_hidden_layers=2,
                                   num_attention_heads=4,
                                   max_position_embeddings=64,
                                   dtype=jnp.float32, remat=False), **kw})


class OPTDecoderLayer(Module):
    def __init__(self, cfg: OPTConfig):
        super().__init__()
        h = cfg.hidden_size
        init = I.Normal(0.0, cfg.initializer_range)
        self.self_attn_layer_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                              dtype=cfg.dtype)
        self.q_proj = init((h, h), cfg.dtype)
        self.k_proj = init((h, h), cfg.dtype)
        self.v_proj = init((h, h), cfg.dtype)
        self.out_proj = init((h, h), cfg.dtype)
        self.q_bias = jnp.zeros((h,), cfg.dtype)
        self.k_bias = jnp.zeros((h,), cfg.dtype)
        self.v_bias = jnp.zeros((h,), cfg.dtype)
        self.out_bias = jnp.zeros((h,), cfg.dtype)
        self.final_layer_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                          dtype=cfg.dtype)
        self.fc1 = init((h, cfg.ffn_dim), cfg.dtype)
        self.fc1_bias = jnp.zeros((cfg.ffn_dim,), cfg.dtype)
        self.fc2 = init((cfg.ffn_dim, h), cfg.dtype)
        self.fc2_bias = jnp.zeros((h,), cfg.dtype)
        self.n_head = cfg.num_attention_heads
        self.pre_norm = cfg.do_layer_norm_before

    def __call__(self, x):
        b, s, hd = x.shape
        nh = self.n_head
        d = hd // nh
        h = self.self_attn_layer_norm(x) if self.pre_norm else x
        q = (h @ self.q_proj + self.q_bias).reshape(b, s, nh, d)
        k = (h @ self.k_proj + self.k_bias).reshape(b, s, nh, d)
        v = (h @ self.v_proj + self.v_bias).reshape(b, s, nh, d)
        att = A.scaled_dot_product_attention(q, k, v, is_causal=True)
        x = x + att.reshape(b, s, hd) @ self.out_proj + self.out_bias
        if not self.pre_norm:
            x = self.self_attn_layer_norm(x)
        h2 = self.final_layer_norm(x) if self.pre_norm else x
        m = jax.nn.relu(h2 @ self.fc1 + self.fc1_bias)
        x = x + m @ self.fc2 + self.fc2_bias
        if not self.pre_norm:
            x = self.final_layer_norm(x)
        return x


class OPTForCausalLM(Module):
    def __init__(self, cfg: OPTConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        e = cfg.word_embed_proj_dim
        h = cfg.hidden_size
        self.embed_tokens = init((cfg.vocab_size, e), cfg.dtype)
        # HF offset: row p+2 holds position p (fairseq padding heritage)
        self.embed_positions = init((cfg.max_position_embeddings + 2, h),
                                    cfg.dtype)
        self.project_in = None if e == h else init((e, h), cfg.dtype)
        self.project_out = None if e == h else init((h, e), cfg.dtype)
        self.layers = [OPTDecoderLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]
        self.final_layer_norm = (LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                           dtype=cfg.dtype)
                                 if cfg.do_layer_norm_before else None)

    def __call__(self, input_ids):
        cfg = self.cfg
        s = input_ids.shape[1]
        x = jnp.take(self.embed_tokens, input_ids, axis=0)
        if self.project_in is not None:
            x = x @ self.project_in
        x = x + self.embed_positions[2: s + 2][None]
        blk = jax.checkpoint(lambda lyr, h: lyr(h)) if cfg.remat \
            else (lambda lyr, h: lyr(h))
        for lyr in self.layers:
            x = blk(lyr, x)
        if self.final_layer_norm is not None:
            x = self.final_layer_norm(x)
        if self.project_out is not None:
            x = x @ self.project_out
        return x @ self.embed_tokens.T       # tied head

    def loss(self, input_ids, labels):
        logits = self(input_ids).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, jnp.maximum(labels, 0)[..., None],
                                 axis=-1)[..., 0]
        mask = (labels >= 0).astype(jnp.float32)
        return -jnp.sum(ll * mask) / jnp.maximum(mask.sum(), 1.0)
