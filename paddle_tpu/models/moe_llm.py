"""MoE decoder LM — the ERNIE-MoE-class expert-parallel model family
(ref: the reference's ERNIE-MoE baseline config exercising ``c_alltoall``;
``paddle/incubate/distributed/models/moe``).

A LLaMA-style decoder whose MLP is a top-2 MoELayer every `moe_every` layers;
experts ride the (dp, fsdp) axes (expert parallel), attention stays tp-sharded.
The gate aux loss is summed into the LM loss.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.distributed.moe import MoELayer
from paddle_tpu.models.llama import (
    LlamaAttention,
    LlamaConfig,
    LlamaMLP,
    LlamaRMSNorm,
)
from paddle_tpu.nn import initializer as I
from paddle_tpu.ops import attention as A
from jax.sharding import PartitionSpec as P


@dataclass
class MoEConfig:
    base: LlamaConfig = None
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    moe_every: int = 2          # every k-th layer is MoE
    aux_loss_weight: float = 0.01

    @staticmethod
    def tiny(**kw):
        return MoEConfig(base=LlamaConfig.tiny(), **kw)


class MoEDecoderLayer(Module):
    def __init__(self, cfg: MoEConfig):
        super().__init__()
        b = cfg.base
        self.input_layernorm = LlamaRMSNorm(b.hidden_size, b.rms_norm_eps, b.dtype)
        self.self_attn = LlamaAttention(b)
        self.post_attention_layernorm = LlamaRMSNorm(b.hidden_size, b.rms_norm_eps, b.dtype)
        self.moe = MoELayer(b.hidden_size, b.intermediate_size, cfg.num_experts,
                            k=cfg.top_k, capacity_factor=cfg.capacity_factor,
                            dtype=b.dtype)

    def __call__(self, x, cos, sin):
        x = x + self.self_attn(self.input_layernorm(x), cos, sin)
        y, aux = self.moe(self.post_attention_layernorm(x))
        return x + y, aux


class MoEForCausalLM(Module):
    def __init__(self, cfg: MoEConfig):
        super().__init__()
        self.cfg = cfg
        b = cfg.base
        init = I.Normal(0.0, b.initializer_range)
        self.embed_tokens = init((b.vocab_size, b.hidden_size), b.dtype)
        self.set_pspec("embed_tokens", P("tp", None))
        self.layers = []
        from paddle_tpu.models.llama import LlamaDecoderLayer
        for i in range(b.num_hidden_layers):
            if (i + 1) % cfg.moe_every == 0:
                self.layers.append(MoEDecoderLayer(cfg))
            else:
                self.layers.append(LlamaDecoderLayer(b))
        self.norm = LlamaRMSNorm(b.hidden_size, b.rms_norm_eps, b.dtype)
        self.lm_head = init((b.hidden_size, b.vocab_size), b.dtype)
        self.set_pspec("lm_head", P(None, "tp"))

    def __call__(self, input_ids):
        b_cfg = self.cfg.base
        x = jnp.take(self.embed_tokens, input_ids, axis=0)
        cos, sin = A.rope_cos_sin(input_ids.shape[1],
                                  b_cfg.hidden_size // b_cfg.num_attention_heads,
                                  base=b_cfg.rope_theta)
        aux_total = jnp.zeros((), jnp.float32)
        for lyr in self.layers:
            if isinstance(lyr, MoEDecoderLayer):
                x, aux = lyr(x, cos, sin)
                aux_total = aux_total + aux
            else:
                x = lyr(x, cos, sin)
        x = self.norm(x)
        from paddle_tpu.quantization import wo_matmul
        return wo_matmul(x, self.lm_head), aux_total

    def loss(self, input_ids, labels):
        from paddle_tpu.distributed.tensor_parallel import parallel_cross_entropy
        logits, aux = self(input_ids)
        per_tok = parallel_cross_entropy(logits, jnp.maximum(labels, 0))
        mask = (labels >= 0).astype(jnp.float32)
        lm = jnp.sum(per_tok * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return lm + self.cfg.aux_loss_weight * aux
