"""Pretrained-weight conversion (ref capability: PaddleNLP
``from_pretrained`` / ``convert_torch_to_paddle`` weight mapping).

Loads HuggingFace-format checkpoints (a ``state_dict``-like mapping of
numpy/torch arrays, e.g. from a local ``transformers`` model or a
safetensors file) into the fused TPU layouts used here:

  * q/k/v projections fuse into one [h, (nh+2*nkv)*d] matmul
    (HF stores [out, in] per projection — transposed + concatenated);
  * gate/up fuse into one [h, 2m];
  * lm_head transposes to [h, vocab].

Covers the LLaMA family (LLaMA / Mistral / Qwen2 — Qwen2 adds q/k/v
biases), GPT-2 (Conv1D [in, out] layout), T5 (v1.0 relu tied + v1.1 gated-gelu
untied) and BERT. Numerical parity with the torch reference is asserted
in tests/test_convert.py (logits match to fp32 tolerance).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def _np(t):
    """torch tensor / numpy array -> numpy (host)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def load_llama_state_dict(model, state_dict, dtype=None):
    """Populate a ``LlamaForCausalLM`` (or Mistral/Qwen2 subclass) from an
    HF-format ``state_dict``. Returns the updated model (functional —
    the input model's arrays are replaced, not mutated)."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k: _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    model.model.embed_tokens = j(sd["model.embed_tokens.weight"])
    model.model.norm.weight = j(sd["model.norm.weight"])
    if model.lm_head is not None:
        if "lm_head.weight" in sd:
            model.lm_head = j(sd["lm_head.weight"].T)
        else:  # tied checkpoint loaded into an untied config
            model.lm_head = j(sd["model.embed_tokens.weight"].T)

    for i, lyr in enumerate(model.model.layers):
        p = f"model.layers.{i}."
        att = lyr.self_attn
        q = sd[p + "self_attn.q_proj.weight"].T  # [h, nh*d]
        k = sd[p + "self_attn.k_proj.weight"].T  # [h, nkv*d]
        v = sd[p + "self_attn.v_proj.weight"].T
        att.qkv_proj = j(np.concatenate([q, k, v], axis=1))
        att.o_proj = j(sd[p + "self_attn.o_proj.weight"].T)
        if att.qkv_bias is not None:  # Qwen2
            qb = sd[p + "self_attn.q_proj.bias"]
            kb = sd[p + "self_attn.k_proj.bias"]
            vb = sd[p + "self_attn.v_proj.bias"]
            att.qkv_bias = j(np.concatenate([qb, kb, vb]))
        gate = sd[p + "mlp.gate_proj.weight"].T  # [h, m]
        up = sd[p + "mlp.up_proj.weight"].T
        lyr.mlp.gate_up_proj = j(np.concatenate([gate, up], axis=1))
        lyr.mlp.down_proj = j(sd[p + "mlp.down_proj.weight"].T)
        lyr.input_layernorm.weight = j(sd[p + "input_layernorm.weight"])
        lyr.post_attention_layernorm.weight = j(
            sd[p + "post_attention_layernorm.weight"])
    return model


def load_bert_state_dict(model, state_dict, dtype=None):
    """Populate a ``BertModel``/``BertForPretraining`` from an HF-format
    BERT ``state_dict`` (bert.* naming)."""
    sd = {k: _np(v) for k, v in state_dict.items()}
    dtype = dtype or jnp.float32

    def j(a):
        return jnp.asarray(a, dtype)

    def get(*names):
        for n in names:
            if n in sd:
                return sd[n]
        raise KeyError(names[0])

    bert = model.bert if hasattr(model, "bert") else model
    emb = bert.embeddings
    emb.word_embeddings.weight = j(get("bert.embeddings.word_embeddings.weight",
                                       "embeddings.word_embeddings.weight"))
    emb.position_embeddings.weight = j(get(
        "bert.embeddings.position_embeddings.weight",
        "embeddings.position_embeddings.weight"))
    emb.token_type_embeddings.weight = j(get(
        "bert.embeddings.token_type_embeddings.weight",
        "embeddings.token_type_embeddings.weight"))
    emb.layer_norm.weight = j(get("bert.embeddings.LayerNorm.weight",
                                  "embeddings.LayerNorm.weight"))
    emb.layer_norm.bias = j(get("bert.embeddings.LayerNorm.bias",
                                "embeddings.LayerNorm.bias"))

    for i, lyr in enumerate(bert.layers):
        p = f"bert.encoder.layer.{i}." \
            if f"bert.encoder.layer.{i}.attention.self.query.weight" in sd \
            else f"encoder.layer.{i}."
        a = lyr.attention
        a.q_proj.weight = j(sd[p + "attention.self.query.weight"].T)
        a.q_proj.bias = j(sd[p + "attention.self.query.bias"])
        a.k_proj.weight = j(sd[p + "attention.self.key.weight"].T)
        a.k_proj.bias = j(sd[p + "attention.self.key.bias"])
        a.v_proj.weight = j(sd[p + "attention.self.value.weight"].T)
        a.v_proj.bias = j(sd[p + "attention.self.value.bias"])
        a.out_proj.weight = j(sd[p + "attention.output.dense.weight"].T)
        a.out_proj.bias = j(sd[p + "attention.output.dense.bias"])
        lyr.attn_norm.weight = j(sd[p + "attention.output.LayerNorm.weight"])
        lyr.attn_norm.bias = j(sd[p + "attention.output.LayerNorm.bias"])
        lyr.intermediate.weight = j(sd[p + "intermediate.dense.weight"].T)
        lyr.intermediate.bias = j(sd[p + "intermediate.dense.bias"])
        lyr.output.weight = j(sd[p + "output.dense.weight"].T)
        lyr.output.bias = j(sd[p + "output.dense.bias"])
        lyr.out_norm.weight = j(sd[p + "output.LayerNorm.weight"])
        lyr.out_norm.bias = j(sd[p + "output.LayerNorm.bias"])
    pool_w = sd.get("bert.pooler.dense.weight", sd.get("pooler.dense.weight"))
    if pool_w is not None:
        bert.pooler.weight = j(pool_w.T)
        bert.pooler.bias = j(sd.get("bert.pooler.dense.bias",
                                    sd.get("pooler.dense.bias")))
    return model


def load_safetensors(path):
    """Read a .safetensors file into a plain dict of numpy arrays (uses the
    safetensors package when present, else the minimal header parser —
    the format is a JSON header + raw little-endian buffers)."""
    try:
        from safetensors.numpy import load_file
        return dict(load_file(path))
    except ImportError:
        pass
    import json
    import struct

    out = {}
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen))
        base = 8 + hlen
        data = np.memmap(path, dtype=np.uint8, mode="r")
        dt = {"F64": np.float64, "F32": np.float32, "F16": np.float16,
              "BF16": None, "I64": np.int64, "I32": np.int32, "I8": np.int8,
              "U8": np.uint8, "BOOL": np.bool_}
        for name, meta in header.items():
            if name == "__metadata__":
                continue
            lo, hi = meta["data_offsets"]
            buf = np.array(data[base + lo:base + hi])
            if meta["dtype"] == "BF16":
                import ml_dtypes
                arr = buf.view(ml_dtypes.bfloat16)
            else:
                arr = buf.view(dt[meta["dtype"]])
            out[name] = arr.reshape(meta["shape"])
    return out


def load_gpt2_state_dict(model, state_dict, dtype=None):
    """Populate a ``GPTForCausalLM`` from an HF GPT-2 ``state_dict``.
    HF GPT-2 uses Conv1D layers that already store [in, out], so the fused
    qkv/fc weights map without transposition."""
    sd = {k: _np(v) for k, v in state_dict.items()}
    dtype = dtype or model.cfg.dtype

    def j(a):
        return jnp.asarray(a, dtype)

    def get(name):
        return sd[name] if name in sd else sd["transformer." + name]

    model.wte = j(get("wte.weight"))
    model.wpe = j(get("wpe.weight"))
    model.ln_f.weight = j(get("ln_f.weight"))
    model.ln_f.bias = j(get("ln_f.bias"))
    for i, blk in enumerate(model.blocks):
        p = f"h.{i}."
        blk.ln1.weight = j(get(p + "ln_1.weight"))
        blk.ln1.bias = j(get(p + "ln_1.bias"))
        blk.qkv = j(get(p + "attn.c_attn.weight"))
        blk.qkv_bias = j(get(p + "attn.c_attn.bias"))
        blk.proj = j(get(p + "attn.c_proj.weight"))
        blk.proj_bias = j(get(p + "attn.c_proj.bias"))
        blk.ln2.weight = j(get(p + "ln_2.weight"))
        blk.ln2.bias = j(get(p + "ln_2.bias"))
        blk.fc1 = j(get(p + "mlp.c_fc.weight"))
        blk.fc1_bias = j(get(p + "mlp.c_fc.bias"))
        blk.fc2 = j(get(p + "mlp.c_proj.weight"))
        blk.fc2_bias = j(get(p + "mlp.c_proj.bias"))
    return model


def load_t5_state_dict(model, state_dict, dtype=None):
    """Populate a ``T5ForConditionalGeneration`` from an HF T5 (v1.0 relu)
    ``state_dict``. Linear weights transpose ([out, in] -> [in, out]);
    relative-attention bias tables map directly ([buckets, heads])."""
    sd = {k: _np(v) for k, v in state_dict.items()}
    dtype = dtype or model.cfg.dtype

    def j(a):
        return jnp.asarray(a, dtype)

    untied = "lm_head.weight" in sd and not np.array_equal(
        sd["lm_head.weight"], sd["shared.weight"])
    if untied and model.lm_head is None:
        raise ValueError(
            "untied T5 checkpoint (distinct lm_head.weight) loaded into a "
            "tied config: construct the model with "
            "tie_word_embeddings=False (v1.1)")
    if not untied and model.lm_head is not None:
        raise ValueError(
            "tied T5 checkpoint loaded into an untied config: the tied head "
            "carries the d_model**-0.5 rescale, so construct the model with "
            "tie_word_embeddings=True")
    t5 = model.t5
    t5.shared = j(sd["shared.weight"])
    if model.lm_head is not None:
        model.lm_head = j(sd["lm_head.weight"].T)

    def load_attn(att, p):
        att.q = j(sd[p + ".q.weight"].T)
        att.k = j(sd[p + ".k.weight"].T)
        att.v = j(sd[p + ".v.weight"].T)
        att.o = j(sd[p + ".o.weight"].T)
        rb = sd.get(p + ".relative_attention_bias.weight")
        if rb is not None and att.rel_bias is not None:
            att.rel_bias = jnp.asarray(rb, jnp.float32)

    for stack, name in ((t5.encoder, "encoder"), (t5.decoder, "decoder")):
        for i, blk in enumerate(stack.blocks):
            p = f"{name}.block.{i}.layer."
            load_attn(blk.attn, p + "0.SelfAttention")
            blk.ln1.weight = j(sd[p + "0.layer_norm.weight"])
            ff_idx = 2 if blk.is_decoder else 1
            if blk.is_decoder:
                load_attn(blk.cross_attn, p + "1.EncDecAttention")
                blk.ln_cross.weight = j(sd[p + "1.layer_norm.weight"])
            gated_key = p + f"{ff_idx}.DenseReluDense.wi_0.weight"
            ckpt_gated = gated_key in sd
            if ckpt_gated != blk.ff.gated:
                raise ValueError(
                    f"T5 FF variant mismatch at layer {i}: checkpoint is "
                    f"{'gated' if ckpt_gated else 'relu'} but the config is "
                    f"{'gated-gelu' if blk.ff.gated else 'relu'}; set "
                    "feed_forward_proj accordingly")
            if ckpt_gated:  # v1.1 gated-gelu: fuse wi_0|wi_1
                wi0 = sd[gated_key].T
                wi1 = sd[p + f"{ff_idx}.DenseReluDense.wi_1.weight"].T
                blk.ff.wi = j(np.concatenate([wi0, wi1], axis=1))
            else:
                blk.ff.wi = j(sd[p + f"{ff_idx}.DenseReluDense.wi.weight"].T)
            blk.ff.wo = j(sd[p + f"{ff_idx}.DenseReluDense.wo.weight"].T)
            blk.ln2.weight = j(sd[p + f"{ff_idx}.layer_norm.weight"])
        stack.final_norm.weight = j(sd[f"{name}.final_layer_norm.weight"])
    return model


def load_bloom_state_dict(model, state_dict, dtype=None):
    """Populate a ``BloomForCausalLM`` from an HF state_dict. HF fuses QKV
    head-INTERLEAVED ([nh, 3, d] on the out dim); ours is [q|k|v] blocks,
    so the fused weight/bias are re-laid out here."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k.removeprefix("transformer."): _np(v)
          for k, v in state_dict.items()}
    nh = cfg.n_head
    d = cfg.hidden_size // nh

    def j(a):
        return jnp.asarray(a, dtype)

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    model.word_embeddings = j(sd["word_embeddings.weight"])
    ln(model.word_embeddings_layernorm, "word_embeddings_layernorm")
    ln(model.ln_f, "ln_f")
    for i, blk in enumerate(model.h):
        p = f"h.{i}."
        ln(blk.input_layernorm, p + "input_layernorm")
        ln(blk.post_attention_layernorm, p + "post_attention_layernorm")
        w = sd[p + "self_attention.query_key_value.weight"]  # [3h, h]
        w = w.reshape(nh, 3, d, cfg.hidden_size)
        blk.qkv = j(np.concatenate(
            [w[:, 0].reshape(nh * d, -1), w[:, 1].reshape(nh * d, -1),
             w[:, 2].reshape(nh * d, -1)], axis=0).T)        # [h, 3h]
        b = sd[p + "self_attention.query_key_value.bias"].reshape(nh, 3, d)
        blk.qkv_bias = j(np.concatenate(
            [b[:, 0].reshape(-1), b[:, 1].reshape(-1),
             b[:, 2].reshape(-1)]))
        blk.dense = j(sd[p + "self_attention.dense.weight"].T)
        blk.dense_bias = j(sd[p + "self_attention.dense.bias"])
        blk.h_to_4h = j(sd[p + "mlp.dense_h_to_4h.weight"].T)
        blk.h_to_4h_bias = j(sd[p + "mlp.dense_h_to_4h.bias"])
        blk.four_h_to_h = j(sd[p + "mlp.dense_4h_to_h.weight"].T)
        blk.four_h_to_h_bias = j(sd[p + "mlp.dense_4h_to_h.bias"])
    return model


def load_opt_state_dict(model, state_dict, dtype=None):
    """Populate an ``OPTForCausalLM`` from an HF state_dict (keys under
    ``model.decoder.``; lm_head is tied to embed_tokens). Covers the 350m
    shape too (project_in/out, post-norm blocks)."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k.removeprefix("model.").removeprefix("decoder."): _np(v)
          for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    model.embed_tokens = j(sd["embed_tokens.weight"])
    model.embed_positions = j(sd["embed_positions.weight"])
    if model.project_in is not None:
        model.project_in = j(sd["project_in.weight"].T)
        model.project_out = j(sd["project_out.weight"].T)
    if model.final_layer_norm is not None:
        ln(model.final_layer_norm, "final_layer_norm")
    for i, blk in enumerate(model.layers):
        p = f"layers.{i}."
        ln(blk.self_attn_layer_norm, p + "self_attn_layer_norm")
        ln(blk.final_layer_norm, p + "final_layer_norm")
        for ours, theirs in [("q_proj", "q_proj"), ("k_proj", "k_proj"),
                             ("v_proj", "v_proj"), ("out_proj", "out_proj")]:
            setattr(blk, ours, j(sd[p + f"self_attn.{theirs}.weight"].T))
            setattr(blk, ours.replace("_proj", "") + "_bias"
                    if ours != "out_proj" else "out_bias",
                    j(sd[p + f"self_attn.{theirs}.bias"]))
        blk.fc1 = j(sd[p + "fc1.weight"].T)
        blk.fc1_bias = j(sd[p + "fc1.bias"])
        blk.fc2 = j(sd[p + "fc2.weight"].T)
        blk.fc2_bias = j(sd[p + "fc2.bias"])
    return model


def load_gpt_neox_state_dict(model, state_dict, dtype=None):
    """Populate a ``GPTNeoXForCausalLM`` from an HF state_dict. HF fuses
    QKV head-interleaved ([nh, 3, d] out-dim, same as BLOOM); ours is
    [q|k|v] blocks. ``embed_out`` is untied ([vocab, h] -> transposed)."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k.removeprefix("gpt_neox."): _np(v)
          for k, v in state_dict.items()}
    nh = cfg.num_attention_heads
    d = cfg.hidden_size // nh

    def j(a):
        return jnp.asarray(a, dtype)

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    model.embed_in = j(sd["embed_in.weight"])
    model.embed_out = j(sd["embed_out.weight"].T)
    ln(model.final_layer_norm, "final_layer_norm")
    for i, blk in enumerate(model.layers):
        p = f"layers.{i}."
        ln(blk.input_layernorm, p + "input_layernorm")
        ln(blk.post_attention_layernorm, p + "post_attention_layernorm")
        w = sd[p + "attention.query_key_value.weight"]       # [3h, h]
        w = w.reshape(nh, 3, d, cfg.hidden_size)
        blk.qkv = j(np.concatenate(
            [w[:, 0].reshape(nh * d, -1), w[:, 1].reshape(nh * d, -1),
             w[:, 2].reshape(nh * d, -1)], axis=0).T)        # [h, 3h]
        b = sd[p + "attention.query_key_value.bias"].reshape(nh, 3, d)
        blk.qkv_bias = j(np.concatenate(
            [b[:, 0].reshape(-1), b[:, 1].reshape(-1),
             b[:, 2].reshape(-1)]))
        blk.dense = j(sd[p + "attention.dense.weight"].T)
        blk.dense_bias = j(sd[p + "attention.dense.bias"])
        blk.h_to_4h = j(sd[p + "mlp.dense_h_to_4h.weight"].T)
        blk.h_to_4h_bias = j(sd[p + "mlp.dense_h_to_4h.bias"])
        blk.four_h_to_h = j(sd[p + "mlp.dense_4h_to_h.weight"].T)
        blk.four_h_to_h_bias = j(sd[p + "mlp.dense_4h_to_h.bias"])
    return model


def load_ernie_state_dict(model, state_dict, dtype=None):
    """Populate an ``ErnieForMaskedLM``/``ErnieModel`` from an HF
    state_dict (``ernie.*`` naming). The encoder block layout is BERT's,
    so the shared parts route through ``load_bert_state_dict`` with the
    prefix remapped; ERNIE's task_type embedding and the MLM head load
    here."""
    cfg = model.cfg
    dtype = dtype or jnp.float32
    sd = {k: _np(v) for k, v in state_dict.items()}
    remapped = {("bert." + k.removeprefix("ernie.")): v
                for k, v in sd.items() if k.startswith("ernie.")}

    def j(a):
        return jnp.asarray(a, dtype)

    ernie = model.ernie if hasattr(model, "ernie") else model

    class _Shim:                       # load_bert_state_dict reads .bert
        bert = ernie
    load_bert_state_dict(_Shim(), remapped, dtype=dtype)
    tte = "ernie.embeddings.task_type_embeddings.weight"
    if ernie.embeddings.task_type_embeddings is not None:
        ernie.embeddings.task_type_embeddings.weight = j(sd[tte])
    if hasattr(model, "mlm_transform") and "cls.predictions.bias" in sd:
        model.mlm_transform.weight = j(
            sd["cls.predictions.transform.dense.weight"].T)
        model.mlm_transform.bias = j(
            sd["cls.predictions.transform.dense.bias"])
        model.mlm_norm.weight = j(
            sd["cls.predictions.transform.LayerNorm.weight"])
        model.mlm_norm.bias = j(
            sd["cls.predictions.transform.LayerNorm.bias"])
        model.mlm_bias = j(sd["cls.predictions.bias"])
    return model


def load_gptj_state_dict(model, state_dict, dtype=None):
    """Populate a ``GPTJForCausalLM`` from an HF state_dict
    (``transformer.*`` naming; separate biased lm_head, untied)."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k.removeprefix("transformer."): _np(v)
          for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    model.wte = j(sd["wte.weight"])
    model.ln_f.weight = j(sd["ln_f.weight"])
    model.ln_f.bias = j(sd["ln_f.bias"])
    model.lm_head = j(sd["lm_head.weight"].T)
    model.lm_head_bias = j(sd["lm_head.bias"])
    for i, blk in enumerate(model.h):
        p = f"h.{i}."
        blk.ln_1.weight = j(sd[p + "ln_1.weight"])
        blk.ln_1.bias = j(sd[p + "ln_1.bias"])
        for name in ("q_proj", "k_proj", "v_proj", "out_proj"):
            setattr(blk, name, j(sd[p + f"attn.{name}.weight"].T))
        blk.fc_in = j(sd[p + "mlp.fc_in.weight"].T)
        blk.fc_in_bias = j(sd[p + "mlp.fc_in.bias"])
        blk.fc_out = j(sd[p + "mlp.fc_out.weight"].T)
        blk.fc_out_bias = j(sd[p + "mlp.fc_out.bias"])
    return model


def load_falcon_state_dict(model, state_dict, dtype=None):
    """Populate a ``FalconForCausalLM`` from an HF state_dict. The fused
    QKV layout differs per variant: grouped [q*r | k | v] per kv head
    (new decoder architecture), [all q | k | v] (multi_query), or
    head-interleaved (falcon-rw) — all re-laid out to separate q/k/v."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k.removeprefix("transformer."): _np(v)
          for k, v in state_dict.items()}
    nh = cfg.num_attention_heads
    nkv = cfg.kv_heads
    d = cfg.hidden_size // nh

    def j(a):
        return jnp.asarray(a, dtype)

    def split_qkv(w):
        """[out, h] (or [out] bias) -> (q, k, v) along the out dim."""
        if cfg.new_decoder_architecture:
            r = nh // nkv
            w = w.reshape((nkv, r + 2, d) + w.shape[1:])
            return (w[:, :r].reshape((nh * d,) + w.shape[3:]),
                    w[:, r].reshape((nkv * d,) + w.shape[3:]),
                    w[:, r + 1].reshape((nkv * d,) + w.shape[3:]))
        if cfg.multi_query:
            return w[:nh * d], w[nh * d:(nh + 1) * d], w[(nh + 1) * d:]
        w = w.reshape((nh, 3, d) + w.shape[1:])      # rw: interleaved
        return (w[:, 0].reshape((nh * d,) + w.shape[3:]),
                w[:, 1].reshape((nh * d,) + w.shape[3:]),
                w[:, 2].reshape((nh * d,) + w.shape[3:]))

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    model.word_embeddings = j(sd["word_embeddings.weight"])
    ln(model.ln_f, "ln_f")
    for i, blk in enumerate(model.h):
        p = f"h.{i}."
        if cfg.new_decoder_architecture:
            ln(blk.ln_attn, p + "ln_attn")
            ln(blk.ln_mlp, p + "ln_mlp")
        else:
            ln(blk.input_layernorm, p + "input_layernorm")
            if blk.post_attention_layernorm is not None:
                ln(blk.post_attention_layernorm,
                   p + "post_attention_layernorm")
        q, k, v = split_qkv(sd[p + "self_attention.query_key_value.weight"])
        blk.wq, blk.wk, blk.wv = j(q.T), j(k.T), j(v.T)
        blk.dense = j(sd[p + "self_attention.dense.weight"].T)
        blk.h_to_4h = j(sd[p + "mlp.dense_h_to_4h.weight"].T)
        blk.four_h_to_h = j(sd[p + "mlp.dense_4h_to_h.weight"].T)
        if cfg.bias:
            qb, kb, vb = split_qkv(
                sd[p + "self_attention.query_key_value.bias"])
            blk.wq_bias, blk.wk_bias, blk.wv_bias = j(qb), j(kb), j(vb)
            blk.dense_bias = j(sd[p + "self_attention.dense.bias"])
            blk.h_to_4h_bias = j(sd[p + "mlp.dense_h_to_4h.bias"])
            blk.four_h_to_h_bias = j(sd[p + "mlp.dense_4h_to_h.bias"])
    return model


def load_roberta_state_dict(model, state_dict, dtype=None):
    """Populate a ``RobertaForMaskedLM``/``RobertaModel`` from an HF
    state_dict (``roberta.*`` naming). The encoder is BERT's layout —
    routed through ``load_bert_state_dict`` with the prefix remapped —
    plus RoBERTa's lm_head (dense+LN+tied decoder)."""
    cfg = model.cfg
    dtype = dtype or jnp.float32
    sd = {k: _np(v) for k, v in state_dict.items()}
    remapped = {("bert." + k.removeprefix("roberta.")): v
                for k, v in sd.items() if k.startswith("roberta.")}

    def j(a):
        return jnp.asarray(a, dtype)

    rob = model.roberta if hasattr(model, "roberta") else model

    class _Shim:
        bert = rob.bert
    load_bert_state_dict(_Shim(), remapped, dtype=dtype)
    if hasattr(model, "lm_dense") and "lm_head.bias" in sd:
        model.lm_dense.weight = j(sd["lm_head.dense.weight"].T)
        model.lm_dense.bias = j(sd["lm_head.dense.bias"])
        model.lm_norm.weight = j(sd["lm_head.layer_norm.weight"])
        model.lm_norm.bias = j(sd["lm_head.layer_norm.bias"])
        model.lm_bias = j(sd["lm_head.bias"])
    return model


def load_electra_state_dict(model, state_dict, dtype=None):
    """Populate an ``ElectraForPreTraining``/``ElectraModel`` from an HF
    state_dict (``electra.*`` naming; factorized embeddings +
    discriminator head)."""
    cfg = model.cfg
    dtype = dtype or jnp.float32
    sd = {k.removeprefix("electra."): _np(v)
          for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    el = model.electra if hasattr(model, "electra") else model
    el.word_embeddings.weight = j(sd["embeddings.word_embeddings.weight"])
    el.position_embeddings.weight = j(
        sd["embeddings.position_embeddings.weight"])
    el.token_type_embeddings.weight = j(
        sd["embeddings.token_type_embeddings.weight"])
    el.emb_norm.weight = j(sd["embeddings.LayerNorm.weight"])
    el.emb_norm.bias = j(sd["embeddings.LayerNorm.bias"])
    if el.embeddings_project is not None:
        el.embeddings_project.weight = j(sd["embeddings_project.weight"].T)
        el.embeddings_project.bias = j(sd["embeddings_project.bias"])
    for i, lyr in enumerate(el.layers):
        p = f"encoder.layer.{i}."
        a = lyr.attention
        a.q_proj.weight = j(sd[p + "attention.self.query.weight"].T)
        a.q_proj.bias = j(sd[p + "attention.self.query.bias"])
        a.k_proj.weight = j(sd[p + "attention.self.key.weight"].T)
        a.k_proj.bias = j(sd[p + "attention.self.key.bias"])
        a.v_proj.weight = j(sd[p + "attention.self.value.weight"].T)
        a.v_proj.bias = j(sd[p + "attention.self.value.bias"])
        a.out_proj.weight = j(sd[p + "attention.output.dense.weight"].T)
        a.out_proj.bias = j(sd[p + "attention.output.dense.bias"])
        lyr.attn_norm.weight = j(sd[p + "attention.output.LayerNorm.weight"])
        lyr.attn_norm.bias = j(sd[p + "attention.output.LayerNorm.bias"])
        lyr.intermediate.weight = j(sd[p + "intermediate.dense.weight"].T)
        lyr.intermediate.bias = j(sd[p + "intermediate.dense.bias"])
        lyr.output.weight = j(sd[p + "output.dense.weight"].T)
        lyr.output.bias = j(sd[p + "output.dense.bias"])
        lyr.out_norm.weight = j(sd[p + "output.LayerNorm.weight"])
        lyr.out_norm.bias = j(sd[p + "output.LayerNorm.bias"])
    if hasattr(model, "disc_dense") and \
            "discriminator_predictions.dense.weight" in sd:
        model.disc_dense.weight = j(
            sd["discriminator_predictions.dense.weight"].T)
        model.disc_dense.bias = j(
            sd["discriminator_predictions.dense.bias"])
        model.disc_out.weight = j(
            sd["discriminator_predictions.dense_prediction.weight"].T)
        model.disc_out.bias = j(
            sd["discriminator_predictions.dense_prediction.bias"])
    return model


def load_bart_state_dict(model, state_dict, dtype=None):
    """Populate a ``BartForConditionalGeneration`` from an HF state_dict
    (``model.encoder/decoder`` naming; lm_head tied to ``model.shared``)."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k.removeprefix("model."): _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def lin(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"].T)
        layer.bias = j(sd[prefix + ".bias"])

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    def attn(a, prefix):
        for ours, theirs in [("q_proj", "q_proj"), ("k_proj", "k_proj"),
                             ("v_proj", "v_proj"), ("out_proj", "out_proj")]:
            lin(getattr(a, ours), f"{prefix}.{theirs}")

    model.shared = j(sd["shared.weight"])
    if "final_logits_bias" in sd:
        model.final_logits_bias = j(sd["final_logits_bias"].reshape(-1))
    model.enc_positions = j(sd["encoder.embed_positions.weight"])
    model.dec_positions = j(sd["decoder.embed_positions.weight"])
    if model.enc_layernorm_embedding is not None:
        ln(model.enc_layernorm_embedding, "encoder.layernorm_embedding")
        ln(model.dec_layernorm_embedding, "decoder.layernorm_embedding")
    if model.enc_final_norm is not None:        # mBART final LNs
        ln(model.enc_final_norm, "encoder.layer_norm")
        ln(model.dec_final_norm, "decoder.layer_norm")
    for i, lyr in enumerate(model.encoder_layers_m):
        p = f"encoder.layers.{i}."
        attn(lyr.self_attn, p + "self_attn")
        ln(lyr.self_attn_layer_norm, p + "self_attn_layer_norm")
        lin(lyr.fc1, p + "fc1")
        lin(lyr.fc2, p + "fc2")
        ln(lyr.final_layer_norm, p + "final_layer_norm")
    for i, lyr in enumerate(model.decoder_layers_m):
        p = f"decoder.layers.{i}."
        attn(lyr.self_attn, p + "self_attn")
        ln(lyr.self_attn_layer_norm, p + "self_attn_layer_norm")
        attn(lyr.encoder_attn, p + "encoder_attn")
        ln(lyr.encoder_attn_layer_norm, p + "encoder_attn_layer_norm")
        lin(lyr.fc1, p + "fc1")
        lin(lyr.fc2, p + "fc2")
        ln(lyr.final_layer_norm, p + "final_layer_norm")
    return model


def load_qwen2_moe_state_dict(model, state_dict, dtype=None):
    """Populate a ``Qwen2MoeForCausalLM`` from an HF state_dict: Qwen2
    attention packing (fused biased QKV) + per-layer expert stacks
    (E separate gate/up/down linears -> stacked [E, h, 2I]/[E, I, h])
    + the shared expert and its sigmoid gate + the router."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k: _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    model.embed_tokens = j(sd["model.embed_tokens.weight"])
    model.norm.weight = j(sd["model.norm.weight"])
    model.lm_head = j(sd.get("lm_head.weight",
                             sd["model.embed_tokens.weight"]).T)
    for i, lyr in enumerate(model.layers):
        p = f"model.layers.{i}."
        att = lyr.self_attn
        q = sd[p + "self_attn.q_proj.weight"].T
        k = sd[p + "self_attn.k_proj.weight"].T
        v = sd[p + "self_attn.v_proj.weight"].T
        att.qkv_proj = j(np.concatenate([q, k, v], axis=1))
        att.o_proj = j(sd[p + "self_attn.o_proj.weight"].T)
        if att.qkv_bias is not None:
            att.qkv_bias = j(np.concatenate(
                [sd[p + "self_attn.q_proj.bias"],
                 sd[p + "self_attn.k_proj.bias"],
                 sd[p + "self_attn.v_proj.bias"]]))
        lyr.input_layernorm.weight = j(sd[p + "input_layernorm.weight"])
        lyr.post_attention_layernorm.weight = j(
            sd[p + "post_attention_layernorm.weight"])
        if not lyr.sparse:
            gate = sd[p + "mlp.gate_proj.weight"].T
            up = sd[p + "mlp.up_proj.weight"].T
            lyr.mlp.gate_up_proj = j(np.concatenate([gate, up], axis=1))
            lyr.mlp.down_proj = j(sd[p + "mlp.down_proj.weight"].T)
            continue
        blk = lyr.mlp
        # router stays f32: the reference computes routing in float
        blk.moe.gate_w = jnp.asarray(sd[p + "mlp.gate.weight"].T,
                                     jnp.float32)
        gu, dn = [], []
        for e in range(cfg.num_experts):
            ep = p + f"mlp.experts.{e}."
            g = sd[ep + "gate_proj.weight"].T       # [h, I]
            u = sd[ep + "up_proj.weight"].T
            gu.append(np.concatenate([g, u], axis=1))
            dn.append(sd[ep + "down_proj.weight"].T)
        blk.moe.experts.gate_up = j(np.stack(gu))
        blk.moe.experts.down = j(np.stack(dn))
        sg = sd[p + "mlp.shared_expert.gate_proj.weight"].T
        su = sd[p + "mlp.shared_expert.up_proj.weight"].T
        blk.shared_gate_up = j(np.concatenate([sg, su], axis=1))
        blk.shared_down = j(sd[p + "mlp.shared_expert.down_proj.weight"].T)
        blk.shared_gate = j(sd[p + "mlp.shared_expert_gate.weight"].T)
    return model


def load_gemma_state_dict(model, state_dict, dtype=None):
    """Populate a ``GemmaForCausalLM`` from an HF state_dict (zero-
    centered norm weights stored as-is; head tied to embeddings)."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k: _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    model.embed_tokens = j(sd["model.embed_tokens.weight"])
    model.norm.weight = j(sd["model.norm.weight"])
    for i, lyr in enumerate(model.layers):
        p = f"model.layers.{i}."
        q = sd[p + "self_attn.q_proj.weight"].T
        k = sd[p + "self_attn.k_proj.weight"].T
        v = sd[p + "self_attn.v_proj.weight"].T
        lyr.qkv_proj = j(np.concatenate([q, k, v], axis=1))
        lyr.o_proj = j(sd[p + "self_attn.o_proj.weight"].T)
        gate = sd[p + "mlp.gate_proj.weight"].T
        up = sd[p + "mlp.up_proj.weight"].T
        lyr.gate_up_proj = j(np.concatenate([gate, up], axis=1))
        lyr.down_proj = j(sd[p + "mlp.down_proj.weight"].T)
        lyr.input_layernorm.weight = j(sd[p + "input_layernorm.weight"])
        lyr.post_attention_layernorm.weight = j(
            sd[p + "post_attention_layernorm.weight"])
    return model


def load_mixtral_state_dict(model, state_dict, dtype=None):
    """Populate a ``MixtralForCausalLM`` from an HF state_dict: llama
    attention packing + per-layer expert stacks (HF w1=gate, w3=up,
    w2=down -> stacked [E, h, 2I]/[E, I, h]) + the router."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k: _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    model.embed_tokens = j(sd["model.embed_tokens.weight"])
    model.norm.weight = j(sd["model.norm.weight"])
    model.lm_head = j(sd.get("lm_head.weight",
                             sd["model.embed_tokens.weight"]).T)
    for i, lyr in enumerate(model.layers):
        p = f"model.layers.{i}."
        att = lyr.self_attn
        q = sd[p + "self_attn.q_proj.weight"].T
        k = sd[p + "self_attn.k_proj.weight"].T
        v = sd[p + "self_attn.v_proj.weight"].T
        att.qkv_proj = j(np.concatenate([q, k, v], axis=1))
        att.o_proj = j(sd[p + "self_attn.o_proj.weight"].T)
        lyr.input_layernorm.weight = j(sd[p + "input_layernorm.weight"])
        lyr.post_attention_layernorm.weight = j(
            sd[p + "post_attention_layernorm.weight"])
        lyr.moe.gate_w = jnp.asarray(
            sd[p + "block_sparse_moe.gate.weight"].T, jnp.float32)
        gu, dn = [], []
        for e in range(cfg.num_local_experts):
            ep = p + f"block_sparse_moe.experts.{e}."
            g = sd[ep + "w1.weight"].T            # gate  [h, I]
            u = sd[ep + "w3.weight"].T            # up
            gu.append(np.concatenate([g, u], axis=1))
            dn.append(sd[ep + "w2.weight"].T)     # down  [I, h]
        lyr.moe.experts.gate_up = j(np.stack(gu))
        lyr.moe.experts.down = j(np.stack(dn))
    return model


def load_glm_state_dict(model, state_dict, dtype=None):
    """Populate a ``GlmForCausalLM`` from an HF state_dict (llama-style
    q/k/v packing with biases; fused gate_up MLP loads directly)."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k: _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    model.embed_tokens = j(sd["model.embed_tokens.weight"])
    model.norm.weight = j(sd["model.norm.weight"])
    model.lm_head = j(sd.get("lm_head.weight",
                             sd["model.embed_tokens.weight"]).T)
    for i, lyr in enumerate(model.layers):
        p = f"model.layers.{i}."
        q = sd[p + "self_attn.q_proj.weight"].T
        k = sd[p + "self_attn.k_proj.weight"].T
        v = sd[p + "self_attn.v_proj.weight"].T
        lyr.qkv_proj = j(np.concatenate([q, k, v], axis=1))
        if lyr.qkv_bias is not None:
            lyr.qkv_bias = j(np.concatenate(
                [sd[p + "self_attn.q_proj.bias"],
                 sd[p + "self_attn.k_proj.bias"],
                 sd[p + "self_attn.v_proj.bias"]]))
        lyr.o_proj = j(sd[p + "self_attn.o_proj.weight"].T)
        lyr.gate_up_proj = j(sd[p + "mlp.gate_up_proj.weight"].T)
        lyr.down_proj = j(sd[p + "mlp.down_proj.weight"].T)
        lyr.input_layernorm.weight = j(sd[p + "input_layernorm.weight"])
        lyr.post_attention_layernorm.weight = j(
            sd[p + "post_attention_layernorm.weight"])
    return model


def load_albert_state_dict(model, state_dict, dtype=None):
    """Populate an ``AlbertForMaskedLM``/``AlbertModel`` from an HF
    state_dict (factorized embeddings + ONE shared layer group)."""
    dtype = dtype or jnp.float32
    sd = {k.removeprefix("albert."): _np(v)
          for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def lin(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"].T)
        layer.bias = j(sd[prefix + ".bias"])

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    al = model.albert if hasattr(model, "albert") else model
    al.word_embeddings.weight = j(sd["embeddings.word_embeddings.weight"])
    al.position_embeddings.weight = j(
        sd["embeddings.position_embeddings.weight"])
    al.token_type_embeddings.weight = j(
        sd["embeddings.token_type_embeddings.weight"])
    ln(al.emb_norm, "embeddings.LayerNorm")
    lin(al.embedding_project, "encoder.embedding_hidden_mapping_in")
    p = "encoder.albert_layer_groups.0.albert_layers.0."
    a = al.shared.attention
    lin(a.q_proj, p + "attention.query")
    lin(a.k_proj, p + "attention.key")
    lin(a.v_proj, p + "attention.value")
    lin(a.out_proj, p + "attention.dense")
    ln(al.shared.attn_norm, p + "attention.LayerNorm")
    lin(al.shared.ffn, p + "ffn")
    lin(al.shared.ffn_output, p + "ffn_output")
    ln(al.shared.full_norm, p + "full_layer_layer_norm")
    if "pooler.weight" in sd:
        lin(al.pooler, "pooler")
    if hasattr(model, "lm_dense") and "predictions.bias" in state_dict:
        sp = {k: _np(v) for k, v in state_dict.items()}
        model.lm_dense.weight = j(sp["predictions.dense.weight"].T)
        model.lm_dense.bias = j(sp["predictions.dense.bias"])
        model.lm_norm.weight = j(sp["predictions.LayerNorm.weight"])
        model.lm_norm.bias = j(sp["predictions.LayerNorm.bias"])
        model.lm_bias = j(sp["predictions.bias"])
    return model


def load_deberta_v2_state_dict(model, state_dict, dtype=None):
    """Populate a ``DebertaV2ForMaskedLM``/``DebertaV2Model`` from an HF
    state_dict (disentangled attention, shared rel embeddings)."""
    dtype = dtype or jnp.float32
    sd = {k.removeprefix("deberta."): _np(v)
          for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def lin(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"].T)
        layer.bias = j(sd[prefix + ".bias"])

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    de = model.deberta if hasattr(model, "deberta") else model
    de.word_embeddings.weight = j(sd["embeddings.word_embeddings.weight"])
    if de.position_embeddings is not None:
        de.position_embeddings.weight = j(
            sd["embeddings.position_embeddings.weight"])
    if de.token_type_embeddings is not None:
        de.token_type_embeddings.weight = j(
            sd["embeddings.token_type_embeddings.weight"])
    if de.embed_proj is not None:
        de.embed_proj = j(sd["embeddings.embed_proj.weight"].T)
    ln(de.emb_norm, "embeddings.LayerNorm")
    if de.rel_embeddings is not None:
        de.rel_embeddings = j(sd["encoder.rel_embeddings.weight"])
        if de.rel_norm is not None:
            ln(de.rel_norm, "encoder.LayerNorm")
    for i, lyr in enumerate(de.layers):
        p = f"encoder.layer.{i}."
        a = lyr.attention
        lin(a.query_proj, p + "attention.self.query_proj")
        lin(a.key_proj, p + "attention.self.key_proj")
        lin(a.value_proj, p + "attention.self.value_proj")
        lin(a.dense, p + "attention.output.dense")
        ln(a.out_norm, p + "attention.output.LayerNorm")
        lin(lyr.intermediate, p + "intermediate.dense")
        lin(lyr.output, p + "output.dense")
        ln(lyr.out_norm, p + "output.LayerNorm")
    if hasattr(model, "mlm_transform") and \
            "cls.predictions.transform.dense.weight" in state_dict:
        sp = {k: _np(v) for k, v in state_dict.items()}
        model.mlm_transform.weight = j(
            sp["cls.predictions.transform.dense.weight"].T)
        model.mlm_transform.bias = j(
            sp["cls.predictions.transform.dense.bias"])
        model.mlm_norm.weight = j(
            sp["cls.predictions.transform.LayerNorm.weight"])
        model.mlm_norm.bias = j(
            sp["cls.predictions.transform.LayerNorm.bias"])
        model.mlm_bias = j(sp["cls.predictions.bias"])
    return model


def load_codegen_state_dict(model, state_dict, dtype=None):
    """Populate a ``CodeGenForCausalLM`` from an HF state_dict. The fused
    qkv_proj is laid out in mp_num=4 groups of (q|v|k) columns with heads
    group-major; unpack to separate q/k/v keeping the group-major head
    order consistently everywhere (out_proj consumes the same order)."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k.removeprefix("transformer."): _np(v)
          for k, v in state_dict.items()}
    mp = 4
    local = cfg.n_embd // mp

    def j(a):
        return jnp.asarray(a, dtype)

    model.wte = j(sd["wte.weight"])
    model.ln_f.weight = j(sd["ln_f.weight"])
    model.ln_f.bias = j(sd["ln_f.bias"])
    model.lm_head = j(sd["lm_head.weight"].T)
    model.lm_head_bias = j(sd["lm_head.bias"])
    for i, blk in enumerate(model.h):
        p = f"h.{i}."
        blk.ln_1.weight = j(sd[p + "ln_1.weight"])
        blk.ln_1.bias = j(sd[p + "ln_1.bias"])
        w = sd[p + "attn.qkv_proj.weight"]            # [3h, h] torch layout
        w = w.reshape(mp, 3, local, cfg.n_embd)       # groups x (q|v|k)
        blk.q_proj = j(w[:, 0].reshape(-1, cfg.n_embd).T)
        blk.v_proj = j(w[:, 1].reshape(-1, cfg.n_embd).T)
        blk.k_proj = j(w[:, 2].reshape(-1, cfg.n_embd).T)
        blk.out_proj = j(sd[p + "attn.out_proj.weight"].T)
        blk.fc_in = j(sd[p + "mlp.fc_in.weight"].T)
        blk.fc_in_bias = j(sd[p + "mlp.fc_in.bias"])
        blk.fc_out = j(sd[p + "mlp.fc_out.weight"].T)
        blk.fc_out_bias = j(sd[p + "mlp.fc_out.bias"])
    return model


def load_ernie_m_state_dict(model, state_dict, dtype=None):
    """Populate an ``ErnieMModel`` from an HF state_dict
    (``ernie_m.*`` / bare naming)."""
    dtype = dtype or jnp.float32
    sd = {k.removeprefix("ernie_m."): _np(v)
          for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def lin(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"].T)
        layer.bias = j(sd[prefix + ".bias"])

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    em = model.ernie_m if hasattr(model, "ernie_m") else model
    em.word_embeddings.weight = j(sd["embeddings.word_embeddings.weight"])
    em.position_embeddings.weight = j(
        sd["embeddings.position_embeddings.weight"])
    ln(em.emb_norm, "embeddings.layer_norm")
    for i, lyr in enumerate(em.layers):
        p = f"encoder.layers.{i}."
        a = lyr.self_attn
        lin(a.q_proj, p + "self_attn.self_attn.q_proj")
        lin(a.k_proj, p + "self_attn.self_attn.k_proj")
        lin(a.v_proj, p + "self_attn.self_attn.v_proj")
        lin(a.out_proj, p + "self_attn.out_proj")
        lin(lyr.linear1, p + "linear1")
        lin(lyr.linear2, p + "linear2")
        ln(lyr.norm1, p + "norm1")
        ln(lyr.norm2, p + "norm2")
    if "pooler.dense.weight" in sd:
        lin(em.pooler, "pooler.dense")
    return model


def load_distilbert_state_dict(model, state_dict, dtype=None):
    """Populate a ``DistilBertForMaskedLM``/``DistilBertModel`` from an
    HF state_dict (``distilbert.*`` naming; projector tied)."""
    dtype = dtype or jnp.float32
    sd = {k.removeprefix("distilbert."): _np(v)
          for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def lin(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"].T)
        layer.bias = j(sd[prefix + ".bias"])

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    db = model.distilbert if hasattr(model, "distilbert") else model
    db.word_embeddings.weight = j(sd["embeddings.word_embeddings.weight"])
    db.position_embeddings.weight = j(
        sd["embeddings.position_embeddings.weight"])
    ln(db.emb_norm, "embeddings.LayerNorm")
    for i, lyr in enumerate(db.layers):
        p = f"transformer.layer.{i}."
        a = lyr.attention
        lin(a.q_proj, p + "attention.q_lin")
        lin(a.k_proj, p + "attention.k_lin")
        lin(a.v_proj, p + "attention.v_lin")
        lin(a.out_proj, p + "attention.out_lin")
        ln(lyr.sa_layer_norm, p + "sa_layer_norm")
        lin(lyr.lin1, p + "ffn.lin1")
        lin(lyr.lin2, p + "ffn.lin2")
        ln(lyr.output_layer_norm, p + "output_layer_norm")
    if hasattr(model, "vocab_transform") and \
            "vocab_transform.weight" in state_dict:
        sp = {k: _np(v) for k, v in state_dict.items()}
        model.vocab_transform.weight = j(sp["vocab_transform.weight"].T)
        model.vocab_transform.bias = j(sp["vocab_transform.bias"])
        model.vocab_norm.weight = j(sp["vocab_layer_norm.weight"])
        model.vocab_norm.bias = j(sp["vocab_layer_norm.bias"])
        model.vocab_bias = j(sp["vocab_projector.bias"])
    return model


def load_xlnet_state_dict(model, state_dict, dtype=None):
    """Populate an ``XLNetLMHeadModel``/``XLNetModel`` from an HF
    state_dict (q/k/v/o/r are [d_model, n_head, d_head] tensors, not
    linears; lm_loss is tied + biased)."""
    dtype = dtype or jnp.float32
    sd = {k.removeprefix("transformer."): _np(v)
          for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    xl = model.transformer if hasattr(model, "transformer") else model
    xl.word_embedding.weight = j(sd["word_embedding.weight"])
    for i, lyr in enumerate(xl.layers):
        p = f"layer.{i}."
        a = lyr.rel_attn
        for name in ("q", "k", "v", "o", "r", "r_w_bias", "r_r_bias",
                     "r_s_bias", "seg_embed"):
            setattr(a, name, j(sd[p + f"rel_attn.{name}"]))
        a.layer_norm.weight = j(sd[p + "rel_attn.layer_norm.weight"])
        a.layer_norm.bias = j(sd[p + "rel_attn.layer_norm.bias"])
        lyr.layer_1.weight = j(sd[p + "ff.layer_1.weight"].T)
        lyr.layer_1.bias = j(sd[p + "ff.layer_1.bias"])
        lyr.layer_2.weight = j(sd[p + "ff.layer_2.weight"].T)
        lyr.layer_2.bias = j(sd[p + "ff.layer_2.bias"])
        lyr.ff_norm.weight = j(sd[p + "ff.layer_norm.weight"])
        lyr.ff_norm.bias = j(sd[p + "ff.layer_norm.bias"])
    if hasattr(model, "lm_bias") and "lm_loss.bias" in state_dict:
        model.lm_bias = j(_np(state_dict["lm_loss.bias"]))
    return model


def load_clip_state_dict(model, state_dict, dtype=None):
    """Populate a ``CLIPModel`` from an HF state_dict (both towers +
    projections + logit_scale)."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k: _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def lin(layer, prefix, bias=True):
        layer.weight = j(sd[prefix + ".weight"].T)
        if bias:
            layer.bias = j(sd[prefix + ".bias"])

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    def tower(layers, prefix):
        for i, lyr in enumerate(layers):
            p = f"{prefix}.encoder.layers.{i}."
            lin(lyr.q_proj, p + "self_attn.q_proj")
            lin(lyr.k_proj, p + "self_attn.k_proj")
            lin(lyr.v_proj, p + "self_attn.v_proj")
            lin(lyr.out_proj, p + "self_attn.out_proj")
            ln(lyr.layer_norm1, p + "layer_norm1")
            ln(lyr.layer_norm2, p + "layer_norm2")
            lin(lyr.fc1, p + "mlp.fc1")
            lin(lyr.fc2, p + "mlp.fc2")

    tm = model.text_model
    tm.token_embedding.weight = j(
        sd["text_model.embeddings.token_embedding.weight"])
    tm.position_embedding.weight = j(
        sd["text_model.embeddings.position_embedding.weight"])
    tower(tm.layers, "text_model")
    ln(tm.final_layer_norm, "text_model.final_layer_norm")

    vm = model.vision_model
    vm.class_embedding = j(sd["vision_model.embeddings.class_embedding"])
    # [h, c, p, p] -> HWIO [p, p, c, h]
    vm.patch_embedding = j(np.transpose(
        sd["vision_model.embeddings.patch_embedding.weight"],
        (2, 3, 1, 0)))
    vm.position_embedding.weight = j(
        sd["vision_model.embeddings.position_embedding.weight"])
    ln(vm.pre_layrnorm, "vision_model.pre_layrnorm")
    tower(vm.layers, "vision_model")
    ln(vm.post_layernorm, "vision_model.post_layernorm")

    lin(model.visual_projection, "visual_projection", bias=False)
    lin(model.text_projection, "text_projection", bias=False)
    model.logit_scale = j(sd["logit_scale"])
    return model


def load_whisper_state_dict(model, state_dict, dtype=None):
    """Populate a ``WhisperForConditionalGeneration`` from an HF
    state_dict (k_proj's missing bias loads as zeros; proj_out tied)."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k.removeprefix("model."): _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def lin(layer, prefix, bias=True):
        layer.weight = j(sd[prefix + ".weight"].T)
        if bias and prefix + ".bias" in sd:
            layer.bias = j(sd[prefix + ".bias"])
        elif layer.bias is not None:
            layer.bias = jnp.zeros_like(layer.bias)

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    def attn(a, prefix):
        for name in ("q_proj", "k_proj", "v_proj", "out_proj"):
            lin(getattr(a, name), f"{prefix}.{name}")

    # encoder conv: torch [out, in, k] -> WIO [k, in, out]
    model.conv1 = j(np.transpose(sd["encoder.conv1.weight"], (2, 1, 0)))
    model.conv1_bias = j(sd["encoder.conv1.bias"])
    model.conv2 = j(np.transpose(sd["encoder.conv2.weight"], (2, 1, 0)))
    model.conv2_bias = j(sd["encoder.conv2.bias"])
    model.enc_positions = j(sd["encoder.embed_positions.weight"])
    ln(model.enc_final_norm, "encoder.layer_norm")
    for i, lyr in enumerate(model.encoder_layers_m):
        p = f"encoder.layers.{i}."
        attn(lyr.self_attn, p + "self_attn")
        ln(lyr.self_attn_layer_norm, p + "self_attn_layer_norm")
        lin(lyr.fc1, p + "fc1")
        lin(lyr.fc2, p + "fc2")
        ln(lyr.final_layer_norm, p + "final_layer_norm")

    model.embed_tokens = j(sd["decoder.embed_tokens.weight"])
    model.dec_positions = j(sd["decoder.embed_positions.weight"])
    ln(model.dec_final_norm, "decoder.layer_norm")
    for i, lyr in enumerate(model.decoder_layers_m):
        p = f"decoder.layers.{i}."
        attn(lyr.self_attn, p + "self_attn")
        ln(lyr.self_attn_layer_norm, p + "self_attn_layer_norm")
        attn(lyr.encoder_attn, p + "encoder_attn")
        ln(lyr.encoder_attn_layer_norm, p + "encoder_attn_layer_norm")
        lin(lyr.fc1, p + "fc1")
        lin(lyr.fc2, p + "fc2")
        ln(lyr.final_layer_norm, p + "final_layer_norm")
    return model


def load_layoutlm_state_dict(model, state_dict, dtype=None):
    """Populate a ``LayoutLMForMaskedLM``/``LayoutLMModel`` from an HF
    state_dict (BERT encoder + the six 2-D layout tables)."""
    dtype = dtype or jnp.float32
    sd = {k.removeprefix("layoutlm."): _np(v)
          for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    lm = model.layoutlm if hasattr(model, "layoutlm") else model
    emb = "embeddings."
    lm.word_embeddings.weight = j(sd[emb + "word_embeddings.weight"])
    lm.position_embeddings.weight = j(
        sd[emb + "position_embeddings.weight"])
    for name in ("x_position_embeddings", "y_position_embeddings",
                 "h_position_embeddings", "w_position_embeddings",
                 "token_type_embeddings"):
        getattr(lm, name).weight = j(sd[emb + name + ".weight"])
    lm.emb_norm.weight = j(sd[emb + "LayerNorm.weight"])
    lm.emb_norm.bias = j(sd[emb + "LayerNorm.bias"])
    # encoder blocks are BERT's layout
    remapped = {"bert." + k: v for k, v in sd.items()
                if k.startswith("encoder.") or k.startswith("pooler.")}
    # load_bert_state_dict also wants embeddings keys; give it ours
    for k, v in sd.items():
        if k.startswith("embeddings.word") or \
                k.startswith("embeddings.position_embeddings") or \
                k.startswith("embeddings.token_type") or \
                k.startswith("embeddings.LayerNorm"):
            remapped["bert." + k] = v

    class _Shim:
        class bert:
            embeddings = type("E", (), {})()
            layers = lm.layers
            pooler = lm.pooler
    # reuse only the per-layer loop: temporary emb holder with .weight attrs
    e = _Shim.bert.embeddings
    for name in ("word_embeddings", "position_embeddings",
                 "token_type_embeddings", "layer_norm"):
        setattr(e, name, type("W", (), {"weight": None, "bias": None})())
    load_bert_state_dict(_Shim(), remapped, dtype=dtype)
    if hasattr(model, "mlm_transform") and "cls.predictions.bias" in \
            state_dict:
        sp = {k: _np(v) for k, v in state_dict.items()}
        model.mlm_transform.weight = j(
            sp["cls.predictions.transform.dense.weight"].T)
        model.mlm_transform.bias = j(
            sp["cls.predictions.transform.dense.bias"])
        model.mlm_norm.weight = j(
            sp["cls.predictions.transform.LayerNorm.weight"])
        model.mlm_norm.bias = j(
            sp["cls.predictions.transform.LayerNorm.bias"])
        model.mlm_bias = j(sp["cls.predictions.bias"])
    return model


def load_phi_state_dict(model, state_dict, dtype=None):
    """Populate a ``PhiForCausalLM`` from an HF state_dict (separate
    biased q/k/v packed into the fused projection; untied biased head)."""
    cfg = model.cfg
    dtype = dtype or cfg.dtype
    sd = {k.removeprefix("model."): _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    model.embed_tokens = j(sd["embed_tokens.weight"])
    model.final_layernorm.weight = j(sd["final_layernorm.weight"])
    model.final_layernorm.bias = j(sd["final_layernorm.bias"])
    model.lm_head = j(_np(state_dict["lm_head.weight"]).T)
    model.lm_head_bias = j(_np(state_dict["lm_head.bias"]))
    for i, lyr in enumerate(model.layers):
        p = f"layers.{i}."
        lyr.input_layernorm.weight = j(sd[p + "input_layernorm.weight"])
        lyr.input_layernorm.bias = j(sd[p + "input_layernorm.bias"])
        q = sd[p + "self_attn.q_proj.weight"].T
        k = sd[p + "self_attn.k_proj.weight"].T
        v = sd[p + "self_attn.v_proj.weight"].T
        lyr.qkv_proj = j(np.concatenate([q, k, v], axis=1))
        lyr.qkv_bias = j(np.concatenate(
            [sd[p + "self_attn.q_proj.bias"],
             sd[p + "self_attn.k_proj.bias"],
             sd[p + "self_attn.v_proj.bias"]]))
        lyr.dense = j(sd[p + "self_attn.dense.weight"].T)
        lyr.dense_bias = j(sd[p + "self_attn.dense.bias"])
        lyr.fc1 = j(sd[p + "mlp.fc1.weight"].T)
        lyr.fc1_bias = j(sd[p + "mlp.fc1.bias"])
        lyr.fc2 = j(sd[p + "mlp.fc2.weight"].T)
        lyr.fc2_bias = j(sd[p + "mlp.fc2.bias"])
    return model


def load_roformer_state_dict(model, state_dict, dtype=None):
    """Populate a ``RoFormerForMaskedLM``/``RoFormerModel`` from an HF
    state_dict (``roformer.*`` naming; rotary has no weights)."""
    dtype = dtype or jnp.float32
    sd = {k.removeprefix("roformer."): _np(v)
          for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def lin(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"].T)
        layer.bias = j(sd[prefix + ".bias"])

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    rf = model.roformer if hasattr(model, "roformer") else model
    rf.word_embeddings.weight = j(sd["embeddings.word_embeddings.weight"])
    rf.token_type_embeddings.weight = j(
        sd["embeddings.token_type_embeddings.weight"])
    ln(rf.emb_norm, "embeddings.LayerNorm")
    for i, lyr in enumerate(rf.layers):
        p = f"encoder.layer.{i}."
        lin(lyr.q_proj, p + "attention.self.query")
        lin(lyr.k_proj, p + "attention.self.key")
        lin(lyr.v_proj, p + "attention.self.value")
        lin(lyr.out_proj, p + "attention.output.dense")
        ln(lyr.attn_norm, p + "attention.output.LayerNorm")
        lin(lyr.intermediate, p + "intermediate.dense")
        lin(lyr.output, p + "output.dense")
        ln(lyr.out_norm, p + "output.LayerNorm")
    if hasattr(model, "mlm_transform") and \
            "cls.predictions.bias" in state_dict:
        sp = {k: _np(v) for k, v in state_dict.items()}
        model.mlm_transform.weight = j(
            sp["cls.predictions.transform.dense.weight"].T)
        model.mlm_transform.bias = j(
            sp["cls.predictions.transform.dense.bias"])
        model.mlm_norm.weight = j(
            sp["cls.predictions.transform.LayerNorm.weight"])
        model.mlm_norm.bias = j(
            sp["cls.predictions.transform.LayerNorm.bias"])
        model.mlm_bias = j(sp["cls.predictions.bias"])
    return model


def load_fnet_state_dict(model, state_dict, dtype=None):
    """Populate an ``FNetForMaskedLM``/``FNetModel`` from an HF
    state_dict (no attention weights — Fourier mixing is parameterless)."""
    dtype = dtype or jnp.float32
    sd = {k.removeprefix("fnet."): _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def lin(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"].T)
        layer.bias = j(sd[prefix + ".bias"])

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    fn = model.fnet if hasattr(model, "fnet") else model
    fn.word_embeddings.weight = j(sd["embeddings.word_embeddings.weight"])
    fn.position_embeddings.weight = j(
        sd["embeddings.position_embeddings.weight"])
    fn.token_type_embeddings.weight = j(
        sd["embeddings.token_type_embeddings.weight"])
    ln(fn.emb_norm, "embeddings.LayerNorm")
    lin(fn.projection, "embeddings.projection")
    for i, lyr in enumerate(fn.layers):
        p = f"encoder.layer.{i}."
        ln(lyr.fourier_norm, p + "fourier.output.LayerNorm")
        lin(lyr.intermediate, p + "intermediate.dense")
        lin(lyr.output, p + "output.dense")
        ln(lyr.out_norm, p + "output.LayerNorm")
    if hasattr(model, "mlm_transform") and \
            "cls.predictions.bias" in state_dict:
        sp = {k: _np(v) for k, v in state_dict.items()}
        model.mlm_transform.weight = j(
            sp["cls.predictions.transform.dense.weight"].T)
        model.mlm_transform.bias = j(
            sp["cls.predictions.transform.dense.bias"])
        model.mlm_norm.weight = j(
            sp["cls.predictions.transform.LayerNorm.weight"])
        model.mlm_norm.bias = j(
            sp["cls.predictions.transform.LayerNorm.bias"])
        model.mlm_bias = j(sp["cls.predictions.bias"])
    return model


def load_mpnet_state_dict(model, state_dict, dtype=None):
    """Populate an ``MPNetForMaskedLM``/``MPNetModel`` from an HF
    state_dict (shared relative_attention_bias table; lm_head tied)."""
    dtype = dtype or jnp.float32
    sd = {k.removeprefix("mpnet."): _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def lin(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"].T)
        layer.bias = j(sd[prefix + ".bias"])

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    mp = model.mpnet if hasattr(model, "mpnet") else model
    mp.word_embeddings.weight = j(sd["embeddings.word_embeddings.weight"])
    mp.position_embeddings.weight = j(
        sd["embeddings.position_embeddings.weight"])
    ln(mp.emb_norm, "embeddings.LayerNorm")
    mp.relative_attention_bias.weight = j(
        sd["encoder.relative_attention_bias.weight"])
    for i, lyr in enumerate(mp.layers):
        p = f"encoder.layer.{i}."
        lin(lyr.q_proj, p + "attention.attn.q")
        lin(lyr.k_proj, p + "attention.attn.k")
        lin(lyr.v_proj, p + "attention.attn.v")
        lin(lyr.o_proj, p + "attention.attn.o")
        ln(lyr.attn_norm, p + "attention.LayerNorm")
        lin(lyr.intermediate, p + "intermediate.dense")
        lin(lyr.output, p + "output.dense")
        ln(lyr.out_norm, p + "output.LayerNorm")
    if hasattr(model, "lm_dense") and "lm_head.bias" in state_dict:
        sp = {k: _np(v) for k, v in state_dict.items()}
        model.lm_dense.weight = j(sp["lm_head.dense.weight"].T)
        model.lm_dense.bias = j(sp["lm_head.dense.bias"])
        model.lm_norm.weight = j(sp["lm_head.layer_norm.weight"])
        model.lm_norm.bias = j(sp["lm_head.layer_norm.bias"])
        model.lm_bias = j(sp["lm_head.bias"])
    return model


def load_nezha_state_dict(model, state_dict, dtype=None):
    """Populate a ``NezhaForMaskedLM``/``NezhaModel`` from an HF
    state_dict (functional positions — no position table to load)."""
    dtype = dtype or jnp.float32
    sd = {k.removeprefix("nezha."): _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def lin(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"].T)
        layer.bias = j(sd[prefix + ".bias"])

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    nz = model.nezha if hasattr(model, "nezha") else model
    nz.word_embeddings.weight = j(sd["embeddings.word_embeddings.weight"])
    nz.token_type_embeddings.weight = j(
        sd["embeddings.token_type_embeddings.weight"])
    ln(nz.emb_norm, "embeddings.LayerNorm")
    for i, lyr in enumerate(nz.layers):
        p = f"encoder.layer.{i}."
        lin(lyr.q_proj, p + "attention.self.query")
        lin(lyr.k_proj, p + "attention.self.key")
        lin(lyr.v_proj, p + "attention.self.value")
        lin(lyr.o_proj, p + "attention.output.dense")
        ln(lyr.attn_norm, p + "attention.output.LayerNorm")
        lin(lyr.intermediate, p + "intermediate.dense")
        lin(lyr.output, p + "output.dense")
        ln(lyr.out_norm, p + "output.LayerNorm")
    if "pooler.dense.weight" in sd:
        lin(nz.pooler, "pooler.dense")
    if hasattr(model, "mlm_transform") and \
            "cls.predictions.bias" in state_dict:
        sp = {k: _np(v) for k, v in state_dict.items()}
        model.mlm_transform.weight = j(
            sp["cls.predictions.transform.dense.weight"].T)
        model.mlm_transform.bias = j(
            sp["cls.predictions.transform.dense.bias"])
        model.mlm_norm.weight = j(
            sp["cls.predictions.transform.LayerNorm.weight"])
        model.mlm_norm.bias = j(
            sp["cls.predictions.transform.LayerNorm.bias"])
        model.mlm_bias = j(sp["cls.predictions.bias"])
    return model


def load_big_bird_state_dict(model, state_dict, dtype=None):
    """Populate a ``BigBirdForMaskedLM``/``BigBirdModel`` from an HF
    state_dict (original_full attention layout)."""
    dtype = dtype or jnp.float32
    sd = {k.removeprefix("bert."): _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def lin(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"].T)
        layer.bias = j(sd[prefix + ".bias"])

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    bb = model.bert if hasattr(model, "bert") else model
    bb.word_embeddings.weight = j(sd["embeddings.word_embeddings.weight"])
    bb.position_embeddings.weight = j(
        sd["embeddings.position_embeddings.weight"])
    bb.token_type_embeddings.weight = j(
        sd["embeddings.token_type_embeddings.weight"])
    ln(bb.emb_norm, "embeddings.LayerNorm")
    for i, lyr in enumerate(bb.layers):
        p = f"encoder.layer.{i}."
        lin(lyr.q_proj, p + "attention.self.query")
        lin(lyr.k_proj, p + "attention.self.key")
        lin(lyr.v_proj, p + "attention.self.value")
        lin(lyr.out_proj, p + "attention.output.dense")
        ln(lyr.attn_norm, p + "attention.output.LayerNorm")
        lin(lyr.intermediate, p + "intermediate.dense")
        lin(lyr.output, p + "output.dense")
        ln(lyr.out_norm, p + "output.LayerNorm")
    if hasattr(model, "mlm_transform") and \
            "cls.predictions.bias" in state_dict:
        sp = {k: _np(v) for k, v in state_dict.items()}
        model.mlm_transform.weight = j(
            sp["cls.predictions.transform.dense.weight"].T)
        model.mlm_transform.bias = j(
            sp["cls.predictions.transform.dense.bias"])
        model.mlm_norm.weight = j(
            sp["cls.predictions.transform.LayerNorm.weight"])
        model.mlm_norm.bias = j(
            sp["cls.predictions.transform.LayerNorm.bias"])
        model.mlm_bias = j(sp["cls.predictions.bias"])
    return model


def load_megatron_bert_state_dict(model, state_dict, dtype=None):
    """Populate a ``MegatronBertForMaskedLM``/``MegatronBertModel`` from
    an HF state_dict (pre-LN layout: attention.ln / layer.ln / final
    encoder.ln)."""
    dtype = dtype or jnp.float32
    sd = {k.removeprefix("bert."): _np(v) for k, v in state_dict.items()}

    def j(a):
        return jnp.asarray(a, dtype)

    def lin(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"].T)
        layer.bias = j(sd[prefix + ".bias"])

    def ln(layer, prefix):
        layer.weight = j(sd[prefix + ".weight"])
        layer.bias = j(sd[prefix + ".bias"])

    mb = model.bert if hasattr(model, "bert") else model
    mb.word_embeddings.weight = j(sd["embeddings.word_embeddings.weight"])
    mb.position_embeddings.weight = j(
        sd["embeddings.position_embeddings.weight"])
    mb.token_type_embeddings.weight = j(
        sd["embeddings.token_type_embeddings.weight"])
    ln(mb.final_ln, "encoder.ln")
    for i, lyr in enumerate(mb.layers):
        p = f"encoder.layer.{i}."
        ln(lyr.attn_ln, p + "attention.ln")
        lin(lyr.q_proj, p + "attention.self.query")
        lin(lyr.k_proj, p + "attention.self.key")
        lin(lyr.v_proj, p + "attention.self.value")
        lin(lyr.out_proj, p + "attention.output.dense")
        ln(lyr.ff_ln, p + "ln")
        lin(lyr.intermediate, p + "intermediate.dense")
        lin(lyr.output, p + "output.dense")
    if "pooler.dense.weight" in sd:
        lin(mb.pooler, "pooler.dense")
    if hasattr(model, "mlm_transform") and \
            "cls.predictions.bias" in state_dict:
        sp = {k: _np(v) for k, v in state_dict.items()}
        model.mlm_transform.weight = j(
            sp["cls.predictions.transform.dense.weight"].T)
        model.mlm_transform.bias = j(
            sp["cls.predictions.transform.dense.bias"])
        model.mlm_norm.weight = j(
            sp["cls.predictions.transform.LayerNorm.weight"])
        model.mlm_norm.bias = j(
            sp["cls.predictions.transform.LayerNorm.bias"])
        model.mlm_bias = j(sp["cls.predictions.bias"])
    return model
