"""Paged KV cache + continuous batched decode (serving story).

Ref capability: PaddleNLP ``llm`` predictor block-attention +
``fused_multi_transformer_op.cu``'s block KV cache. TPU-native split of
responsibilities:

  * DEVICE: fixed-shape jitted steps — ``llama_prefill_paged`` (padded
    ragged prompts through the varlen flash path, K/V scattered into the
    block pool) and ``llama_decode_step_paged`` (one token per sequence,
    pool-direct paged attention via the scalar-prefetch Pallas kernel).
  * HOST: ``BlockManager`` — the free-list/allocation policy (what vLLM's
    scheduler does). Between steps it grows block tables and recycles a
    finished sequence's blocks. Host-side management is the TPU-idiomatic
    design: allocation is control flow, not math, and the device program
    keeps a single static shape.

HBM for the cache is ``num_blocks * block_size`` tokens ≈ Σ actual sequence
lengths (rounded up per block) — NOT batch × max_len as in the static
``KVCache`` (models/decoding.py), which this complements, not replaces.
"""
from __future__ import annotations

import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.observability.memledger import MemLedger
from paddle_tpu.ops import attention as A
from paddle_tpu.ops.pallas.paged_attention import (_note_trace,
                                                   paged_chunk_attention,
                                                   paged_decode_attention)
from paddle_tpu.quantization import wo_matmul as _wo


@dataclass
class PagedKVCache:
    """Per-layer block pools + per-sequence block tables (pytree).

    ``k_scales``/``v_scales`` are EMPTY for the bf16 pool (the legacy
    4-arg construction still works) and hold per-layer
    [N_blocks, block_size, H_kv] f32 scale pools when the KV pool is
    int8 (``init(..., kv_dtype="int8")``): element (n, o, h) is the
    absmax/127 scale of pool row (n, o, h, :). Tuple truthiness is
    STATIC pytree structure, so jitted forwards branch on
    ``if cache.k_scales:`` at trace time — the bf16 trace is unchanged."""
    k_pools: list   # [L] of [N_blocks, block_size, H_kv, D]
    v_pools: list
    block_tables: jnp.ndarray  # [B, max_blocks] int32 (pad = n_blocks)
    lens: jnp.ndarray          # [B] int32 — tokens currently in cache
    k_scales: tuple = ()       # [L] of [N_blocks, block_size, H_kv] f32
    v_scales: tuple = ()

    @property
    def block_size(self):
        return self.k_pools[0].shape[1]

    @property
    def num_blocks(self):
        return self.k_pools[0].shape[0]

    def pool_tokens(self):
        """Total cache capacity in tokens (the HBM bound)."""
        return self.num_blocks * self.block_size

    @staticmethod
    def init(num_layers, num_blocks, block_size, num_kv_heads, head_dim,
             batch, max_blocks_per_seq, dtype, kv_dtype=None):
        pool_dtype = dtype
        k_scales = v_scales = ()
        if kv_dtype is not None:
            if jnp.dtype(kv_dtype) != jnp.int8:
                raise ValueError(
                    f"unsupported kv_dtype {kv_dtype!r}: only 'int8' "
                    "(per-position absmax scales) or None (model dtype)")
            pool_dtype = jnp.int8
            zs = lambda: jnp.zeros((num_blocks, block_size, num_kv_heads),
                                   jnp.float32)
            k_scales = tuple(zs() for _ in range(num_layers))
            v_scales = tuple(zs() for _ in range(num_layers))
        z = lambda: jnp.zeros((num_blocks, block_size, num_kv_heads,
                               head_dim), pool_dtype)
        return PagedKVCache(
            [z() for _ in range(num_layers)],
            [z() for _ in range(num_layers)],
            jnp.full((batch, max_blocks_per_seq), num_blocks, jnp.int32),
            jnp.zeros((batch,), jnp.int32), k_scales, v_scales)


jax.tree_util.register_pytree_node(
    PagedKVCache,
    lambda c: ((c.k_pools, c.v_pools, c.block_tables, c.lens,
                c.k_scales, c.v_scales), None),
    lambda aux, ch: PagedKVCache(*ch))


# ------------------------------------------------ int8 KV quantization
def kv_quant_enabled() -> bool:
    """The ``PT_QUANT_KV`` kill switch, read at TRACE time (flip it
    between engine constructions together with ``clear_jit_caches``)."""
    return os.environ.get("PT_QUANT_KV", "1").strip().lower() \
        not in ("0", "off")


def _quantize_kv(vals):
    """Per-(position, head) symmetric int8: vals [..., H, D] ->
    (int8 [..., H, D], f32 scales [..., H]). absmax over D / 127; the
    epsilon floor keeps all-zero rows (padding) at scale ~0 without a
    0/0."""
    if not kv_quant_enabled():
        raise RuntimeError(
            "PT_QUANT_KV=0 but an int8 KV pool is being traced — rebuild "
            "the engine under the kill switch (bf16 pool) and call "
            "models.paged.clear_jit_caches() so no stale int8 trace runs")
    _note_trace("kv:int8-write")
    f = vals.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(f), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(f / scale[..., None]), -127, 127) \
        .astype(jnp.int8)
    return q, scale


def _scatter_kv(cache, li, k, v, scatter, *args):
    """Scatter layer ``li``'s new K/V through ``scatter`` (one of the
    three scatter primitives below — all are ``(pool, vals, *rest)`` and
    trailing-dim generic). bf16 pool: plain writes, scale slots None.
    int8 pool: quantize-on-write — the int8 codes land in the pools and
    the absmax scales in the parallel scale pools via the SAME scatter
    (same table/len/active masking, so codes and scales never desync)."""
    if not cache.k_scales:
        return (scatter(cache.k_pools[li], k, *args),
                scatter(cache.v_pools[li], v, *args), None, None)
    qk, sk = _quantize_kv(k)
    qv, sv = _quantize_kv(v)
    return (scatter(cache.k_pools[li], qk, *args),
            scatter(cache.v_pools[li], qv, *args),
            scatter(cache.k_scales[li], sk, *args),
            scatter(cache.v_scales[li], sv, *args))


class BlockManager:
    """Host-side free-list allocator for the shared block pool."""

    def __init__(self, num_blocks: int, block_size: int):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free = list(range(num_blocks - 1, -1, -1))
        self.tables: dict[int, list[int]] = {}
        self._prefix_done: dict[int, int] = {}  # free_prefix resume index
        # per-pool memory ledger: every mutation choke point below
        # notifies it (test_lint enforces the list), so the five-state
        # block classification reconciles by construction
        self.ledger = MemLedger(num_blocks, block_size)

    @property
    def free_blocks(self):
        return len(self._free)

    def blocks_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.block_size)

    def allocate(self, seq_id: int, n_tokens: int):
        """Ensure seq_id owns enough blocks for n_tokens; grow as needed."""
        table = self.tables.setdefault(seq_id, [])
        need = self.blocks_needed(n_tokens) - len(table)
        if need > self.free_blocks:
            raise MemoryError(
                f"paged cache out of blocks: need {need}, "
                f"free {self.free_blocks} (of {self.num_blocks})")
        for _ in range(max(need, 0)):
            blk = self._pop_free()
            table.append(blk)
            self.ledger.table_enter(seq_id, blk)
        return table

    def _pop_free(self) -> int:
        """Take one block off the free list (prefix-cache eviction hook)."""
        if not self._free:
            raise MemoryError("paged cache out of blocks")
        return self._free.pop()

    def free(self, seq_id: int):
        for b in reversed(self.tables.pop(seq_id, [])):
            if b is None:
                continue
            self.ledger.table_exit(seq_id, b)
            self._free.append(b)
        self.ledger.table_drop(seq_id)
        self._prefix_done.pop(seq_id, None)

    def free_prefix(self, seq_id: int, n_blocks: int):
        """Release the first ``n_blocks`` table entries (sliding-window
        recycling: positions below ``cur - window`` are never attended
        again — ref block-attention's window cache bound). Table POSITIONS
        are kept as ``None`` placeholders so later block indices stay
        aligned; returns the freed (position, block) pairs. Scans resume
        from the last freed index, so each block is visited once over the
        sequence's whole lifetime (not O(length^2) re-walks)."""
        table = self.tables.get(seq_id, [])
        upto = min(n_blocks, len(table))
        start = self._prefix_done.get(seq_id, 0)
        freed = []
        for idx in range(start, upto):
            if table[idx] is not None:
                freed.append((idx, table[idx]))
                self.ledger.table_exit(seq_id, table[idx], hole=True)
                self._release(table[idx])
                table[idx] = None
        if upto > start:
            self._prefix_done[seq_id] = upto
        return freed

    def _release(self, blk: int):
        """Return one block to the free list (refcount hook point)."""
        self._free.append(blk)

    def table_array(self, seq_ids, max_blocks):
        """[B, max_blocks] int32; unused slots = num_blocks (OOB sentinel,
        dropped by scatter, clamped-masked by the kernel contract)."""
        out = np.full((len(seq_ids), max_blocks), self.num_blocks, np.int32)
        for row, sid in enumerate(seq_ids):
            t = self.tables.get(sid, [])
            if self._prefix_done.get(sid, 0) == 0:   # no None placeholders
                out[row, :len(t)] = t
            else:
                for idx, b in enumerate(t):
                    if b is not None:
                        out[row, idx] = b
        return jnp.asarray(out)


class RefBlockManager(BlockManager):
    """BlockManager + refcounts: beams FORK a sequence by sharing its full
    (immutable — the pool is append-only) blocks and privately copying only
    the partial last block. The reference's block-attention serving keeps
    the same share/copy split for beams (vLLM-style copy-on-write, but
    append-only KV means ONLY the tail block can ever need the copy)."""

    def __init__(self, num_blocks: int, block_size: int):
        super().__init__(num_blocks, block_size)
        self._rc: dict[int, int] = {}

    def allocate(self, seq_id, n_tokens):
        before = set(self.tables.get(seq_id, []))
        table = super().allocate(seq_id, n_tokens)
        for blk in table:
            if blk not in before:
                self._rc[blk] = 1
        return table

    def fork(self, src_id, dst_id, n_tokens: int):
        """dst shares src's blocks; if the last block is partial (n_tokens
        not block-aligned) dst gets a PRIVATE fresh block for it. Returns
        (src_blk, dst_blk) to copy on device, or None."""
        src = self.tables[src_id]
        table = list(src)
        copy = None
        partial = (n_tokens % self.block_size != 0 and table
                   and table[-1] is not None)
        if partial and not self.free_blocks:
            # capacity check BEFORE the retain loop: a failed fork must
            # leave refcounts untouched (callers retry after preempting —
            # a leaked retain would permanently shrink the pool)
            raise MemoryError("paged cache out of blocks for beam fork")
        for blk in (table[:-1] if partial else table):
            if blk is None:   # window-recycled placeholder: nothing shared
                continue
            self._retain(blk)
        # the fork inherits the recycled-prefix marker: table_array's fast
        # path and future free_prefix scans key on it
        if src_id in self._prefix_done:
            self._prefix_done[dst_id] = self._prefix_done[src_id]
        if partial:
            fresh = self._pop_free()
            self._rc[fresh] = 1
            copy = (table[-1], fresh)
            table[-1] = fresh
        self.tables[dst_id] = table
        for blk in table:
            if blk is not None:
                self.ledger.table_enter(dst_id, blk)
        return copy

    def free(self, seq_id):
        for blk in self.tables.pop(seq_id, []):
            if blk is None:
                continue
            self.ledger.table_exit(seq_id, blk)
            self._release(blk)
        self.ledger.table_drop(seq_id)
        self._prefix_done.pop(seq_id, None)

    def _release(self, blk):
        """Refcounted release: the block returns to the free list only at
        rc == 0 (free_prefix routes through here too, so windowed
        recycling can never double-free a beam-shared block)."""
        self._rc[blk] -= 1
        if self._rc[blk] == 0:
            del self._rc[blk]
            self._free.append(blk)

    def _retain(self, blk):
        """Take one more reference on a live block (beam fork sharing)."""
        self._rc[blk] = self._rc.get(blk, 0) + 1


class PrefixCachingBlockManager(RefBlockManager):
    """RefBlockManager + cross-request prefix reuse (ref capability:
    PaddleNLP ``llm/predict`` block-attention serving; vLLM-style
    hash-block caching).

    Every FULL block of a committed prompt gets a content chain hash
    ``sha1(parent_digest || block token bytes)`` — the digest identifies
    the whole prefix up to and including the block, so equal digests mean
    equal KV contents (the pool is append-only and KV is a deterministic
    function of the token prefix). Blocks whose refcount drops to zero
    but that carry a digest are PARKED in an LRU ``evictable`` pool (still
    resident in HBM) instead of the free list; a later request whose
    prompt chain-hashes onto them re-shares the blocks (rc+1, zero
    recompute) and prefills only the uncached suffix. When the free list
    runs dry, allocation evicts parked blocks LRU-first — so caching
    never reduces usable capacity."""

    def __init__(self, num_blocks: int, block_size: int):
        super().__init__(num_blocks, block_size)
        import collections
        self._hash_to_block: dict[bytes, int] = {}
        self._block_hash: dict[int, bytes] = {}
        self._evictable = collections.OrderedDict()   # blk -> None, LRU order
        # hit_blocks / evictions / lookup_blocks are CUMULATIVE — the
        # engine exports them as serving_prefix_* metrics (deltas pushed
        # at each gauge refresh); lookup_blocks counts the full prompt
        # blocks every match_prefix probe COULD have hit, the hit-rate
        # denominator
        self.cache_stats = {"hit_blocks": 0, "evictions": 0,
                            "lookup_blocks": 0}
        # bumped whenever the set of matchable blocks changes (eviction
        # or a new commit) — the scheduler's per-request match memo keys
        # on it, so a queued prompt is re-hashed only when a probe could
        # actually return something different
        self.cache_epoch = 0

    # ---- capacity: parked blocks are reclaimable, so they count as free
    @property
    def free_blocks(self):
        return len(self._free) + len(self._evictable)

    def _pop_free(self):
        if self._free:
            return self._free.pop()
        if self._evictable:
            blk, _ = self._evictable.popitem(last=False)     # LRU eviction
            h = self._block_hash.pop(blk, None)
            if h is not None and self._hash_to_block.get(h) == blk:
                del self._hash_to_block[h]
            self.cache_stats["evictions"] += 1
            self.cache_epoch += 1
            self.ledger.unpark(blk)
            return blk
        raise MemoryError("paged cache out of blocks")

    def _release(self, blk):
        self._rc[blk] -= 1
        if self._rc[blk] == 0:
            del self._rc[blk]
            if blk in self._block_hash:       # park, MRU end
                self._evictable[blk] = None
                self._evictable.move_to_end(blk)
                self.ledger.park(blk)
            else:
                self._free.append(blk)

    def _retain(self, blk):
        if blk in self._evictable:            # revive a parked block
            del self._evictable[blk]
            self.ledger.unpark(blk)
        super()._retain(blk)

    # ------------------------------------------------------------ hashing
    def _chain_digests(self, tokens, n_full, adapter=None):
        import hashlib
        toks = np.asarray(tokens, np.int32)
        # adapter identity seeds the chain (ISSUE 14): KV computed under
        # one LoRA adapter differs numerically from another tenant's, so
        # two tenants' identical prompts must never share blocks. None
        # keeps the legacy empty seed — old digests stay bit-identical.
        digest = (b"" if adapter is None
                  else hashlib.sha1(repr(adapter).encode()).digest())
        out = []
        for i in range(n_full):
            digest = hashlib.sha1(
                digest + toks[i * self.block_size:
                              (i + 1) * self.block_size].tobytes()).digest()
            out.append(digest)
        return out

    def match_prefix(self, tokens, adapter=None) -> list[int]:
        """Longest run of resident full-block prefix matches for this
        prompt. Capped at (len-1)//block_size so at least the last prompt
        token is always prefilled — its logits seed the first sample."""
        n_full = (len(tokens) - 1) // self.block_size
        self.cache_stats["lookup_blocks"] += n_full
        blocks = []
        for d in self._chain_digests(tokens, n_full, adapter):
            blk = self._hash_to_block.get(d)
            if blk is None:
                break
            blocks.append(blk)
        return blocks

    def adopt_prefix(self, seq_id, blocks):
        """Install shared cached blocks as seq_id's table prefix (rc+1
        each; parked blocks are revived). The caller prefills from
        ``len(blocks) * block_size`` onward."""
        assert seq_id not in self.tables
        for blk in blocks:
            self._retain(blk)
        self.tables[seq_id] = list(blocks)
        for blk in self.tables[seq_id]:
            self.ledger.table_enter(seq_id, blk)
        self.cache_stats["hit_blocks"] += len(blocks)
        return self.tables[seq_id]

    def commit_prefix(self, seq_id, tokens, adapter=None):
        """Register chain digests for seq_id's full prompt blocks so later
        requests can share them. First-writer-wins per digest; safe to call
        before the prefill has executed on device — any matching request's
        program consumes the pool AFTER this one's writes (jax data
        dependency orders them)."""
        table = self.tables.get(seq_id, [])
        n_full = min(len(tokens) // self.block_size, len(table))
        for i, d in enumerate(self._chain_digests(tokens, n_full, adapter)):
            blk = table[i]
            if blk is None:
                break                          # window-recycled: stop
            if d not in self._hash_to_block and blk not in self._block_hash:
                self._hash_to_block[d] = blk
                self._block_hash[blk] = d
                self.cache_epoch += 1


class PrefixMatch:
    """Longest shared TOKEN span found by
    :meth:`RadixPrefixBlockManager.match_prefix`.

    ``blocks`` are fully-shared blocks (adopted rc+1, zero copies);
    ``cow`` is the optional partial boundary share — ``(src_block,
    hit_tokens)`` with ``0 < hit_tokens < block_size`` — the adopter gets
    a private copy of ``src_block`` and prefills from token ``hit``
    inside it. ``len()`` is the number of fully-shared blocks so the
    scheduler's block-denominated reservation math stays
    manager-agnostic; truthiness is any token hit at all."""

    __slots__ = ("blocks", "token_count", "cow")

    def __init__(self, blocks, token_count, cow=None):
        self.blocks = blocks
        self.token_count = token_count
        self.cow = cow

    def __len__(self):
        return len(self.blocks)

    def __bool__(self):
        return self.token_count > 0

    def __iter__(self):
        return iter(self.blocks)

    def __repr__(self):
        return (f"PrefixMatch(blocks={self.blocks}, "
                f"token_count={self.token_count}, cow={self.cow})")


class _RadixNode:
    """One radix-trie edge: a token span owning the physical blocks that
    hold its KV. Spans start block-aligned; only a childless tail may be
    partial (len(tokens) % block_size != 0)."""

    __slots__ = ("tokens", "blocks", "children", "parent", "touch")

    def __init__(self, tokens, blocks, parent):
        self.tokens = tokens          # np.int32 span
        self.blocks = blocks          # list[int], ceil(len(tokens)/bs)
        self.children = []            # children start block-aligned
        self.parent = parent
        self.touch = 0


class _PendingCopy:
    """One host-side COW order: copy pool block ``src`` into ``dst``
    before the adopter's prefill chunk. ``dead`` marks orders whose dst
    was freed (adopter cancelled/preempted) before the engine drained
    the plan — the copy must not run into a reallocated block."""

    __slots__ = ("src", "dst", "dead")

    def __init__(self, src, dst):
        self.src = src
        self.dst = dst
        self.dead = False


def _common_len(a, b):
    """Length of the common prefix of two int32 token arrays."""
    n = min(len(a), len(b))
    if n == 0:
        return 0
    neq = np.nonzero(a[:n] != b[:n])[0]
    return int(neq[0]) if len(neq) else n


class RadixPrefixBlockManager(RefBlockManager):
    """RefBlockManager + a token-level radix trie over the block pool
    (SGLang RadixAttention on vLLM-style paging).

    Where :class:`PrefixCachingBlockManager` matches whole aligned
    blocks by chain hash, this trie matches the longest shared TOKEN
    span: edges own ref-counted physical blocks, a partially-filled
    boundary block is shared read-only and copied-on-write at first
    divergence (one fresh block; the engine applies the device copy via
    ``take_copy_plan`` before the adopter's prefill chunk), and
    ``commit_prefix`` inserts partial tails too — so divergence inside a
    block forfeits only the divergent suffix, not the whole tail.

    Blocks whose refcount drops to zero but that live in the trie are
    PARKED (still resident, counted as free); when the free list runs
    dry, eviction walks unreferenced trie leaves LRU-by-touch, one tail
    block at a time — so caching never reduces usable capacity.
    ``cache_epoch`` bumps on every eviction and commit; the scheduler's
    per-request match memo keys on it."""

    def __init__(self, num_blocks: int, block_size: int):
        super().__init__(num_blocks, block_size)
        self._root = _RadixNode(np.empty(0, np.int32), [], None)
        # one trie PER ADAPTER IDENTITY (ISSUE 14): KV computed under a
        # LoRA adapter is numerically that adapter's — tenants must never
        # adopt each other's blocks. The base-model (None) trie is the
        # legacy ``_root`` so adapter-free serving is untouched.
        self._roots: dict[object, _RadixNode] = {None: self._root}
        self._in_trie: dict[int, _RadixNode] = {}   # blk -> owning node
        self._parked: set[int] = set()              # trie blocks, rc == 0
        self._touch = 0
        self.cache_epoch = 0
        self._pending: list[_PendingCopy] = []
        self._copy_dst: dict[int, _PendingCopy] = {}
        self.cache_stats = {"hit_blocks": 0, "evictions": 0,
                            "lookup_blocks": 0, "token_hits": 0,
                            "partial_hits": 0, "lookup_tokens": 0}

    # ---- capacity: parked trie blocks are reclaimable, so count as free
    @property
    def free_blocks(self):
        return len(self._free) + len(self._parked)

    def _pop_free(self):
        if self._free:
            return self._free.pop()
        if self._parked:
            return self._evict_one()
        raise MemoryError("paged cache out of blocks")

    def _evict_one(self) -> int:
        """Reclaim ONE parked block: the tail block of the least-recently
        touched childless leaf whose tail is unreferenced. Because
        adoption always takes the full matched path and release frees a
        table all at once, a parked block's whole suffix (deeper blocks
        of its node + every descendant) is parked too — so such a leaf
        always exists while ``_parked`` is non-empty."""
        victim = None
        stack = [ch for root in self._roots.values()
                 for ch in root.children]
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children)
            elif node.blocks and node.blocks[-1] in self._parked:
                if victim is None or node.touch < victim.touch:
                    victim = node
        if victim is None:       # unreachable by the suffix invariant
            raise MemoryError("paged cache out of blocks")
        from paddle_tpu.utils.faults import fault_point
        # chaos site: fires BEFORE any mutation, so an injected exception
        # leaves the trie, refcounts, and free list exactly as they were
        fault_point("serving.prefix_evict", manager=self,
                    blk=victim.blocks[-1], touch=victim.touch)
        blk = victim.blocks.pop()
        self._parked.discard(blk)
        self.ledger.unpark(blk)
        del self._in_trie[blk]
        victim.tokens = victim.tokens[:len(victim.blocks)
                                      * self.block_size]
        if not victim.blocks and victim.parent is not None:
            victim.parent.children.remove(victim)
        self.cache_stats["evictions"] += 1
        self.cache_epoch += 1
        return blk

    def _release(self, blk):
        self._rc[blk] -= 1
        if self._rc[blk] == 0:
            del self._rc[blk]
            pend = self._copy_dst.pop(blk, None)
            if pend is not None:
                # the adopter died before its COW executed: cancel the
                # order and drop the pin on the source block
                pend.dead = True
                self.ledger.unpin(pend.src)
                self._release(pend.src)
            if blk in self._in_trie:
                self._parked.add(blk)
                self.ledger.park(blk)
            else:
                self._free.append(blk)

    def _retain(self, blk):
        self._parked.discard(blk)
        self.ledger.unpark(blk)
        super()._retain(blk)

    # --------------------------------------------------------- matching
    def _best_child(self, node, rem):
        """Child with the longest common token prefix with ``rem``.
        Siblings may overlap (first-writer-wins keeps physically distinct
        blocks for the same tokens), so this is argmax, not a dict hop."""
        best, bl = None, 0
        for ch in node.children:
            n = _common_len(ch.tokens, rem)
            if n > bl:
                best, bl = ch, n
        return best, bl

    def _root_for(self, adapter) -> _RadixNode:
        root = self._roots.get(adapter)
        if root is None:
            root = self._roots[adapter] = _RadixNode(
                np.empty(0, np.int32), [], None)
        return root

    def match_prefix(self, tokens, adapter=None) -> PrefixMatch:
        """Longest shared token span for this prompt, capped at len-1 so
        the last prompt token always prefills (its logits seed the first
        sample). Fully-matched aligned blocks are shared outright; the
        boundary block (divergence or span end mid-block) is offered as a
        copy-on-write partial hit. Matching walks ONLY the trie of the
        request's adapter identity — cross-tenant spans never match."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        cap = len(toks) - 1
        bs = self.block_size
        self.cache_stats["lookup_blocks"] += max(cap, 0) // bs
        self.cache_stats["lookup_tokens"] += max(cap, 0)
        self._touch += 1
        node, depth = self._root_for(adapter), 0
        blocks, cow = [], None
        while depth < cap:
            best, bl = self._best_child(node, toks[depth:cap])
            if best is None or bl == 0:
                break
            best.touch = self._touch
            if bl == len(best.tokens) and bl % bs == 0:
                blocks.extend(best.blocks)
                depth += bl
                node = best
                continue
            # boundary inside ``best``: share its full sub-blocks, offer
            # the partial one copy-on-write
            n_full = bl // bs
            blocks.extend(best.blocks[:n_full])
            hit = bl % bs
            if hit:
                cow = (best.blocks[n_full], hit)
            depth += bl
            break
        return PrefixMatch(blocks, depth, cow)

    # --------------------------------------------------------- adoption
    def adopt_prefix(self, seq_id, match) -> list:
        """Install a match as seq_id's table prefix: retain the shared
        blocks, and for a partial hit allocate one private block and
        queue the (src, dst) device copy. Exception-atomic: a failed
        allocation rolls every retain back."""
        assert seq_id not in self.tables
        blocks = list(match.blocks) if isinstance(match, PrefixMatch) \
            else list(match)
        cow = getattr(match, "cow", None)
        retained = []
        try:
            for blk in blocks:
                self._retain(blk)
                retained.append(blk)
            table = list(blocks)
            if cow is not None:
                src, hit = cow
                # pin src until the plan drains: a parked source must not
                # be evicted/reallocated before the copy program is issued
                self._retain(src)
                retained.append(src)
                dst = self._pop_free()
                self._rc[dst] = 1
                entry = _PendingCopy(src, dst)
                self._pending.append(entry)
                self._copy_dst[dst] = entry
                table.append(dst)
        except BaseException:
            for blk in reversed(retained):
                self._release(blk)
            raise
        self.tables[seq_id] = table
        # ledger transitions only on the success path: the rollback above
        # re-parks/frees via _release, whose own hooks keep it consistent
        for blk in table:
            self.ledger.table_enter(seq_id, blk)
        if cow is not None:
            self.ledger.pin(cow[0])
        self.cache_stats["hit_blocks"] += len(blocks)
        self.cache_stats["token_hits"] += getattr(
            match, "token_count", len(blocks) * self.block_size)
        if cow is not None:
            self.cache_stats["partial_hits"] += 1
        return table

    def take_copy_plan(self) -> list:
        """Drain the pending COW orders as (src, dst) pairs and drop the
        source pins. The engine applies them in ONE device copy before
        any other program of the tick writes the pool — jax data
        dependencies then order the copy before the adopters' prefill
        chunks and before any reallocation of a source block."""
        pairs = []
        pending, self._pending = self._pending, []
        for e in pending:
            if e.dead:
                continue
            pairs.append((e.src, e.dst))
            self._copy_dst.pop(e.dst, None)
            self.ledger.unpin(e.src)
            self._release(e.src)
        return pairs

    # ------------------------------------------------------- insertion
    def commit_prefix(self, seq_id, tokens, adapter=None):
        """Insert seq_id's token span — INCLUDING the partial tail block
        — so later requests can share it. Safe before the writes have
        executed on device (data dependencies order consumers after).
        Callers must pass only tokens whose KV is resident (the engine
        passes the cache frontier, not the just-sampled token). The span
        lands in the trie of ``adapter``'s identity only."""
        table = self.tables.get(seq_id, [])
        toks = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        n_tok = min(len(toks), len(table) * bs)
        for i, b in enumerate(table):     # window-recycled holes: stop
            if b is None:
                n_tok = min(n_tok, i * bs)
                break
        if n_tok <= 0:
            return
        self._insert(toks[:n_tok], table, self._root_for(adapter))
        self.cache_epoch += 1

    def _insert(self, toks, table, root=None):
        bs = self.block_size
        node, depth = (root if root is not None else self._root), 0
        while depth < len(toks):
            rem = toks[depth:]
            best, bl = self._best_child(node, rem)
            if best is None or bl == 0:
                self._attach(node, toks, depth, table)
                return
            if bl == len(best.tokens):
                if bl % bs == 0:
                    node = best
                    depth += bl
                    continue
                # fully matched a partial-tail leaf
                if len(rem) <= bl:
                    return                     # nothing new to insert
                own = table[(depth + bl) // bs]
                if (best.blocks[-1] == own
                        and best.blocks == table[depth // bs:
                                                 depth // bs
                                                 + len(best.blocks)]):
                    # same physical tail block: the original writer
                    # appended — extend the span in place
                    self._extend(best, toks, depth, table)
                else:
                    # same tokens, different block (a COW fork that grew
                    # past the shared span): overlapping sibling; match
                    # picks whichever overlaps a query longest
                    self._attach(node, toks, depth, table)
                return
            # divergence inside ``best``: split at the enclosing block
            # boundary, then attach the new branch (a committer that is
            # merely a PREFIX of ``best`` adds nothing — skip)
            sp = (bl // bs) * bs
            if 0 < sp < len(best.tokens):
                node = self._split(best, sp)
            if len(rem) > bl:
                self._attach(node, toks, depth + sp, table)
            return

    def _attach(self, parent, toks, depth, table):
        """New child of ``parent`` owning the committer's blocks from
        token ``depth`` on (block-aligned by construction)."""
        bs = self.block_size
        span = toks[depth:]
        start = depth // bs
        blocks = []
        for j in range(start, min(len(table),
                                  start + -(-len(span) // bs))):
            b = table[j]
            if b is None or b in self._in_trie:
                break                      # one trie home per block
            blocks.append(b)
        if not blocks:
            return
        span = span[:min(len(span), len(blocks) * bs)]
        self._touch += 1
        node = _RadixNode(span, blocks, parent)
        node.touch = self._touch
        parent.children.append(node)
        for b in blocks:
            self._in_trie[b] = node

    def _extend(self, node, toks, depth, table):
        """Grow a partial-tail node in place: same physical tail block,
        the committer wrote more tokens into it (and possibly beyond)."""
        bs = self.block_size
        span = toks[depth:]
        start = depth // bs
        blocks = list(node.blocks)
        for j in range(start + len(blocks),
                       min(len(table), start + -(-len(span) // bs))):
            b = table[j]
            if b is None or b in self._in_trie:
                break
            blocks.append(b)
        span = span[:min(len(span), len(blocks) * bs)]
        if len(span) <= len(node.tokens):
            return
        for b in blocks[len(node.blocks):]:
            self._in_trie[b] = node
        node.tokens = span
        node.blocks = blocks
        self._touch += 1
        node.touch = self._touch

    def _split(self, node, sp):
        """Split a node at block-aligned token offset ``sp``: the upper
        half keeps the shared prefix, the original node becomes its child
        with the remainder."""
        bs = self.block_size
        upper = _RadixNode(node.tokens[:sp], node.blocks[:sp // bs],
                           node.parent)
        upper.touch = node.touch
        parent = node.parent
        parent.children[parent.children.index(node)] = upper
        node.tokens = node.tokens[sp:]
        node.blocks = node.blocks[sp // bs:]
        node.parent = upper
        upper.children.append(node)
        for b in upper.blocks:
            self._in_trie[b] = upper
        return upper


def _rope_rows(positions, head_dim, base, scaling=None, max_pos=None):
    """cos/sin for PER-ROW positions: [B] -> [B, 1, 1, D/2] (ragged decode:
    every sequence sits at a different position). Shares the scaling math
    with ops.attention; dynamic-NTK uses each ROW's traced current length
    (positions + 1), so every sequence scales by its own length."""
    base, pos_div = A.resolve_rope_scaling(
        base, head_dim, scaling, allow_dynamic=False,
        max_position_embeddings=max_pos,
        cur_len=(positions + 1 if (scaling or {}).get("type") == "dynamic"
                 else None))
    base = jnp.asarray(base, jnp.float32).reshape(-1, 1)     # [B|1, 1]
    inv = 1.0 / (base ** (jnp.arange(0, head_dim, 2,
                                     jnp.float32)[None, :] / head_dim))
    f = (positions.astype(jnp.float32) / pos_div)[:, None] * inv
    return (jnp.cos(f)[:, None, None, :], jnp.sin(f)[:, None, None, :])


def _apply_rope_rows(x, cos, sin):
    """x: [B, 1, H, D]; cos/sin: [B, 1, 1, D/2] (rotate-half, NeoX)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                           axis=-1).astype(x.dtype)


def _scatter_prefill(pool, vals, tables, lens, num_blocks, block_size):
    """Write [B, S, H, D] tokens into the pool at table positions; token
    (b, i) -> (tables[b, i // bs], i % bs), dropped where i >= lens[b]."""
    bsz, s = vals.shape[:2]
    i = jnp.arange(s)
    blk = jnp.take_along_axis(tables, (i[None, :] // block_size), axis=1)
    blk = jnp.where(i[None, :] < lens[:, None], blk, num_blocks)  # OOB=drop
    off = jnp.broadcast_to(i[None, :] % block_size, (bsz, s))
    return pool.at[blk, off].set(vals, mode="drop")


def _scatter_decode(pool, vals, tables, lens, active, num_blocks, block_size):
    """Write ONE token per sequence at position lens[b]; inactive rows
    write nowhere (their blocks may already be recycled)."""
    blk = jnp.take_along_axis(tables, (lens // block_size)[:, None],
                              axis=1)[:, 0]
    blk = jnp.where(active, blk, num_blocks)  # OOB -> dropped
    off = lens % block_size
    return pool.at[blk, off].set(vals[:, 0], mode="drop")


# --------------------------------------------- context parallelism (cp)
# Under LLMEngine(cp=N) the executor runs every forward below inside
# shard_map over the "cp" mesh axis: pool arrays are sharded on their
# block axis (member s owns GLOBAL block ids [s*per, (s+1)*per),
# per = num_blocks/cp) while block tables, lens and activations stay
# replicated with GLOBAL ids. The forwards translate tables to LOCAL
# coordinates at their use sites (scatters drop non-owned writes, the
# attention kernels' partials mode masks non-owned reads) and merge the
# per-shard online-softmax partials — so the host-side block managers,
# radix trie and ledger never learn about sharding. ``cp_axis=None``
# (the default everywhere) leaves every trace byte-identical to pre-cp
# builds.

def _cp_local_tables(tables, cp_axis, per):
    """GLOBAL block-table entries -> this cp member's LOCAL pool
    coordinates: ids in [s*per, (s+1)*per) become [0, per); everything
    else (other members' blocks and the global OOB sentinel) becomes the
    LOCAL sentinel ``per`` — scatter-dropped on write, ownership-masked
    on read."""
    if cp_axis is None:
        return tables
    s = jax.lax.axis_index(cp_axis)
    loc = tables - s * per
    return jnp.where((loc >= 0) & (loc < per), loc, per)


def _cp_merge_chunk(o, m, l, cp_axis, dtype):
    """Merge chunk-prefill partials across cp. ``PT_CP_IMPL`` (read at
    TRACE time — flip between engine constructions) picks the ring
    rotation (default) or the Ulysses all_to_all head-reshard; both are
    bit-identical across members (global-order fold / symmetric
    collectives)."""
    from paddle_tpu.distributed.ring_attention import (finalize_partials,
                                                       ring_merge_partials)
    impl = os.environ.get("PT_CP_IMPL", "ring").strip().lower()
    if impl == "ulysses":
        from paddle_tpu.distributed.ulysses import ulysses_merge_partials
        o, m, l = ulysses_merge_partials(o, m, l, cp_axis)
    else:
        o, m, l = ring_merge_partials(o, m, l, cp_axis)
    return finalize_partials(o, l, dtype)


def _backbone(model):
    """Decoder backbone holding embed_tokens/layers/norm. Llama-family
    models wrap it in ``.model``; the MoE families (Mixtral, Qwen2-MoE,
    MoEForCausalLM) hang the parts directly off the LM."""
    return getattr(model, "model", model)


def _model_logits(model, x):
    """LM head: ``model.logits`` where it exists (weight-only-quant aware),
    the plain ``lm_head`` matmul otherwise (MoE families)."""
    fn = getattr(model, "logits", None)
    if callable(fn):
        return fn(x)
    return _wo(x, model.lm_head)


def _mlp_out(lyr, h):
    """Per-layer MLP adapter: Mixtral-style layers carry an ``.moe``
    MoELayer, Qwen2-MoE puts a sparse block (or a dense LlamaMLP) at
    ``.mlp``. MoE blocks return ``(y, aux_loss)`` — the aux loss is a
    training regulariser, dropped at inference."""
    blk = lyr.moe if hasattr(lyr, "moe") else lyr.mlp
    out = blk(h)
    return out[0] if isinstance(out, (tuple, list)) else out


def is_moe_model(model) -> bool:
    """True when any decoder layer routes through an MoE block (drives
    the ``serving.moe_dispatch`` chaos site in LLMEngine)."""
    return any(hasattr(lyr, "moe") or getattr(lyr, "sparse", False)
               for lyr in getattr(_backbone(model), "layers", ()))


def _lora_delta(x, lora, kind, li):
    """Batched multi-LoRA correction for ONE projection of ONE layer
    (ISSUE 14): ``delta[b] = (x[b] @ A_{aidx[b]}) @ B_{aidx[b]}`` with
    the alpha/r scale pre-folded into the B stack and zero for
    null-adapter rows. ``lora`` is the engine-built pytree:

      qkv_a/qkv_b/o_a/o_b  [L, cap, ...]  stacked adapter tensors
      perm / inv           [B]  rows sorted by cache index (null last) /
                                the inverse permutation
      gs                   [cap] TOKEN count per cache index (row count
                                × per-row width, in sorted order)
      aidx                 [B]  original-order cache index, -1 = null

    Default impl flattens the sorted rows to [B*S, k] and runs TWO
    grouped GEMMs (``ops/pallas/grouped_matmul`` — Pallas on TPU, XLA
    segment fallback elsewhere) so a heterogeneous batch is ragged
    per-adapter segments through one kernel. Rows past ``sum(gs)`` (the
    null-adapter tail) are UNSPECIFIED per the kernel contract and are
    masked to zero here. ``PT_MULTILORA_IMPL=gather`` (trace-time; needs
    ``clear_jit_caches()`` to flip) selects the naive per-row dense
    path — the bench baseline the grouped path is measured against."""
    from paddle_tpu.ops.pallas.grouped_matmul import grouped_matmul
    a_stack = lora[kind + "_a"][li]          # [cap, k, r]
    b_stack = lora[kind + "_b"][li]          # [cap, r, n]
    bsz, s, kdim = x.shape
    xf = x.astype(jnp.float32)
    if os.environ.get("PT_MULTILORA_IMPL", "grouped") == "gather":
        sel = jnp.maximum(lora["aidx"], 0)
        t = jnp.einsum("bsk,bkr->bsr", xf, a_stack[sel])
        d = jnp.einsum("bsr,brn->bsn", t, b_stack[sel])
        return jnp.where((lora["aidx"] >= 0)[:, None, None],
                         d, 0.0).astype(x.dtype)
    xp = xf[lora["perm"]].reshape(bsz * s, kdim)
    t = grouped_matmul(xp, a_stack, lora["gs"])
    d = grouped_matmul(t, b_stack, lora["gs"])
    d = jnp.where(jnp.arange(bsz * s)[:, None] < jnp.sum(lora["gs"]),
                  d, 0.0)
    return d.reshape(bsz, s, -1)[lora["inv"]].astype(x.dtype)


def llama_prefill_paged(model, input_ids, prompt_lens, cache: PagedKVCache,
                        slot_ids=None, table_rows=None, lora=None,
                        cp_axis=None):
    """Prefill padded ragged prompts [B, S]; returns (last_logits, cache).

    Attention runs the padded-varlen path (kv_lens) — the fused kernel on
    TPU; K/V of every valid position is scattered into the block pool.
    ``last_logits`` are taken at each row's LAST VALID position.

    MID-FLIGHT ADMISSION (the continuous-batching engine): with
    ``slot_ids`` [A] + ``table_rows`` [A, max_blocks], the A prompt rows
    are written into cache SLOTS ``slot_ids`` (their new block-table rows
    installed on device) while every other slot's pools/tables/lens stay
    untouched — so prefill of admitted requests interleaves with decode of
    in-flight ones. Padding rows use slot_id >= num_slots (scatter-drop)
    and prompt_len 0."""
    cfg = model.cfg
    if getattr(cfg, "fp8", False):
        raise NotImplementedError(
            "paged serving ignores the fp8 training path (its inline "
            "decoder forward runs bf16 matmuls); serve an fp8-trained "
            "model with fp8=False weights, or use weight-only quantization")
    b, s = input_ids.shape
    nb, bs = cache.num_blocks, cache.block_size
    prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
    if slot_ids is None:
        tables = cache.block_tables          # row i == slot i (legacy)
        new_lens = prompt_lens
        new_tables = cache.block_tables
    else:
        slot_ids = jnp.asarray(slot_ids, jnp.int32)
        tables = jnp.asarray(table_rows, jnp.int32)   # [A, max_blocks]
        new_tables = cache.block_tables.at[slot_ids].set(tables, mode="drop")
        new_lens = cache.lens.at[slot_ids].set(prompt_lens, mode="drop")
    # cp: tables stay GLOBAL on device; only the pool scatters see the
    # LOCAL view (non-owned writes drop). In-prompt attention is dense
    # over the local pre-quant k/v — replicated compute, no merge needed.
    rtables = _cp_local_tables(tables, cp_axis, cache.num_blocks)
    x = jnp.take(_backbone(model).embed_tokens, input_ids, axis=0)
    d = cfg.hidden_size // cfg.num_attention_heads
    scaling = getattr(cfg, "rope_scaling", None)
    cos, sin = A.rope_cos_sin(
        s, d, base=cfg.rope_theta, scaling=scaling,
        max_position_embeddings=getattr(cfg, "max_position_embeddings",
                                        None),
        # dynamic-NTK: each ragged row scales by ITS prompt length
        cur_len=(prompt_lens if (scaling or {}).get("type") == "dynamic"
                 else None),
        allow_dynamic=False)
    k_pools, v_pools, k_scales, v_scales = [], [], [], []
    for li, lyr in enumerate(_backbone(model).layers):
        h = lyr.input_layernorm(x)
        att = lyr.self_attn
        qkv = _wo(h, att.qkv_proj)
        if lora is not None:
            qkv = qkv + _lora_delta(h, lora, "qkv", li)
        if getattr(att, "qkv_bias", None) is not None:
            qkv = qkv + att.qkv_bias
        nh, nkv, hd = att.num_heads, att.num_kv_heads, att.head_dim
        q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
        q = A.apply_rope(q.reshape(b, s, nh, hd), cos, sin)
        k = A.apply_rope(k.reshape(b, s, nkv, hd), cos, sin)
        v = v.reshape(b, s, nkv, hd)
        # the prompt's own attention is dense over the LOCAL pre-
        # quantization k/v — only the pool writes quantize, so prefill
        # quality is exactly the decode dequantization error, never worse
        out = A.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             kv_lens=prompt_lens,
                                             window=getattr(cfg, "sliding_window", None))
        kp, vp, ks, vs = _scatter_kv(cache, li, k, v, _scatter_prefill,
                                     rtables, prompt_lens, nb, bs)
        k_pools.append(kp)
        v_pools.append(vp)
        if ks is not None:
            k_scales.append(ks)
            v_scales.append(vs)
        attn_out = out.reshape(b, s, nh * hd)
        proj = _wo(attn_out, att.o_proj)
        if lora is not None:
            proj = proj + _lora_delta(attn_out, lora, "o", li)
        x = x + proj
        x = x + _mlp_out(lyr, lyr.post_attention_layernorm(x))
    x = _backbone(model).norm(x)
    logits = _model_logits(model, x)
    last = jnp.take_along_axis(
        logits, jnp.maximum(prompt_lens - 1, 0)[:, None, None].astype(jnp.int32),
        axis=1)[:, 0]
    new_cache = PagedKVCache(k_pools, v_pools, new_tables, new_lens,
                             tuple(k_scales), tuple(v_scales))
    return last, new_cache


def llama_decode_step_paged(model, tokens, cache: PagedKVCache, active,
                            lora=None, cp_axis=None):
    """One decode token per sequence. tokens: [B] int32; active: [B] bool
    (finished rows neither write KV nor advance). Returns (logits, cache)."""
    cfg = model.cfg
    b = tokens.shape[0]
    nb, bs = cache.num_blocks, cache.block_size
    x = jnp.take(_backbone(model).embed_tokens, tokens[:, None], axis=0)  # [B,1,E]
    d = cfg.hidden_size // cfg.num_attention_heads
    cos, sin = _rope_rows(cache.lens, d, cfg.rope_theta,
                          getattr(cfg, "rope_scaling", None),
                          getattr(cfg, "max_position_embeddings", None))
    window = getattr(cfg, "sliding_window", None)
    k_pools, v_pools, k_scales, v_scales = [], [], [], []
    new_lens = jnp.where(active, cache.lens + 1, cache.lens)
    rtables = _cp_local_tables(cache.block_tables, cp_axis, nb)
    for li, lyr in enumerate(_backbone(model).layers):
        h = lyr.input_layernorm(x)
        att = lyr.self_attn
        qkv = _wo(h, att.qkv_proj)
        if lora is not None:
            qkv = qkv + _lora_delta(h, lora, "qkv", li)
        if getattr(att, "qkv_bias", None) is not None:
            qkv = qkv + att.qkv_bias
        nh, nkv, hd = att.num_heads, att.num_kv_heads, att.head_dim
        q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
        q = _apply_rope_rows(q.reshape(b, 1, nh, hd), cos, sin)
        k = _apply_rope_rows(k.reshape(b, 1, nkv, hd), cos, sin)
        v = v.reshape(b, 1, nkv, hd)
        k_pool, v_pool, ks, vs = _scatter_kv(
            cache, li, k, v, _scatter_decode, rtables,
            cache.lens, active, nb, bs)
        k_pools.append(k_pool)
        v_pools.append(v_pool)
        if ks is not None:
            k_scales.append(ks)
            v_scales.append(vs)
        # sliding-window configs: the pool retains all tokens (blocks
        # below the window could be recycled — not done yet) but decode
        # attends only the last `window` positions, matching prefill
        if cp_axis is None:
            out = paged_decode_attention(q[:, 0], k_pool, v_pool,
                                         rtables, new_lens,
                                         window=window, k_scale=ks,
                                         v_scale=vs)
        else:
            # per-shard partials over the locally-owned blocks + ONE
            # psum-style merge: O(heads*dim) cross-shard bytes per step,
            # bit-identical on every member (replicated sampling)
            from paddle_tpu.distributed.ring_attention import (
                finalize_partials, psum_merge_partials)
            o_p, m_p, l_p = paged_decode_attention(
                q[:, 0], k_pool, v_pool, rtables, new_lens,
                window=window, k_scale=ks, v_scale=vs, partials=True)
            o_p, m_p, l_p = psum_merge_partials(o_p, m_p, l_p, cp_axis)
            out = finalize_partials(o_p, l_p, q.dtype)
        attn_out = out.reshape(b, 1, nh * hd)
        proj = _wo(attn_out, att.o_proj)
        if lora is not None:
            proj = proj + _lora_delta(attn_out, lora, "o", li)
        x = x + proj
        x = x + _mlp_out(lyr, lyr.post_attention_layernorm(x))
    x = _backbone(model).norm(x)
    logits = _model_logits(model, x)[:, 0]
    return logits, PagedKVCache(k_pools, v_pools, cache.block_tables,
                                new_lens, tuple(k_scales), tuple(v_scales))


def llama_decode_tick(model, tokens, cache: PagedKVCache, active,
                      upd_rows, upd_cols, upd_vals, rng, temps, top_ps,
                      top_k=None, want_logp=False, lora=None,
                      logit_bias=None, cp_axis=None):
    """ONE fused serving tick: apply incremental block-table updates
    (``tables[upd_rows[i], upd_cols[i]] = upd_vals[i]``, sentinel rows
    dropped — no host-side table rebuild/re-upload), run the decode step,
    and sample the next token ON DEVICE. The only per-tick host traffic is
    the [B] sampled-token fetch the engine needs for streaming/EOS.

    ``temps``/``top_ps``: [B] traced per-slot sampling params (each
    request its own; 0 temperature = greedy for that row). ``top_k`` is
    static/global. ``want_logp`` (static): also return the [B, vocab]
    log-probs for beam selection, LEFT ON DEVICE. When False
    (greedy-only ticks) logp is () so no [B, vocab] f32 buffer is ever
    materialised."""
    from paddle_tpu.models.decoding import _sample_rows
    tables = cache.block_tables.at[upd_rows, upd_cols].set(upd_vals,
                                                           mode="drop")
    cache = PagedKVCache(cache.k_pools, cache.v_pools, tables, cache.lens,
                         cache.k_scales, cache.v_scales)
    logits, cache = llama_decode_step_paged(model, tokens, cache, active,
                                            lora, cp_axis=cp_axis)
    logp = (jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            if want_logp else ())
    nxt = _sample_rows(logits.astype(jnp.float32), rng, temps, top_ps,
                       top_k, logit_bias)
    nxt = jnp.where(active, nxt.astype(jnp.int32), tokens)
    return nxt, logp, cache


def llama_decode_tick_async(model, tokens, cache: PagedKVCache, active,
                            stop, gen, max_gen, rng, temps, top_ps,
                            eos_id, top_k=None):
    """The pipelined twin of :func:`llama_decode_tick` (ISSUE 20): the
    token array it returns stays ON DEVICE and feeds the next call's
    ``tokens`` directly — the engine dispatches up to ``async_depth`` of
    these back-to-back and fetches results one tick late, hiding host
    emission under the in-flight device work.

    Because the host has not seen tick N's token when tick N+1
    dispatches, EOS/max-gen stop is evaluated IN THE JIT: ``stop`` is
    the accumulated device-side stop mask, and a row that sampled EOS
    (or hit ``max_gen``) at tick N is masked out of tick N+1's compute
    (``ran = active & ~stop``) before the host ever sees the token —
    over-dispatched ticks where every row is stopped run as all-masked
    no-ops the engine bills to nothing. ``eos_id`` is a traced int32
    (-1 when the engine has no EOS: token ids are non-negative, so the
    compare never fires).

    No table updates, grammar bias, LoRA, or beam logp: the engine
    drains the window and takes the synchronous tick for any tick that
    needs them, so this program stays a pure decode-cruise fast path.
    Returns (nxt, ran, stop', gen', cache) — ``ran`` is the mask of
    rows that actually computed this tick, which is exactly the rows
    the synchronous loop would have run."""
    from paddle_tpu.models.decoding import _sample_rows
    _note_trace("tick:async")
    ran = active & ~stop
    logits, cache = llama_decode_step_paged(model, tokens, cache, ran,
                                            None)
    nxt = _sample_rows(logits.astype(jnp.float32), rng, temps, top_ps,
                       top_k, None)
    nxt = jnp.where(ran, nxt.astype(jnp.int32), tokens)
    new_gen = gen + ran.astype(gen.dtype)
    stopped = ran & ((nxt == eos_id) | (new_gen >= max_gen))
    return nxt, ran, stop | stopped, new_gen, cache


# The forwards above are structure-agnostic via _backbone/_model_logits/
# _mlp_out, so they are ALSO the paged entry points for the MoE families
# (Mixtral, Qwen2-MoE): expert routing runs inside the same jitted
# prefill/decode, expert-parallel when traced under a mesh with ep > 1
# (MoELayer shards tokens over the data axes and all_to_alls expert
# slices via shard_map).
moe_prefill_paged = llama_prefill_paged
moe_decode_step_paged = llama_decode_step_paged
moe_decode_tick = llama_decode_tick


# module-level jit wrappers: their compile caches persist across
# paged_generate calls (a per-call jax.jit would recompile every request)
_PREFILL_JIT = jax.jit(llama_prefill_paged)
_DECODE_JIT = jax.jit(llama_decode_step_paged)
_TICK_JIT = jax.jit(llama_decode_tick, static_argnums=(10, 11),
                    donate_argnums=(2,))
# The async tick donates the cache only on accelerator backends: PJRT's
# CPU client executes a computation inline on the dispatching thread
# when it must alias a donated input, which serializes the depth-K
# pipeline the tick exists to feed (dispatch would block for the full
# tick). On CPU the extra cache copy buys a dispatch that actually
# returns; on TPU dispatch is async regardless and donation keeps the
# KV pool single-buffered in HBM.
def _async_tick_donate():
    try:
        return () if jax.default_backend() == "cpu" else (2,)
    except RuntimeError:         # backend init failed — donate-free is safe
        return ()


_ASYNC_TICK_JIT = jax.jit(llama_decode_tick_async, static_argnums=(11,),
                          donate_argnums=_async_tick_donate())


# jits registered by downstream serving modules (serving/quant.py,
# serving/transfer.py) so ONE clear_jit_caches() call covers every
# serving trace — the env-flip contract (PT_QUANT_KV, PT_QUANT_WEIGHTS,
# PT_PAGED_CHUNK, ...) needs no second clearing entry point
_EXTRA_CLEAR: list = []


def clear_jit_caches():
    """Drop every module-level serving jit cache. Needed when trace-time
    context changes under the same call signature — flipping
    ``PT_GROUPED_GEMM`` or ``PT_MULTILORA_IMPL``, or entering/leaving a
    mesh re-routes layers, but the jit caches key on shapes only."""
    for f in (_PREFILL_JIT, _DECODE_JIT, _TICK_JIT, _ASYNC_TICK_JIT,
              _PREFILL_CHUNK_JIT, _VERIFY_CHUNK_JIT, _REWIND_LENS_JIT,
              _PREFIX_COW_JIT, *_EXTRA_CLEAR):
        f.clear_cache()


def _copy_partial_blocks(pools, copy_src, copy_dst):
    """Copy-on-write pool block copies shared by every beam path.
    copy_src/copy_dst: [K] block ids, sentinel num_blocks = no copy."""
    return [p.at[copy_dst].set(p[jnp.clip(copy_src, 0, p.shape[0] - 1)],
                               mode="drop") for p in pools]


def _cow_pools(cache: PagedKVCache, copy_src, copy_dst):
    """COW-copy the K/V pools AND (when quantized) their scale pools —
    a partial block's int8 codes are meaningless without the matching
    scale rows, so the two must fork together."""
    return (_copy_partial_blocks(cache.k_pools, copy_src, copy_dst),
            _copy_partial_blocks(cache.v_pools, copy_src, copy_dst),
            tuple(_copy_partial_blocks(cache.k_scales, copy_src, copy_dst)),
            tuple(_copy_partial_blocks(cache.v_scales, copy_src, copy_dst)))


def _beam_cache_update(cache: PagedKVCache, new_tables, copy_src, copy_dst):
    """Apply a beam reorder to the paged cache: install the forked block
    tables and copy the (at most one per beam) private partial blocks."""
    k, v, ks, vs = _cow_pools(cache, copy_src, copy_dst)
    return PagedKVCache(k, v, new_tables, cache.lens, ks, vs)


def _cp_copy_blocks(pools, copy_src, copy_dst, per, cp_axis):
    """Cross-shard block copy (cp COW): a copy's src and dst blocks may
    live on DIFFERENT cp members. Every member contributes its owned src
    rows (zeros elsewhere); since exactly one member owns each id, ONE
    psum replicates the K src blocks everywhere; the local-translated
    dst scatter then drops on all members but the dst owner. Sentinel
    pairs (src = dst = global num_blocks) contribute zero and drop."""
    s = jax.lax.axis_index(cp_axis)
    loc_src = copy_src - s * per
    own = (loc_src >= 0) & (loc_src < per)
    src_c = jnp.clip(loc_src, 0, per - 1)
    loc_dst = copy_dst - s * per
    loc_dst = jnp.where((loc_dst >= 0) & (loc_dst < per), loc_dst, per)
    out = []
    for p in pools:
        rows = jnp.where(own.reshape(own.shape + (1,) * (p.ndim - 1)),
                         p[src_c], 0)
        rows = jax.lax.psum(rows, cp_axis)
        out.append(p.at[loc_dst].set(rows.astype(p.dtype), mode="drop"))
    return out


def _prefix_cow_update(cache: PagedKVCache, copy_src, copy_dst,
                       cp_axis=None):
    """Radix prefix cache: copy adopted partial boundary blocks into the
    adopters' private blocks (copy-on-write at first divergence). Tables
    and lens are untouched — the adopters' tables already point at the
    dst blocks. copy_src/copy_dst: [K] block ids, sentinel num_blocks =
    no copy."""
    if cp_axis is not None:
        per = cache.num_blocks
        cp = lambda pools: _cp_copy_blocks(pools, copy_src, copy_dst,
                                           per, cp_axis)
        return PagedKVCache(cp(cache.k_pools), cp(cache.v_pools),
                            cache.block_tables, cache.lens,
                            tuple(cp(cache.k_scales)),
                            tuple(cp(cache.v_scales)))
    k, v, ks, vs = _cow_pools(cache, copy_src, copy_dst)
    return PagedKVCache(k, v, cache.block_tables, cache.lens, ks, vs)


_PREFIX_COW_JIT = jax.jit(_prefix_cow_update, donate_argnums=(0,))


def _beam_select(running_lp, seqs, fin_seqs, fin_scores, logp, i,
                 prompt_len, eos_token_id, length_penalty):
    """b=1 adapter over decoding.beam_select — ONE shared implementation,
    so paged beam == static beam exactly by construction."""
    from paddle_tpu.models.decoding import beam_select
    out = beam_select(running_lp[None], seqs[None], fin_seqs[None],
                      fin_scores[None], logp[None], i, prompt_len,
                      eos_token_id, length_penalty)
    return tuple(x[0] for x in out)


def _beam_group_update(cache: PagedKVCache, slot_ids, rows, lens_val,
                       copy_src, copy_dst):
    """Engine-shaped beam reorder: install the K forked table rows at the
    group's cache slots, pin their lens, and copy the private partial
    blocks. slot_ids [K] int32; rows [K, max_blocks]; lens_val scalar;
    copy_src/copy_dst [K] (sentinel num_blocks = no copy)."""
    tables = cache.block_tables.at[slot_ids].set(rows)
    lens = cache.lens.at[slot_ids].set(jnp.int32(lens_val))
    k, v, ks, vs = _cow_pools(cache, copy_src, copy_dst)
    return PagedKVCache(k, v, tables, lens, ks, vs)


def _beam_finalize(running_lp, seqs, fin_seqs, fin_scores, prompt_len,
                   max_new_tokens, eos_token_id, length_penalty):
    """Pick the best hypothesis among finished + still-running beams and
    EOS-fill past the first EOS — shared by ``paged_beam_search`` and the
    serving engine's beam groups. Returns (best_seq, best_score)."""
    run_score = running_lp / (float(max_new_tokens) ** length_penalty)
    all_scores = jnp.concatenate([fin_scores, run_score])
    all_seqs = jnp.concatenate([fin_seqs, seqs], axis=0)
    best = int(jnp.argmax(all_scores))
    best_seq = all_seqs[best]
    best_score = all_scores[best]
    if eos_token_id is not None:
        gen = best_seq[prompt_len:]
        seen = jnp.cumsum(gen == eos_token_id)
        after = jnp.concatenate([jnp.zeros((1,), bool), (seen > 0)[:-1]])
        best_seq = best_seq.at[prompt_len:].set(
            jnp.where(after, eos_token_id, gen))
    return best_seq, best_score


_BEAM_SELECT_JIT = jax.jit(_beam_select, static_argnums=(6, 7, 8))
_BEAM_UPDATE_JIT = jax.jit(_beam_cache_update, donate_argnums=(0,))
_BEAM_GROUP_UPDATE_JIT = jax.jit(_beam_group_update, donate_argnums=(0,))


def paged_beam_search(model, prompt, max_new_tokens=32, num_beams=4,
                      length_penalty=1.0, eos_token_id=None,
                      block_size=16, num_blocks=None):
    """Beam search IN THE PAGED PATH (single prompt, K beams as cache
    slots). Prompt blocks are SHARED across beams via refcounts
    (RefBlockManager); each reorder forks the parents' tables and copies
    only the private partial tail block — the append-only-pool
    copy-on-write. Selection math mirrors ``decoding.beam_search`` so the
    result equals the static-cache beam exactly.

    Returns (best_sequence [prompt+max_new], best_score).
    """
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    s = len(prompt)
    cfg = model.cfg
    K = num_beams
    max_len = s + max_new_tokens
    max_blocks = -(-max_len // block_size)
    if num_blocks is None:
        num_blocks = K * max_blocks
    mgr = RefBlockManager(num_blocks, block_size)
    cache = PagedKVCache.init(cfg.num_hidden_layers, num_blocks, block_size,
                              cfg.num_key_value_heads,
                              cfg.hidden_size // cfg.num_attention_heads,
                              K, max_blocks, cfg.dtype)

    # prefill once into beam 0's blocks, then fork the other beams
    sid = {j: j for j in range(K)}          # beam j -> mgr sequence id
    next_sid = K
    mgr.allocate(0, s)
    rows = np.full((K, max_blocks), num_blocks, np.int32)
    copy_src = np.full(K, num_blocks, np.int32)
    copy_dst = np.full(K, num_blocks, np.int32)
    for j in range(1, K):
        pair = mgr.fork(0, j, s)
        if pair is not None:
            copy_src[j], copy_dst[j] = pair
    for j in range(K):
        t = mgr.tables[j]
        rows[j, :len(t)] = t

    logits, cache = _PREFILL_JIT(
        model, jnp.asarray(prompt[None, :]), jnp.asarray([s], jnp.int32),
        cache, jnp.asarray([0], jnp.int32),
        jnp.asarray(rows[:1]))
    cache = PagedKVCache(cache.k_pools, cache.v_pools,
                         jnp.asarray(rows),
                         jnp.full((K,), s, jnp.int32),
                         cache.k_scales, cache.v_scales)
    cache = _BEAM_UPDATE_JIT(cache, jnp.asarray(rows),
                             jnp.asarray(copy_src), jnp.asarray(copy_dst))

    NEG = jnp.float32(-1e9)
    logp0 = jax.nn.log_softmax(logits[0].astype(jnp.float32))
    logp = jnp.broadcast_to(logp0[None], (K, cfg.vocab_size))
    running_lp = jnp.asarray([0.0] + [NEG] * (K - 1), jnp.float32)
    seqs = jnp.zeros((K, max_len), jnp.int32).at[:, :s].set(
        jnp.asarray(prompt)[None])
    fin_seqs = jnp.zeros_like(seqs)
    fin_scores = jnp.full((K,), NEG)

    for i in range(max_new_tokens):
        running_lp, seqs, fin_seqs, fin_scores, new_beam, new_tok = \
            _BEAM_SELECT_JIT(running_lp, seqs, fin_seqs, fin_scores, logp,
                             jnp.int32(i), s, eos_token_id,
                             float(length_penalty))
        if i == max_new_tokens - 1:
            break                      # pure selection, no forward after
        parents = np.asarray(new_beam)
        cur = s + i                    # tokens stored per beam so far
        # fork: new beam j adopts parent p's blocks; ensure room for the
        # write at position cur, privately per beam
        new_rows = np.full((K, max_blocks), num_blocks, np.int32)
        copy_src = np.full(K, num_blocks, np.int32)
        copy_dst = np.full(K, num_blocks, np.int32)
        new_sid_map = {}
        for j in range(K):
            dst = next_sid
            next_sid += 1
            pair = mgr.fork(sid[int(parents[j])], dst, cur)
            if pair is not None:
                copy_src[j], copy_dst[j] = pair
            new_sid_map[j] = dst
        for j in range(K):
            mgr.free(sid[j])
        sid = new_sid_map
        for j in range(K):
            t = mgr.allocate(sid[j], cur + 1)    # grow for this write
            new_rows[j, :len(t)] = t
        cache = _BEAM_UPDATE_JIT(cache, jnp.asarray(new_rows),
                                 jnp.asarray(copy_src),
                                 jnp.asarray(copy_dst))
        logits, cache = _DECODE_JIT(model, new_tok.astype(jnp.int32), cache,
                                    jnp.ones((K,), bool))
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    return _beam_finalize(running_lp, seqs, fin_seqs, fin_scores, s,
                          max_new_tokens, eos_token_id, length_penalty)


def paged_generate(model, input_ids, prompt_lens, max_new_tokens=32,
                   block_size=16, num_blocks=None, eos_token_id=None,
                   temperature=0.0, top_k=None, top_p=None, rng=None):
    """Greedy continuous-batch decode over a paged cache.

    ``input_ids``: [B, S] right-padded ragged prompts with ``prompt_lens``
    [B]. The pool holds ``num_blocks`` blocks (default: exactly enough for
    Σ(prompt_len + max_new_tokens), the ragged bound — NOT B × max_len);
    finished sequences release their blocks back to the manager.

    Host-driven step loop (the serving-engine shape: scheduling/allocation
    on host, fixed-shape jitted compute on device). Returns [B, S +
    max_new_tokens] tokens (finished rows are tail-padded with
    ``eos_token_id``). ``temperature``/``top_k``/``top_p`` enable sampling
    (0.0 = greedy), sharing the sampler with models/decoding.py.
    """
    from paddle_tpu.models.decoding import _sample
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def pick(logits, key):
        return np.asarray(_sample(logits.astype(jnp.float32), key,
                                  temperature, top_k, top_p))
    cfg = model.cfg
    b, s = input_ids.shape
    lens_np = np.asarray(prompt_lens, np.int64)
    max_total = lens_np + max_new_tokens
    max_blocks = int(-(-(int(max_total.max())) // block_size))
    if num_blocks is None:
        num_blocks = int(sum(-(-int(t) // block_size) for t in max_total))
    mgr = BlockManager(num_blocks, block_size)
    for sid in range(b):
        mgr.allocate(sid, int(lens_np[sid]))
    cache = PagedKVCache.init(cfg.num_hidden_layers, num_blocks, block_size,
                              cfg.num_key_value_heads,
                              cfg.hidden_size // cfg.num_attention_heads,
                              b, max_blocks, cfg.dtype)
    cache.block_tables = mgr.table_array(range(b), max_blocks)

    prefill = _PREFILL_JIT
    step = _DECODE_JIT

    logits, cache = prefill(model, jnp.asarray(input_ids),
                            jnp.asarray(lens_np, jnp.int32), cache)
    tokens = np.concatenate(
        [np.asarray(input_ids),
         np.zeros((b, max_new_tokens), np.asarray(input_ids).dtype)], axis=1)
    rng, sub = jax.random.split(rng)
    next_tok = pick(logits, sub)
    active = np.ones((b,), bool)
    cur = lens_np.copy()
    for sid in range(b):
        tokens[sid, cur[sid]] = next_tok[sid]
    if eos_token_id is not None:
        newly = next_tok == eos_token_id
        for sid in np.nonzero(newly)[0]:
            active[sid] = False
            mgr.free(int(sid))

    for _ in range(max_new_tokens - 1):
        if not active.any():
            break
        # grow tables for rows about to cross a block boundary
        for sid in range(b):
            if active[sid]:
                mgr.allocate(sid, int(cur[sid]) + 1)
        cache.block_tables = mgr.table_array(range(b), max_blocks)
        logits, cache = step(model, jnp.asarray(next_tok, jnp.int32), cache,
                             jnp.asarray(active))
        rng, sub = jax.random.split(rng)
        nxt = pick(logits, sub)
        next_tok = np.where(active, nxt, next_tok)
        cur = cur + active.astype(np.int64)
        for sid in range(b):
            if active[sid]:
                tokens[sid, cur[sid]] = next_tok[sid]
        if eos_token_id is not None:
            newly = active & (next_tok == eos_token_id)
            for sid in np.nonzero(newly)[0]:
                active[sid] = False
                mgr.free(int(sid))
    if eos_token_id is not None:
        # finished rows: pad the tail with EOS (HF/PaddleNLP convention)
        for sid in range(b):
            if not active[sid]:
                tokens[sid, int(cur[sid]) + 1:] = eos_token_id
    return jnp.asarray(tokens), cache


def llama_prefill_chunk_paged(model, input_ids, chunk_lens, offsets,
                              cache: PagedKVCache, slot_ids, table_rows,
                              full_logits=False, lora=None, cp_axis=None):
    """CONTINUE a prefill: write chunk tokens at positions
    ``offsets[a] .. offsets[a]+chunk_lens[a]-1`` of their slots and attend
    each chunk query over the slot's WHOLE pool prefix (gather-based) —
    the vLLM-style chunked prefill that lets prompts longer than the
    prefill window stream in across engine ticks while other slots keep
    decoding. Returns (last_logits, cache); ``last_logits`` at each row's
    final chunk position (only meaningful on a request's last chunk).

    input_ids [A, C] (zero-padded), chunk_lens [A], offsets [A] (tokens
    already in the pool), slot_ids [A] (sentinel >= num_slots drops the
    row), table_rows [A, max_blocks] CURRENT tables covering
    offset+chunk. Dynamic-NTK rope is refused (chunk-end bases would
    desync across chunks).

    ``full_logits=True`` returns the whole [A, C, V] logit block instead
    of each row's last position — the speculative VERIFY forward: logit i
    of a row judges the proposal at position offset+i+1, so the engine
    needs every chunk position, not just the last."""
    cfg = model.cfg
    if (getattr(cfg, "rope_scaling", None) or {}).get("type") == "dynamic":
        raise NotImplementedError(
            "chunked prefill with dynamic-NTK rope is not supported "
            "(per-chunk bases would desync from the one-shot prefill)")
    if getattr(cfg, "fp8", False):
        raise NotImplementedError(
            "paged serving ignores the fp8 training path (see "
            "llama_prefill_paged); serve with fp8=False weights")
    a, c = input_ids.shape
    nb, bs = cache.num_blocks, cache.block_size
    chunk_lens = jnp.asarray(chunk_lens, jnp.int32)
    offsets = jnp.asarray(offsets, jnp.int32)
    slot_ids = jnp.asarray(slot_ids, jnp.int32)
    tables = jnp.asarray(table_rows, jnp.int32)
    new_tables = cache.block_tables.at[slot_ids].set(tables, mode="drop")
    new_lens = cache.lens.at[slot_ids].set(offsets + chunk_lens,
                                           mode="drop")
    window = getattr(cfg, "sliding_window", None)
    # cp (ring-attention chunked prefill): quantize-on-write scatters land
    # each chunk's K/V in the owning shard via the LOCAL table view; the
    # pool read below computes per-shard partials over owned blocks only
    # and merges them across cp (ring rotation / Ulysses all_to_all)
    rtables = _cp_local_tables(tables, cp_axis, cache.num_blocks)

    x = jnp.take(_backbone(model).embed_tokens, input_ids, axis=0)
    d = cfg.hidden_size // cfg.num_attention_heads
    positions = offsets[:, None] + jnp.arange(c, dtype=jnp.int32)  # [A, C]
    base, pos_div = A.resolve_rope_scaling(
        cfg.rope_theta, d, getattr(cfg, "rope_scaling", None),
        allow_dynamic=False,
        max_position_embeddings=getattr(cfg, "max_position_embeddings",
                                        None))
    inv = 1.0 / (jnp.asarray(base, jnp.float32)
                 ** (jnp.arange(0, d, 2, jnp.float32) / d))
    f = (positions.astype(jnp.float32) / pos_div)[:, :, None] * inv
    cos, sin = jnp.cos(f)[:, :, None, :], jnp.sin(f)[:, :, None, :]

    def rope(t):
        d2 = t.shape[-1] // 2
        t1, t2 = t[..., :d2], t[..., d2:]
        return jnp.concatenate([t1 * cos - t2 * sin, t2 * cos + t1 * sin],
                               axis=-1).astype(t.dtype)

    k_pools, v_pools, k_scales, v_scales = [], [], [], []
    for li, lyr in enumerate(_backbone(model).layers):
        h = lyr.input_layernorm(x)
        att = lyr.self_attn
        qkv = _wo(h, att.qkv_proj)
        if lora is not None:
            qkv = qkv + _lora_delta(h, lora, "qkv", li)
        if getattr(att, "qkv_bias", None) is not None:
            qkv = qkv + att.qkv_bias
        nh, nkv, hd = att.num_heads, att.num_kv_heads, att.head_dim
        q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
        q = rope(q.reshape(a, c, nh, hd))
        k = rope(k.reshape(a, c, nkv, hd))
        v = v.reshape(a, c, nkv, hd)
        # scatter the chunk FIRST so the gathered view holds prefix+chunk
        k_pool, v_pool, ks, vs = _scatter_kv(
            cache, li, k, v, _scatter_decode_chunk, rtables, offsets,
            chunk_lens, nb, bs)
        k_pools.append(k_pool)
        v_pools.append(v_pool)
        if ks is not None:
            k_scales.append(ks)
            v_scales.append(vs)
        # ragged pool-direct attention: the kernel reads only each row's
        # live blocks (the XLA fallback reconstructs the old full
        # gather + dense-mask view, bit-compatible)
        if cp_axis is None:
            out = paged_chunk_attention(q, k_pool, v_pool, rtables,
                                        offsets, chunk_lens, window=window,
                                        k_scale=ks, v_scale=vs)
        else:
            o_p, m_p, l_p = paged_chunk_attention(
                q, k_pool, v_pool, rtables, offsets, chunk_lens,
                window=window, k_scale=ks, v_scale=vs, partials=True)
            out = _cp_merge_chunk(o_p, m_p, l_p, cp_axis, q.dtype)
        attn_out = out.reshape(a, c, nh * hd)
        proj = _wo(attn_out, att.o_proj)
        if lora is not None:
            proj = proj + _lora_delta(attn_out, lora, "o", li)
        x = x + proj
        x = x + _mlp_out(lyr, lyr.post_attention_layernorm(x))
    x = _backbone(model).norm(x)
    logits = _model_logits(model, x)
    new_cache = PagedKVCache(k_pools, v_pools, new_tables, new_lens,
                             tuple(k_scales), tuple(v_scales))
    if full_logits:
        return logits, new_cache
    last = jnp.take_along_axis(
        logits, jnp.maximum(chunk_lens - 1, 0)[:, None, None].astype(
            jnp.int32), axis=1)[:, 0]
    return last, new_cache


def _scatter_decode_chunk(pool, vals, tables, offsets, chunk_lens, nb, bs):
    """Scatter [A, C] chunk K/V at positions offset..offset+len-1 into the
    pool via each row's table; padding (i >= chunk_lens) scatters OOB."""
    a, c = vals.shape[:2]
    pos = offsets[:, None] + jnp.arange(c)[None, :]          # [A, C]
    blk_idx = pos // bs
    blk = jnp.take_along_axis(tables, jnp.minimum(blk_idx,
                                                  tables.shape[1] - 1),
                              axis=1)
    dest = blk * bs + pos % bs
    dest = jnp.where(jnp.arange(c)[None, :] < chunk_lens[:, None],
                     dest, nb * bs)                          # OOB drop
    flat = pool.reshape(nb * bs, *pool.shape[2:])
    flat = flat.at[dest.reshape(-1)].set(
        vals.reshape(a * c, *vals.shape[2:]), mode="drop")
    return flat.reshape(pool.shape)


_PREFILL_CHUNK_JIT = jax.jit(llama_prefill_chunk_paged,
                             donate_argnums=(4,))


# ------------------------------------------------ speculative helpers
# The multi-append/rewind primitives speculation needs, shared by the
# standalone generators (models/speculative.py) and the serving engine:
# the target VERIFY forward is the chunk prefill with full logits (multi-
# token append through the block tables), and the rollback past rejected
# positions is a pure LENGTH rewind — block tables untouched, because
# stale KV beyond a row's length pointer is masked by attention and
# positionally overwritten by the next append.

def llama_verify_chunk_paged(model, input_ids, chunk_lens, offsets,
                             cache: PagedKVCache, slot_ids, table_rows,
                             lora=None, cp_axis=None):
    """Speculative verify: one chunk forward returning [A, C, V] logits
    (see ``llama_prefill_chunk_paged`` — same append semantics, every
    chunk position's logits kept for accept/reject)."""
    return llama_prefill_chunk_paged(model, input_ids, chunk_lens, offsets,
                                     cache, slot_ids, table_rows,
                                     full_logits=True, lora=lora,
                                     cp_axis=cp_axis)


def spec_rewind_lens(cache: PagedKVCache, slot_ids, new_lens):
    """Roll the given slots' length pointers back past rejected
    speculative positions. Block tables are NOT touched: the blocks
    holding rejected KV stay owned by their sequences, their stale
    contents unreachable (attention masks ``pos >= lens``) until the next
    append overwrites them. slot_ids sentinel >= num_slots drops the
    row."""
    slot_ids = jnp.asarray(slot_ids, jnp.int32)
    lens = cache.lens.at[slot_ids].set(
        jnp.asarray(new_lens, jnp.int32), mode="drop")
    return PagedKVCache(cache.k_pools, cache.v_pools, cache.block_tables,
                        lens, cache.k_scales, cache.v_scales)


def spec_advance_frontiers(pos, draft_pos, n_new):
    """Commit one speculative round: the target frontier advances by the
    ``n_new`` committed tokens (accepted prefix + correction/bonus) and
    the draft frontier rolls back past everything it proposed beyond the
    new frontier — its stale cache entries get positionally overwritten
    by the next round's feed. Works on scalars or per-row arrays."""
    new_pos = pos + n_new
    return new_pos, np.minimum(draft_pos, new_pos)


def greedy_accept_length(verify_tokens, proposals):
    """Longest matching prefix between the target's argmax tokens and the
    draft's proposals — the greedy accept rule. ``verify_tokens`` may be
    longer than ``proposals`` (it usually carries the bonus position);
    works on [gamma] rows or [B, gamma] batches, returning a scalar or
    [B] counts."""
    v = np.asarray(verify_tokens)
    p = np.asarray(proposals)
    match = np.cumprod(v[..., : p.shape[-1]] == p, axis=-1)
    return match.sum(axis=-1)


def stochastic_accept_row(props, qs, ps, rng):
    """The Leviathan/Chen accept-reject rule over ONE row: accept
    proposal x_i with probability min(1, p_i(x_i)/q_i(x_i)); the first
    rejection resamples from the residual norm(max(0, p_i - q_i)); a
    fully accepted row draws the bonus token from p_gamma. ``ps`` holds
    len(props)+1 distributions (the extra one is the bonus position).
    Returns (committed tokens, n_accepted); the emitted stream is
    distributed exactly as sampling from ``ps`` alone, for ANY proposal
    distribution ``qs``."""
    new: list[int] = []
    n_acc = 0
    for i, x in enumerate(props):
        x = int(x)
        if rng.uniform() < min(1.0, float(ps[i][x])
                               / max(float(qs[i][x]), 1e-20)):
            new.append(x)
            n_acc += 1
        else:
            resid = np.maximum(ps[i] - qs[i], 0.0)
            z = resid.sum()
            resid = resid / z if z > 0 else ps[i]
            new.append(int(rng.choice(resid.size, p=resid)))
            break
    else:
        new.append(int(rng.choice(ps[len(props)].size, p=ps[len(props)])))
    return new, n_acc


_VERIFY_CHUNK_JIT = jax.jit(llama_verify_chunk_paged, donate_argnums=(4,))
_REWIND_LENS_JIT = jax.jit(spec_rewind_lens, donate_argnums=(0,))
