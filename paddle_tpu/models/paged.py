"""Paged KV cache + continuous batched decode (serving story).

Ref capability: PaddleNLP ``llm`` predictor block-attention +
``fused_multi_transformer_op.cu``'s block KV cache. TPU-native split of
responsibilities:

  * DEVICE: fixed-shape jitted steps — ``llama_prefill_paged`` (padded
    ragged prompts through the varlen flash path, K/V scattered into the
    block pool) and ``llama_decode_step_paged`` (one token per sequence,
    pool-direct paged attention via the scalar-prefetch Pallas kernel).
  * HOST: ``BlockManager`` — the free-list/allocation policy (what vLLM's
    scheduler does). Between steps it grows block tables and recycles a
    finished sequence's blocks. Host-side management is the TPU-idiomatic
    design: allocation is control flow, not math, and the device program
    keeps a single static shape.

HBM for the cache is ``num_blocks * block_size`` tokens ≈ Σ actual sequence
lengths (rounded up per block) — NOT batch × max_len as in the static
``KVCache`` (models/decoding.py), which this complements, not replaces.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops import attention as A
from paddle_tpu.ops.pallas.paged_attention import paged_decode_attention
from paddle_tpu.quantization import wo_matmul as _wo


@dataclass
class PagedKVCache:
    """Per-layer block pools + per-sequence block tables (pytree)."""
    k_pools: list   # [L] of [N_blocks, block_size, H_kv, D]
    v_pools: list
    block_tables: jnp.ndarray  # [B, max_blocks] int32 (pad = n_blocks)
    lens: jnp.ndarray          # [B] int32 — tokens currently in cache

    @property
    def block_size(self):
        return self.k_pools[0].shape[1]

    @property
    def num_blocks(self):
        return self.k_pools[0].shape[0]

    def pool_tokens(self):
        """Total cache capacity in tokens (the HBM bound)."""
        return self.num_blocks * self.block_size

    @staticmethod
    def init(num_layers, num_blocks, block_size, num_kv_heads, head_dim,
             batch, max_blocks_per_seq, dtype):
        z = lambda: jnp.zeros((num_blocks, block_size, num_kv_heads,
                               head_dim), dtype)
        return PagedKVCache(
            [z() for _ in range(num_layers)],
            [z() for _ in range(num_layers)],
            jnp.full((batch, max_blocks_per_seq), num_blocks, jnp.int32),
            jnp.zeros((batch,), jnp.int32))


jax.tree_util.register_pytree_node(
    PagedKVCache,
    lambda c: ((c.k_pools, c.v_pools, c.block_tables, c.lens), None),
    lambda aux, ch: PagedKVCache(*ch))


class BlockManager:
    """Host-side free-list allocator for the shared block pool."""

    def __init__(self, num_blocks: int, block_size: int):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free = list(range(num_blocks - 1, -1, -1))
        self.tables: dict[int, list[int]] = {}

    @property
    def free_blocks(self):
        return len(self._free)

    def blocks_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.block_size)

    def allocate(self, seq_id: int, n_tokens: int):
        """Ensure seq_id owns enough blocks for n_tokens; grow as needed."""
        table = self.tables.setdefault(seq_id, [])
        need = self.blocks_needed(n_tokens) - len(table)
        if need > len(self._free):
            raise MemoryError(
                f"paged cache out of blocks: need {need}, "
                f"free {len(self._free)} (of {self.num_blocks})")
        for _ in range(max(need, 0)):
            table.append(self._free.pop())
        return table

    def free(self, seq_id: int):
        self._free.extend(reversed(self.tables.pop(seq_id, [])))

    def table_array(self, seq_ids, max_blocks):
        """[B, max_blocks] int32; unused slots = num_blocks (OOB sentinel,
        dropped by scatter, clamped-masked by the kernel contract)."""
        out = np.full((len(seq_ids), max_blocks), self.num_blocks, np.int32)
        for row, sid in enumerate(seq_ids):
            t = self.tables.get(sid, [])
            out[row, :len(t)] = t
        return jnp.asarray(out)


def _rope_rows(positions, head_dim, base, scaling=None):
    """cos/sin for PER-ROW positions: [B] -> [B, 1, 1, D/2] (ragged decode:
    every sequence sits at a different position). Shares the scaling math
    with ops.attention (linear/ntk; dynamic raises — fixed-shape path)."""
    base, pos_div = A.resolve_rope_scaling(base, head_dim, scaling,
                                           allow_dynamic=False)
    inv = 1.0 / (base ** (jnp.arange(0, head_dim, 2, jnp.float32) / head_dim))
    f = (positions.astype(jnp.float32) / pos_div)[:, None] * inv[None, :]
    return (jnp.cos(f)[:, None, None, :], jnp.sin(f)[:, None, None, :])


def _apply_rope_rows(x, cos, sin):
    """x: [B, 1, H, D]; cos/sin: [B, 1, 1, D/2] (rotate-half, NeoX)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                           axis=-1).astype(x.dtype)


def _scatter_prefill(pool, vals, tables, lens, num_blocks, block_size):
    """Write [B, S, H, D] tokens into the pool at table positions; token
    (b, i) -> (tables[b, i // bs], i % bs), dropped where i >= lens[b]."""
    bsz, s = vals.shape[:2]
    i = jnp.arange(s)
    blk = jnp.take_along_axis(tables, (i[None, :] // block_size), axis=1)
    blk = jnp.where(i[None, :] < lens[:, None], blk, num_blocks)  # OOB=drop
    off = jnp.broadcast_to(i[None, :] % block_size, (bsz, s))
    return pool.at[blk, off].set(vals, mode="drop")


def _scatter_decode(pool, vals, tables, lens, active, num_blocks, block_size):
    """Write ONE token per sequence at position lens[b]; inactive rows
    write nowhere (their blocks may already be recycled)."""
    blk = jnp.take_along_axis(tables, (lens // block_size)[:, None],
                              axis=1)[:, 0]
    blk = jnp.where(active, blk, num_blocks)  # OOB -> dropped
    off = lens % block_size
    return pool.at[blk, off].set(vals[:, 0], mode="drop")


def llama_prefill_paged(model, input_ids, prompt_lens, cache: PagedKVCache):
    """Prefill padded ragged prompts [B, S]; returns (last_logits, cache).

    Attention runs the padded-varlen path (kv_lens) — the fused kernel on
    TPU; K/V of every valid position is scattered into the block pool.
    ``last_logits`` are taken at each row's LAST VALID position."""
    cfg = model.cfg
    if getattr(cfg, "fp8", False):
        raise NotImplementedError(
            "paged serving ignores the fp8 training path (its inline "
            "decoder forward runs bf16 matmuls); serve an fp8-trained "
            "model with fp8=False weights, or use weight-only quantization")
    b, s = input_ids.shape
    nb, bs = cache.num_blocks, cache.block_size
    x = jnp.take(model.model.embed_tokens, input_ids, axis=0)
    d = cfg.hidden_size // cfg.num_attention_heads
    cos, sin = A.rope_cos_sin(s, d, base=cfg.rope_theta,
                              scaling=getattr(cfg, "rope_scaling", None),
                              allow_dynamic=False)
    k_pools, v_pools = [], []
    for li, lyr in enumerate(model.model.layers):
        h = lyr.input_layernorm(x)
        att = lyr.self_attn
        qkv = _wo(h, att.qkv_proj)
        if getattr(att, "qkv_bias", None) is not None:
            qkv = qkv + att.qkv_bias
        nh, nkv, hd = att.num_heads, att.num_kv_heads, att.head_dim
        q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
        q = A.apply_rope(q.reshape(b, s, nh, hd), cos, sin)
        k = A.apply_rope(k.reshape(b, s, nkv, hd), cos, sin)
        v = v.reshape(b, s, nkv, hd)
        out = A.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             kv_lens=prompt_lens,
                                             window=getattr(cfg, "sliding_window", None))
        k_pools.append(_scatter_prefill(cache.k_pools[li], k,
                                        cache.block_tables, prompt_lens,
                                        nb, bs))
        v_pools.append(_scatter_prefill(cache.v_pools[li], v,
                                        cache.block_tables, prompt_lens,
                                        nb, bs))
        x = x + _wo(out.reshape(b, s, nh * hd), att.o_proj)
        x = x + lyr.mlp(lyr.post_attention_layernorm(x))
    x = model.model.norm(x)
    logits = model.logits(x)
    last = jnp.take_along_axis(
        logits, (prompt_lens - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]
    new_cache = PagedKVCache(k_pools, v_pools, cache.block_tables,
                             prompt_lens.astype(jnp.int32))
    return last, new_cache


def llama_decode_step_paged(model, tokens, cache: PagedKVCache, active):
    """One decode token per sequence. tokens: [B] int32; active: [B] bool
    (finished rows neither write KV nor advance). Returns (logits, cache)."""
    cfg = model.cfg
    b = tokens.shape[0]
    nb, bs = cache.num_blocks, cache.block_size
    x = jnp.take(model.model.embed_tokens, tokens[:, None], axis=0)  # [B,1,E]
    d = cfg.hidden_size // cfg.num_attention_heads
    cos, sin = _rope_rows(cache.lens, d, cfg.rope_theta,
                          getattr(cfg, "rope_scaling", None))
    window = getattr(cfg, "sliding_window", None)
    k_pools, v_pools = [], []
    new_lens = jnp.where(active, cache.lens + 1, cache.lens)
    for li, lyr in enumerate(model.model.layers):
        h = lyr.input_layernorm(x)
        att = lyr.self_attn
        qkv = _wo(h, att.qkv_proj)
        if getattr(att, "qkv_bias", None) is not None:
            qkv = qkv + att.qkv_bias
        nh, nkv, hd = att.num_heads, att.num_kv_heads, att.head_dim
        q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
        q = _apply_rope_rows(q.reshape(b, 1, nh, hd), cos, sin)
        k = _apply_rope_rows(k.reshape(b, 1, nkv, hd), cos, sin)
        v = v.reshape(b, 1, nkv, hd)
        k_pool = _scatter_decode(cache.k_pools[li], k, cache.block_tables,
                                 cache.lens, active, nb, bs)
        v_pool = _scatter_decode(cache.v_pools[li], v, cache.block_tables,
                                 cache.lens, active, nb, bs)
        k_pools.append(k_pool)
        v_pools.append(v_pool)
        # sliding-window configs: the pool retains all tokens (blocks
        # below the window could be recycled — not done yet) but decode
        # attends only the last `window` positions, matching prefill
        out = paged_decode_attention(q[:, 0], k_pool, v_pool,
                                     cache.block_tables, new_lens,
                                     window=window)
        x = x + _wo(out.reshape(b, 1, nh * hd), att.o_proj)
        x = x + lyr.mlp(lyr.post_attention_layernorm(x))
    x = model.model.norm(x)
    logits = model.logits(x)[:, 0]
    return logits, PagedKVCache(k_pools, v_pools, cache.block_tables,
                                new_lens)


# module-level jit wrappers: their compile caches persist across
# paged_generate calls (a per-call jax.jit would recompile every request)
_PREFILL_JIT = jax.jit(llama_prefill_paged)
_DECODE_JIT = jax.jit(llama_decode_step_paged)


def paged_generate(model, input_ids, prompt_lens, max_new_tokens=32,
                   block_size=16, num_blocks=None, eos_token_id=None,
                   temperature=0.0, top_k=None, top_p=None, rng=None):
    """Greedy continuous-batch decode over a paged cache.

    ``input_ids``: [B, S] right-padded ragged prompts with ``prompt_lens``
    [B]. The pool holds ``num_blocks`` blocks (default: exactly enough for
    Σ(prompt_len + max_new_tokens), the ragged bound — NOT B × max_len);
    finished sequences release their blocks back to the manager.

    Host-driven step loop (the serving-engine shape: scheduling/allocation
    on host, fixed-shape jitted compute on device). Returns [B, S +
    max_new_tokens] tokens (finished rows are tail-padded with
    ``eos_token_id``). ``temperature``/``top_k``/``top_p`` enable sampling
    (0.0 = greedy), sharing the sampler with models/decoding.py.
    """
    from paddle_tpu.models.decoding import _sample
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def pick(logits, key):
        return np.asarray(_sample(logits.astype(jnp.float32), key,
                                  temperature, top_k, top_p))
    cfg = model.cfg
    b, s = input_ids.shape
    lens_np = np.asarray(prompt_lens, np.int64)
    max_total = lens_np + max_new_tokens
    max_blocks = int(-(-(int(max_total.max())) // block_size))
    if num_blocks is None:
        num_blocks = int(sum(-(-int(t) // block_size) for t in max_total))
    mgr = BlockManager(num_blocks, block_size)
    for sid in range(b):
        mgr.allocate(sid, int(lens_np[sid]))
    cache = PagedKVCache.init(cfg.num_hidden_layers, num_blocks, block_size,
                              cfg.num_key_value_heads,
                              cfg.hidden_size // cfg.num_attention_heads,
                              b, max_blocks, cfg.dtype)
    cache.block_tables = mgr.table_array(range(b), max_blocks)

    prefill = _PREFILL_JIT
    step = _DECODE_JIT

    logits, cache = prefill(model, jnp.asarray(input_ids),
                            jnp.asarray(lens_np, jnp.int32), cache)
    tokens = np.concatenate(
        [np.asarray(input_ids),
         np.zeros((b, max_new_tokens), np.asarray(input_ids).dtype)], axis=1)
    rng, sub = jax.random.split(rng)
    next_tok = pick(logits, sub)
    active = np.ones((b,), bool)
    cur = lens_np.copy()
    for sid in range(b):
        tokens[sid, cur[sid]] = next_tok[sid]
    if eos_token_id is not None:
        newly = next_tok == eos_token_id
        for sid in np.nonzero(newly)[0]:
            active[sid] = False
            mgr.free(int(sid))

    for _ in range(max_new_tokens - 1):
        if not active.any():
            break
        # grow tables for rows about to cross a block boundary
        for sid in range(b):
            if active[sid]:
                mgr.allocate(sid, int(cur[sid]) + 1)
        cache.block_tables = mgr.table_array(range(b), max_blocks)
        logits, cache = step(model, jnp.asarray(next_tok, jnp.int32), cache,
                             jnp.asarray(active))
        rng, sub = jax.random.split(rng)
        nxt = pick(logits, sub)
        next_tok = np.where(active, nxt, next_tok)
        cur = cur + active.astype(np.int64)
        for sid in range(b):
            if active[sid]:
                tokens[sid, cur[sid]] = next_tok[sid]
        if eos_token_id is not None:
            newly = active & (next_tok == eos_token_id)
            for sid in np.nonzero(newly)[0]:
                active[sid] = False
                mgr.free(int(sid))
    if eos_token_id is not None:
        # finished rows: pad the tail with EOS (HF/PaddleNLP convention)
        for sid in range(b):
            if not active[sid]:
                tokens[sid, int(cur[sid]) + 1:] = eos_token_id
    return jnp.asarray(tokens), cache
