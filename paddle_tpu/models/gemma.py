"""Gemma decoder LM (ref capability: PaddleNLP ``gemma`` model family).

The zero-centered-norm member of the zoo, with three departures from the
LLaMA recipe that make it NOT a config of ``LlamaForCausalLM``:
  * RMSNorm multiplies by ``1 + weight`` (weights stored zero-centered);
  * ``head_dim`` is decoupled from ``hidden_size / num_heads`` (gemma-7b:
    16 heads x 256 dims on a 3072 hidden) — q/k/v project h -> nh*hd and
    o projects nh*hd -> h;
  * embeddings are scaled by ``sqrt(hidden_size)`` at the input and the
    MLP activation is tanh-gelu. Head tied to the embeddings.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I
from paddle_tpu.ops import attention as A


@dataclass
class GemmaConfig:
    vocab_size: int = 256000
    hidden_size: int = 3072
    intermediate_size: int = 24576
    num_hidden_layers: int = 28
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    head_dim: int = 256
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    dtype: object = None
    remat: bool = True

    def __post_init__(self):
        if self.dtype is None:
            self.dtype = get_default_dtype()

    @staticmethod
    def tiny(**kw):
        return GemmaConfig(**{**dict(vocab_size=128, hidden_size=32,
                                     intermediate_size=64,
                                     num_hidden_layers=2,
                                     num_attention_heads=4,
                                     num_key_value_heads=2, head_dim=16,
                                     max_position_embeddings=64,
                                     dtype=jnp.float32, remat=False), **kw})


class GemmaRMSNorm(Module):
    """RMSNorm with a ZERO-CENTERED weight: y = norm(x) * (1 + w)."""

    def __init__(self, size, eps, dtype):
        super().__init__()
        self.weight = jnp.zeros((size,), dtype)
        self.eps = eps

    def __call__(self, x):
        h = x.astype(jnp.float32)
        h = h * jax.lax.rsqrt(jnp.mean(h * h, -1, keepdims=True) + self.eps)
        return (h * (1.0 + self.weight.astype(jnp.float32))).astype(x.dtype)


class GemmaDecoderLayer(Module):
    def __init__(self, cfg: GemmaConfig):
        super().__init__()
        h, hd = cfg.hidden_size, cfg.head_dim
        nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
        init = I.Normal(0.0, cfg.initializer_range)
        self.input_layernorm = GemmaRMSNorm(h, cfg.rms_norm_eps, cfg.dtype)
        self.qkv_proj = init((h, (nh + 2 * nkv) * hd), cfg.dtype)
        self.o_proj = init((nh * hd, h), cfg.dtype)
        self.post_attention_layernorm = GemmaRMSNorm(h, cfg.rms_norm_eps,
                                                     cfg.dtype)
        self.gate_up_proj = init((h, 2 * cfg.intermediate_size), cfg.dtype)
        self.down_proj = init((cfg.intermediate_size, h), cfg.dtype)
        self.dims = (nh, nkv, hd)

    def __call__(self, x, cos, sin):
        b, s, hdim = x.shape
        nh, nkv, hd = self.dims
        h = self.input_layernorm(x)
        qkv = h @ self.qkv_proj
        q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
        q = A.apply_rope(q.reshape(b, s, nh, hd), cos, sin)
        k = A.apply_rope(k.reshape(b, s, nkv, hd), cos, sin)
        att = A.scaled_dot_product_attention(q, k, v.reshape(b, s, nkv, hd),
                                             is_causal=True)
        x = x + att.reshape(b, s, nh * hd) @ self.o_proj
        h2 = self.post_attention_layernorm(x)
        gate, up = jnp.split(h2 @ self.gate_up_proj, 2, axis=-1)
        m = jax.nn.gelu(gate, approximate=True) * up
        return x + m @ self.down_proj


class GemmaForCausalLM(Module):
    def __init__(self, cfg: GemmaConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.embed_tokens = init((cfg.vocab_size, cfg.hidden_size),
                                 cfg.dtype)
        self.layers = [GemmaDecoderLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]
        self.norm = GemmaRMSNorm(cfg.hidden_size, cfg.rms_norm_eps,
                                 cfg.dtype)

    def __call__(self, input_ids):
        cfg = self.cfg
        s = input_ids.shape[1]
        cos, sin = A.rope_cos_sin(s, cfg.head_dim, base=cfg.rope_theta)
        x = jnp.take(self.embed_tokens, input_ids, axis=0)
        x = x * jnp.asarray(cfg.hidden_size ** 0.5, x.dtype)
        blk = (jax.checkpoint(lambda lyr, h: lyr(h, cos, sin))
               if cfg.remat else (lambda lyr, h: lyr(h, cos, sin)))
        for lyr in self.layers:
            x = blk(lyr, x)
        x = self.norm(x)
        return x @ self.embed_tokens.T       # tied head

    def loss(self, input_ids, labels):
        logits = self(input_ids).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, jnp.maximum(labels, 0)[..., None],
                                 axis=-1)[..., 0]
        mask = (labels >= 0).astype(jnp.float32)
        return -jnp.sum(ll * mask) / jnp.maximum(mask.sum(), 1.0)
