"""ALBERT (ref: PaddleNLP ``paddlenlp/transformers/albert/modeling.py``).

The parameter-sharing encoder: ONE transformer layer's weights are
applied ``num_hidden_layers`` times (the ALBERT recycling trick — a
natural fit for ``lax.scan``-over-depth with a constant carry of shared
weights), on top of a factorized embedding (``embedding_size`` <<
``hidden_size`` + projection). Post-LN blocks, gelu_new activation, MLM
head back in embedding space with the decoder tied to the word table.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Dropout, Embedding, LayerNorm, Linear
from paddle_tpu.nn.transformer import MultiHeadAttention


@dataclass
class AlbertConfig:
    vocab_size: int = 30000
    embedding_size: int = 128
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    hidden_dropout_prob: float = 0.0
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    @staticmethod
    def tiny(**kw):
        return AlbertConfig(**{**dict(vocab_size=128, embedding_size=16,
                                      hidden_size=32, num_hidden_layers=3,
                                      num_attention_heads=2,
                                      intermediate_size=64,
                                      max_position_embeddings=64), **kw})


class AlbertSharedLayer(Module):
    """The ONE layer whose weights every depth step reuses."""

    def __init__(self, cfg: AlbertConfig):
        super().__init__()
        h = cfg.hidden_size
        self.attention = MultiHeadAttention(h, cfg.num_attention_heads,
                                            dtype=cfg.dtype)
        self.attn_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                   dtype=cfg.dtype)
        self.ffn = Linear(h, cfg.intermediate_size, dtype=cfg.dtype)
        self.ffn_output = Linear(cfg.intermediate_size, h, dtype=cfg.dtype)
        self.full_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                   dtype=cfg.dtype)

    def __call__(self, x, attn_mask=None):
        x = self.attn_norm(x + self.attention(x, attn_mask=attn_mask))
        m = self.ffn_output(F.gelu(self.ffn(x), approximate=True))
        return self.full_norm(x + m)


class AlbertModel(Module):
    def __init__(self, cfg: AlbertConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        e = cfg.embedding_size
        self.word_embeddings = Embedding(cfg.vocab_size, e,
                                         weight_init=init, dtype=cfg.dtype)
        self.position_embeddings = Embedding(cfg.max_position_embeddings, e,
                                             weight_init=init,
                                             dtype=cfg.dtype)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size, e,
                                               weight_init=init,
                                               dtype=cfg.dtype)
        self.emb_norm = LayerNorm(e, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.embedding_project = Linear(e, cfg.hidden_size, dtype=cfg.dtype)
        self.shared = AlbertSharedLayer(cfg)
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size,
                             dtype=cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        cfg = self.cfg
        s = input_ids.shape[1]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if attention_mask is not None:
            attention_mask = (1.0 - attention_mask[:, None, None, :]
                              .astype(jnp.float32)) * -1e9
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(jnp.arange(s)[None, :])
             + self.token_type_embeddings(token_type_ids))
        x = self.embedding_project(self.emb_norm(x))
        # weight recycling: the SAME layer params each depth step
        for _ in range(cfg.num_hidden_layers):
            x = self.shared(x, attn_mask=attention_mask)
        pooled = jnp.tanh(self.pooler(x[:, 0]))
        return x, pooled


class AlbertForMaskedLM(Module):
    def __init__(self, cfg: AlbertConfig):
        super().__init__()
        self.cfg = cfg
        self.albert = AlbertModel(cfg)
        self.lm_dense = Linear(cfg.hidden_size, cfg.embedding_size,
                               dtype=cfg.dtype)
        self.lm_norm = LayerNorm(cfg.embedding_size,
                                 epsilon=cfg.layer_norm_eps,
                                 dtype=cfg.dtype)
        self.lm_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, _ = self.albert(input_ids, token_type_ids, attention_mask)
        h = self.lm_norm(F.gelu(self.lm_dense(seq), approximate=True))
        emb = self.albert.word_embeddings.weight
        return h @ emb.T + self.lm_bias

    def loss(self, input_ids, mlm_labels, token_type_ids=None,
             attention_mask=None):
        logits = self(input_ids, token_type_ids, attention_mask)
        ce = F.cross_entropy(logits.astype(jnp.float32),
                             jnp.maximum(mlm_labels, 0), reduction="none")
        mask = (mlm_labels >= 0).astype(jnp.float32)
        return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
