"""DistilBERT (ref: PaddleNLP ``paddlenlp/transformers/distilbert``).

The distilled 6-layer BERT shape: no token-type stream, no pooler,
post-LN blocks, MLM head = transform + LN + tied projector.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Embedding, LayerNorm, Linear
from paddle_tpu.nn.transformer import MultiHeadAttention


@dataclass
class DistilBertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 6
    n_heads: int = 12
    hidden_dim: int = 3072
    max_position_embeddings: int = 512
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    @staticmethod
    def tiny(**kw):
        return DistilBertConfig(**{**dict(vocab_size=128, dim=32,
                                          n_layers=2, n_heads=2,
                                          hidden_dim=64,
                                          max_position_embeddings=64),
                                   **kw})


class DistilBertLayer(Module):
    def __init__(self, cfg: DistilBertConfig):
        super().__init__()
        self.attention = MultiHeadAttention(cfg.dim, cfg.n_heads,
                                            dtype=cfg.dtype)
        self.sa_layer_norm = LayerNorm(cfg.dim, epsilon=1e-12,
                                       dtype=cfg.dtype)
        self.lin1 = Linear(cfg.dim, cfg.hidden_dim, dtype=cfg.dtype)
        self.lin2 = Linear(cfg.hidden_dim, cfg.dim, dtype=cfg.dtype)
        self.output_layer_norm = LayerNorm(cfg.dim, epsilon=1e-12,
                                           dtype=cfg.dtype)

    def __call__(self, x, attn_mask=None):
        x = self.sa_layer_norm(x + self.attention(x, attn_mask=attn_mask))
        return self.output_layer_norm(
            x + self.lin2(F.gelu(self.lin1(x))))


class DistilBertModel(Module):
    def __init__(self, cfg: DistilBertConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.dim,
                                         weight_init=init, dtype=cfg.dtype)
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.dim, weight_init=init,
                                             dtype=cfg.dtype)
        self.emb_norm = LayerNorm(cfg.dim, epsilon=1e-12, dtype=cfg.dtype)
        self.layers = [DistilBertLayer(cfg) for _ in range(cfg.n_layers)]

    def __call__(self, input_ids, attention_mask=None):
        s = input_ids.shape[1]
        if attention_mask is not None:
            attention_mask = (1.0 - attention_mask[:, None, None, :]
                              .astype(jnp.float32)) * -1e9
        x = self.emb_norm(self.word_embeddings(input_ids)
                          + self.position_embeddings(
                              jnp.arange(s)[None, :]))
        for lyr in self.layers:
            x = lyr(x, attn_mask=attention_mask)
        return x


class DistilBertForMaskedLM(Module):
    def __init__(self, cfg: DistilBertConfig):
        super().__init__()
        self.cfg = cfg
        self.distilbert = DistilBertModel(cfg)
        self.vocab_transform = Linear(cfg.dim, cfg.dim, dtype=cfg.dtype)
        self.vocab_norm = LayerNorm(cfg.dim, epsilon=1e-12, dtype=cfg.dtype)
        self.vocab_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids, attention_mask=None):
        seq = self.distilbert(input_ids, attention_mask)
        h = self.vocab_norm(F.gelu(self.vocab_transform(seq)))
        return h @ self.distilbert.word_embeddings.weight.T + self.vocab_bias
