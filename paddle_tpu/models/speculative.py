"""Speculative decoding with a draft model (ref capability: the PaddleNLP
``llm`` predictor's speculative/draft-model decoding).

Greedy verification: the draft proposes ``gamma`` tokens autoregressively;
the target verifies them in ONE forward over the chunk and commits the
longest matching prefix plus its own next token (the correction, or the
"bonus" token when everything matched). Output is EXACTLY the target's own
greedy decode — speculation only changes how many target forwards it takes.

TPU-native notes:
  * both models run the static KV cache (models/decoding.py); "rollback"
    of rejected tokens is free — chunk writes are positional overwrites and
    causal masking never attends beyond the current query position, so
    stale cache entries are always either overwritten or masked.
  * chunk lengths vary with the acceptance count, so the jitted chunk
    forward retraces at most gamma+1 times per model (then every shape is
    cached).
  * single-sequence (B == 1): per-row acceptance counts would make batched
    positions ragged; the reference's speculative predictor is likewise
    sequence-at-a-time.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models.decoding import KVCache, llama_forward_with_cache


def _greedy(logits):
    return int(np.asarray(jnp.argmax(logits.astype(jnp.float32),
                                     axis=-1)).reshape(-1)[0])


def speculative_generate(target, draft, input_ids, max_new_tokens: int = 32,
                         gamma: int = 4, eos_token_id=None):
    """Greedy speculative decode. input_ids: [1, S]. Returns
    (tokens [1, S + max_new_tokens], stats dict with acceptance info)."""
    t_cfg, d_cfg = target.cfg, draft.cfg
    if input_ids.shape[0] != 1:
        raise ValueError("speculative_generate is single-sequence (B == 1)")
    if getattr(t_cfg, "sliding_window", None) or \
            getattr(d_cfg, "sliding_window", None):
        raise NotImplementedError(
            "speculative decoding over a windowed ring cache is not "
            "supported (positional overwrite-rollback needs the full cache)")
    prompt_len = input_ids.shape[1]
    max_len = prompt_len + max_new_tokens + gamma + 2

    def make_cache(cfg):
        return KVCache.init(cfg.num_hidden_layers, 1, max_len,
                            cfg.num_key_value_heads,
                            cfg.hidden_size // cfg.num_attention_heads,
                            cfg.dtype)

    fwd = jax.jit(llama_forward_with_cache, static_argnums=())

    cache_t, cache_d = make_cache(t_cfg), make_cache(d_cfg)
    ids = jnp.asarray(input_ids)
    logits_t, cache_t = fwd(target, ids, cache_t, 0)
    _, cache_d = fwd(draft, ids, cache_d, 0)

    committed: list[int] = []          # tokens at positions prompt_len + i
    c = _greedy(logits_t[:, -1])       # first committed token
    committed.append(c)
    pos = prompt_len                   # target cache valid through pos - 1
    draft_pos = prompt_len             # draft cache valid through draft_pos-1
    rounds = 0
    accepted_total = 0

    def done():
        return (len(committed) >= max_new_tokens
                or (eos_token_id is not None and eos_token_id in committed))

    while not done():
        rounds += 1
        # ---- draft proposes gamma tokens ------------------------------
        # first feed it any committed tokens it has not processed yet
        # (suffix from draft_pos .. pos); its last logit starts proposals
        pending = committed[draft_pos - prompt_len:]
        chunk_d = jnp.asarray([pending], jnp.int32)
        dl, cache_d = fwd(draft, chunk_d, cache_d, draft_pos)
        draft_pos += len(pending)
        props = [_greedy(dl[:, -1])]
        for _ in range(gamma - 1):
            dl, cache_d = fwd(draft, jnp.asarray([[props[-1]]], jnp.int32),
                              cache_d, draft_pos)
            draft_pos += 1
            props.append(_greedy(dl[:, -1]))

        # ---- target verifies the whole chunk in one forward ------------
        chunk_t = jnp.asarray([[c] + props], jnp.int32)
        # written at positions pos..pos+gamma
        tl, cache_t = fwd(target, chunk_t, cache_t, pos)
        vs = np.asarray(jnp.argmax(tl.astype(jnp.float32), axis=-1))[0]
        # vs[i] = target's token for position pos+1+i
        n_acc = 0
        while n_acc < gamma and vs[n_acc] == props[n_acc]:
            n_acc += 1
        # accepted prefix + the target's own next token (correction, or the
        # bonus token when every proposal matched — n_acc == gamma)
        new = props[:n_acc] + [int(vs[n_acc])]
        committed.extend(new)
        accepted_total += n_acc
        pos += n_acc + 1
        c = committed[-1]
        # draft cache holds proposals up to draft_pos-1; positions beyond
        # the new committed frontier are stale but will be overwritten (its
        # next chunk write starts at the frontier) — reset the pointer
        draft_pos = min(draft_pos, pos)

    committed = committed[:max_new_tokens]
    if eos_token_id is not None and eos_token_id in committed:
        # match generate()'s single-sequence semantics exactly: the buffer
        # past the first EOS stays zero-initialized
        committed = committed[: committed.index(eos_token_id) + 1]
    out = np.concatenate(
        [np.asarray(ids)[0],
         np.asarray(committed, np.asarray(ids).dtype),
         np.zeros((max_new_tokens - len(committed),),
                  np.asarray(ids).dtype)])
    stats = {"rounds": rounds,
             "proposed": rounds * gamma,
             "accepted": accepted_total,
             "acceptance_rate": accepted_total / max(rounds * gamma, 1)}
    return jnp.asarray(out[None]), stats
