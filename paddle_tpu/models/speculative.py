"""Speculative decoding with a draft model (ref capability: the PaddleNLP
``llm`` predictor's speculative/draft-model decoding).

Greedy verification: the draft proposes ``gamma`` tokens autoregressively;
the target verifies them in ONE forward over the chunk and commits the
longest matching prefix plus its own next token (the correction, or the
"bonus" token when everything matched). Output is EXACTLY the target's own
greedy decode — speculation only changes how many target forwards it takes.

TPU-native notes:
  * both models run the static KV cache (models/decoding.py); "rollback"
    of rejected tokens is free — chunk writes are positional overwrites and
    causal masking never attends beyond the current query position, so
    stale cache entries are always either overwritten or masked.
  * chunk lengths vary with the acceptance count, so the jitted chunk
    forward retraces at most gamma+1 times per model (then every shape is
    cached).
  * single-sequence (B == 1): per-row acceptance counts would make batched
    positions ragged; the reference's speculative predictor is likewise
    sequence-at-a-time.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models.decoding import KVCache, llama_forward_with_cache
from paddle_tpu.models.paged import (greedy_accept_length,
                                     spec_advance_frontiers,
                                     stochastic_accept_row)
from paddle_tpu.ops import attention as A
from paddle_tpu.quantization import wo_matmul as _wo


def _forward_rows(model, input_ids, cache: KVCache, row_pos,
                  chunk_end_len=None, chunk_lens=None):
    """Chunk forward with PER-ROW positions: row b's tokens occupy cache
    positions ``row_pos[b] .. row_pos[b]+C-1`` (rope, cache writes, and
    causal visibility all per-row). This is what makes speculation
    batchable: after the first round every row sits at a different
    position (different acceptance counts), so the scalar-``pos`` forward
    no longer fits. Stale cache entries beyond a row's frontier are never
    visible (key j attends iff j <= row_pos[b]+i) and are overwritten by
    the row's next chunk.

    ``chunk_end_len`` ([B] int32, dynamic-NTK only): rotate the WHOLE
    chunk with the row's chunk-end base alpha(chunk_end_len[b]) — what
    ``generate()``'s prefill does (decoding.py cur_len = pos + C). Without
    it each position uses its own base alpha(pos+1), matching the
    one-token-per-step decode that verify chunks must reproduce. Prefill
    MUST pass it or long-prompt dynamic-NTK caches desync from plain
    ``generate()``.

    ``chunk_lens`` ([B] int32): per-row WRITE mask — row b commits only
    its first chunk_lens[b] positions to the cache (a row at 0 writes
    nothing at all). The serving engine's ragged draft feeds need this:
    slots propose different k, so padding columns — and whole padding
    rows — must not clobber cache entries. Masked writes are routed
    out-of-bounds and dropped (NOT clamped: the scatter default would
    silently corrupt position cap-1)."""
    cfg = model.cfg
    if getattr(cfg, "sliding_window", None):
        raise NotImplementedError("speculative rows-forward: no window")
    b, c = input_ids.shape
    x = jnp.take(model.model.embed_tokens, input_ids, axis=0)
    d = cfg.hidden_size // cfg.num_attention_heads
    positions = row_pos[:, None] + jnp.arange(c, dtype=jnp.int32)  # [B, C]
    scaling = getattr(cfg, "rope_scaling", None)
    if (scaling or {}).get("type") == "dynamic":
        cur_len = (chunk_end_len[:, None].astype(jnp.int32)  # [B, 1]
                   if chunk_end_len is not None else positions + 1)
    else:
        cur_len = None
    base, pos_div = A.resolve_rope_scaling(
        cfg.rope_theta, d, scaling, allow_dynamic=False,
        max_position_embeddings=getattr(cfg, "max_position_embeddings",
                                        None),
        cur_len=cur_len)
    base = jnp.asarray(base, jnp.float32)
    base = base.reshape((1, 1) if base.ndim == 0 else base.shape)  # [B|1,C|1]
    inv = 1.0 / (base[:, :, None]
                 ** (jnp.arange(0, d, 2, jnp.float32)[None, None, :] / d))
    f = (positions.astype(jnp.float32) / pos_div)[:, :, None] * inv
    cos, sin = jnp.cos(f)[:, :, None, :], jnp.sin(f)[:, :, None, :]

    def rope(t):
        d2 = t.shape[-1] // 2
        t1, t2 = t[..., :d2], t[..., d2:]
        return jnp.concatenate([t1 * cos - t2 * sin, t2 * cos + t1 * sin],
                               axis=-1).astype(t.dtype)

    row = jnp.arange(b)[:, None]
    cache_len = cache.k[0].shape[1]
    vis = (jnp.arange(cache_len)[None, None, :]
           <= positions[:, :, None])[:, None]            # [B,1,C,L]
    if chunk_lens is None:
        wpos = positions
    else:
        wpos = jnp.where(jnp.arange(c, dtype=jnp.int32)[None, :]
                         < chunk_lens[:, None], positions, cache_len)
    new_k, new_v = [], []
    for li, lyr in enumerate(model.model.layers):
        h = lyr.input_layernorm(x)
        att = lyr.self_attn
        qkv = _wo(h, att.qkv_proj)
        if getattr(att, "qkv_bias", None) is not None:
            qkv = qkv + att.qkv_bias
        nh, nkv, hd = att.num_heads, att.num_kv_heads, att.head_dim
        q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
        q = rope(q.reshape(b, c, nh, hd))
        k = rope(k.reshape(b, c, nkv, hd))
        v = v.reshape(b, c, nkv, hd)
        k_c = cache.k[li].at[row, wpos].set(k, mode="drop")
        v_c = cache.v[li].at[row, wpos].set(v, mode="drop")
        new_k.append(k_c)
        new_v.append(v_c)
        out = A.xla_attention(q, k_c, v_c, attn_mask=vis)
        x = x + _wo(out.reshape(b, c, nh * hd), att.o_proj)
        x = x + lyr.mlp(lyr.post_attention_layernorm(x))
    x = model.model.norm(x)
    return model.logits(x), KVCache(new_k, new_v, cache.length,
                                    cache.slot_pos)


_FWD_ROWS_JIT = jax.jit(_forward_rows)


def _greedy(logits):
    return int(np.asarray(jnp.argmax(logits.astype(jnp.float32),
                                     axis=-1)).reshape(-1)[0])


def speculative_generate(target, draft, input_ids, max_new_tokens: int = 32,
                         gamma: int = 4, eos_token_id=None):
    """Greedy speculative decode. input_ids: [1, S]. Returns
    (tokens [1, S + max_new_tokens], stats dict with acceptance info)."""
    t_cfg, d_cfg = target.cfg, draft.cfg
    if input_ids.shape[0] != 1:
        raise ValueError("speculative_generate is single-sequence (B == 1)")
    if getattr(t_cfg, "sliding_window", None) or \
            getattr(d_cfg, "sliding_window", None):
        raise NotImplementedError(
            "speculative decoding over a windowed ring cache is not "
            "supported (positional overwrite-rollback needs the full cache)")
    prompt_len = input_ids.shape[1]
    max_len = prompt_len + max_new_tokens + gamma + 2

    def make_cache(cfg):
        return KVCache.init(cfg.num_hidden_layers, 1, max_len,
                            cfg.num_key_value_heads,
                            cfg.hidden_size // cfg.num_attention_heads,
                            cfg.dtype)

    dynamic = any((getattr(c, "rope_scaling", None) or {}).get("type")
                  == "dynamic" for c in (t_cfg, d_cfg))
    if dynamic:
        # verify chunks must rotate every position with ITS current length
        # exactly generate()'s one-token-per-step bases — or the chunk-end
        # base would silently desync the cache from plain decode; the
        # PREFILL however must use the chunk-end base for the whole prompt
        # (what generate()'s prefill does), passed via chunk_end
        def fwd(model, ids, cache, pos, chunk_end=None):
            ce = (None if chunk_end is None
                  else jnp.full((ids.shape[0],), chunk_end, jnp.int32))
            return _FWD_ROWS_JIT(model, jnp.asarray(ids, jnp.int32), cache,
                                 jnp.full((ids.shape[0],), pos, jnp.int32),
                                 ce)
    else:
        _fwd_chunk = jax.jit(llama_forward_with_cache, static_argnums=())

        def fwd(model, ids, cache, pos, chunk_end=None):
            # llama_forward_with_cache is natively chunk-end based
            return _fwd_chunk(model, ids, cache, pos)

    cache_t, cache_d = make_cache(t_cfg), make_cache(d_cfg)
    ids = jnp.asarray(input_ids)
    logits_t, cache_t = fwd(target, ids, cache_t, 0, chunk_end=prompt_len)
    _, cache_d = fwd(draft, ids, cache_d, 0, chunk_end=prompt_len)

    committed: list[int] = []          # tokens at positions prompt_len + i
    c = _greedy(logits_t[:, -1])       # first committed token
    committed.append(c)
    pos = prompt_len                   # target cache valid through pos - 1
    draft_pos = prompt_len             # draft cache valid through draft_pos-1
    rounds = 0
    accepted_total = 0

    def done():
        return (len(committed) >= max_new_tokens
                or (eos_token_id is not None and eos_token_id in committed))

    while not done():
        rounds += 1
        # ---- draft proposes gamma tokens ------------------------------
        # first feed it any committed tokens it has not processed yet
        # (suffix from draft_pos .. pos); its last logit starts proposals
        pending = committed[draft_pos - prompt_len:]
        chunk_d = jnp.asarray([pending], jnp.int32)
        dl, cache_d = fwd(draft, chunk_d, cache_d, draft_pos)
        draft_pos += len(pending)
        props = [_greedy(dl[:, -1])]
        for _ in range(gamma - 1):
            dl, cache_d = fwd(draft, jnp.asarray([[props[-1]]], jnp.int32),
                              cache_d, draft_pos)
            draft_pos += 1
            props.append(_greedy(dl[:, -1]))

        # ---- target verifies the whole chunk in one forward ------------
        chunk_t = jnp.asarray([[c] + props], jnp.int32)
        # written at positions pos..pos+gamma
        tl, cache_t = fwd(target, chunk_t, cache_t, pos)
        vs = np.asarray(jnp.argmax(tl.astype(jnp.float32), axis=-1))[0]
        # vs[i] = target's token for position pos+1+i
        n_acc = int(greedy_accept_length(vs[:gamma], props))
        # accepted prefix + the target's own next token (correction, or the
        # bonus token when every proposal matched — n_acc == gamma)
        new = props[:n_acc] + [int(vs[n_acc])]
        committed.extend(new)
        accepted_total += n_acc
        pos, draft_pos = spec_advance_frontiers(pos, draft_pos, len(new))
        pos, draft_pos = int(pos), int(draft_pos)
        c = committed[-1]

    committed = committed[:max_new_tokens]
    if eos_token_id is not None and eos_token_id in committed:
        # match generate()'s single-sequence semantics exactly: the buffer
        # past the first EOS stays zero-initialized
        committed = committed[: committed.index(eos_token_id) + 1]
    out = np.concatenate(
        [np.asarray(ids)[0],
         np.asarray(committed, np.asarray(ids).dtype),
         np.zeros((max_new_tokens - len(committed),),
                  np.asarray(ids).dtype)])
    stats = {"rounds": rounds,
             "proposed": rounds * gamma,
             "accepted": accepted_total,
             "acceptance_rate": accepted_total / max(rounds * gamma, 1)}
    return jnp.asarray(out[None]), stats


def speculative_generate_batched(target, draft, input_ids, prompt_lens=None,
                                 max_new_tokens: int = 32, gamma: int = 4,
                                 eos_token_id=None):
    """BATCHED greedy speculative decoding (ref: the serving predictor's
    draft-model decode, batch>1). input_ids: [B, S] right-padded ragged
    prompts with ``prompt_lens`` [B] (defaults to S for every row).

    Rows advance at their own acceptance rates — after round one every row
    sits at a different position — so all chunk forwards run through
    ``_forward_rows`` (per-row rope/writes/visibility). Every row's output
    is EXACTLY its solo greedy decode; rows that finish early are frozen
    (their re-verifications rewrite identical KV, a no-op).

    Returns (tokens [B, S + max_new_tokens], stats). Per-row semantics
    match ``speculative_generate``: positions past a row's first EOS stay
    zero."""
    ids_np = np.asarray(input_ids)
    b, s = ids_np.shape
    if prompt_lens is None:
        prompt_lens = np.full((b,), s, np.int64)
    lens_np = np.asarray(prompt_lens, np.int64)
    max_len = int(lens_np.max()) + max_new_tokens + gamma + 2

    def make_cache(cfg):
        return KVCache.init(cfg.num_hidden_layers, b, max_len,
                            cfg.num_key_value_heads,
                            cfg.hidden_size // cfg.num_attention_heads,
                            cfg.dtype)

    for cfg in (target.cfg, draft.cfg):
        if getattr(cfg, "sliding_window", None):
            raise NotImplementedError(
                "speculative decoding needs the full (un-windowed) cache")

    cache_t, cache_d = make_cache(target.cfg), make_cache(draft.cfg)
    zero = jnp.zeros((b,), jnp.int32)
    ids = jnp.asarray(ids_np, jnp.int32)
    # ragged prefill: every row at position 0; per-row last-valid logit.
    # Dynamic-NTK: each row's prompt rotates with ITS chunk-end base
    # alpha(prompt_len[r]) — generate()'s prefill semantics (padding
    # positions past a row's length are stale/overwritten, base moot)
    lens32 = jnp.asarray(lens_np, jnp.int32)
    logits_t, cache_t = _FWD_ROWS_JIT(target, ids, cache_t, zero, lens32)
    _, cache_d = _FWD_ROWS_JIT(draft, ids, cache_d, zero, lens32)
    last = np.asarray(jnp.argmax(
        jnp.take_along_axis(
            logits_t, jnp.asarray(lens_np - 1)[:, None, None].astype(
                jnp.int32), axis=1)[:, 0].astype(jnp.float32), axis=-1))

    committed = [[int(last[r])] for r in range(b)]
    c = last.astype(np.int64)              # last committed token per row
    pos = lens_np.copy()                   # target frontier per row
    draft_pos = lens_np.copy()
    done = np.zeros((b,), bool)
    rounds = 0
    accepted_total = 0
    proposed_total = 0

    def row_done(r):
        return (len(committed[r]) >= max_new_tokens
                or (eos_token_id is not None
                    and eos_token_id in committed[r]))

    while not all(row_done(r) for r in range(b)):
        rounds += 1
        proposed_total += gamma * int((~done).sum())
        # ---- draft catches up on each row's pending committed suffix ----
        pend = [committed[r][int(draft_pos[r] - lens_np[r]):] if not done[r]
                else [int(c[r])] for r in range(b)]
        pmax = max(len(p) for p in pend)
        chunk = np.zeros((b, pmax), np.int32)
        for r in range(b):
            chunk[r, :len(pend[r])] = pend[r]
        dl, cache_d = _FWD_ROWS_JIT(draft, jnp.asarray(chunk), cache_d,
                                    jnp.asarray(draft_pos, jnp.int32))
        plen = np.asarray([len(p) for p in pend], np.int64)
        draft_pos = np.where(done, draft_pos, draft_pos + plen)
        dlast = jnp.take_along_axis(
            dl, jnp.asarray(plen - 1)[:, None, None].astype(jnp.int32),
            axis=1)[:, 0]
        props = [np.asarray(jnp.argmax(dlast.astype(jnp.float32), -1))]
        for _ in range(gamma - 1):
            dl, cache_d = _FWD_ROWS_JIT(
                draft, jnp.asarray(props[-1][:, None], jnp.int32), cache_d,
                jnp.asarray(draft_pos, jnp.int32))
            draft_pos = np.where(done, draft_pos, draft_pos + 1)
            props.append(np.asarray(
                jnp.argmax(dl[:, 0].astype(jnp.float32), -1)))
        props = np.stack(props, axis=1)            # [B, gamma]

        # ---- target verifies every row's chunk in one forward -----------
        chunk_t = np.concatenate([c[:, None], props], axis=1).astype(np.int32)
        tl, cache_t = _FWD_ROWS_JIT(target, jnp.asarray(chunk_t), cache_t,
                                    jnp.asarray(pos, jnp.int32))
        vs = np.asarray(jnp.argmax(tl.astype(jnp.float32), axis=-1))

        n_acc = greedy_accept_length(vs[:, :gamma], props)     # [B]
        for r in range(b):                         # per ROUND, not per token
            if done[r]:
                continue
            na = int(n_acc[r])
            new = list(props[r, :na]) + [int(vs[r, na])]
            committed[r].extend(int(t) for t in new)
            accepted_total += na
            pos[r], draft_pos[r] = spec_advance_frontiers(
                int(pos[r]), int(draft_pos[r]), len(new))
            c[r] = committed[r][-1]
            done[r] = row_done(r)

    out = np.zeros((b, s + max_new_tokens), ids_np.dtype)
    for r in range(b):
        toks = committed[r][:max_new_tokens]
        if eos_token_id is not None and eos_token_id in toks:
            toks = toks[: toks.index(eos_token_id) + 1]
        out[r, : lens_np[r]] = ids_np[r, : lens_np[r]]
        out[r, lens_np[r]: lens_np[r] + len(toks)] = toks
    stats = {"rounds": rounds,
             "proposed": proposed_total,
             "accepted": accepted_total,
             "acceptance_rate": accepted_total / max(proposed_total, 1)}
    return jnp.asarray(out), stats


def speculative_sample(target, draft, input_ids, max_new_tokens: int = 32,
                       gamma: int = 4, temperature: float = 1.0,
                       eos_token_id=None, seed: int = 0):
    """STOCHASTIC speculative decoding (the original speculative-sampling
    acceptance rule; ref: the serving predictor's sampling decode with a
    draft model). The draft proposes gamma tokens BY SAMPLING from its own
    distribution q; the target verifies the chunk once and accepts token
    x_i with probability ``min(1, p_i(x_i) / q_i(x_i))``; the first
    rejection resamples from the residual ``norm(max(0, p_i - q_i))``.
    The emitted token stream is distributed EXACTLY as sampling from the
    target alone (Leviathan et al. / Chen et al.) — verified
    statistically in tests.

    input_ids: [1, S]. Returns (tokens [1, S + max_new_tokens], stats).
    ``temperature`` scales BOTH models' logits (0 falls back to the
    lossless greedy path)."""
    if temperature == 0.0:
        return speculative_generate(target, draft, input_ids,
                                    max_new_tokens=max_new_tokens,
                                    gamma=gamma, eos_token_id=eos_token_id)
    t_cfg, d_cfg = target.cfg, draft.cfg
    if input_ids.shape[0] != 1:
        raise ValueError("speculative_sample is single-sequence (B == 1)")
    rs = np.random.RandomState(seed)
    prompt_len = input_ids.shape[1]
    max_len = prompt_len + max_new_tokens + gamma + 2

    def make_cache(cfg):
        return KVCache.init(cfg.num_hidden_layers, 1, max_len,
                            cfg.num_key_value_heads,
                            cfg.hidden_size // cfg.num_attention_heads,
                            cfg.dtype)

    fwd = jax.jit(llama_forward_with_cache, static_argnums=())

    def probs(logits):
        return np.asarray(jax.nn.softmax(
            logits.astype(jnp.float32) / temperature, axis=-1)).reshape(-1)

    cache_t, cache_d = make_cache(t_cfg), make_cache(d_cfg)
    ids = jnp.asarray(input_ids)
    logits_t, cache_t = fwd(target, ids, cache_t, 0)
    _, cache_d = fwd(draft, ids, cache_d, 0)

    committed: list[int] = []
    p0 = probs(logits_t[:, -1])
    c = int(rs.choice(p0.size, p=p0))
    committed.append(c)
    pos = prompt_len
    draft_pos = prompt_len
    rounds = accepted_total = 0

    def done():
        return (len(committed) >= max_new_tokens
                or (eos_token_id is not None and eos_token_id in committed))

    while not done():
        rounds += 1
        pending = committed[draft_pos - prompt_len:]
        dl, cache_d = fwd(draft, jnp.asarray([pending], jnp.int32),
                          cache_d, draft_pos)
        draft_pos += len(pending)
        props, qs = [], []
        q = probs(dl[:, -1])
        for _ in range(gamma):
            x = int(rs.choice(q.size, p=q))
            props.append(x)
            qs.append(q)
            dl, cache_d = fwd(draft, jnp.asarray([[x]], jnp.int32),
                              cache_d, draft_pos)
            draft_pos += 1
            q = probs(dl[:, -1])

        chunk_t = jnp.asarray([[c] + props], jnp.int32)
        tl, cache_t = fwd(target, chunk_t, cache_t, pos)
        ps = [probs(tl[:, i]) for i in range(gamma + 1)]

        new, n_acc = stochastic_accept_row(props, qs, ps, rs)
        committed.extend(new)
        accepted_total += n_acc
        pos, draft_pos = spec_advance_frontiers(pos, draft_pos, len(new))
        pos, draft_pos = int(pos), int(draft_pos)
        c = committed[-1]

    committed = committed[:max_new_tokens]
    if eos_token_id is not None and eos_token_id in committed:
        committed = committed[: committed.index(eos_token_id) + 1]
    out = np.concatenate(
        [np.asarray(ids)[0],
         np.asarray(committed, np.asarray(ids).dtype),
         np.zeros((max_new_tokens - len(committed),),
                  np.asarray(ids).dtype)])
    stats = {"rounds": rounds, "proposed": rounds * gamma,
             "accepted": accepted_total,
             "acceptance_rate": accepted_total / max(rounds * gamma, 1)}
    return jnp.asarray(out[None]), stats
