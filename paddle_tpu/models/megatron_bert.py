"""MegatronBERT (ref: PaddleNLP ``paddlenlp/transformers/megatronbert``).

The PRE-LN BERT: every sublayer norms its INPUT (residual stays on the
raw stream), embeddings carry no LayerNorm (the first block's pre-LN
covers it), and the encoder ends with a final LN — the arrangement that
made large-scale BERT training stable.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Embedding, LayerNorm, Linear
from paddle_tpu.ops import attention as A


@dataclass
class MegatronBertConfig:
    vocab_size: int = 29056
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: int = 4096
    type_vocab_size: int = 2
    max_position_embeddings: int = 512
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    @staticmethod
    def tiny(**kw):
        return MegatronBertConfig(**{**dict(vocab_size=128, hidden_size=32,
                                            num_hidden_layers=2,
                                            num_attention_heads=2,
                                            intermediate_size=64,
                                            max_position_embeddings=64),
                                     **kw})


class MegatronBertLayer(Module):
    def __init__(self, cfg: MegatronBertConfig):
        super().__init__()
        h = cfg.hidden_size
        self.attn_ln = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                 dtype=cfg.dtype)
        self.q_proj = Linear(h, h, dtype=cfg.dtype)
        self.k_proj = Linear(h, h, dtype=cfg.dtype)
        self.v_proj = Linear(h, h, dtype=cfg.dtype)
        self.out_proj = Linear(h, h, dtype=cfg.dtype)
        self.ff_ln = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                               dtype=cfg.dtype)
        self.intermediate = Linear(h, cfg.intermediate_size, dtype=cfg.dtype)
        self.output = Linear(cfg.intermediate_size, h, dtype=cfg.dtype)
        self.heads = cfg.num_attention_heads

    def __call__(self, x, attn_mask=None):
        b, s, hd = x.shape
        nh = self.heads
        d = hd // nh
        hin = self.attn_ln(x)
        q = self.q_proj(hin).reshape(b, s, nh, d)
        k = self.k_proj(hin).reshape(b, s, nh, d)
        v = self.v_proj(hin).reshape(b, s, nh, d)
        att = A.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask)
        x = x + self.out_proj(att.reshape(b, s, hd))
        return x + self.output(F.gelu(self.intermediate(self.ff_ln(x))))


class MegatronBertModel(Module):
    def __init__(self, cfg: MegatronBertConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        h = cfg.hidden_size
        self.word_embeddings = Embedding(cfg.vocab_size, h,
                                         weight_init=init, dtype=cfg.dtype)
        self.position_embeddings = Embedding(cfg.max_position_embeddings, h,
                                             weight_init=init,
                                             dtype=cfg.dtype)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size, h,
                                               weight_init=init,
                                               dtype=cfg.dtype)
        self.layers = [MegatronBertLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]
        self.final_ln = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.pooler = Linear(h, h, dtype=cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        s = input_ids.shape[1]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if attention_mask is not None:
            attention_mask = (1.0 - attention_mask[:, None, None, :]
                              .astype(jnp.float32)) * -1e9
        # NO embedding LayerNorm — pre-LN blocks norm their own input
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(jnp.arange(s)[None, :])
             + self.token_type_embeddings(token_type_ids))
        for lyr in self.layers:
            x = lyr(x, attn_mask=attention_mask)
        x = self.final_ln(x)
        pooled = jnp.tanh(self.pooler(x[:, 0]))
        return x, pooled


class MegatronBertForMaskedLM(Module):
    def __init__(self, cfg: MegatronBertConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = MegatronBertModel(cfg)
        self.mlm_transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                    dtype=cfg.dtype)
        self.mlm_norm = LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.mlm_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, _ = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        return h @ self.bert.word_embeddings.weight.T + self.mlm_bias
