"""BLOOM decoder LM (ref capability: PaddleNLP ``bloom`` model family /
``paddlenlp.transformers.BloomForCausalLM``).

The ALiBi-positioned member of the model zoo: no rotary/learned positions —
attention carries per-head linear distance penalties. On TPU the slopes
feed ``scaled_dot_product_attention(alibi_slopes=...)``, whose Pallas path
computes the bias from iota IN-KERNEL (ops/pallas/flash_attention.py): the
O(S^2) bias tensor HF materialises (``build_alibi_tensor``) never exists.
HF's form (``m * k_pos``) differs from ours (``-m * (q_pos - k_pos)``) by a
per-row constant, which softmax cancels — logits parity is asserted in
tests/test_convert.py.

Architecture (HF ``BloomModel``): word embeddings + embedding LayerNorm,
blocks of [LN -> fused-QKV attention (head-interleaved in HF, re-laid out
at load) -> dense] and [LN -> h->4h gelu(tanh) -> 4h->h], final LN, tied
lm head.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import LayerNorm
from paddle_tpu.ops import attention as A


def alibi_slopes(n_heads: int):
    """The ALiBi slope schedule (HF build_alibi_tensor's head geometry):
    powers of ``2^(-8/n)`` for the closest power-of-two head count,
    interleaved extras when n is not a power of two."""
    p = 2 ** math.floor(math.log2(n_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(p) - 3)))
    slopes = [base ** (i + 1) for i in range(p)]
    if p < n_heads:
        extra = 2.0 ** (-(2.0 ** -(math.log2(2 * p) - 3)))
        slopes += [extra ** (2 * i + 1) for i in range(n_heads - p)]
    return jnp.asarray(slopes, jnp.float32)


@dataclass
class BloomConfig:
    vocab_size: int = 250880
    hidden_size: int = 1024
    n_layer: int = 24
    n_head: int = 16
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    dtype: object = None
    remat: bool = True

    def __post_init__(self):
        if self.dtype is None:
            self.dtype = get_default_dtype()

    @staticmethod
    def tiny(**kw):
        return BloomConfig(**{**dict(vocab_size=128, hidden_size=32,
                                     n_layer=2, n_head=4, dtype=jnp.float32,
                                     remat=False), **kw})


class BloomBlock(Module):
    def __init__(self, cfg: BloomConfig):
        super().__init__()
        h = cfg.hidden_size
        init = I.Normal(0.0, cfg.initializer_range)
        self.input_layernorm = LayerNorm(h, epsilon=cfg.layer_norm_epsilon,
                                         dtype=cfg.dtype)
        # our layout: [h, 3h] columns = [q all heads | k | v] (HF's
        # head-interleaved fused weight is re-laid out at load time)
        self.qkv = init((h, 3 * h), cfg.dtype)
        self.qkv_bias = jnp.zeros((3 * h,), cfg.dtype)
        self.dense = init((h, h), cfg.dtype)
        self.dense_bias = jnp.zeros((h,), cfg.dtype)
        self.post_attention_layernorm = LayerNorm(
            h, epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype)
        self.h_to_4h = init((h, 4 * h), cfg.dtype)
        self.h_to_4h_bias = jnp.zeros((4 * h,), cfg.dtype)
        self.four_h_to_h = init((4 * h, h), cfg.dtype)
        self.four_h_to_h_bias = jnp.zeros((h,), cfg.dtype)
        self.n_head = cfg.n_head
        self.head_dim = h // cfg.n_head

    def __call__(self, x, slopes):
        b, s, hd = x.shape
        nh, d = self.n_head, self.head_dim
        h = self.input_layernorm(x)
        qkv = h @ self.qkv + self.qkv_bias
        q, k, v = jnp.split(qkv, 3, axis=-1)
        att = A.scaled_dot_product_attention(
            q.reshape(b, s, nh, d), k.reshape(b, s, nh, d),
            v.reshape(b, s, nh, d), is_causal=True, alibi_slopes=slopes)
        x = x + att.reshape(b, s, hd) @ self.dense + self.dense_bias
        h2 = self.post_attention_layernorm(x)
        m = jax.nn.gelu(h2 @ self.h_to_4h + self.h_to_4h_bias,
                        approximate=True)
        return x + m @ self.four_h_to_h + self.four_h_to_h_bias


class BloomForCausalLM(Module):
    def __init__(self, cfg: BloomConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = init((cfg.vocab_size, cfg.hidden_size),
                                    cfg.dtype)
        self.word_embeddings_layernorm = LayerNorm(
            cfg.hidden_size, epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype)
        self.h = [BloomBlock(cfg) for _ in range(cfg.n_layer)]
        self.ln_f = LayerNorm(cfg.hidden_size,
                              epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype)

    def __call__(self, input_ids):
        cfg = self.cfg
        slopes = alibi_slopes(cfg.n_head)
        x = jnp.take(self.word_embeddings, input_ids, axis=0)
        x = self.word_embeddings_layernorm(x)
        blk = (jax.checkpoint(lambda lyr, h: lyr(h, slopes))
               if cfg.remat else (lambda lyr, h: lyr(h, slopes)))
        for lyr in self.h:
            x = blk(lyr, x)
        x = self.ln_f(x)
        return x @ self.word_embeddings.T     # tied head

    def loss(self, input_ids, labels):
        logits = self(input_ids).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, jnp.maximum(labels, 0)[..., None],
                                 axis=-1)[..., 0]
        mask = (labels >= 0).astype(jnp.float32)
        return -jnp.sum(ll * mask) / jnp.maximum(mask.sum(), 1.0)
