"""RoFormer (ref: PaddleNLP ``paddlenlp/transformers/roformer`` — the
rotary-position BERT, a Chinese-NLP staple).

Post-LN BERT blocks whose attention rotates q/k with INTERLEAVED rotary
embeddings over the full head dim (the paper that introduced RoPE);
embeddings carry word + token-type only (no position table). MLM head =
transform + LN + tied decoder.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Embedding, LayerNorm, Linear
from paddle_tpu.ops import attention as A


@dataclass
class RoFormerConfig:
    vocab_size: int = 50000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    type_vocab_size: int = 2
    max_position_embeddings: int = 1536
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    @staticmethod
    def tiny(**kw):
        return RoFormerConfig(**{**dict(vocab_size=128, hidden_size=32,
                                        num_hidden_layers=2,
                                        num_attention_heads=2,
                                        intermediate_size=64,
                                        max_position_embeddings=64), **kw})


class RoFormerLayer(Module):
    def __init__(self, cfg: RoFormerConfig):
        super().__init__()
        h = cfg.hidden_size
        self.q_proj = Linear(h, h, dtype=cfg.dtype)
        self.k_proj = Linear(h, h, dtype=cfg.dtype)
        self.v_proj = Linear(h, h, dtype=cfg.dtype)
        self.out_proj = Linear(h, h, dtype=cfg.dtype)
        self.attn_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                   dtype=cfg.dtype)
        self.intermediate = Linear(h, cfg.intermediate_size, dtype=cfg.dtype)
        self.output = Linear(cfg.intermediate_size, h, dtype=cfg.dtype)
        self.out_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.heads = cfg.num_attention_heads

    def __call__(self, x, cos, sin, attn_mask=None):
        b, s, hd = x.shape
        nh = self.heads
        d = hd // nh
        q = A.apply_rope_interleaved(
            self.q_proj(x).reshape(b, s, nh, d), cos, sin)
        k = A.apply_rope_interleaved(
            self.k_proj(x).reshape(b, s, nh, d), cos, sin)
        v = self.v_proj(x).reshape(b, s, nh, d)
        att = A.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask)
        x = self.attn_norm(x + self.out_proj(att.reshape(b, s, hd)))
        return self.out_norm(x + self.output(F.gelu(self.intermediate(x))))


class RoFormerModel(Module):
    def __init__(self, cfg: RoFormerConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        h = cfg.hidden_size
        self.word_embeddings = Embedding(cfg.vocab_size, h,
                                         weight_init=init, dtype=cfg.dtype)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size, h,
                                               weight_init=init,
                                               dtype=cfg.dtype)
        self.emb_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.layers = [RoFormerLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        cfg = self.cfg
        s = input_ids.shape[1]
        d = cfg.hidden_size // cfg.num_attention_heads
        cos, sin = A.rope_cos_sin(s, d)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if attention_mask is not None:
            attention_mask = (1.0 - attention_mask[:, None, None, :]
                              .astype(jnp.float32)) * -1e9
        x = self.emb_norm(self.word_embeddings(input_ids)
                          + self.token_type_embeddings(token_type_ids))
        for lyr in self.layers:
            x = lyr(x, cos, sin, attn_mask=attention_mask)
        return x


class RoFormerForMaskedLM(Module):
    def __init__(self, cfg: RoFormerConfig):
        super().__init__()
        self.cfg = cfg
        self.roformer = RoFormerModel(cfg)
        self.mlm_transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                    dtype=cfg.dtype)
        self.mlm_norm = LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.mlm_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        seq = self.roformer(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        return h @ self.roformer.word_embeddings.weight.T + self.mlm_bias
