"""BigBird (ref: PaddleNLP ``paddlenlp/transformers/bigbird``), in
``original_full`` attention mode.

On TPU the block-sparse attention pattern that motivated BigBird's GPU
kernels is usually DOMINATED by dense flash attention until very long
sequences (sparse gathers fragment the MXU pipeline), and for long
sequences this framework's ring/Ulysses sequence parallelism covers the
memory axis — so the zoo ships the exact ``original_full`` computation
(what HF itself recommends switching to at moderate lengths), with
gelu_new activations and BigBird's embed-dropout-then-LN order.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Embedding, LayerNorm, Linear
from paddle_tpu.ops import attention as A


@dataclass
class BigBirdConfig:
    vocab_size: int = 50358
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    type_vocab_size: int = 2
    max_position_embeddings: int = 4096
    rescale_embeddings: bool = False
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    @staticmethod
    def tiny(**kw):
        return BigBirdConfig(**{**dict(vocab_size=128, hidden_size=32,
                                       num_hidden_layers=2,
                                       num_attention_heads=2,
                                       intermediate_size=64,
                                       max_position_embeddings=64), **kw})


class BigBirdLayer(Module):
    def __init__(self, cfg: BigBirdConfig):
        super().__init__()
        h = cfg.hidden_size
        self.q_proj = Linear(h, h, dtype=cfg.dtype)
        self.k_proj = Linear(h, h, dtype=cfg.dtype)
        self.v_proj = Linear(h, h, dtype=cfg.dtype)
        self.out_proj = Linear(h, h, dtype=cfg.dtype)
        self.attn_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                   dtype=cfg.dtype)
        self.intermediate = Linear(h, cfg.intermediate_size, dtype=cfg.dtype)
        self.output = Linear(cfg.intermediate_size, h, dtype=cfg.dtype)
        self.out_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.heads = cfg.num_attention_heads

    def __call__(self, x, attn_mask=None):
        b, s, hd = x.shape
        nh = self.heads
        d = hd // nh
        q = self.q_proj(x).reshape(b, s, nh, d)
        k = self.k_proj(x).reshape(b, s, nh, d)
        v = self.v_proj(x).reshape(b, s, nh, d)
        att = A.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask)
        x = self.attn_norm(x + self.out_proj(att.reshape(b, s, hd)))
        m = self.output(jax.nn.gelu(self.intermediate(x),
                                    approximate=True))
        return self.out_norm(x + m)


class BigBirdModel(Module):
    def __init__(self, cfg: BigBirdConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        h = cfg.hidden_size
        self.word_embeddings = Embedding(cfg.vocab_size, h,
                                         weight_init=init, dtype=cfg.dtype)
        self.position_embeddings = Embedding(cfg.max_position_embeddings, h,
                                             weight_init=init,
                                             dtype=cfg.dtype)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size, h,
                                               weight_init=init,
                                               dtype=cfg.dtype)
        self.emb_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.layers = [BigBirdLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        cfg = self.cfg
        s = input_ids.shape[1]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if attention_mask is not None:
            attention_mask = (1.0 - attention_mask[:, None, None, :]
                              .astype(jnp.float32)) * -1e9
        x = self.word_embeddings(input_ids)
        if cfg.rescale_embeddings:
            x = x * (cfg.hidden_size ** 0.5)
        x = (x + self.token_type_embeddings(token_type_ids)
             + self.position_embeddings(jnp.arange(s)[None, :]))
        x = self.emb_norm(x)                 # HF: dropout then LN (eval ok)
        for lyr in self.layers:
            x = lyr(x, attn_mask=attention_mask)
        return x


class BigBirdForMaskedLM(Module):
    def __init__(self, cfg: BigBirdConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = BigBirdModel(cfg)
        self.mlm_transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                    dtype=cfg.dtype)
        self.mlm_norm = LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.mlm_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        seq = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(jax.nn.gelu(self.mlm_transform(seq),
                                      approximate=True))
        return h @ self.bert.word_embeddings.weight.T + self.mlm_bias
