"""GPT-3 style decoder (ref: PaddleNLP ``paddlenlp/transformers/gpt/
modeling.py`` + the reference's ``llm/gpt-3`` Fleet TensorParallel config).

Pre-LN GPT with learned positions, GELU MLP, fused-attention dispatch; qkv
and mlp projections carry tp PartitionSpecs like LLaMA so the GPT-3 1.3B
TensorParallel baseline config maps straight onto the mesh.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Dropout, Embedding, LayerNorm
from paddle_tpu.ops import attention as A


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 2048
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: int = 8192
    max_position_embeddings: int = 2048
    dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    dtype: object = jnp.bfloat16
    remat: bool = True

    @staticmethod
    def gpt3_1p3b(**kw):
        return GPTConfig(**kw)

    @staticmethod
    def tiny(**kw):
        return GPTConfig(**{**dict(vocab_size=128, hidden_size=32,
                                   num_hidden_layers=2, num_attention_heads=2,
                                   intermediate_size=64, max_position_embeddings=64,
                                   dtype=jnp.float32, remat=False), **kw})


class GPTBlock(Module):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        init = I.Normal(0.0, cfg.initializer_range)
        self.ln1 = LayerNorm(h, epsilon=cfg.layer_norm_eps, dtype=cfg.dtype)
        self.qkv = init((h, 3 * h), cfg.dtype)
        self.qkv_bias = jnp.zeros((3 * h,), cfg.dtype)
        self.proj = init((h, h), cfg.dtype)
        self.proj_bias = jnp.zeros((h,), cfg.dtype)
        self.ln2 = LayerNorm(h, epsilon=cfg.layer_norm_eps, dtype=cfg.dtype)
        self.fc1 = init((h, cfg.intermediate_size), cfg.dtype)
        self.fc1_bias = jnp.zeros((cfg.intermediate_size,), cfg.dtype)
        self.fc2 = init((cfg.intermediate_size, h), cfg.dtype)
        self.fc2_bias = jnp.zeros((h,), cfg.dtype)
        self.set_pspec("qkv", P(None, "tp"))
        self.set_pspec("qkv_bias", P("tp"))
        self.set_pspec("proj", P("tp", None))
        self.set_pspec("fc1", P(None, "tp"))
        self.set_pspec("fc1_bias", P("tp"))
        self.set_pspec("fc2", P("tp", None))
        self.num_heads = cfg.num_attention_heads
        self.dropout = Dropout(cfg.dropout)

    def __call__(self, x, rng=None):
        b, s, h = x.shape
        nh = self.num_heads
        d = h // nh
        r1, r2 = (None, None) if rng is None else tuple(jax.random.split(rng))
        y = self.ln1(x)
        qkv = y @ self.qkv + self.qkv_bias
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, nh, d)
        k = k.reshape(b, s, nh, d)
        v = v.reshape(b, s, nh, d)
        attn = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                              training=self.training, rng=r1)
        x = x + self.dropout(attn.reshape(b, s, h) @ self.proj + self.proj_bias, rng=r1)
        y = self.ln2(x)
        y = F.gelu(y @ self.fc1 + self.fc1_bias, approximate=True) @ self.fc2 + self.fc2_bias
        return x + self.dropout(y, rng=r2)


class GPTForCausalLM(Module):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.wte = init((cfg.vocab_size, cfg.hidden_size), cfg.dtype)
        self.wpe = init((cfg.max_position_embeddings, cfg.hidden_size), cfg.dtype)
        self.set_pspec("wte", P("tp", None))
        self.blocks = [GPTBlock(cfg) for _ in range(cfg.num_hidden_layers)]
        self.ln_f = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps, dtype=cfg.dtype)

    def __call__(self, input_ids, rng=None):
        s = input_ids.shape[1]
        from paddle_tpu.distributed.sharded import maybe_shard
        x = jnp.take(self.wte, input_ids, axis=0) + self.wpe[None, :s]
        x = maybe_shard(x, ("dp", "fsdp"), "sp", None)
        blk_fn = (jax.checkpoint(lambda blk, h, r: blk(h, rng=r))
                  if self.cfg.remat else (lambda blk, h, r: blk(h, rng=r)))
        for i, blk in enumerate(self.blocks):
            sub = None if rng is None else jax.random.fold_in(rng, i)
            x = blk_fn(blk, x, sub)
        x = self.ln_f(x)
        return x @ self.wte.T  # tied lm head

    def loss(self, input_ids, labels, rng=None):
        from paddle_tpu.distributed.tensor_parallel import parallel_cross_entropy
        logits = self(input_ids, rng=rng)
        per_tok = parallel_cross_entropy(logits, jnp.maximum(labels, 0))
        mask = (labels >= 0).astype(jnp.float32)
        return jnp.sum(per_tok * mask) / jnp.maximum(jnp.sum(mask), 1.0)
