"""ERNIE (ref: PaddleNLP ``paddlenlp/transformers/ernie/modeling.py`` —
Baidu's flagship pretrained encoder family, ERNIE 1.0/3.0).

Structurally a BERT-style post-LN encoder (the blocks ARE ``BertLayer``)
plus ERNIE's task-type embedding: a third id stream (``task_type_ids``)
marking which pretraining task a segment came from, added into the
embedding sum when ``use_task_id`` (ERNIE 3.0 checkpoints). HF's
``ErnieForMaskedLM`` is the parity reference (tests/test_convert.py).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.models.bert import BertLayer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Dropout, Embedding, LayerNorm, Linear


@dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 2048
    type_vocab_size: int = 4
    use_task_id: bool = True
    task_type_vocab_size: int = 3
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    @staticmethod
    def tiny(**kw):
        return ErnieConfig(**{**dict(vocab_size=128, hidden_size=32,
                                     num_hidden_layers=2,
                                     num_attention_heads=2,
                                     intermediate_size=64,
                                     max_position_embeddings=64), **kw})

    def _bert_view(self):
        """The shared-field view BertLayer construction reads."""
        from paddle_tpu.models.bert import BertConfig
        return BertConfig(
            vocab_size=self.vocab_size, hidden_size=self.hidden_size,
            num_hidden_layers=self.num_hidden_layers,
            num_attention_heads=self.num_attention_heads,
            intermediate_size=self.intermediate_size,
            hidden_dropout_prob=self.hidden_dropout_prob,
            attention_probs_dropout_prob=self.attention_probs_dropout_prob,
            max_position_embeddings=self.max_position_embeddings,
            type_vocab_size=self.type_vocab_size,
            layer_norm_eps=self.layer_norm_eps,
            initializer_range=self.initializer_range, dtype=self.dtype)


class ErnieEmbeddings(Module):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        h = cfg.hidden_size
        self.word_embeddings = Embedding(cfg.vocab_size, h,
                                         weight_init=init, dtype=cfg.dtype)
        self.position_embeddings = Embedding(cfg.max_position_embeddings, h,
                                             weight_init=init,
                                             dtype=cfg.dtype)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size, h,
                                               weight_init=init,
                                               dtype=cfg.dtype)
        self.task_type_embeddings = (
            Embedding(cfg.task_type_vocab_size, h, weight_init=init,
                      dtype=cfg.dtype) if cfg.use_task_id else None)
        self.layer_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                    dtype=cfg.dtype)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def __call__(self, input_ids, token_type_ids=None, position_ids=None,
                 task_type_ids=None, rng=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = jnp.arange(s)[None, :]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        if self.task_type_embeddings is not None:
            if task_type_ids is None:
                task_type_ids = jnp.zeros_like(input_ids)
            x = x + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(x), rng=rng)


class ErnieModel(Module):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        bcfg = cfg._bert_view()
        self.embeddings = ErnieEmbeddings(cfg)
        self.layers = [BertLayer(bcfg)
                       for _ in range(cfg.num_hidden_layers)]
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size,
                             dtype=cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 task_type_ids=None, rng=None):
        import jax
        if attention_mask is not None:
            attention_mask = (1.0 - attention_mask[:, None, None, :]
                              .astype(jnp.float32)) * -1e9
        x = self.embeddings(input_ids, token_type_ids,
                            task_type_ids=task_type_ids, rng=rng)
        for i, lyr in enumerate(self.layers):
            sub = None if rng is None else jax.random.fold_in(rng, i)
            x = lyr(x, attn_mask=attention_mask, rng=sub)
        pooled = jnp.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForMaskedLM(Module):
    """MLM head (HF ``ErnieForMaskedLM``): transform + LN + tied decoder."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.ernie = ErnieModel(cfg)
        self.mlm_transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                    dtype=cfg.dtype)
        self.mlm_norm = LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.mlm_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 task_type_ids=None, rng=None):
        seq, _ = self.ernie(input_ids, token_type_ids, attention_mask,
                            task_type_ids=task_type_ids, rng=rng)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        return (h @ self.ernie.embeddings.word_embeddings.weight.T
                + self.mlm_bias)

    def loss(self, input_ids, mlm_labels, token_type_ids=None,
             attention_mask=None, task_type_ids=None, rng=None):
        logits = self(input_ids, token_type_ids, attention_mask,
                      task_type_ids=task_type_ids, rng=rng)
        ce = F.cross_entropy(logits, jnp.maximum(mlm_labels, 0),
                             reduction="none")
        mask = (mlm_labels >= 0).astype(jnp.float32)
        return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)


class ErnieForSequenceClassification(Module):
    def __init__(self, cfg: ErnieConfig, num_classes: int = 2):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.classifier = Linear(cfg.hidden_size, num_classes,
                                 dtype=cfg.dtype)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 task_type_ids=None, rng=None):
        _, pooled = self.ernie(input_ids, token_type_ids, attention_mask,
                               task_type_ids=task_type_ids, rng=rng)
        return self.classifier(self.dropout(pooled, rng=rng))
