"""MPNet (ref: PaddleNLP ``paddlenlp/transformers/mpnet``).

Masked-and-permuted pretraining encoder: post-LN BERT blocks whose
attention adds a SHARED T5-style bucketed relative position bias
(one [num_buckets, heads] table for the whole stack), RoBERTa-style
position ids computed from the pad mask.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.models.roberta import roberta_position_ids
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Embedding, LayerNorm, Linear


@dataclass
class MPNetConfig:
    vocab_size: int = 30527
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 514
    relative_attention_num_buckets: int = 32
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    pad_token_id: int = 1
    dtype: object = jnp.float32

    @staticmethod
    def tiny(**kw):
        return MPNetConfig(**{**dict(vocab_size=128, hidden_size=32,
                                     num_hidden_layers=2,
                                     num_attention_heads=2,
                                     intermediate_size=64,
                                     max_position_embeddings=66), **kw})


def _relative_position_bucket(rel, num_buckets=32, max_distance=128):
    """MPNet/T5 bidirectional log-bucket (HF convention: n = -rel)."""
    n = -rel
    num_buckets //= 2
    ret = (n < 0).astype(jnp.int32) * num_buckets
    n = jnp.abs(n)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class MPNetLayer(Module):
    def __init__(self, cfg: MPNetConfig):
        super().__init__()
        h = cfg.hidden_size
        self.q_proj = Linear(h, h, dtype=cfg.dtype)
        self.k_proj = Linear(h, h, dtype=cfg.dtype)
        self.v_proj = Linear(h, h, dtype=cfg.dtype)
        self.o_proj = Linear(h, h, dtype=cfg.dtype)
        self.attn_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                   dtype=cfg.dtype)
        self.intermediate = Linear(h, cfg.intermediate_size, dtype=cfg.dtype)
        self.output = Linear(cfg.intermediate_size, h, dtype=cfg.dtype)
        self.out_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.heads = cfg.num_attention_heads

    def __call__(self, x, position_bias, attn_mask=None):
        b, s, hd = x.shape
        nh = self.heads
        d = hd // nh
        q = self.q_proj(x).reshape(b, s, nh, d).transpose(0, 2, 1, 3)
        k = self.k_proj(x).reshape(b, s, nh, d).transpose(0, 2, 1, 3)
        v = self.v_proj(x).reshape(b, s, nh, d).transpose(0, 2, 1, 3)
        scores = (jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
                  + position_bias)
        if attn_mask is not None:
            scores = scores + attn_mask
        probs = jax.nn.softmax(scores.astype(jnp.float32),
                               axis=-1).astype(v.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, hd)
        x = self.attn_norm(x + self.o_proj(out))
        return self.out_norm(x + self.output(F.gelu(self.intermediate(x))))


class MPNetModel(Module):
    def __init__(self, cfg: MPNetConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        h = cfg.hidden_size
        self.word_embeddings = Embedding(cfg.vocab_size, h,
                                         weight_init=init, dtype=cfg.dtype)
        self.position_embeddings = Embedding(cfg.max_position_embeddings, h,
                                             weight_init=init,
                                             dtype=cfg.dtype)
        self.emb_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.relative_attention_bias = Embedding(
            cfg.relative_attention_num_buckets, cfg.num_attention_heads,
            weight_init=init, dtype=cfg.dtype)
        self.layers = [MPNetLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]

    def __call__(self, input_ids, attention_mask=None):
        cfg = self.cfg
        s = input_ids.shape[1]
        pos = roberta_position_ids(input_ids, cfg.pad_token_id)
        x = self.emb_norm(self.word_embeddings(input_ids)
                          + self.position_embeddings(pos))
        rel = jnp.arange(s)[None, :] - jnp.arange(s)[:, None]
        buckets = _relative_position_bucket(
            rel, cfg.relative_attention_num_buckets)
        bias = self.relative_attention_bias(buckets)      # [S, S, H]
        bias = bias.transpose(2, 0, 1)[None]              # [1, H, S, S]
        mask = None
        if attention_mask is not None:
            mask = (1.0 - attention_mask[:, None, None, :]
                    .astype(jnp.float32)) * -1e9
        for lyr in self.layers:
            x = lyr(x, bias, attn_mask=mask)
        return x


class MPNetForMaskedLM(Module):
    def __init__(self, cfg: MPNetConfig):
        super().__init__()
        self.cfg = cfg
        self.mpnet = MPNetModel(cfg)
        self.lm_dense = Linear(cfg.hidden_size, cfg.hidden_size,
                               dtype=cfg.dtype)
        self.lm_norm = LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_eps,
                                 dtype=cfg.dtype)
        self.lm_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids, attention_mask=None):
        seq = self.mpnet(input_ids, attention_mask)
        h = self.lm_norm(F.gelu(self.lm_dense(seq)))
        return h @ self.mpnet.word_embeddings.weight.T + self.lm_bias
