"""GLM-4 decoder LM (ref capability: PaddleNLP ``chatglm``/``glm`` model
families — the ChatGLM lineage, HF ``GlmForCausalLM``).

GLM rotates only the first ``partial_rotary_factor`` of each head dim,
with GPT-J-style INTERLEAVED even/odd pairing (its ``rotate_half`` helper
interleaves despite the name — parity-verified against HF). Attention
carries q/k/v biases (no o bias), the MLP is a fused gate_up SwiGLU,
norms are RMS, head untied.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I
from paddle_tpu.ops import attention as A


@dataclass
class GlmConfig:
    vocab_size: int = 151552
    hidden_size: int = 4096
    intermediate_size: int = 13696
    num_hidden_layers: int = 40
    num_attention_heads: int = 32
    num_key_value_heads: int = 2
    partial_rotary_factor: float = 0.5
    max_position_embeddings: int = 131072
    rms_norm_eps: float = 1.5625e-07
    rope_theta: float = 10000.0
    attention_bias: bool = True
    initializer_range: float = 0.02
    dtype: object = None
    remat: bool = True

    def __post_init__(self):
        if self.dtype is None:
            self.dtype = get_default_dtype()

    @staticmethod
    def tiny(**kw):
        return GlmConfig(**{**dict(vocab_size=128, hidden_size=32,
                                   intermediate_size=64,
                                   num_hidden_layers=2,
                                   num_attention_heads=4,
                                   num_key_value_heads=2,
                                   max_position_embeddings=64,
                                   rms_norm_eps=1e-6,
                                   dtype=jnp.float32, remat=False), **kw})


def glm_rope(x, cos, sin):
    """GLM rope over the leading rotary dims: GPT-J-style INTERLEAVED
    even/odd pairing (GLM's ``rotate_half`` interleaves despite the
    name). x: [B,S,H,rd]; cos/sin: [S, rd/2] unique freqs."""
    return A.apply_rope_interleaved(x, cos, sin)


class GlmRMSNorm(Module):
    def __init__(self, size, eps, dtype):
        super().__init__()
        self.weight = jnp.ones((size,), dtype)
        self.eps = eps

    def __call__(self, x):
        h = x.astype(jnp.float32)
        h = h * jax.lax.rsqrt(jnp.mean(h * h, -1, keepdims=True) + self.eps)
        return (h * self.weight.astype(jnp.float32)).astype(x.dtype)


class GlmDecoderLayer(Module):
    def __init__(self, cfg: GlmConfig):
        super().__init__()
        h = cfg.hidden_size
        nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
        d = h // nh
        init = I.Normal(0.0, cfg.initializer_range)
        self.input_layernorm = GlmRMSNorm(h, cfg.rms_norm_eps, cfg.dtype)
        self.qkv_proj = init((h, (nh + 2 * nkv) * d), cfg.dtype)
        self.qkv_bias = (jnp.zeros(((nh + 2 * nkv) * d,), cfg.dtype)
                         if cfg.attention_bias else None)
        self.o_proj = init((h, h), cfg.dtype)
        self.post_attention_layernorm = GlmRMSNorm(h, cfg.rms_norm_eps,
                                                   cfg.dtype)
        self.gate_up_proj = init((h, 2 * cfg.intermediate_size), cfg.dtype)
        self.down_proj = init((cfg.intermediate_size, h), cfg.dtype)
        self.dims = (nh, nkv, d, int(d * cfg.partial_rotary_factor))

    def __call__(self, x, cos, sin):
        b, s, hd = x.shape
        nh, nkv, d, rd = self.dims
        h = self.input_layernorm(x)
        qkv = h @ self.qkv_proj
        if self.qkv_bias is not None:
            qkv = qkv + self.qkv_bias
        q, k, v = jnp.split(qkv, [nh * d, (nh + nkv) * d], axis=-1)

        def rope(t, n):
            t = t.reshape(b, s, n, d)
            return jnp.concatenate(
                [glm_rope(t[..., :rd], cos, sin), t[..., rd:]], axis=-1)

        q, k = rope(q, nh), rope(k, nkv)
        att = A.scaled_dot_product_attention(q, k, v.reshape(b, s, nkv, d),
                                             is_causal=True)
        x = x + att.reshape(b, s, hd) @ self.o_proj
        h2 = self.post_attention_layernorm(x)
        gate, up = jnp.split(h2 @ self.gate_up_proj, 2, axis=-1)
        return x + (up * jax.nn.silu(gate)) @ self.down_proj


class GlmForCausalLM(Module):
    def __init__(self, cfg: GlmConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.embed_tokens = init((cfg.vocab_size, cfg.hidden_size),
                                 cfg.dtype)
        self.layers = [GlmDecoderLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]
        self.norm = GlmRMSNorm(cfg.hidden_size, cfg.rms_norm_eps, cfg.dtype)
        self.lm_head = init((cfg.hidden_size, cfg.vocab_size), cfg.dtype)

    def __call__(self, input_ids):
        cfg = self.cfg
        s = input_ids.shape[1]
        d = cfg.hidden_size // cfg.num_attention_heads
        rd = int(d * cfg.partial_rotary_factor)
        cos, sin = A.rope_cos_sin(s, rd, base=cfg.rope_theta)
        x = jnp.take(self.embed_tokens, input_ids, axis=0)
        blk = (jax.checkpoint(lambda lyr, h: lyr(h, cos, sin))
               if cfg.remat else (lambda lyr, h: lyr(h, cos, sin)))
        for lyr in self.layers:
            x = blk(lyr, x)
        return self.norm(x) @ self.lm_head

    def loss(self, input_ids, labels):
        logits = self(input_ids).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, jnp.maximum(labels, 0)[..., None],
                                 axis=-1)[..., 0]
        mask = (labels >= 0).astype(jnp.float32)
        return -jnp.sum(ll * mask) / jnp.maximum(mask.sum(), 1.0)
