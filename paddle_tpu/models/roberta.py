"""RoBERTa (ref: PaddleNLP ``paddlenlp/transformers/roberta/modeling.py``).

Structurally BERT with two embedding quirks: position ids start at
``padding_idx + 1`` (fairseq heritage — position of token i is
``i + 2`` for unpadded input, computed from the attention mask so padded
positions reuse ``padding_idx``), and token types are a single zero row.
The encoder IS ``BertModel``; the MLM head is dense+gelu+LN with the
decoder tied to the word embeddings.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.models.bert import BertConfig, BertModel
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layers import LayerNorm, Linear


@dataclass
class RobertaConfig(BertConfig):
    vocab_size: int = 50265
    max_position_embeddings: int = 514
    type_vocab_size: int = 1
    pad_token_id: int = 1

    @staticmethod
    def tiny(**kw):
        return RobertaConfig(**{**dict(vocab_size=128, hidden_size=32,
                                       num_hidden_layers=2,
                                       num_attention_heads=2,
                                       intermediate_size=64,
                                       max_position_embeddings=66), **kw})


def roberta_position_ids(input_ids, pad_token_id):
    """fairseq-style: pad positions stay at padding_idx; real tokens get
    padding_idx + their 1-based index among non-pad tokens."""
    mask = (input_ids != pad_token_id).astype(jnp.int32)
    return jnp.cumsum(mask, axis=1) * mask + pad_token_id


class RobertaModel(Module):
    def __init__(self, cfg: RobertaConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)

    def __call__(self, input_ids, attention_mask=None, rng=None):
        pos = roberta_position_ids(input_ids, self.cfg.pad_token_id)
        return self.bert(input_ids, attention_mask=attention_mask,
                         rng=rng, position_ids=pos)


class RobertaForMaskedLM(Module):
    def __init__(self, cfg: RobertaConfig):
        super().__init__()
        self.cfg = cfg
        self.roberta = RobertaModel(cfg)
        self.lm_dense = Linear(cfg.hidden_size, cfg.hidden_size,
                               dtype=cfg.dtype)
        self.lm_norm = LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_eps,
                                 dtype=cfg.dtype)
        self.lm_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids, attention_mask=None, rng=None):
        seq, _ = self.roberta(input_ids, attention_mask, rng=rng)
        h = self.lm_norm(F.gelu(self.lm_dense(seq)))
        emb = self.roberta.bert.embeddings.word_embeddings.weight
        return h @ emb.T + self.lm_bias

    def loss(self, input_ids, mlm_labels, attention_mask=None, rng=None):
        logits = self(input_ids, attention_mask, rng=rng)
        ce = F.cross_entropy(logits, jnp.maximum(mlm_labels, 0),
                             reduction="none")
        mask = (mlm_labels >= 0).astype(jnp.float32)
        return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)


class RobertaForSequenceClassification(Module):
    """HF-style classification head over <s> (no pooler tanh): dense +
    tanh + out_proj, both trained from scratch."""

    def __init__(self, cfg: RobertaConfig, num_classes: int = 2):
        super().__init__()
        self.roberta = RobertaModel(cfg)
        self.dense = Linear(cfg.hidden_size, cfg.hidden_size,
                            dtype=cfg.dtype)
        self.out_proj = Linear(cfg.hidden_size, num_classes,
                               dtype=cfg.dtype)

    def __call__(self, input_ids, attention_mask=None, rng=None):
        seq, _ = self.roberta(input_ids, attention_mask, rng=rng)
        h = jnp.tanh(self.dense(seq[:, 0]))
        return self.out_proj(h)
