"""ResNet family (ref: ``python/paddle/vision/models/resnet.py`` —
resnet18/34/50/101/152; the reference's single-device CPU-runnable baseline
config in BASELINE.json).

TPU notes: NCHW at the API for reference parity (XLA re-lays out convs for
the MXU internally); BatchNorm in inference uses running stats; training
uses the functional batch_norm with explicit stat threading (see
train_step_with_bn below) because modules are pure under jit.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layers import (
    AdaptiveAvgPool2D,
    BatchNorm2D,
    Conv2D,
    Linear,
    MaxPool2D,
)


class BasicBlock(Module):
    expansion = 1

    def __init__(self, in_ch, ch, stride=1, downsample=None):
        super().__init__()
        self.conv1 = Conv2D(in_ch, ch, 3, stride=stride, padding=1, bias_attr=False)
        self.bn1 = BatchNorm2D(ch)
        self.conv2 = Conv2D(ch, ch, 3, padding=1, bias_attr=False)
        self.bn2 = BatchNorm2D(ch)
        self.downsample = downsample

    def __call__(self, x):
        idt = x if self.downsample is None else self.downsample(x)
        y = F.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return F.relu(y + idt)


class BottleneckBlock(Module):
    expansion = 4

    def __init__(self, in_ch, ch, stride=1, downsample=None, groups=1,
                 base_width=64):
        super().__init__()
        width = int(ch * (base_width / 64.0)) * groups
        self.conv1 = Conv2D(in_ch, width, 1, bias_attr=False)
        self.bn1 = BatchNorm2D(width)
        self.conv2 = Conv2D(width, width, 3, stride=stride, padding=1,
                            groups=groups, bias_attr=False)
        self.bn2 = BatchNorm2D(width)
        self.conv3 = Conv2D(width, ch * 4, 1, bias_attr=False)
        self.bn3 = BatchNorm2D(ch * 4)
        self.downsample = downsample

    def __call__(self, x):
        idt = x if self.downsample is None else self.downsample(x)
        y = F.relu(self.bn1(self.conv1(x)))
        y = F.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        return F.relu(y + idt)


class _Downsample(Module):
    def __init__(self, in_ch, out_ch, stride):
        super().__init__()
        self.conv = Conv2D(in_ch, out_ch, 1, stride=stride, bias_attr=False)
        self.bn = BatchNorm2D(out_ch)

    def __call__(self, x):
        return self.bn(self.conv(x))


class ResNet(Module):
    def __init__(self, block, depths, num_classes=1000, in_channels=3, width=64,
                 groups=1, width_per_group=64):
        super().__init__()
        self.conv1 = Conv2D(in_channels, width, 7, stride=2, padding=3, bias_attr=False)
        self.bn1 = BatchNorm2D(width)
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        if block is BasicBlock and (groups != 1 or width_per_group != 64):
            raise ValueError("BasicBlock only supports groups=1 and "
                             "width_per_group=64 (reference behaviour)")
        self.in_ch = width
        self.groups, self.base_width = groups, width_per_group
        self.layer1 = self._make_layer(block, width, depths[0])
        self.layer2 = self._make_layer(block, width * 2, depths[1], stride=2)
        self.layer3 = self._make_layer(block, width * 4, depths[2], stride=2)
        self.layer4 = self._make_layer(block, width * 8, depths[3], stride=2)
        self.avgpool = AdaptiveAvgPool2D(1)
        self.fc = Linear(width * 8 * block.expansion, num_classes)

    def _make_layer(self, block, ch, n, stride=1):
        downsample = None
        kw = {} if block is BasicBlock else \
            dict(groups=self.groups, base_width=self.base_width)
        if stride != 1 or self.in_ch != ch * block.expansion:
            downsample = _Downsample(self.in_ch, ch * block.expansion, stride)
        layers = [block(self.in_ch, ch, stride, downsample, **kw)]
        self.in_ch = ch * block.expansion
        for _ in range(1, n):
            layers.append(block(self.in_ch, ch, **kw))
        return layers

    def __call__(self, x):
        x = self.maxpool(F.relu(self.bn1(self.conv1(x))))
        for group in (self.layer1, self.layer2, self.layer3, self.layer4):
            for blk in group:
                x = blk(x)
        x = self.avgpool(x)
        return self.fc(x.reshape(x.shape[0], -1))


def resnet18(num_classes=1000, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet101(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes, **kw)


def resnet152(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], num_classes, **kw)


def resnext50_32x4d(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes,
                  groups=32, width_per_group=4, **kw)


def resnext101_32x4d(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes,
                  groups=32, width_per_group=4, **kw)


def resnext101_64x4d(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes,
                  groups=64, width_per_group=4, **kw)


def resnext152_32x4d(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], num_classes,
                  groups=32, width_per_group=4, **kw)


def resnext152_64x4d(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], num_classes,
                  groups=64, width_per_group=4, **kw)


def wide_resnet50_2(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes,
                  width_per_group=128, **kw)


def wide_resnet101_2(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes,
                  width_per_group=128, **kw)
