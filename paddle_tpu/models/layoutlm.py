"""LayoutLM (ref: PaddleNLP ``paddlenlp/transformers/layoutlm`` — the
document-AI encoder behind the PaddleOCR/ERNIE-Layout ecosystem).

BERT encoder + 2-D LAYOUT embeddings: each token carries its bounding
box (x0, y0, x1, y1 on a 0..1023 grid) and the embedding sum adds
x/y position tables for all four coordinates plus width/height... (v1
uses the four corner tables; the HF reference is ``LayoutLMModel``).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.models.bert import BertConfig, BertLayer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Dropout, Embedding, LayerNorm, Linear


@dataclass
class LayoutLMConfig(BertConfig):
    vocab_size: int = 30522
    max_2d_position_embeddings: int = 1024

    @staticmethod
    def tiny(**kw):
        return LayoutLMConfig(**{**dict(vocab_size=128, hidden_size=32,
                                        num_hidden_layers=2,
                                        num_attention_heads=2,
                                        intermediate_size=64,
                                        max_position_embeddings=64,
                                        max_2d_position_embeddings=128),
                                 **kw})


class LayoutLMModel(Module):
    def __init__(self, cfg: LayoutLMConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        h = cfg.hidden_size
        self.word_embeddings = Embedding(cfg.vocab_size, h,
                                         weight_init=init, dtype=cfg.dtype)
        self.position_embeddings = Embedding(cfg.max_position_embeddings, h,
                                             weight_init=init,
                                             dtype=cfg.dtype)
        p2 = cfg.max_2d_position_embeddings
        self.x_position_embeddings = Embedding(p2, h, weight_init=init,
                                               dtype=cfg.dtype)
        self.y_position_embeddings = Embedding(p2, h, weight_init=init,
                                               dtype=cfg.dtype)
        self.h_position_embeddings = Embedding(p2, h, weight_init=init,
                                               dtype=cfg.dtype)
        self.w_position_embeddings = Embedding(p2, h, weight_init=init,
                                               dtype=cfg.dtype)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size, h,
                                               weight_init=init,
                                               dtype=cfg.dtype)
        self.emb_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self.layers = [BertLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]
        self.pooler = Linear(h, h, dtype=cfg.dtype)

    def __call__(self, input_ids, bbox, token_type_ids=None,
                 attention_mask=None, rng=None):
        """bbox: [B, S, 4] int (x0, y0, x1, y1) on the 2-D grid."""
        s = input_ids.shape[1]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if attention_mask is not None:
            attention_mask = (1.0 - attention_mask[:, None, None, :]
                              .astype(jnp.float32)) * -1e9
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(jnp.arange(s)[None, :])
             + self.x_position_embeddings(bbox[..., 0])
             + self.y_position_embeddings(bbox[..., 1])
             + self.x_position_embeddings(bbox[..., 2])
             + self.y_position_embeddings(bbox[..., 3])
             + self.h_position_embeddings(bbox[..., 3] - bbox[..., 1])
             + self.w_position_embeddings(bbox[..., 2] - bbox[..., 0])
             + self.token_type_embeddings(token_type_ids))
        x = self.dropout(self.emb_norm(x), rng=rng)
        for i, lyr in enumerate(self.layers):
            sub = None if rng is None else jax.random.fold_in(rng, i)
            x = lyr(x, attn_mask=attention_mask, rng=sub)
        pooled = jnp.tanh(self.pooler(x[:, 0]))
        return x, pooled


class LayoutLMForMaskedLM(Module):
    def __init__(self, cfg: LayoutLMConfig):
        super().__init__()
        self.cfg = cfg
        self.layoutlm = LayoutLMModel(cfg)
        self.mlm_transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                    dtype=cfg.dtype)
        self.mlm_norm = LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.mlm_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids, bbox, token_type_ids=None,
                 attention_mask=None):
        seq, _ = self.layoutlm(input_ids, bbox, token_type_ids,
                               attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        return (h @ self.layoutlm.word_embeddings.weight.T
                + self.mlm_bias)
