"""NeZha (ref: PaddleNLP ``paddlenlp/transformers/nezha`` — the
Chinese-NLP BERT variant with FUNCTIONAL relative positions).

No position table at all: every layer's attention adds sinusoidal
relative-distance encodings (clipped at ±max_relative_position) to BOTH
the key scores and the value aggregation — parameter-free positions that
extrapolate past the training length. Everything else is post-LN BERT.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Embedding, LayerNorm, Linear


@dataclass
class NezhaConfig:
    vocab_size: int = 21128
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    type_vocab_size: int = 2
    max_relative_position: int = 64
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    dtype: object = jnp.float32

    @staticmethod
    def tiny(**kw):
        return NezhaConfig(**{**dict(vocab_size=128, hidden_size=32,
                                     num_hidden_layers=2,
                                     num_attention_heads=2,
                                     intermediate_size=64,
                                     max_relative_position=8), **kw})


def relative_positions_encoding(s, depth, max_rel):
    """[S, S, depth] sinusoidal encodings of clip(j - i, ±max_rel)."""
    pos = np.arange(2 * max_rel + 1, dtype=np.float32)[:, None]
    div = np.exp(np.arange(0, depth, 2, dtype=np.float32)
                 * (-math.log(10000.0) / depth))
    table = np.zeros((2 * max_rel + 1, depth), np.float32)
    table[:, 0::2] = np.sin(pos * div)
    table[:, 1::2] = np.cos(pos * div)
    d = np.clip(np.arange(s)[None, :] - np.arange(s)[:, None],
                -max_rel, max_rel) + max_rel
    return jnp.asarray(table[d])


class NezhaLayer(Module):
    def __init__(self, cfg: NezhaConfig):
        super().__init__()
        h = cfg.hidden_size
        self.q_proj = Linear(h, h, dtype=cfg.dtype)
        self.k_proj = Linear(h, h, dtype=cfg.dtype)
        self.v_proj = Linear(h, h, dtype=cfg.dtype)
        self.o_proj = Linear(h, h, dtype=cfg.dtype)
        self.attn_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                   dtype=cfg.dtype)
        self.intermediate = Linear(h, cfg.intermediate_size, dtype=cfg.dtype)
        self.output = Linear(cfg.intermediate_size, h, dtype=cfg.dtype)
        self.out_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.heads = cfg.num_attention_heads

    def __call__(self, x, rel, attn_mask=None):
        b, s, hd = x.shape
        nh = self.heads
        d = hd // nh
        q = self.q_proj(x).reshape(b, s, nh, d).transpose(0, 2, 1, 3)
        k = self.k_proj(x).reshape(b, s, nh, d).transpose(0, 2, 1, 3)
        v = self.v_proj(x).reshape(b, s, nh, d).transpose(0, 2, 1, 3)
        scores = (jnp.einsum("bhid,bhjd->bhij", q, k)
                  + jnp.einsum("bhid,ijd->bhij", q, rel)) / math.sqrt(d)
        if attn_mask is not None:
            scores = scores + attn_mask
        probs = jax.nn.softmax(scores.astype(jnp.float32),
                               axis=-1).astype(v.dtype)
        ctx = (jnp.einsum("bhij,bhjd->bhid", probs, v)
               + jnp.einsum("bhij,ijd->bhid", probs, rel.astype(v.dtype)))
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, hd)
        x = self.attn_norm(x + self.o_proj(ctx))
        return self.out_norm(x + self.output(F.gelu(self.intermediate(x))))


class NezhaModel(Module):
    def __init__(self, cfg: NezhaConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        h = cfg.hidden_size
        self.word_embeddings = Embedding(cfg.vocab_size, h,
                                         weight_init=init, dtype=cfg.dtype)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size, h,
                                               weight_init=init,
                                               dtype=cfg.dtype)
        self.emb_norm = LayerNorm(h, epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.layers = [NezhaLayer(cfg)
                       for _ in range(cfg.num_hidden_layers)]
        self.pooler = Linear(h, h, dtype=cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        cfg = self.cfg
        s = input_ids.shape[1]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        rel = relative_positions_encoding(
            s, cfg.hidden_size // cfg.num_attention_heads,
            cfg.max_relative_position)
        mask = None
        if attention_mask is not None:
            mask = (1.0 - attention_mask[:, None, None, :]
                    .astype(jnp.float32)) * -1e9
        x = self.emb_norm(self.word_embeddings(input_ids)
                          + self.token_type_embeddings(token_type_ids))
        for lyr in self.layers:
            x = lyr(x, rel, attn_mask=mask)
        pooled = jnp.tanh(self.pooler(x[:, 0]))
        return x, pooled


class NezhaForMaskedLM(Module):
    def __init__(self, cfg: NezhaConfig):
        super().__init__()
        self.cfg = cfg
        self.nezha = NezhaModel(cfg)
        self.mlm_transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                    dtype=cfg.dtype)
        self.mlm_norm = LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_eps,
                                  dtype=cfg.dtype)
        self.mlm_bias = jnp.zeros((cfg.vocab_size,), cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, _ = self.nezha(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        return h @ self.nezha.word_embeddings.weight.T + self.mlm_bias
