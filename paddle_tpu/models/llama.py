"""LLaMA-2 family (flagship; ref: PaddleNLP ``paddlenlp/transformers/llama/
modeling.py`` + ``llm/llama`` training entrypoints).

TPU-first design decisions vs the reference:
  * bf16 params by default with fp32 master weights in the optimizer.
  * fused QKV and gate+up projections — two big MXU matmuls instead of five.
  * attention through the Pallas flash kernel ([B,S,H,D] layout).
  * tensor parallel via PartitionSpecs (qkv/gate_up column-, o/down row-
    sharded on ``tp``); sequence axis optionally sharded on ``sp``.
  * per-layer ``jax.checkpoint`` (remat) instead of the reference's
    recompute pass.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Dropout, Embedding, Linear
from paddle_tpu.ops import attention as A
from paddle_tpu.ops import fused_rms_norm


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    dtype: object = jnp.bfloat16
    remat: bool = True
    # selective remat (VERDICT r4 item 1): what the per-layer checkpoint
    # SAVES instead of recomputing in backward. The tags live on the
    # layer's named activations (checkpoint_name below); recompute cost
    # falls as more is saved, HBM cost rises:
    #   None/"full"  save nothing (classic full remat — max recompute)
    #   "hidden"     save the hidden-sized dot outputs (attn context,
    #                attn out, ffn down out) — recomputes qkv + gate/up
    #   "no_ffn"     save every named activation EXCEPT the [B,S,2m]
    #                gate/up intermediate (the one that doesn't fit) —
    #                backward recomputes only gate/up + elementwise
    #   "dots"       save all dot outputs (near no-remat recompute, most
    #                memory that still skips attention internals)
    remat_policy: str | None = None
    use_flash: bool = True
    fp8: bool = False  # e4m3/e5m2 projections with delayed scaling (amp.fp8)
    scan_layers: bool = False  # stack layers + lax.scan: O(1) compile depth
    sliding_window: int | None = None  # Mistral-style causal window
    attention_bias: bool = False       # Qwen2: bias on fused qkv only
    sequence_parallel: str | None = None  # "ring" | "ulysses" over sp
    # long-context extension (ref rope_scaling: linear | ntk | dynamic)
    rope_scaling: dict | None = None

    def save_names(self) -> tuple:
        """The checkpoint_name tags each remat_policy mode SAVES (see the
        field comment above); everything else is recomputed in backward."""
        try:
            return _REMAT_SAVE_NAMES[self.remat_policy]
        except KeyError:
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r}; expected one "
                f"of {sorted(k for k in _REMAT_SAVE_NAMES if k)} or None")

    @staticmethod
    def llama2_7b(**kw):
        return LlamaConfig(**{**dict(hidden_size=4096, intermediate_size=11008,
                                     num_hidden_layers=32, num_attention_heads=32), **kw})

    @staticmethod
    def llama2_13b(**kw):
        return LlamaConfig(**{**dict(hidden_size=5120, intermediate_size=13824,
                                     num_hidden_layers=40, num_attention_heads=40,
                                     num_key_value_heads=40), **kw})

    @staticmethod
    def tiny(**kw):
        return LlamaConfig(**{**dict(vocab_size=256, hidden_size=64,
                                     intermediate_size=128, num_hidden_layers=2,
                                     num_attention_heads=4, num_key_value_heads=2,
                                     max_position_embeddings=128,
                                     dtype=jnp.float32, remat=False), **kw})


# remat_policy mode -> checkpoint_name tags saved by the per-layer
# jax.checkpoint (empty = classic full remat: save nothing named)
_REMAT_SAVE_NAMES = {
    None: (), "full": (),
    "hidden": ("attn_ctx", "ffn_out"),
    "no_ffn": ("qkv", "attn_ctx", "ffn_out"),
    "dots": ("qkv", "attn_ctx", "ffn_gu", "ffn_out"),
}


class LlamaRMSNorm(Module):
    def __init__(self, size, eps, dtype):
        super().__init__()
        self.weight = jnp.ones((size,), dtype)
        self.eps = eps

    def __call__(self, x):
        return fused_rms_norm(x, self.weight, self.eps)


class LlamaAttention(Module):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        h, nh, nkv = cfg.hidden_size, cfg.num_attention_heads, cfg.num_key_value_heads
        self.head_dim = h // nh
        init = I.Normal(0.0, cfg.initializer_range)
        # fused qkv: [h, (nh + 2*nkv) * head_dim], column-parallel on tp
        self.qkv_proj = init((h, (nh + 2 * nkv) * self.head_dim), cfg.dtype)
        self.o_proj = init((nh * self.head_dim, h), cfg.dtype)
        self.set_pspec("qkv_proj", P(None, "tp"))
        self.set_pspec("o_proj", P("tp", None))
        if cfg.attention_bias:  # Qwen2: q/k/v biased, o_proj not
            self.qkv_bias = jnp.zeros(((nh + 2 * nkv) * self.head_dim,), cfg.dtype)
            self.set_pspec("qkv_bias", P("tp"))
        else:
            self.qkv_bias = None
        self.num_heads, self.num_kv_heads = nh, nkv
        self.use_flash = cfg.use_flash
        self.window = cfg.sliding_window
        self.sequence_parallel = cfg.sequence_parallel
        if cfg.fp8:
            from paddle_tpu.amp.fp8 import new_fp8_meta
            self.fp8_meta = {"qkv": new_fp8_meta(), "o": new_fp8_meta()}
        else:
            self.fp8_meta = None

    def _attend(self, q, k, v, attn_mask):
        # sequence parallelism over the sp axis — trace-time dispatch,
        # falling back to flash/XLA attention when no sp mesh is active:
        #   "ring":    KV blocks rotate on ICI (ppermute) while the MXU
        #              works on the current block; best when S/chip is big.
        #   "ulysses": two all_to_alls re-shard seq<->heads and full
        #              attention (incl. the flash kernel) runs on a head
        #              slice; best when num_heads >= sp and S/chip is small.
        if self.sequence_parallel in ("ring", "ulysses"):
            from paddle_tpu.distributed.mesh import current_mesh
            mesh = current_mesh()
            if mesh is not None and mesh.size("sp") > 1:
                # normalise attn_mask into one of the two sp-path forms:
                #   mask3: [B, S, S] bool over global positions (boolean
                #     masks; [B, S] / [B,1,1,S] key padding broadcasts)
                #   bias4: [B|1, H|1, S, S] float ADDITIVE scores — soft
                #     biases (ALiBi/T5 relative bias) AND per-head bool
                #     masks (folded to 0/-inf), which have no [B,S,S] form
                mask3 = None
                bias4 = None
                s_full = q.shape[1]
                if attn_mask is not None:
                    m = attn_mask
                    per_head = m.ndim == 4 and m.shape[1] > 1
                    if jnp.issubdtype(m.dtype, jnp.floating) or per_head:
                        if m.dtype == jnp.bool_:
                            m = jnp.where(m, 0.0, -1e30)
                        m = m.astype(jnp.float32)
                        if m.ndim == 2:
                            m = m[None, None]      # [S,S] or [1,S] rows
                        elif m.ndim == 3:
                            m = m[:, None]         # [B,S,S] -> [B,1,S,S]
                        if m.shape[2] == 1:        # broadcast rows to S
                            m = jnp.broadcast_to(
                                m, m.shape[:2] + (s_full, m.shape[3]))
                        bias4 = m
                    else:
                        m = m.astype(bool)
                        if m.ndim == 4:
                            m = m[:, 0]          # [B,(1|S),S]
                        elif m.ndim == 2:
                            m = m[:, None, :]    # key padding -> rows
                        if m.shape[1] == 1:
                            m = jnp.broadcast_to(
                                m, (m.shape[0], s_full, s_full))
                        mask3 = m
                from paddle_tpu.distributed.sp import sp_attention
                head_spec = "tp" if mesh.size("tp") > 1 else None
                return sp_attention(mesh, self.sequence_parallel, q, k, v,
                                    causal=True, window=self.window,
                                    head_spec=head_spec, attn_mask=mask3,
                                    attn_bias=bias4)
        return F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=True,
            training=self.training, window=self.window)

    def __call__(self, x, cos, sin, attn_mask=None):
        b, s, h = x.shape
        nh, nkv, d = self.num_heads, self.num_kv_heads, self.head_dim
        if self.fp8_meta is not None:
            from paddle_tpu.amp.fp8 import fp8_matmul
            qkv = fp8_matmul(x, self.qkv_proj, self.fp8_meta["qkv"])
        else:
            from paddle_tpu.quantization import wo_matmul
            qkv = wo_matmul(x, self.qkv_proj)
        if self.qkv_bias is not None:
            qkv = qkv + self.qkv_bias
        qkv = checkpoint_name(qkv, "qkv")
        q, k, v = jnp.split(qkv, [nh * d, (nh + nkv) * d], axis=-1)
        q = q.reshape(b, s, nh, d)
        k = k.reshape(b, s, nkv, d)
        v = v.reshape(b, s, nkv, d)
        q = A.apply_rope(q, cos, sin)
        k = A.apply_rope(k, cos, sin)
        out = self._attend(q, k, v, attn_mask)
        out = checkpoint_name(out, "attn_ctx")
        out = out.reshape(b, s, nh * d)
        if self.fp8_meta is not None:
            from paddle_tpu.amp.fp8 import fp8_matmul
            return fp8_matmul(out, self.o_proj, self.fp8_meta["o"])
        from paddle_tpu.quantization import wo_matmul
        return wo_matmul(out, self.o_proj)


class LlamaMLP(Module):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        h, m = cfg.hidden_size, cfg.intermediate_size
        init = I.Normal(0.0, cfg.initializer_range)
        # fused gate+up (SwiGLU): one [h, 2m] matmul
        self.gate_up_proj = init((h, 2 * m), cfg.dtype)
        self.down_proj = init((m, h), cfg.dtype)
        self.set_pspec("gate_up_proj", P(None, "tp"))
        self.set_pspec("down_proj", P("tp", None))
        self.intermediate_size = m
        if cfg.fp8:
            from paddle_tpu.amp.fp8 import new_fp8_meta
            self.fp8_meta = {"gate_up": new_fp8_meta(),
                             "down": new_fp8_meta()}
        else:
            self.fp8_meta = None

    def __call__(self, x):
        if self.fp8_meta is not None:
            from paddle_tpu.amp.fp8 import fp8_matmul
            gu = fp8_matmul(x, self.gate_up_proj, self.fp8_meta["gate_up"])
            gate, up = jnp.split(gu, 2, axis=-1)
            return fp8_matmul(jax.nn.silu(gate) * up, self.down_proj,
                              self.fp8_meta["down"])
        from paddle_tpu.quantization import wo_matmul
        gu = checkpoint_name(wo_matmul(x, self.gate_up_proj), "ffn_gu")
        gate, up = jnp.split(gu, 2, axis=-1)
        return checkpoint_name(
            wo_matmul(jax.nn.silu(gate) * up, self.down_proj), "ffn_out")


class LlamaDecoderLayer(Module):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_layernorm = LlamaRMSNorm(cfg.hidden_size, cfg.rms_norm_eps, cfg.dtype)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = LlamaRMSNorm(cfg.hidden_size, cfg.rms_norm_eps, cfg.dtype)
        self.mlp = LlamaMLP(cfg)

    def __call__(self, x, cos, sin, attn_mask=None):
        x = x + self.self_attn(self.input_layernorm(x), cos, sin, attn_mask)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(Module):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.embed_tokens = init((cfg.vocab_size, cfg.hidden_size), cfg.dtype)
        self.set_pspec("embed_tokens", P("tp", None))
        layers = [LlamaDecoderLayer(cfg) for _ in range(cfg.num_hidden_layers)]
        if cfg.scan_layers:
            # stacked pytree [L, ...]: one traced layer, lax.scan over depth —
            # compile time independent of depth, leading axis a natural fsdp dim
            self.layers_stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs, axis=0), *layers)
            self.layers = []
        else:
            self.layers = layers
            self.layers_stacked = None
        self.norm = LlamaRMSNorm(cfg.hidden_size, cfg.rms_norm_eps, cfg.dtype)

    def __call__(self, input_ids, attn_mask=None, position_ids=None):
        cfg = self.cfg
        x = jnp.take(self.embed_tokens, input_ids, axis=0)
        # activations sharded batch over data axes, sequence over sp
        from paddle_tpu.distributed.sharded import maybe_shard
        x = maybe_shard(x, ("dp", "fsdp"), "sp", None)
        cos, sin = A.rope_cos_sin(input_ids.shape[1], cfg.hidden_size // cfg.num_attention_heads,
                                  base=cfg.rope_theta, position_ids=position_ids,
                                  scaling=cfg.rope_scaling,
                                  max_position_embeddings=cfg.max_position_embeddings)
        if cfg.remat:
            # selective remat: save only the tagged activations the policy
            # names (checkpoint_name tags in attention/MLP); None/"full"
            # saves nothing — classic full remat
            names = cfg.save_names()
            policy = (jax.checkpoint_policies.save_only_these_names(*names)
                      if names else None)
            layer_fn = jax.checkpoint(
                lambda lyr, h: lyr(h, cos, sin, attn_mask),
                static_argnums=(), policy=policy)
        else:
            layer_fn = (lambda lyr, h: lyr(h, cos, sin, attn_mask))
        if cfg.scan_layers:
            def body(h, lyr):
                return layer_fn(lyr, h), None
            x, _ = jax.lax.scan(body, x, self.layers_stacked)
        else:
            for lyr in self.layers:
                x = layer_fn(lyr, x)
        return self.norm(x)


class LlamaForCausalLM(Module):
    """Decoder LM with parallel (tp-sharded) LM head + fused CE."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        if cfg.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = I.Normal(0.0, cfg.initializer_range)(
                (cfg.hidden_size, cfg.vocab_size), cfg.dtype)
            self.set_pspec("lm_head", P(None, "tp"))

    def logits(self, hidden):
        from paddle_tpu.quantization import wo_matmul
        w = self.model.embed_tokens.T if self.lm_head is None else self.lm_head
        return wo_matmul(hidden, w)

    def __call__(self, input_ids, attn_mask=None, position_ids=None):
        hidden = self.model(input_ids, attn_mask, position_ids)
        return self.logits(hidden)

    def loss(self, input_ids, labels, attn_mask=None):
        """Causal LM loss; labels = input shifted, ignore_index=-100."""
        from paddle_tpu.distributed.tensor_parallel import parallel_cross_entropy
        logits = self(input_ids, attn_mask)
        per_tok = parallel_cross_entropy(logits, jnp.maximum(labels, 0))
        mask = (labels >= 0).astype(jnp.float32)
        return jnp.sum(per_tok * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def llama_pipeline_train_step(model: "LlamaForCausalLM", mesh, input_ids,
                              labels, num_microbatches: int, batch_axes=(),
                              schedule: str = "1f1b"):
    """1F1B pipeline-parallel loss + grads for LLaMA over the pp mesh axis.

    Decoder layers are the pipeline stages; the embedding runs at stage 0
    and the (final-norm + lm_head + masked-CE) head at the last stage, both
    with replicated grads. Per-microbatch losses are averaged, which equals
    ``model.loss`` exactly when every microbatch masks the same number of
    label positions (the standard shifted-labels -100 tail does).
    Ref: ``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py``.

    Returns ``(loss, grads)`` with ``grads = {layers, embed_tokens,
    norm_weight, lm_head}`` — ``layers`` stacked [L, ...] and sharded
    P("pp", ...) like the stage params. NOTE on a tp>1 mesh the layer
    grads come back in the tp-INTERLEAVED column layout (matching the
    weights the schedule trained on); convert to the canonical layout with
    ``tp_shuffle_llama_params(grads, cfg, tp, inverse=True)``.
    """
    _check_pp_model(model)
    params = _pp_params(model, copy=False)
    if hasattr(mesh, "size") and mesh.size("tp") > 1:
        params = tp_shuffle_llama_params(params, model.cfg, mesh.size("tp"))
    return _pp_loss_and_grads(model.cfg, len(model.model.layers), mesh,
                              params, input_ids, labels, num_microbatches,
                              batch_axes, schedule=schedule)


def _check_pp_model(model):
    assert model.lm_head is not None, \
        "pipeline head needs untied embeddings (tie_word_embeddings=False)"
    assert model.model.layers, "pipeline stages need scan_layers=False"


def _pp_params(model, copy: bool):
    """The canonical pp param tree. ``copy=True`` makes every leaf a fresh
    buffer so a DONATING train loop can never delete the module's own
    weights out from under later eval/checkpoint use."""
    from paddle_tpu.distributed.pipeline import stack_layers
    params = dict(layers=stack_layers(model.model.layers),  # stack = copy
                  embed_tokens=model.model.embed_tokens,
                  norm_weight=model.model.norm.weight,
                  lm_head=model.lm_head)
    if copy:
        params = {k: jax.tree_util.tree_map(jnp.copy, v) if k != "layers"
                  else v for k, v in params.items()}
    return PpParams.make(params, 1)


def make_llama_pp_train_step(model: "LlamaForCausalLM", mesh, optimizer,
                             num_microbatches: int, batch_axes=()):
    """End-to-end 1F1B TRAINING: a jitted ``step(params, opt_state, ids,
    labels) -> (params, opt_state, loss)`` where params =
    ``{layers (stacked, P("pp",...)), embed_tokens, norm_weight, lm_head}``
    and the optimizer consumes the pipeline's grads directly. Composes pp
    with dp via ``batch_axes`` (each dp member pipelines its batch shard;
    grads are dp-averaged inside the schedule). params and opt_state are
    DONATED each step (the reference make_train_step's memory discipline).

    Use ``init_llama_pp_state(model, optimizer)`` for the initial
    (params, opt_state).
    """
    _check_pp_model(model)
    # capture only scalars — holding the module would pin a duplicate set
    # of unstacked weights for the loop's lifetime
    cfg, n_layers = model.cfg, len(model.model.layers)

    def step(params, opt_state, input_ids, labels):
        loss, grads = _pp_loss_and_grads(
            cfg, n_layers, mesh, params, input_ids, labels,
            num_microbatches, batch_axes)
        new_params, new_opt = optimizer.step(params, grads, opt_state)
        return new_params, new_opt, loss

    return jax.jit(step, donate_argnums=(0, 1))


def _pp_loss_and_grads(cfg, n_layers, mesh, params, input_ids, labels,
                       num_microbatches, batch_axes, schedule="1f1b"):
    """The ONE pipeline-LLaMA forward/backward: reads weights from
    ``params`` ({layers, embed_tokens, norm_weight, lm_head}) so both the
    module-level wrapper (llama_pipeline_train_step) and the jitted
    optimizer loop share it."""
    from paddle_tpu.distributed.pipeline import (PipelineLayer,
                                                 pipeline_train_step)
    pipe = PipelineLayer.from_stacked(
        params["layers"], n_layers=n_layers, num_stages=mesh.pp,
        num_microbatches=num_microbatches, remat=cfg.remat)

    cos, sin = A.rope_cos_sin(input_ids.shape[1],
                              cfg.hidden_size // cfg.num_attention_heads,
                              base=cfg.rope_theta, scaling=cfg.rope_scaling,
                              max_position_embeddings=cfg.max_position_embeddings)
    eps = cfg.rms_norm_eps

    tp = mesh.size("tp") if hasattr(mesh, "size") else 1
    stage_specs = None
    if cfg.fp8:
        # fp8 amax-history leaves would travel through the schedule's
        # masked-sum dstage accumulator and come out scaled by 1/M (and
        # dp-meaned) — the optimizer's overwrite-with-gradient splice would
        # then install a mean of rolled histories instead of the step amax,
        # under-estimating amax and over-scaling into e4m3 clipping
        raise NotImplementedError(
            "fp8 delayed scaling is not supported inside the 1F1B pipeline "
            "(amax histories need max/last-write combining across "
            "microbatches, not the schedule's mean); train fp8 with GSPMD "
            "dp/tp/fsdp instead")
    if tp > 1:
        # manual tensor parallelism inside the pipeline: weights must be in
        # the tp-interleaved layout (tp_shuffle_llama_params) so each shard
        # holds matched q/k/v (gate/up) slices
        assert (cfg.num_attention_heads % tp == 0
                and cfg.num_key_value_heads % tp == 0
                and cfg.intermediate_size % tp == 0), \
            f"tp={tp} must divide heads/kv-heads/intermediate"
        layout = getattr(params, "tp_layout", None)
        if layout != tp:
            raise ValueError(
                f"params are in tp_layout={layout!r} but the mesh has "
                f"tp={tp}; build them with init_llama_pp_state(model, opt, "
                "mesh) / tp_shuffle_llama_params so the fused projections "
                "are interleaved for this tp degree (wrong-layout weights "
                "would silently split the wrong q/k/v columns)")
        from paddle_tpu.quantization import QuantizedWeight
        if any(isinstance(l, QuantizedWeight)
               for l in jax.tree_util.tree_leaves(
                   params["layers"], is_leaf=lambda x: isinstance(
                       x, QuantizedWeight))):
            raise NotImplementedError(
                "weight-only quantized layers are inference-path only; the "
                "manual-tp pipeline trains full-precision weights")
        layer_call = make_tp_layer_call(cos, sin)
        stage_specs = llama_tp_stage_specs(params["layers"])
    else:
        layout = getattr(params, "tp_layout", 1)
        if layout not in (None, 1):
            raise ValueError(
                f"params are tp-interleaved for tp={layout} but the mesh "
                "has tp=1; convert back with tp_shuffle_llama_params(..., "
                "inverse=True) first (the plain layer path would split the "
                "wrong q/k/v columns)")

        def layer_call(lyr, h):
            return lyr(h, cos, sin, None)

    def embed_fn(emb_w, ids):
        return jnp.take(emb_w, ids, axis=0)

    def head_loss(hp, hidden, lbl):
        norm_w, head_w = hp
        h = fused_rms_norm(hidden, norm_w, eps)
        logits = (h @ head_w).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        per_tok = -jnp.take_along_axis(
            logp, jnp.maximum(lbl, 0)[..., None], -1)[..., 0]
        mask = (lbl >= 0).astype(jnp.float32)
        return jnp.sum(per_tok * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    loss, dstage, dembed, dhead = pipeline_train_step(
        pipe, mesh, input_ids, labels, layer_call=layer_call,
        head_loss_fn=head_loss,
        head_params=(params["norm_weight"], params["lm_head"]),
        embed_fn=embed_fn, embed_params=params["embed_tokens"],
        batch_axes=batch_axes, stage_specs=stage_specs, schedule=schedule)
    grads = PpParams.make(
        dict(layers=dstage, embed_tokens=dembed,
             norm_weight=dhead[0], lm_head=dhead[1]),
        getattr(params, "tp_layout", 1))
    return loss, grads


class PpParams(dict):
    """The canonical pp param tree with a STATIC layout tag: ``tp_layout``
    records which tp degree the fused projections are interleaved for
    (1 = canonical [Q|K|V]/[gate|up] order). The tag rides the pytree aux
    data, so it survives jit/donation/optimizer tree_maps — and the tp
    pipeline path can refuse weights in the wrong layout instead of
    silently splitting wrong columns."""

    tp_layout: int = 1

    @staticmethod
    def make(d: dict, tp_layout: int = 1) -> "PpParams":
        p = PpParams(d)
        p.tp_layout = tp_layout
        return p


jax.tree_util.register_pytree_with_keys(
    PpParams,
    lambda p: ([(jax.tree_util.DictKey(k), p[k]) for k in sorted(p)],
               (tuple(sorted(p)), p.tp_layout)),
    lambda aux, vals: PpParams.make(dict(zip(aux[0], vals)), aux[1]),
)


def _tp_interleave_perm(n_blocks_per_group: list[int], block: int, tp: int):
    """Column permutation turning globally-grouped fused projections (e.g.
    [Q|K|V] or [gate|up]) into per-tp-shard groups ([q0|k0|v0 | q1|k1|v1]).

    Contiguous tp column-sharding of a fused projection would otherwise
    hand shard 0 only Q (or only gate) columns — the standard Megatron
    trick is to pre-permute so every shard holds matched slices.
    ``n_blocks_per_group``: #blocks (of ``block`` columns) per fused group;
    each group's blocks are dealt round-robin-contiguously to shards."""
    import numpy as np
    offs = np.cumsum([0] + [n * block for n in n_blocks_per_group])
    perm = []
    for i in range(tp):
        for g, n in enumerate(n_blocks_per_group):
            per = n // tp
            start = offs[g] + i * per * block
            perm.extend(range(start, start + per * block))
    return np.asarray(perm)


def tp_shuffle_llama_params(params: dict, cfg: LlamaConfig, tp: int,
                            inverse: bool = False):
    """(Un)permute the stacked layer params for manual-tp pipeline use:
    qkv_proj / qkv_bias columns to per-shard [q_i|k_i|v_i], gate_up_proj
    columns to per-shard [g_i|u_i]. o_proj/down_proj need no permutation
    (their row order already matches the per-shard slices)."""
    import numpy as np
    cur = getattr(params, "tp_layout", 1) or 1
    want_cur = tp if inverse else 1
    if cur != want_cur:
        raise ValueError(
            f"tp_shuffle_llama_params: params are in tp_layout={cur}, "
            f"expected {want_cur} for {'inverse ' if inverse else ''}"
            f"shuffle to tp={tp} — double-(un)shuffling would scramble "
            "the fused projection columns")
    nh, nkv, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.hidden_size // cfg.num_attention_heads)
    m = cfg.intermediate_size
    qkv_perm = _tp_interleave_perm([nh, nkv, nkv], hd, tp)
    gu_perm = _tp_interleave_perm([m, m], 1, tp)
    if inverse:
        qkv_perm = np.argsort(qkv_perm)
        gu_perm = np.argsort(gu_perm)
    layers = params["layers"]
    flat, treedef = jax.tree_util.tree_flatten_with_path(layers)
    out = []
    for path, leaf in flat:
        from paddle_tpu.core.module import _path_to_str
        ps = _path_to_str(path)
        if leaf is None:
            out.append(leaf)
        elif ps.endswith("qkv_proj") or ps.endswith("qkv_bias"):
            out.append(leaf[..., qkv_perm])
        elif ps.endswith("gate_up_proj"):
            out.append(leaf[..., gu_perm])
        else:
            out.append(leaf)
    new = {**params, "layers": jax.tree_util.tree_unflatten(treedef, out)}
    return PpParams.make(new, 1 if inverse else tp)


def make_tp_layer_call(cos, sin, tp_axis: str = "tp"):
    """Decoder-layer call for MANUAL tensor parallelism inside shard_map:
    local q/k/v head slices attend locally; the row-parallel o_proj and
    down_proj partial products are psum'd over the tp axis. Expects weights
    permuted by ``tp_shuffle_llama_params``."""
    from jax import lax as _lax

    from paddle_tpu.distributed._compat import axis_size as _axis_size

    def call(lyr, h):
        att, mlp = lyr.self_attn, lyr.mlp
        tp = _axis_size(tp_axis)
        hd = att.head_dim
        nh_l = att.num_heads // tp
        nkv_l = att.num_kv_heads // tp

        x = h
        hn = lyr.input_layernorm(x)
        qkv = hn @ att.qkv_proj                      # local columns
        if att.qkv_bias is not None:
            qkv = qkv + att.qkv_bias
        b, s, _ = hn.shape
        q, k, v = jnp.split(qkv, [nh_l * hd, (nh_l + nkv_l) * hd], axis=-1)
        q = A.apply_rope(q.reshape(b, s, nh_l, hd), cos, sin)
        k = A.apply_rope(k.reshape(b, s, nkv_l, hd), cos, sin)
        v = v.reshape(b, s, nkv_l, hd)
        ctx = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             window=att.window)
        partial_o = ctx.reshape(b, s, nh_l * hd) @ att.o_proj
        x = x + _lax.psum(partial_o, tp_axis)        # row-parallel reduce

        hn2 = lyr.post_attention_layernorm(x)
        gu = hn2 @ mlp.gate_up_proj                  # local [g_i|u_i]
        gate, up = jnp.split(gu, 2, axis=-1)
        partial_d = (jax.nn.silu(gate) * up) @ mlp.down_proj
        return x + _lax.psum(partial_d, tp_axis)
    return call


def llama_tp_stage_specs(stacked, tp_axis: str = "tp"):
    """Per-leaf specs for the STACKED [L, ...] layer tree:
    P("pp", *tp_spec) — fused projections column-sharded, o/down
    row-sharded over tp, everything else replicated over tp."""
    from paddle_tpu.core.module import _path_to_str
    flat, treedef = jax.tree_util.tree_flatten_with_path(stacked)
    specs = []
    for path, leaf in flat:
        if leaf is None or not hasattr(leaf, "ndim"):
            specs.append(None)
            continue
        ps = _path_to_str(path)
        if ps.endswith(("qkv_proj", "gate_up_proj")):
            dims = (None, tp_axis)
        elif ps.endswith("qkv_bias"):
            dims = (tp_axis,)
        elif ps.endswith(("o_proj", "down_proj")):
            dims = (tp_axis, None)
        else:
            dims = (None,) * (leaf.ndim - 1)  # minus the stacked L dim
        specs.append(P("pp", *dims))
    return jax.tree_util.tree_unflatten(treedef, specs)


def init_llama_pp_state(model: "LlamaForCausalLM", optimizer, mesh=None):
    """(params, opt_state) for ``make_llama_pp_train_step``. Every leaf is
    a FRESH buffer (the train step donates its params, and donated aliases
    of module weights would delete them for later eval/checkpointing).

    With a mesh whose tp > 1 the stacked layer weights are converted to the
    tp-interleaved layout (training then stays in that layout; convert back
    for export with ``tp_shuffle_llama_params(..., inverse=True)``)."""
    _check_pp_model(model)
    params = _pp_params(model, copy=True)
    if mesh is not None and mesh.size("tp") > 1:
        params = tp_shuffle_llama_params(params, model.cfg, mesh.size("tp"))
    return params, optimizer.init(params)


def num_flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Training FLOPs/token ≈ 6*N_params + attention term (for MFU)."""
    h, m, L, v = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers, cfg.vocab_size
    nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    d = h // nh
    per_layer = 2 * h * (nh + 2 * nkv) * d + 2 * nh * d * h + 2 * h * 2 * m + 2 * m * h
    n_matmul = L * per_layer + 2 * h * v  # fwd matmul FLOPs per token (x2 mult-add folded)
    attn = L * 2 * 2 * seq_len * nh * d  # qk^T and pv per token
    return 3.0 * (n_matmul + attn)  # fwd + 2x bwd
